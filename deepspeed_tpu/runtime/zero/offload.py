"""ZeRO-Offload: optimizer state and master weights in TPU-VM host RAM.

Parity target: reference stage2 ``cpu_offload`` (stage2.py:156,326-342,
775-873,1416-1427) + ``DeepSpeedCPUAdam`` (csrc/adam/cpu_adam.cpp). The
device keeps only compute-dtype params; fp32 masters and both Adam moments
live in host numpy arrays, updated by the C++ SIMD kernel
(ops/cpu_adam.py), and the updated params return to HBM as a bf16 staging
buffer produced in the same pass (ds_adam_step_plus_copy parity).

The step is BUCKETED: the flat master-leaf list is split into contiguous
~``offload_bucket_size``-byte groups (the reference's per-bucket async
copies, stage2.py:775-873), and one step is a two-phase protocol over
those buckets:

  phase 1 (norm):  per-bucket squared grad norms accumulate as bucket
                   grads land on the host; once every bucket is in, the
                   global norm resolves the fp16 overflow vote and the
                   clip coefficient (stage2.py:1371-1411 semantics) —
                   until then NO master or moment may mutate, so an
                   overflow step leaves every bucket untouched;
  phase 2 (apply): per-bucket SIMD Adam (explicit bias-correction tick
                   shared by all buckets) + the bucket's compute-dtype
                   upload leaves, released bucket-by-bucket.

``run_bucketed_step`` executes the protocol either serially (the parity
baseline: fetch → norm → vote → apply → upload, bucket by bucket, each
transfer individually fenced) or overlapped: the caller's thread streams
bucket fetches (D2H waits) while a ``ThreadPoolExecutor`` runs the norm
kernels, then runs Adam per bucket in the pool and hands each finished
bucket back for immediate async H2D. Norms pipeline with D2H; applies
pipeline with H2D; device compute of the next step overlaps the tail.
Both modes walk buckets in index order for every floating-point
accumulation, so their masters/moments/params are bit-identical.
"""
from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor, as_completed
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ... import constants as C
from ...ops.cpu_adam import (DeepSpeedCPUAdam, _f32_to_bf16_np, _is_bf16,
                             host_f32)
from ...utils.logging import log_dist

# Optimizers that may drive offloaded state (reference zero/utils.py:41
# restricts ZeRO wrapping to known-compatible optimizers the same way).
SUPPORTED = (C.ADAM_OPTIMIZER, C.ADAMW_OPTIMIZER)


def _partition_axis(shape, num: int) -> Optional[int]:
    """First axis divisible by ``num`` — the SAME rule zero/partition.py's
    _leaf_spec uses for grad/moment shardings, so host shards and device
    grad shards are element-aligned by construction."""
    for i, d in enumerate(shape):
        if d >= num and d % num == 0:
            return i
    return None


def _partition_buckets(leaf_nbytes: List[int], bucket_bytes: int) \
        -> List[List[int]]:
    """Contiguous leaf-index groups of ~``bucket_bytes`` each (greedy fill;
    a single oversized leaf gets its own bucket). Contiguity in flatten
    order keeps the device grad outputs, the host masters/moments, and the
    bf16 staging views all indexable by the same bucket lists."""
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, nb in enumerate(leaf_nbytes):
        if cur and cur_bytes + nb > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
    if cur:
        buckets.append(cur)
    return buckets


def grad_to_host(g) -> np.ndarray:
    """Device grad leaf -> host array the SIMD kernels accept: bf16 stays
    bf16 (the native Adam/norm kernels widen inline — no host cast pass,
    half the gradient read traffic), everything else becomes fp32."""
    a = np.asarray(g)
    return a if _is_bf16(a) else np.asarray(a, np.float32)


class ZeroOffloadOptimizer:
    """Host-side optimizer state + step for the engine's offload path.

    ``partition_rank``/``partition_num`` partition the host masters AND
    moments across dp ranks (reference stage2.py:326-342: each rank's host
    buffers hold only its partition): each leaf is sliced along its
    partition axis; leaves with no divisible axis are replicated (every
    rank applies the identical update — same result everywhere). Host RSS
    for the sharded leaves scales as 1/partition_num.
    """

    def __init__(self, master_params: Any, opt_name: str,
                 opt_params: Dict[str, Any], schedule_fn: Callable,
                 compute_dtype, gradient_clipping: float = 0.0,
                 fp16: bool = False, scaler_cfg: Optional[Dict] = None,
                 partition_rank: int = 0, partition_num: int = 1,
                 axis_divisor: Optional[int] = None,
                 sumsq_allreduce: Optional[Callable[[float], float]] = None,
                 bucket_bytes: int = 0, host_threads: int = 0):
        """``axis_divisor``: divisibility used to PICK each leaf's partition
        axis (defaults to partition_num). The multi-host engine passes the
        dp degree here so the host partition axis coincides with the axis
        zero/partition.py shards the device grads on (dp is a multiple of
        the process count, so the same axis divides both ways).

        ``sumsq_allreduce``: cross-rank sum of the partition-local squared
        grad norm; required for correct clipping when partition_num > 1
        (each rank sees only its shard — without the reduction the clip
        coefficients diverge and replicated leaves drift).

        ``bucket_bytes``: target bucket size in fp32-master bytes (0 =
        ``constants.ZERO_OFFLOAD_BUCKET_SIZE_DEFAULT``). ``host_threads``:
        worker-pool width for the overlapped executor (0 = os.cpu_count())."""
        name = (opt_name or C.ADAM_OPTIMIZER).lower()
        if name not in SUPPORTED:
            raise ValueError(
                f"zero_optimization.cpu_offload supports {SUPPORTED}, got "
                f"'{opt_name}' (reference gate: zero/utils.py:41)")
        p = dict(opt_params or {})
        adamw_mode = p.get("adam_w_mode", name == C.ADAMW_OPTIMIZER)

        self.partition_rank = int(partition_rank)
        self.partition_num = int(partition_num)
        self.sumsq_allreduce = sumsq_allreduce
        divisor = int(axis_divisor or self.partition_num)
        if divisor % self.partition_num != 0:
            raise ValueError(f"axis_divisor {divisor} must be a multiple of "
                             f"partition_num {self.partition_num}")
        leaves, self.treedef = jax.tree_util.tree_flatten(master_params)
        self.full_shapes = [np.shape(l) for l in leaves]
        self._axes = [
            _partition_axis(s, divisor)
            if self.partition_num > 1 else None for s in self.full_shapes]
        self.masters = [
            host_f32(self.slice_leaf(i, np.asarray(l, np.float32)))
            for i, l in enumerate(leaves)]
        self.shapes = [m.shape for m in self.masters]
        local_tree = jax.tree_util.tree_unflatten(self.treedef, self.masters)
        self.opt = DeepSpeedCPUAdam(
            local_tree, lr=p.get("lr", 1e-3),
            betas=tuple(p.get("betas", (0.9, 0.999))), eps=p.get("eps", 1e-8),
            weight_decay=p.get("weight_decay", 0.0), adamw_mode=adamw_mode)
        self.schedule_fn = schedule_fn
        self.clip = float(gradient_clipping or 0.0)
        self.compute_dtype = compute_dtype
        self._bf16_staging = None
        if compute_dtype == jnp.bfloat16:
            self._bf16_staging = [np.empty(m.shape, np.uint16)
                                  for m in self.masters]

        # Host-side loss-scale state machine (fp16 offload): mirrors
        # fp16/loss_scaler.py dynamics without device round-trips.
        self.fp16 = fp16
        sc = scaler_cfg or {}
        self.loss_scale = float(sc.get("init_scale", 1.0))
        self.static_scale = bool(sc.get("static", True))
        self.scale_window = int(sc.get("scale_window", 1000))
        self.min_scale = float(sc.get("min_scale", 1.0))
        self.hysteresis_init = int(sc.get("hysteresis", 2))
        self.hysteresis = self.hysteresis_init
        self.growth_count = 0
        self.step_count = 0
        self.skipped_steps = 0

        # Bucketed two-phase step state (see module docstring).
        import os
        self.bucket_bytes = int(bucket_bytes) or \
            C.ZERO_OFFLOAD_BUCKET_SIZE_DEFAULT
        self.host_threads = int(host_threads) or (os.cpu_count() or 1)
        self.buckets = _partition_buckets(
            [m.nbytes for m in self.masters], self.bucket_bytes)
        self._pending_t: Optional[int] = None
        self._pool: Optional[ThreadPoolExecutor] = None

        nbytes = sum(m.nbytes for m in self.masters) + \
            sum(a.nbytes for a in self.opt.exp_avg) + \
            sum(a.nbytes for a in self.opt.exp_avg_sq)
        log_dist(f"ZeRO-Offload: {len(self.masters)} tensors in "
                 f"{len(self.buckets)} bucket(s) "
                 f"(~{self.bucket_bytes / 2**20:.0f} MiB), "
                 f"{nbytes / 2**20:.1f} MiB optimizer state in host RAM "
                 f"(native SIMD: {self.opt.native}, "
                 f"host threads: {self.host_threads})", ranks=[0])

    # ------------------------------------------------------------------ #
    def local_param_leaves(self):
        """Compute-dtype param leaves, partition-local, as host arrays
        (bf16 via the fused staging copy — zero additional cast)."""
        import ml_dtypes
        if self.compute_dtype == jnp.bfloat16:
            if self._bf16_staging is not None and self.step_count > 0:
                # zero-copy view of the kernel's fused down-cast output
                return [s.view(ml_dtypes.bfloat16)
                        for s in self._bf16_staging]
            return [m.astype(ml_dtypes.bfloat16) for m in self.masters]
        return [m.astype(np.dtype(self.compute_dtype))
                for m in self.masters]

    def device_params(self, shardings=None) -> Any:
        """Compute-dtype params for HBM. With partition_num > 1 the
        returned leaves are partition-local; the multi-host engine instead
        assembles via _assemble_offload_params (process-sharded upload +
        XLA all-gather)."""
        tree = jax.tree_util.tree_unflatten(self.treedef,
                                            self.local_param_leaves())
        if shardings is not None:
            return jax.device_put(tree, shardings)
        return jax.device_put(tree)

    def master_tree(self) -> Any:
        return jax.tree_util.tree_unflatten(self.treedef, self.masters)

    def slice_leaf(self, i: int, leaf: np.ndarray) -> np.ndarray:
        """Full leaf -> this rank's partition (identity when unsharded or
        already local-shaped)."""
        ax = self._axes[i]
        if ax is None or leaf.shape != self.full_shapes[i]:
            return leaf
        d = leaf.shape[ax] // self.partition_num
        sl = [slice(None)] * leaf.ndim
        sl[ax] = slice(self.partition_rank * d, (self.partition_rank + 1) * d)
        return leaf[tuple(sl)]

    # ------------------------------------------------------------------ #
    # Bucketed two-phase step protocol
    # ------------------------------------------------------------------ #
    def num_buckets(self) -> int:
        return len(self.buckets)

    def ensure_pool(self) -> ThreadPoolExecutor:
        if self._pool is None:
            import weakref
            self._pool = ThreadPoolExecutor(
                max_workers=self.host_threads,
                thread_name_prefix="ds-offload")
            # Engines have no teardown hook: reap the idle workers when
            # the optimizer is collected (thread sweeps / test suites
            # build many engines per process).
            weakref.finalize(self, self._pool.shutdown, wait=False)
        return self._pool

    def bucket_sumsq(self, b: int, g_local) -> Tuple[float, float]:
        """Phase-1 norm for bucket ``b``: (partitioned, replicated) squared
        norm partials over its partition-local grad leaves, per-leaf in
        bucket order. Partitioned leaves are DISJOINT shards whose partials
        sum across ranks; replicated leaves are identical everywhere and
        contribute once, outside the reduction — the reference
        stage2.py:1371-1411 partition-then-allreduce decomposition."""
        inv_scale = 1.0 / self.loss_scale
        part = repl = 0.0
        for leaf_i, g in zip(self.buckets[b], g_local):
            s = self.opt.grad_norm_sq([g], inv_scale)
            if self._axes[leaf_i] is not None:
                part += s
            else:
                repl += s
        return part, repl

    def resolve_vote(self, part_sumsqs, repl_sumsqs) -> Dict[str, float]:
        """Resolve the global norm once every bucket's partials are in
        (lists indexed by bucket — ALWAYS summed in bucket order, so
        overlapped completion order cannot perturb the double). Runs the
        overflow vote + loss-scale state machine and computes the clip
        coefficient; on overflow no bucket may apply (masters/moments
        untouched). Returns the step metrics; phase 2 reads
        ``clip_coeff``/``lr`` from them."""
        local_part = 0.0
        for s in part_sumsqs:
            local_part += s
        if self.sumsq_allreduce is not None:
            total = float(self.sumsq_allreduce(local_part))
        elif self.partition_num > 1 and (self.clip > 0 or self.fp16):
            # Norm DRIVES behavior (clip coeff / overflow vote): a
            # partition-local value would diverge across ranks and
            # drift the replicated leaves apart.
            raise RuntimeError(
                "partition_num > 1 with gradient clipping or fp16 "
                "requires sumsq_allreduce (cross-rank norm reduction)")
        else:
            total = local_part                 # metric-only when sharded
        for s in repl_sumsqs:
            total += s
        grad_norm = float(np.sqrt(total))
        overflow = self.fp16 and not np.isfinite(grad_norm)
        if overflow:
            self.skipped_steps += 1
            self._scale_down()
            return {"loss_scale": self.loss_scale, "grad_norm": grad_norm,
                    "overflow": True, "lr": self._lr(), "clip_coeff": 1.0}
        coeff = 1.0
        if self.clip > 0 and np.isfinite(grad_norm) and grad_norm > self.clip:
            coeff = self.clip / (grad_norm + 1e-6)
        # All buckets share ONE bias-correction tick; step_count advances
        # in finish_step, after the last bucket applied.
        self._pending_t = self.opt.step_count + 1
        return {"loss_scale": self.loss_scale, "grad_norm": grad_norm,
                "overflow": False, "lr": self._lr(), "clip_coeff": coeff}

    def bucket_apply(self, b: int, g_local, lr: float, clip_coeff: float,
                     want_upload: bool = True) -> Optional[list]:
        """Phase-2 Adam for bucket ``b`` (in place, explicit shared tick),
        then return its upload-ready compute-dtype host leaves (bf16: the
        kernel's fused staging down-cast, zero extra passes; skipped when
        the caller uploads the whole tree afterwards). Buckets touch
        disjoint leaves — safe to run concurrently."""
        assert self._pending_t is not None, \
            "bucket_apply before resolve_vote (or after an overflow vote)"
        self.opt.step_leaves(
            self.masters, g_local, self.buckets[b], lr=lr,
            grad_scale=(1.0 / self.loss_scale) * clip_coeff,
            bf16_out=self._bf16_staging, step=self._pending_t)
        return self.upload_leaves(self.buckets[b]) if want_upload else None

    def finish_step(self) -> None:
        """Commit the step after every bucket applied: advance the shared
        optimizer tick, then run the loss-scale growth side of the state
        machine."""
        assert self._pending_t is not None
        self.opt.step_count = self._pending_t
        self._pending_t = None
        self.step_count += 1
        self._scale_up()

    def upload_leaves(self, idxs) -> list:
        """Compute-dtype host leaves for the given indices (same source
        buffers as local_param_leaves, per bucket)."""
        if self.compute_dtype == jnp.bfloat16:
            import ml_dtypes
            return [self._bf16_staging[i].view(ml_dtypes.bfloat16)
                    for i in idxs]
        dt = np.dtype(self.compute_dtype)
        return [self.masters[i].astype(dt) for i in idxs]

    # ------------------------------------------------------------------ #
    def host_step(self, grads: Any) -> Dict[str, float]:
        """One optimizer step from device-computed (loss-scaled) grads —
        the serial execution of the bucketed protocol (the engine's
        overlapped path drives run_bucketed_step itself, with device
        fetch/upload callbacks).

        Grad leaves may be full-shaped (sliced here to the local partition)
        or already partition-local."""
        g_leaves = [self.slice_leaf(i, grad_to_host(g))
                    for i, g in enumerate(jax.tree_util.tree_leaves(grads))]
        metrics, _ = run_bucketed_step(
            self, lambda b: [g_leaves[i] for i in self.buckets[b]],
            overlap=False)
        return metrics

    def _lr(self) -> float:
        return float(self.schedule_fn(self.step_count))

    def _scale_down(self) -> None:
        if self.static_scale or not self.fp16:
            return
        if self.hysteresis > 1:
            self.hysteresis -= 1
        else:
            self.loss_scale = max(self.loss_scale / 2.0, self.min_scale)
            self.hysteresis = self.hysteresis_init
        self.growth_count = 0

    def _scale_up(self) -> None:
        if self.static_scale or not self.fp16:
            return
        self.growth_count += 1
        if self.growth_count >= self.scale_window:
            self.loss_scale *= 2.0
            self.growth_count = 0

    # ------------------------------------------------------------------ #
    def state_dict(self) -> Dict[str, Any]:
        return {"optimizer": self.opt.state_dict(),
                "masters": list(self.masters),
                "loss_scale": self.loss_scale,
                "growth_count": self.growth_count,
                "hysteresis": self.hysteresis,
                "step_count": self.step_count,
                "skipped_steps": self.skipped_steps}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.opt.load_state_dict(sd["optimizer"])
        self.set_masters(sd["masters"])
        self.loss_scale = float(sd.get("loss_scale", self.loss_scale))
        self.growth_count = int(sd.get("growth_count", 0))
        self.hysteresis = int(sd.get("hysteresis", self.hysteresis_init))
        self.step_count = int(sd.get("step_count", 0))
        self.skipped_steps = int(sd.get("skipped_steps", 0))

    def set_masters(self, leaves) -> None:
        """Replace the fp32 masters (checkpoint load; full or local-shaped
        leaves). ALWAYS goes through here so the bf16 staging buffers can
        never serve stale weights: device_params() reads staging whenever
        step_count > 0, including on the load_optimizer_states=False path
        that bypasses load_state_dict."""
        self.masters = [
            host_f32(self.slice_leaf(i, np.asarray(m, np.float32)))
            for i, m in enumerate(leaves)]
        self._sync_staging()

    def _sync_staging(self) -> None:
        if self._bf16_staging is not None:
            for buf, m in zip(self._bf16_staging, self.masters):
                buf[...] = _f32_to_bf16_np(m)


# --------------------------------------------------------------------- #
# Bucketed step executor: serial parity baseline OR overlapped pipeline
# --------------------------------------------------------------------- #
def run_bucketed_step(off: ZeroOffloadOptimizer,
                      fetch_bucket: Callable[[int], list],
                      upload_bucket: Optional[Callable[[int, list], None]]
                      = None,
                      overlap: bool = False) \
        -> Tuple[Dict[str, float], Dict[str, Any]]:
    """Execute one two-phase bucketed offload step over ``off``.

    ``fetch_bucket(b)`` blocks until bucket ``b``'s partition-local host
    grad leaves are materialized (the D2H wait — for the serial path each
    call is its own fence: nothing else is in flight, so the per-bucket
    timing cannot bleed). ``upload_bucket(b, leaves)`` dispatches the
    bucket's async H2D; it is always invoked on the caller's thread (jax
    dispatch stays single-threaded), in bucket order when serial and in
    Adam-completion order when overlapped.

    Overlapped dataflow (``overlap=True``, pool width =
    ``off.host_threads``):

        caller thread:  fetch b0 | fetch b1 | ... | upload as applies land
        worker pool:         norm b0 | norm b1 | ...  [vote]  adam b*

    Floating-point accumulations (norm partials) are reduced in
    bucket-index order in both modes, and every bucket's Adam shares one
    explicit bias-correction tick, so serial and overlapped execution
    produce bit-identical masters, moments, and uploads.

    Returns ``(metrics, timings)`` — timings carry per-bucket fenced
    ``d2h_ms``/``norm_ms``/``adam_ms``/``h2d_ms`` lists plus phase sums,
    the host-pipeline span, and the span-vs-work ``overlap_fraction``
    (0 when serial; > 0 exactly when concurrency hid host work)."""
    nb = off.num_buckets()
    pb: Dict[str, List[float]] = {
        "d2h_ms": [0.0] * nb, "norm_ms": [0.0] * nb,
        "adam_ms": [0.0] * nb, "h2d_ms": [0.0] * nb}
    # Per-bucket phase START offsets (seconds since t_start), kept OUTSIDE
    # ``pb`` so work_ms stays a pure duration sum. With ``t_origin`` they
    # let telemetry synthesize Chrome-trace spans from these already-fenced
    # measurements instead of adding fences of its own.
    t0s: Dict[str, List[float]] = {
        "d2h_t0": [0.0] * nb, "norm_t0": [0.0] * nb,
        "adam_t0": [0.0] * nb, "h2d_t0": [0.0] * nb}
    parts = [0.0] * nb
    repls = [0.0] * nb
    host_grads: List[Optional[list]] = [None] * nb
    t_start = time.perf_counter()

    def fetch(b: int) -> None:
        t0 = time.perf_counter()
        t0s["d2h_t0"][b] = t0 - t_start
        host_grads[b] = fetch_bucket(b)
        pb["d2h_ms"][b] = (time.perf_counter() - t0) * 1e3

    def norm(b: int) -> None:
        t0 = time.perf_counter()
        t0s["norm_t0"][b] = t0 - t_start
        parts[b], repls[b] = off.bucket_sumsq(b, host_grads[b])
        pb["norm_ms"][b] = (time.perf_counter() - t0) * 1e3

    def adam(b: int, lr: float, coeff: float) -> Optional[list]:
        t0 = time.perf_counter()
        t0s["adam_t0"][b] = t0 - t_start
        out = off.bucket_apply(b, host_grads[b], lr, coeff,
                               want_upload=upload_bucket is not None)
        pb["adam_ms"][b] = (time.perf_counter() - t0) * 1e3
        return out

    def upload(b: int, leaves: list) -> None:
        if upload_bucket is None:
            return
        t0 = time.perf_counter()
        t0s["h2d_t0"][b] = t0 - t_start
        upload_bucket(b, leaves)
        pb["h2d_ms"][b] = (time.perf_counter() - t0) * 1e3

    if not overlap:
        for b in range(nb):
            fetch(b)
            norm(b)
        metrics = off.resolve_vote(parts, repls)
        if not metrics["overflow"]:
            for b in range(nb):
                upload(b, adam(b, metrics["lr"], metrics["clip_coeff"]))
            off.finish_step()
    else:
        pool = off.ensure_pool()
        norm_futs = []
        for b in range(nb):
            fetch(b)                      # D2H wait on the caller's thread
            norm_futs.append(pool.submit(norm, b))   # ...norms in the pool
        for f in norm_futs:
            f.result()
        metrics = off.resolve_vote(parts, repls)
        if not metrics["overflow"]:
            lr, coeff = metrics["lr"], metrics["clip_coeff"]
            futs = {pool.submit(adam, b, lr, coeff): b for b in range(nb)}
            for f in as_completed(futs):  # H2D the moment a bucket lands
                upload(futs[f], f.result())
            off.finish_step()

    span_ms = (time.perf_counter() - t_start) * 1e3
    work_ms = sum(sum(v) for v in pb.values())
    timings = {
        "per_bucket": pb,
        "per_bucket_t0": t0s,
        "t_origin": t_start,
        "d2h_ms": sum(pb["d2h_ms"]),
        "host_norm_ms": sum(pb["norm_ms"]),
        "host_step_ms": sum(pb["adam_ms"]),
        "h2d_dispatch_ms": sum(pb["h2d_ms"]),
        "pipeline_span_ms": span_ms,
        "pipeline_work_ms": work_ms,
        "overlap_fraction": max(0.0, 1.0 - span_ms / work_ms)
        if overlap and work_ms > 0 else 0.0,
        "num_buckets": nb,
        "overlapped": bool(overlap),
    }
    return metrics, timings
