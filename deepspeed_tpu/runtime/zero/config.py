"""ZeRO configuration.

Parity with reference ``runtime/zero/config.py``: fields stage,
contiguous_gradients, reduce_bucket_size, reduce_scatter, overlap_comm,
allgather_partitions, allgather_bucket_size, load_from_fp32_weights,
cpu_offload, elastic_checkpoint (zero/config.py:61-107); legacy bool→dict
migration (zero/config.py:36-53).

TPU mapping notes: bucket sizes become scan-chunk hints for the sharded
update; ``reduce_scatter: false`` selects the dense all-reduce gradient
path (stage-2 grads stay replicated, reference semantics), and
``grad_sync`` picks how the reduce-scatter is obtained when it is on —
"declarative" (GSPMD sharding declaration), "explicit" (guaranteed
``lax.psum_scatter`` under shard_map), or "auto" (probe the compiled
lowering, go explicit iff the declaration regresses to all-reduce+slice;
see parallel/hlo_audit.py). For the device collectives ``overlap_comm``
is advisory (XLA's latency-hiding scheduler overlaps reduce-scatter with
backward automatically — the engine says so at init instead of silently
swallowing the knob); ``cpu_offload`` moves optimizer state to TPU-VM host RAM,
and there ``overlap_comm`` is load-bearing: it selects the bucketed
overlapped offload pipeline (D2H / host Adam / H2D streamed per
``offload_bucket_size`` bucket through an ``offload_host_threads`` worker
pool) over the serial fetch-step-upload path.

Stage 3 (``stage: 3``) shards the PARAMETER tree itself over dp in
addition to grads and optimizer state; ``prefetch_depth`` controls how
many layers ahead the per-layer param all-gather is issued inside the
model's layer scan (0 = gather at use, the parity baseline; see
runtime/zero/stage3.py). Stage 3 requires ``reduce_scatter: true`` —
the update is shard-local so the grads must come back as the owning
shard.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Union

from .. import config_utils
from ... import constants as C


class ZeroConfig:
    def __init__(self, param_dict: Optional[Dict[str, Any]] = None):
        self.stage = C.ZERO_STAGE_DEFAULT
        self.contiguous_gradients = C.ZERO_CONTIGUOUS_GRADIENTS_DEFAULT
        self.reduce_scatter = C.ZERO_REDUCE_SCATTER_DEFAULT
        self.grad_sync = C.ZERO_GRAD_SYNC_DEFAULT
        self.prefetch_depth = C.ZERO_PREFETCH_DEPTH_DEFAULT
        self.dcn_compression = C.ZERO_DCN_COMPRESSION_DEFAULT
        self.reduce_bucket_size = C.ZERO_REDUCE_BUCKET_SIZE_DEFAULT
        self.allgather_partitions = C.ZERO_ALLGATHER_PARTITIONS_DEFAULT
        self.allgather_bucket_size = C.ZERO_ALLGATHER_BUCKET_SIZE_DEFAULT
        self.overlap_comm = C.ZERO_OVERLAP_COMM_DEFAULT
        self.load_from_fp32_weights = C.ZERO_LOAD_FROM_FP32_WEIGHTS_DEFAULT
        self.cpu_offload = C.ZERO_CPU_OFFLOAD_DEFAULT
        self.offload_bucket_size = C.ZERO_OFFLOAD_BUCKET_SIZE_DEFAULT
        self.offload_host_threads = C.ZERO_OFFLOAD_HOST_THREADS_DEFAULT
        self.elastic_checkpoint = C.ZERO_ELASTIC_CHECKPOINT_DEFAULT
        self.max_elements_per_comm = C.ZERO_MAX_ELEMENTS_PER_COMM_DEFAULT

        if param_dict is not None and C.ZERO_OPTIMIZATION in param_dict:
            zero_config_dict = param_dict[C.ZERO_OPTIMIZATION]
            if isinstance(zero_config_dict, bool):
                # Legacy: "zero_optimization": true → stage 1.
                zero_config_dict = {
                    C.ZERO_STAGE: 1 if zero_config_dict else 0
                }
            self._initialize(zero_config_dict)

    def _initialize(self, d: Dict[str, Any]) -> None:
        get = config_utils.get_scalar_param
        self.stage = get(d, C.ZERO_STAGE, C.ZERO_STAGE_DEFAULT)
        self.contiguous_gradients = get(d, C.ZERO_CONTIGUOUS_GRADIENTS,
                                        C.ZERO_CONTIGUOUS_GRADIENTS_DEFAULT)
        self.reduce_bucket_size = get(d, C.ZERO_REDUCE_BUCKET_SIZE,
                                      C.ZERO_REDUCE_BUCKET_SIZE_DEFAULT)
        self.reduce_scatter = get(d, C.ZERO_REDUCE_SCATTER, C.ZERO_REDUCE_SCATTER_DEFAULT)
        self.grad_sync = get(d, C.ZERO_GRAD_SYNC, C.ZERO_GRAD_SYNC_DEFAULT)
        if self.grad_sync not in C.ZERO_GRAD_SYNC_MODES:
            raise ValueError(
                f"{C.ZERO_GRAD_SYNC} must be one of "
                f"{C.ZERO_GRAD_SYNC_MODES}, got {self.grad_sync!r}")
        if not self.reduce_scatter and self.grad_sync == "explicit":
            raise ValueError(
                f"{C.ZERO_GRAD_SYNC}='explicit' requires "
                f"{C.ZERO_REDUCE_SCATTER}: true — reduce_scatter: false "
                "selects the dense all-reduce gradient path")
        self.prefetch_depth = get(d, C.ZERO_PREFETCH_DEPTH,
                                  C.ZERO_PREFETCH_DEPTH_DEFAULT)
        if not isinstance(self.prefetch_depth, int) \
                or self.prefetch_depth < 0:
            raise ValueError(
                f"{C.ZERO_PREFETCH_DEPTH} must be a non-negative int "
                f"(layers gathered ahead of use), got "
                f"{self.prefetch_depth!r}")
        self.dcn_compression = get(d, C.ZERO_DCN_COMPRESSION,
                                   C.ZERO_DCN_COMPRESSION_DEFAULT)
        if not isinstance(self.dcn_compression, bool):
            raise ValueError(
                f"{C.ZERO_DCN_COMPRESSION} must be a bool (compress the "
                f"inter-slice DCN gradient hop), got "
                f"{self.dcn_compression!r}")
        if self.dcn_compression and self.stage < 2:
            raise ValueError(
                f"{C.ZERO_DCN_COMPRESSION} requires ZeRO stage >= 2: the "
                "compressed DCN hop carries the 1/dp-sharded residual of "
                "the in-slice reduce-scatter, which only exists when "
                "grads are sharded")
        self.overlap_comm = get(d, C.ZERO_OVERLAP_COMM, C.ZERO_OVERLAP_COMM_DEFAULT)
        self.allgather_partitions = get(d, C.ZERO_ALLGATHER_PARTITIONS,
                                        C.ZERO_ALLGATHER_PARTITIONS_DEFAULT)
        self.allgather_bucket_size = get(d, C.ZERO_ALLGATHER_BUCKET_SIZE,
                                         C.ZERO_ALLGATHER_BUCKET_SIZE_DEFAULT)
        self.load_from_fp32_weights = get(d, C.ZERO_LOAD_FROM_FP32_WEIGHTS,
                                          C.ZERO_LOAD_FROM_FP32_WEIGHTS_DEFAULT)
        self.cpu_offload = get(d, C.ZERO_CPU_OFFLOAD, C.ZERO_CPU_OFFLOAD_DEFAULT)
        self.offload_bucket_size = get(d, C.ZERO_OFFLOAD_BUCKET_SIZE,
                                       C.ZERO_OFFLOAD_BUCKET_SIZE_DEFAULT)
        self.offload_host_threads = get(d, C.ZERO_OFFLOAD_HOST_THREADS,
                                        C.ZERO_OFFLOAD_HOST_THREADS_DEFAULT)
        self.elastic_checkpoint = get(d, C.ZERO_ELASTIC_CHECKPOINT,
                                      C.ZERO_ELASTIC_CHECKPOINT_DEFAULT)
        if not isinstance(self.offload_bucket_size, int) \
                or self.offload_bucket_size <= 0:
            raise ValueError(
                f"{C.ZERO_OFFLOAD_BUCKET_SIZE} must be a positive byte "
                f"count, got {self.offload_bucket_size!r}")
        if not isinstance(self.offload_host_threads, int) \
                or self.offload_host_threads < 0:
            raise ValueError(
                f"{C.ZERO_OFFLOAD_HOST_THREADS} must be a non-negative int "
                f"(0 = auto), got {self.offload_host_threads!r}")
        self.max_elements_per_comm = get(d, C.ZERO_MAX_ELEMENTS_PER_COMM,
                                         C.ZERO_MAX_ELEMENTS_PER_COMM_DEFAULT)
        if not isinstance(self.stage, int) or not (0 <= self.stage <= C.MAX_STAGE_ZERO_OPTIMIZATION):
            raise ValueError(
                f"ZeRO stage must be an int in [0, {C.MAX_STAGE_ZERO_OPTIMIZATION}], got {self.stage}")
        if self.stage >= 3 and not self.reduce_scatter:
            # Stage 3 has no dense-gradient mode: the optimizer update is
            # shard-local over dp-sharded params, so the grads MUST come
            # back as the owning shard (reduce-scatter), never replicated.
            raise ValueError(
                f"{C.ZERO_REDUCE_SCATTER}: false does not compose with "
                "ZeRO stage 3 — sharded parameters require the gradient "
                "reduce-scattered back to the owning shard")

    def repr_dict(self) -> Dict[str, Any]:
        return dict(self.__dict__)

    def __repr__(self) -> str:
        return f"ZeroConfig({self.repr_dict()})"
