"""ZeRO stage 3: parameter partitioning with prefetch-overlapped gathers.

The reference hard-stops at stage 2 (engine.py:707-708 raises for any
other stage); this module is the TPU-native stage 3. Parameters are
*born* dp-sharded (``partition.stage3_param_specs`` — the same
first-divisible-dim rule grads and moments follow, so the optimizer
apply stays shard-local with no resharding), gathered just-in-time for
use, and dropped right after their forward/backward consumption, with
the gradient reduce-scattered back to the owning shard. Per step each
parameter crosses the wire three times — fwd gather, bwd re-gather,
grad reduce-scatter — the classic ZeRO-3 3x schedule (Rajbhandari et
al., 2020 §5), priced by ``hlo_audit.grad_sync_wire_model(zero3=...)``.

Two gather lowerings mirror the engine's ``grad_sync`` honesty split:

- **declarative**: params carry dp ``NamedSharding``s into the jitted
  step and GSPMD inserts the all-gathers at each use point (inside the
  model's layer scan the use point is the per-layer slice, so gathers
  land in the loop body); XLA's collective pipeliner owns the
  compute/gather overlap. Correct wherever the partitioner is honest.
- **explicit**: on backends whose partitioner regresses declarations
  (this repo's CPU dev backend), the engine computes grads under
  ``shard_map`` over dp and this module's ``gather_cast`` performs the
  gather by construction: the fp32 master shard is cast to the compute
  dtype and ``lax.all_gather``-ed (compute-dtype wire — half the bytes
  of an fp32 gather under fp16/bf16), and its custom transpose
  reduce-scatters the cotangent in fp32 — the same widen-then-scatter
  the explicit ZeRO-2 path performs, so one stage-3 step is
  BIT-identical to the stage-2 step from the same state.

``zero3_block_scan`` is the rebuilt fwd/bwd layer scan for
stacked-layer models (models/transformer.apply_blocks): a manual-VJP
scan whose forward gathers each layer's shard ``prefetch_depth`` layers
ahead of use (the gather for layer i+k is issued before layer i's
compute, so it overlaps), and whose backward walks the layers in
reverse with the same prefetch window, re-gathering each layer,
recomputing its forward (full per-layer remat — the usual ZeRO-3/FSDP
pairing), and reduce-scattering its grads inside the scan. Because the
VJP is manual, the gathered weights are NEVER saved as residuals at any
prefetch depth — the live gather working set is bounded at
``prefetch_depth + 1`` layers (``gather_working_set_bytes``), which the
lint materialization gate checks (analysis/passes.py reads
``zero3_gather_bytes`` from the engine's path meta).

``prefetch_depth: 0`` gathers at use — no overlap structure, the
parity baseline ``ablate_zero3_prefetch.py`` measures against.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["gather_cast", "gather_tree", "Zero3Scan", "zero3_block_scan",
           "gather_working_set_bytes"]


# --------------------------------------------------------------------- #
# The explicit gather: compute-dtype all-gather, fp32 scatter transpose
# --------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def gather_cast(x, axis_name: str, dim: Optional[int], dtype):
    """``all_gather(x.astype(dtype), axis_name, axis=dim, tiled=True)``
    with a custom transpose that widens the cotangent to fp32 BEFORE the
    reduce-scatter and returns the fp32 owning shard.

    The primal input is the fp32 master shard (or a bf16 master-free
    shard); the gather wire moves ``dtype`` bytes (the compute dtype),
    and the gradient reduction runs in fp32 regardless — the exact
    widen-then-scatter the explicit ZeRO-2 path performs, which is what
    makes one stage-3 step bit-identical to stage 2. ``dim=None`` skips
    the collective (a replicated leaf): cast only.
    """
    x = x.astype(dtype)
    if dim is None:
        return x
    return lax.all_gather(x, axis_name, axis=dim, tiled=True)


def _gather_cast_fwd(x, axis_name, dim, dtype):
    # Residual: a 0-d dtype carrier (a raw np.dtype is not a JAX type).
    return gather_cast(x, axis_name, dim, dtype), jnp.zeros((), x.dtype)


def _gather_cast_bwd(axis_name, dim, dtype, res, ct):
    ct = ct.astype(jnp.float32)
    if dim is not None:
        ct = lax.psum_scatter(ct, axis_name, scatter_dimension=dim,
                              tiled=True)
    else:
        ct = lax.psum(ct, axis_name)
    return (ct.astype(res.dtype),)


gather_cast.defvjp(_gather_cast_fwd, _gather_cast_bwd)


def gather_tree(tree: Any, dims: Any, axis_name: str, dtype) -> Any:
    """Per-leaf ``gather_cast`` over a pytree of shards. ``dims`` is the
    matching tree of dp partition dims (None = replicated leaf: cast +
    psum-transpose only). Non-float leaves pass through untouched."""
    def one(leaf, d):
        if not hasattr(leaf, "dtype") or \
                not jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf
        return gather_cast(leaf, axis_name, d, dtype)
    return jax.tree_util.tree_map(one, tree, dims)


# --------------------------------------------------------------------- #
# The engine <-> model contract for the per-layer prefetched scan
# --------------------------------------------------------------------- #
class Zero3Scan:
    """Binds the model's stacked-layer scan to the engine's stage-3
    layout. Build one, hand it to BOTH the loss builder (e.g.
    ``gpt2_loss_fn(cfg, zero3=spec)``) and the engine
    (``deepspeed_tpu.initialize(..., zero3_scan=spec)``); the engine
    binds mode/mesh/dims at construction, the model reads them at trace
    time (the first train step, which follows engine init).

    ``scope``: substring of the param-tree path marking the leaves the
    model gathers ITSELF per layer (default ``"blocks"`` — the
    transformer's stacked subtree). Leaf names inside the scope must be
    unique (the transformer block dict is). The engine's generic gather
    skips covered leaves; ``partition.stage3_param_specs`` keeps their
    layer axis (dim 0) unsharded so per-layer slices stay dp-sharded.
    """

    def __init__(self, prefetch_depth: Optional[int] = None,
                 scope: str = "blocks"):
        self.prefetch_depth = prefetch_depth   # None -> engine config
        self.scope = scope
        self.mode = "unbound"                  # explicit|declarative|unbound
        self.mesh: Optional[Mesh] = None
        self.axis_name: Optional[str] = None
        self.compute_dtype = None
        # name -> (gather dim AFTER the layer slice, gathered P after the
        # slice) for covered leaves; gather dim None = replicated leaf.
        self.layer_info: Dict[str, Tuple[Optional[int], P]] = {}

    def covers(self, path_str: str) -> bool:
        # Exact key-segment match, not substring: a leaf named
        # "blocks_ln_scale" must NOT silently join the scan scope (a
        # covered-but-unscanned leaf would skip the engine's gather AND
        # the model's per-layer scatter — its grads would never reduce
        # across dp).
        return f"['{self.scope}']" in path_str

    def bind(self, *, mode: str, mesh: Mesh, axis_name: str, compute_dtype,
             prefetch_depth: int,
             layer_info: Dict[str, Tuple[Optional[int], P]]) -> None:
        self.mode = mode
        self.mesh = mesh
        self.axis_name = axis_name
        self.compute_dtype = compute_dtype
        if self.prefetch_depth is None:
            self.prefetch_depth = int(prefetch_depth)
        self.layer_info = dict(layer_info)

    @property
    def bound(self) -> bool:
        return self.mode in ("explicit", "declarative")

    # ---- per-layer gather (the sliced view: layer axis dropped) ---- #
    def gather_layer(self, p_layer: Dict[str, Any]) -> Dict[str, Any]:
        """Gather one layer's param dict to full (replicated-over-dp)
        arrays in the compute dtype."""
        out = {}
        for name, leaf in p_layer.items():
            gdim, gspec = self.layer_info.get(name, (None, P()))
            if not hasattr(leaf, "dtype") or \
                    not jnp.issubdtype(leaf.dtype, jnp.floating):
                out[name] = leaf
            elif self.mode == "explicit":
                out[name] = gather_cast(leaf, self.axis_name, gdim,
                                        self.compute_dtype)
            else:   # declarative: constrain to the dp-free spec; GSPMD
                    # lowers the all-gather at this use point.
                out[name] = lax.with_sharding_constraint(
                    leaf, NamedSharding(self.mesh, gspec))
        return out


# --------------------------------------------------------------------- #
# The rebuilt fwd/bwd layer scan
# --------------------------------------------------------------------- #
def zero3_block_scan(block_fn: Callable, stacked: Dict[str, Any],
                     x: Any, keys: Any, spec: Zero3Scan) -> Any:
    """Run L stacked layers with per-layer just-in-time param gathers.

    ``block_fn(layer_params_full, h, key) -> h`` is the single-layer
    apply (already closed over cfg/mask/attention_fn). ``stacked`` is
    the layer-stacked param dict — under the engine's explicit stage-3
    path these arrive as the per-rank SHARDS (fp32 masters), under the
    declarative path as dp-sharded global arrays.

    Explicit mode is a manual-VJP scan (module docstring): forward
    gathers layer i+prefetch_depth while layer i computes; backward
    walks reversed with the same window, re-gathers, recomputes the
    layer forward (full per-layer remat) and reduce-scatters each
    layer's grads inside the scan. Residuals are the per-layer input
    activations plus the shards — the gathered weights are never saved,
    so the live gather working set is prefetch_depth + 1 layers.

    Declarative mode gathers at use inside a rematted scan body (XLA's
    collective pipeliner owns the overlap there — the structural
    prefetch knob is an explicit-mode device).
    """
    if not spec.bound:
        raise ValueError(
            "zero3_block_scan needs a bound Zero3Scan (the engine binds "
            "it at construction; build the engine before tracing the "
            "loss, or bind the spec manually in tests)")
    names = sorted(stacked.keys())
    L = int(stacked[names[0]].shape[0])
    depth = max(0, min(int(spec.prefetch_depth or 0), L - 1))

    if spec.mode == "declarative":
        def body(h, xs):
            p_shard, key = xs

            def blk(p_, h_):
                return block_fn(spec.gather_layer(p_), h_, key)
            # Remat: the gathered weights are re-gathered in backward
            # instead of being saved stacked across the scan.
            h = jax.checkpoint(blk)(p_shard, h)
            return h, None
        h, _ = lax.scan(body, x, (stacked, keys))
        return h

    # ---- explicit mode: manual-VJP prefetched fwd/bwd scan ---- #
    axis = spec.axis_name

    def gather_layer(p_layer):
        return spec.gather_layer(p_layer)

    def slice_layer(tree, i):
        return {n: tree[n][i] for n in names}

    def roll(tree, k):
        if k == 0:
            return tree
        return {n: jnp.roll(tree[n], -k, axis=0) for n in names}

    def scatter_grads(dp_full, p_layer_shard):
        """fp32 reduce-scatter of one layer's full-grad dict back to the
        owning shard (the gather_cast transpose, inlined)."""
        out = {}
        for n in names:
            g = dp_full[n].astype(jnp.float32)
            gdim, _ = spec.layer_info.get(n, (None, P()))
            if gdim is None:
                g = lax.psum(g, axis)
            else:
                g = lax.psum_scatter(g, axis, scatter_dimension=gdim,
                                     tiled=True)
            out[n] = g.astype(p_layer_shard[n].dtype)
        return out

    def prime_window(tree):
        """The first ``depth`` layers gathered ahead of the scan."""
        return tuple(gather_layer(slice_layer(tree, i))
                     for i in range(depth))

    @jax.custom_vjp
    def run(shards, h, keys):
        out, _ = _fwd(shards, h, keys)
        return out

    def _fwd(shards, h, keys):
        if depth == 0:
            def body(hh, xs):
                p_shard, key = xs
                h_out = block_fn(gather_layer(p_shard), hh, key)
                return h_out, hh
            hf, h_ins = lax.scan(body, h, (shards, keys))
            return hf, h_ins

        def body(carry, xs):
            hh, window = carry
            p_next_shard, key = xs
            # Issue layer i+depth's gather FIRST: it has no data
            # dependence on layer i's compute, so the scheduler overlaps
            # them — the prefetch.
            p_next = gather_layer(p_next_shard)
            h_out = block_fn(window[0], hh, key)
            return (h_out, window[1:] + (p_next,)), hh
        # xs deliver layer i+depth at iteration i; the trailing wrap
        # slices re-gather the first ``depth`` layers harmlessly —
        # schedule overhead of 2·depth one-layer gathers per step (fwd +
        # bwd) that the analytic wire model deliberately omits (it is
        # depth/L of the covered gather wire; the audit's compiled-vs-
        # model checks run on the unscanned program).
        (hf, _), h_ins = lax.scan(body, (h, prime_window(shards)),
                                  (roll(shards, depth), keys))
        return hf, h_ins

    def run_fwd(shards, h, keys):
        out, h_ins = _fwd(shards, h, keys)
        # Residuals: the SHARDS (already 1/dp), per-layer input
        # activations, and the keys — never a gathered layer.
        return out, (shards, h_ins, keys)

    def run_bwd(res, dh):
        shards, h_ins, keys = res
        rev = {n: shards[n][::-1] for n in names}
        rev_h = jax.tree_util.tree_map(lambda a: a[::-1], h_ins)
        rev_k = keys[::-1]

        def layer_vjp(p_full, hin, key, dhh):
            _, vjp = jax.vjp(lambda p, hh: block_fn(p, hh, key),
                             p_full, hin)
            return vjp(dhh)

        if depth == 0:
            def body(dhh, xs):
                p_shard, hin, key = xs
                p_full = gather_layer(p_shard)
                dp_full, dhh = layer_vjp(p_full, hin, key, dhh)
                return dhh, scatter_grads(dp_full, p_shard)
            dh0, dps = lax.scan(body, dh, (rev, rev_h, rev_k))
        else:
            def body(carry, xs):
                dhh, window = carry
                p_next_shard, p_cur_shard, hin, key = xs
                p_next = gather_layer(p_next_shard)   # prefetch (reverse)
                dp_full, dhh = layer_vjp(window[0], hin, key, dhh)
                dps = scatter_grads(dp_full, p_cur_shard)
                return (dhh, window[1:] + (p_next,)), dps
            (dh0, _), dps = lax.scan(
                body, (dh, prime_window(rev)),
                (roll(rev, depth), rev, rev_h, rev_k))
        dshards = {n: dps[n][::-1] for n in names}
        return dshards, dh0, None

    run.defvjp(run_fwd, run_bwd)
    return run(stacked, x, keys)


# --------------------------------------------------------------------- #
# Analytic memory: the bounded gather working set
# --------------------------------------------------------------------- #
def gather_working_set_bytes(params: Any, specs: Any, axis_name: str,
                             compute_itemsize: int,
                             prefetch_depth: int = 0,
                             scan_paths: Optional[Callable] = None,
                             mesh: Optional[Mesh] = None) -> int:
    """Per-device bytes of gathered (compute-dtype) parameters live at
    once under stage 3.

    Only leaves sharded on the DP axis gather (a TP-only leaf never
    crosses the dp wire — counting it would loosen the materialization
    gate by the whole TP-sharded portion). Leaves the model gathers per
    layer (``scan_paths``) contribute ``(prefetch_depth + 1)`` layer
    slices; everything else is gathered leaf-at-use and contributes its
    dp-gathered size — still divided by any OTHER mesh axes on the leaf
    (pass ``mesh``; a dp+TP leaf gathers to 1/mp per device, not full).
    This is the term the engine adds to the analytic state footprint
    for the memory watermark and the lint materialization gate —
    "declared per-device state plus a bounded gather working set, never
    the full parameter tree at fp32 master width".
    """
    from .partition import spec_dp_dim
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    spec_leaves = treedef.flatten_up_to(specs)
    scanned_layer_bytes = 0
    generic_bytes = 0
    for (path, leaf), sp in zip(flat, spec_leaves):
        shape = getattr(leaf, "shape", None)
        if shape is None or spec_dp_dim(sp, axis_name) is None:
            continue    # replicated or TP-only: no dp gather
        n = int(compute_itemsize)
        for d in shape:
            n *= int(d)
        if mesh is not None:
            for entry in sp:
                for ax in ((entry,) if isinstance(entry, str)
                           else (entry or ())):
                    if ax != axis_name:
                        n //= max(1, int(mesh.shape.get(ax, 1)))
        if scan_paths is not None and \
                scan_paths(jax.tree_util.keystr(path)):
            scanned_layer_bytes += n // max(1, int(shape[0]))
        else:
            generic_bytes += n
    return generic_bytes + (int(prefetch_depth) + 1) * scanned_layer_bytes
