"""Runtime utilities.

Parity with reference ``runtime/utils.py``:
- overflow detection (CheckOverflow, utils.py:41-131) → jit-safe pytree
  inf/nan test; the cross-rank "vote" is a psum inside shard_map, done by the
  caller.
- global grad/weight norms with model-parallel filtering (utils.py:148-271)
- balanced partitioning ``partition_uniform`` / ``partition_balanced``
  (binary search over prefix sums, utils.py:289-371) — used by pipeline
  layer placement.
- ``PartitionedTensor`` (utils.py:373-479): shard a flat tensor over a mesh
  axis and re-gather; in JAX a thin wrapper over ravel + dynamic slices.
- memory reporting (utils.py:483-537) → jax device memory stats.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# --------------------------------------------------------------------- #
# Overflow detection
# --------------------------------------------------------------------- #
def tree_has_inf_or_nan(tree: Any) -> jax.Array:
    """Jit-safe: True iff any leaf contains inf/nan.

    The reference's CheckOverflow does a cross-rank MAX allreduce of this bit
    (utils.py:41-131); under pjit/shard_map the reduction happens naturally
    when the caller psums the float indicator.
    """
    leaves = [l for l in jax.tree_util.tree_leaves(tree) if hasattr(l, "dtype")]
    if not leaves:
        return jnp.array(False)
    flags = [jnp.logical_not(jnp.isfinite(l.astype(jnp.float32)).all()) for l in leaves]
    return jnp.stack(flags).any()


class CheckOverflow:
    """Host-side convenience wrapper (stateless on TPU)."""

    def __init__(self, param_groups=None, mpu=None, zero_reduce_scatter=False):
        self.mpu = mpu

    def check(self, tree) -> bool:
        return bool(jax.device_get(tree_has_inf_or_nan(tree)))

    @staticmethod
    def has_overflow_serial(tree) -> bool:
        return bool(jax.device_get(tree_has_inf_or_nan(tree)))


# --------------------------------------------------------------------- #
# Norms
# --------------------------------------------------------------------- #
def global_norm(tree: Any, ord: int = 2) -> jax.Array:
    """L2 (or L1/inf) norm over all leaves of a pytree, jit-safe."""
    leaves = [l.astype(jnp.float32) for l in jax.tree_util.tree_leaves(tree)
              if hasattr(l, "dtype")]
    if not leaves:
        return jnp.array(0.0)
    if ord == 2:
        return jnp.sqrt(sum(jnp.sum(l * l) for l in leaves))
    if ord == 1:
        return sum(jnp.sum(jnp.abs(l)) for l in leaves)
    return jnp.max(jnp.stack([jnp.max(jnp.abs(l)) for l in leaves]))


def get_grad_norm(grads: Any, mpu=None, norm_type: int = 2) -> jax.Array:
    """Parity shim: in SPMD each replica computes the same global norm; the
    reference's model-parallel duplicate filtering (utils.py:148-205) is
    unnecessary because sharded grads are already unique per mesh position."""
    return global_norm(grads, ord=norm_type)


def get_weight_norm(params: Any, mpu=None, norm_type: int = 2) -> jax.Array:
    return global_norm(params, ord=norm_type)


def clip_coefficient(total_norm: jax.Array, max_norm: float) -> jax.Array:
    """The global-clip multiplier. Single definition shared by the optax
    fallback (clip_grad_norm_) and the fused apply's in-kernel folding
    (runtime/engine.py), so the two paths cannot silently diverge."""
    return jnp.minimum(1.0, max_norm / (total_norm + 1e-6))


def clip_grad_norm_(grads: Any, max_norm: float, norm_type: int = 2,
                    precomputed_norm: Optional[jax.Array] = None) -> Tuple[Any, jax.Array]:
    """Return (clipped_grads, total_norm); jit-safe, non-mutating."""
    total_norm = precomputed_norm if precomputed_norm is not None \
        else global_norm(grads, ord=norm_type)
    clip_coef = clip_coefficient(total_norm, max_norm)
    clipped = jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * clip_coef).astype(g.dtype), grads)
    return clipped, total_norm


# --------------------------------------------------------------------- #
# Balanced partitioning (pipeline layer placement)
# --------------------------------------------------------------------- #
def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Boundaries of `num_parts` near-equal contiguous chunks of `num_items`.

    Returns num_parts+1 offsets, parity with utils.py:289-303.
    """
    parts = [0] * (num_parts + 1)
    if num_items <= num_parts:
        for p in range(num_parts + 1):
            parts[p] = min(p, num_items)
        return parts
    chunksize = num_items // num_parts
    residual = num_items - (chunksize * num_parts)
    parts = list(range(0, (num_parts + 1) * chunksize, chunksize))
    for i in range(1, residual + 1):
        parts[i] += i
    for i in range(residual + 1, num_parts + 1):
        parts[i] += residual
    return parts


def _lprobe(weights: Sequence[float], num_parts: int, bottleneck: float) -> bool:
    """Can `weights` be split into num_parts contiguous parts each ≤ bottleneck?"""
    parts_used = 1
    current = 0.0
    for w in weights:
        if w > bottleneck:
            return False
        if current + w > bottleneck:
            parts_used += 1
            current = w
            if parts_used > num_parts:
                return False
        else:
            current += w
    return True


def partition_balanced(weights: Sequence[float], num_parts: int,
                       eps: float = 1e-3) -> List[int]:
    """Contiguous partition minimizing the max part weight.

    Binary search over the bottleneck value (parity with utils.py:305-371's
    prefix-sum search), then greedy placement at the found bottleneck.
    """
    num_items = len(weights)
    if num_items <= num_parts:
        return partition_uniform(num_items, num_parts)

    weights = [float(w) for w in weights]
    lo = max(weights)
    hi = sum(weights)
    while hi - lo > eps * max(1.0, lo):
        mid = (lo + hi) / 2
        if _lprobe(weights, num_parts, mid):
            hi = mid
        else:
            lo = mid
    bottleneck = hi

    # Greedy split at the bottleneck; then pad to exactly num_parts+1 offsets.
    parts = [0]
    current = 0.0
    for i, w in enumerate(weights):
        if current + w > bottleneck and i > parts[-1]:
            parts.append(i)
            current = w
        else:
            current += w
    while len(parts) < num_parts + 1:
        parts.append(num_items)
    parts = parts[:num_parts] + [num_items]
    return parts


def prefix_sum_inc(weights: Sequence[float]) -> List[float]:
    out: List[float] = []
    total = 0.0
    for w in weights:
        total += float(w)
        out.append(total)
    return out


# --------------------------------------------------------------------- #
# PartitionedTensor
# --------------------------------------------------------------------- #
class PartitionedTensor:
    """Shard a tensor's flat view into world_size pieces; re-gather later.

    Parity with utils.py:373-479 (used by the pipeline engine to ship
    model-parallel activations once instead of world_size times). In JAX the
    "communication" is the caller's concern (psum/all_gather under shard_map
    or resharding under pjit); this class provides the deterministic
    split/merge math so checkpoint shards stay layout-compatible.
    """

    def __init__(self, tensor: jax.Array, world_size: int, rank: int):
        self.orig_shape = tensor.shape
        self.orig_dtype = tensor.dtype
        self.world_size = world_size
        self.rank = rank
        flat = tensor.reshape(-1)
        self.orig_size = flat.shape[0]
        padded = int(np.ceil(self.orig_size / world_size)) * world_size
        self.padded_size = padded
        if padded != self.orig_size:
            flat = jnp.pad(flat, (0, padded - self.orig_size))
        self.part_size = padded // world_size
        self.local_data = jax.lax.dynamic_slice(
            flat, (rank * self.part_size,), (self.part_size,))

    @staticmethod
    def partition_sizes(numel: int, world_size: int) -> List[int]:
        padded = int(np.ceil(numel / world_size)) * world_size
        return [padded // world_size] * world_size

    def to_meta(self) -> dict:
        return {"orig_shape": self.orig_shape, "orig_size": self.orig_size,
                "world_size": self.world_size, "dtype": str(self.orig_dtype)}

    def full(self, gathered_parts: Sequence[jax.Array]) -> jax.Array:
        """Reassemble from all shards (caller gathers them)."""
        flat = jnp.concatenate(list(gathered_parts))[: self.orig_size]
        return flat.reshape(self.orig_shape).astype(self.orig_dtype)


# --------------------------------------------------------------------- #
# Memory reporting
# --------------------------------------------------------------------- #
def see_memory_usage(message: str, force: bool = False) -> None:
    """Log device memory stats (parity with utils.py:525-537) across ALL
    local devices — max and sum per field, via the same sampler the
    telemetry memory watermarks use (monitor/memory.py). Sampling only
    device 0 hid per-chip imbalance (a sharding bug inflates one chip
    while device 0 looks fine)."""
    from ..utils.logging import logger
    from ..monitor.memory import device_memory_stats
    stats = device_memory_stats()
    if stats is None:
        logger.info(f"{message} | device memory stats unavailable on this "
                    "backend")
        return
    gib = 2 ** 30
    logger.info(
        f"{message} | device mem ({stats['num_devices']} device(s)): "
        f"in_use max={stats['bytes_in_use_max'] / gib:.2f}GB "
        f"sum={stats['bytes_in_use_sum'] / gib:.2f}GB | "
        f"peak max={stats['peak_bytes_in_use_max'] / gib:.2f}GB "
        f"sum={stats['peak_bytes_in_use_sum'] / gib:.2f}GB | "
        f"limit max={stats['bytes_limit_max'] / gib:.2f}GB")


def call_to_str(base: str, *args, **kwargs) -> str:
    name = f"{base}("
    if args:
        name += ", ".join(repr(a) for a in args)
        if kwargs:
            name += ", "
    if kwargs:
        name += ", ".join(f"{k}={v!r}" for k, v in kwargs.items())
    name += ")"
    return name
