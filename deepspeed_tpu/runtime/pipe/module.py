"""Pipeline model expression.

Parity with reference ``runtime/pipe/module.py``: ``LayerSpec`` (module.py:23
— delayed construction so each stage builds only its own layers),
``TiedLayerSpec`` (module.py:71 — e.g. shared embedding/unembedding),
``PipelineModule`` (module.py:85) with partitioning methods ``uniform`` /
``parameters`` / ``type:regex`` (module.py:348-404) over
``partition_uniform``/``partition_balanced``.

TPU-native design: a "layer" is a pure function (or flax module) taking the
activation pytree; the PipelineModule compiles each *stage* to one fused
function layers[lo:hi] which the pipeline engine maps over the pp mesh axis.
Per-layer deterministic seeding (module.py:200-206) becomes fold_in(layer_idx).
"""
from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..utils import partition_balanced, partition_uniform
from ...utils.logging import logger


class LayerSpec:
    """Delayed layer construction: store class + args, build per stage."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not issubclass(typename, object):
            raise RuntimeError("LayerSpec requires a class")

    def build(self, log: bool = False):
        if log:
            logger.info(f"building {repr(self)}")
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self) -> str:
        from ..utils import call_to_str
        return call_to_str(self.typename.__name__, *self.module_args,
                           **self.module_kwargs)


class TiedLayerSpec(LayerSpec):
    """A layer whose parameters are shared with every other spec of the same
    ``key`` (reference module.py:71; used for tied embeddings). The pipeline
    engine reduces tied-weight grads across the owning stages
    (ReduceTiedGrads parity)."""

    def __init__(self, key, typename, *module_args, forward_fn=None,
                 tied_weight_attr="embedding", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


class PipelineModule:
    """A model as a list of layers, partitioned into pipeline stages.

    ``layers``: sequence of LayerSpec / callables / flax modules. A callable
    layer is used as ``fn(params_i, x, rng) -> x`` when it accepts params, or
    ``fn(x) -> x`` for stateless ops.
    """

    def __init__(self, layers: Sequence[Any], num_stages: Optional[int] = None,
                 topology=None, loss_fn: Optional[Callable] = None,
                 seed_layers: bool = False, base_seed: int = 1234,
                 partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 0,
                 profile_input: Any = None):
        self._layer_specs = list(layers)
        self.loss_fn = loss_fn
        self.seed_layers = seed_layers
        self.base_seed = base_seed
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.profile_input = profile_input
        self._topo = topology

        if topology is not None:
            self.num_stages = topology.get_dim("pipe")
        else:
            self.num_stages = num_stages if num_stages is not None else 1

        # Build all layers (single-control SPMD: one process owns the whole
        # program; stage locality is a sharding property, not a build
        # property — unlike the reference's per-rank partial build).
        self.layers = [self._build_layer(i, spec)
                       for i, spec in enumerate(self._layer_specs)]
        self.parts = self._partition_layers()
        # key → all layer indices sharing that parameter set.
        self.tied_specs: Dict[str, List[int]] = {}
        for i, spec in enumerate(self._layer_specs):
            if isinstance(spec, TiedLayerSpec):
                self.tied_specs.setdefault(spec.key, []).append(i)

    def param_key(self, layer_idx: int) -> str:
        """Param-tree key for a layer; tied layers share one key, which is
        what makes their weights (and their grad accumulation — the
        ReduceTiedGrads instruction in the reference) actually shared."""
        spec = self._layer_specs[layer_idx]
        if isinstance(spec, TiedLayerSpec):
            return f"tied_{spec.key}"
        return f"layer_{layer_idx}"

    def layer_spec(self, layer_idx: int):
        return self._layer_specs[layer_idx]

    def mpu(self):
        return self._topo

    def topology(self):
        return self._topo

    def _build_layer(self, idx: int, spec):
        if isinstance(spec, LayerSpec):
            return spec.build()
        return spec

    # ------------------------------------------------------------------ #
    def _count_layer_params(self) -> List[float]:
        """Per-layer parameter counts for balanced partitioning."""
        counts = []
        for layer in self.layers:
            n = 0
            if hasattr(layer, "param_count"):
                n = layer.param_count()
            elif hasattr(layer, "params") and layer.params is not None:
                n = sum(np.prod(l.shape) for l in
                        jax.tree_util.tree_leaves(layer.params))
            counts.append(float(max(n, 1)))
        return counts

    def _partition_layers(self) -> List[int]:
        """Stage boundaries (module.py:348-404)."""
        num_layers = len(self.layers)
        method = (self.partition_method or "parameters").lower()
        if method == "uniform":
            parts = partition_uniform(num_layers, self.num_stages)
        elif method == "parameters":
            parts = partition_balanced(self._count_layer_params(), self.num_stages)
        elif method.startswith("type:"):
            regex = method.split(":", 1)[1]
            weights = [1.0 if re.search(regex, type(l).__name__, re.IGNORECASE)
                       else 0.0 for l in self.layers]
            # Avoid empty stages when few matches: give epsilon weight.
            weights = [w if w > 0 else 1e-6 for w in weights]
            parts = partition_balanced(weights, self.num_stages)
        elif method == "profile":
            # The reference never implemented this (its module.py:374-375
            # raises); on TPU it falls out of XLA's analytic cost model —
            # no timed microruns, no device needed, deterministic.
            if self.profile_input is None:
                raise ValueError(
                    'partition_method="profile" needs a sample input: '
                    "PipelineModule(..., profile_input=batch_x) so each "
                    "layer can be lowered through XLA's cost model")
            parts = partition_balanced(
                self._profile_layer_costs(self.profile_input),
                self.num_stages)
        else:
            raise KeyError(f"unknown partition method {self.partition_method}")
        return parts

    def _profile_layer_costs(self, sample_input) -> List[float]:
        """Per-layer cost from XLA's analytic cost model: each layer is
        jit-lowered at the activation shape that actually reaches it (the
        sample flows layer to layer) and its compiled FLOPs are the
        balance weight. Backward cost is proportional to forward for the
        layer types a pipeline scans, so forward FLOPs rank stages the
        same way measured step times would — without timing noise."""
        import jax.numpy as jnp
        x = jnp.asarray(sample_input)
        rng = jax.random.PRNGKey(self.base_seed)
        costs: List[float] = []
        for i, layer in enumerate(self.layers):
            lrng = self.layer_rng(i, rng)
            if hasattr(layer, "init") and hasattr(layer, "apply"):
                p = layer.init(lrng, x)
                fn = (lambda layer, p, lrng: lambda xx: layer.apply(
                    p, xx, rngs={"dropout": lrng}))(layer, p, lrng)
            elif callable(layer):
                fn = layer
            else:
                raise TypeError(f"layer {i} ({type(layer)}) is not callable")
            flops = 1.0
            try:
                compiled = jax.jit(fn).lower(x).compile()
                ca = compiled.cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else {}
                flops = float((ca or {}).get("flops", 0.0))
            except Exception as e:  # non-jittable layer: fall back flat
                logger.warning(f"profile partitioning: layer {i} could not "
                               f"be lowered ({e}); weighting it 1.0")
            costs.append(max(flops, 1.0))
            x = fn(x)
        logger.info(f"profile partition costs (MFLOPs/layer): "
                    f"{[round(c / 1e6, 3) for c in costs]}")
        return costs

    def stage_layers(self, stage_id: int) -> List[Any]:
        lo, hi = self.parts[stage_id], self.parts[stage_id + 1]
        return self.layers[lo:hi]

    def stage_owner(self, layer_idx: int) -> int:
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise IndexError(layer_idx)

    def layer_rng(self, layer_idx: int, base_rng):
        """Per-layer deterministic seeding (module.py:200-206)."""
        if self.seed_layers:
            return jax.random.fold_in(base_rng, self.base_seed + layer_idx)
        return base_rng

    def __len__(self) -> int:
        return len(self.layers)

    def describe(self) -> str:
        lines = [f"PipelineModule: {len(self.layers)} layers over "
                 f"{self.num_stages} stages ({self.partition_method})"]
        for s in range(self.num_stages):
            lo, hi = self.parts[s], self.parts[s + 1]
            names = [type(l).__name__ for l in self.layers[lo:hi]]
            lines.append(f"  stage {s}: layers {lo}..{hi - 1} {names}")
        return "\n".join(lines)

    # ------------------------------------------------------------------ #
    def to_pipe_spec(self, params: Dict[str, Any], embed_fn=None,
                     head_fn=None):
        """Uniform-stage conversion: the documented path from a layer-list
        PipelineModule to the compiled pp>1 SPMD pipeline.

        Requirements (checked): every layer is the SAME function (one block
        program scanned over stacked weights — the shape the SPMD pipeline
        executes), no tied layers, and every layer's param tree has one
        structure/shape. Models that don't fit (heterogeneous stages, tied
        embeddings) should be expressed directly as a PipeSpec
        (models/gpt2_pipe.py) instead.

        ``params``: the engine-style {param_key: layer_params} tree.
        Returns a PipeSpec consumable by PipelineEngine on a pp>1 mesh.
        """
        from ...models.gpt2_pipe import PipeSpec
        from .spmd import pipeline_param_shardings
        from jax.sharding import PartitionSpec as P
        import jax.numpy as jnp
        from jax import lax

        L = len(self.layers)
        keys = [self.param_key(i) for i in range(L)]
        if len(set(keys)) != L:
            raise ValueError(
                "tied layers cannot be auto-converted to a PipeSpec; "
                "express the model as a PipeSpec with a shared param group")
        layer0 = self.layers[0]
        if hasattr(layer0, "apply") and hasattr(layer0, "init"):
            raise ValueError(
                "to_pipe_spec converts plain fn(params, x) layers only; "
                "flax-module layers need an explicit PipeSpec whose "
                "stage_fn calls module.apply")
        for i in range(L):
            if keys[i] not in params:
                raise ValueError(
                    f"params is missing '{keys[i]}' — stateless layers "
                    "(no params) cannot be pipelined via to_pipe_spec")
        code0 = getattr(layer0, "__code__", None)
        for l in self.layers[1:]:
            if l is layer0:
                continue
            # Same code object is NOT enough: factory-made closures share
            # __code__ but capture different values, and stage_fn would
            # silently run layer0's closure for every layer. Accept distinct
            # objects only when both are closure-free plain functions.
            same_code = code0 is not None and \
                getattr(l, "__code__", None) is code0
            closure_free = getattr(layer0, "__closure__", None) is None and \
                getattr(l, "__closure__", None) is None
            if same_code and closure_free:
                continue
            raise ValueError(
                "pp>1 conversion needs uniform stages: every layer must be "
                "the SAME function object (closures with captured state "
                "cannot be verified equal); got differing layer callables")
        trees = [params[k] for k in keys]
        td0 = jax.tree_util.tree_structure(trees[0])
        for t in trees[1:]:
            if jax.tree_util.tree_structure(t) != td0:
                raise ValueError("layer param trees differ in structure; "
                                 "uniform stages required for pp>1")
        blocks = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)

        # Non-uniform stage cuts (partition_method="parameters" boundaries,
        # or L % stages != 0): pad each stage's slice to the max stage
        # length; pad slots lax.cond-skip at run time (identity), so the
        # SPMD stage program stays uniform. Reference analogue:
        # module.py:348-404's per-rank non-uniform layer builds.
        parts = list(self.parts)
        stage_lens = [parts[s + 1] - parts[s] for s in range(len(parts) - 1)]
        stage_valid = None
        if len(set(stage_lens)) > 1:
            from ...models.gpt2_pipe import pad_stacked_blocks
            blocks, flat_valid = pad_stacked_blocks(blocks, L, stage_lens)
            stage_valid = jnp.reshape(
                flat_valid, (len(stage_lens), max(stage_lens)))
            L = len(stage_lens) * max(stage_lens)

        def stage_fn(blocks_local, x, rng):
            if stage_valid is None:
                def body(h, p):
                    return layer0(p, h), None
                x, _ = lax.scan(body, x, blocks_local)
                return x

            from ...parallel.topology import PP_AXIS
            valid = stage_valid[lax.axis_index(PP_AXIS)]

            def body(h, pv):
                p, v = pv
                h = lax.cond(v != 0, lambda hh: layer0(p, hh),
                             lambda hh: hh, h)
                return h, None
            x, _ = lax.scan(body, x, (blocks_local, valid))
            return x

        if embed_fn is None:
            embed_fn = lambda shared, tokens, rng: tokens
        if head_fn is None:
            loss_head = self.loss_fn
            if loss_head is None:
                raise ValueError("PipelineModule has no loss_fn; pass "
                                 "head_fn explicitly")
            head_fn = lambda shared, x, targets, rng: loss_head(x, targets)

        shardings = pipeline_param_shardings(
            shared_specs={},
            block_specs=jax.tree_util.tree_map(lambda _: P(), blocks))
        return PipeSpec(embed_fn=embed_fn, stage_fn=stage_fn, head_fn=head_fn,
                        params={"shared": {}, "blocks": blocks},
                        shardings=shardings, num_layers=L,
                        stage_layers=(stage_lens if stage_valid is not None
                                      else None))
