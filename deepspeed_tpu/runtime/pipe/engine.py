"""Pipeline-parallel engine.

Parity target: reference ``runtime/pipe/engine.py`` (PipelineEngine,
engine.py:45) driving a 1F1B instruction schedule (schedule.py:182-290) with
p2p activation/grad exchange. TPU-native plan: the schedule is compiled, not
interpreted — micro-batches flow through pp stages via ``ppermute`` rotations
inside one jitted step (see ``schedule.py`` here for the instruction-level
parity layer and GPipe/1F1B step programs).

This first increment composes the PipelineModule's layers into a single
fused function: correct for pp=1 meshes (pipeline expressed, not yet
parallelized). The pp>1 execution path lands with ``schedule.py``.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from .module import PipelineModule, TiedLayerSpec
from ..engine import DeepSpeedEngine
from ...utils.logging import log_dist, logger


def _is_flax_module(layer) -> bool:
    return hasattr(layer, "init") and hasattr(layer, "apply")


class PipelineEngine(DeepSpeedEngine):
    """Engine for pipelined models.

    Two model forms:
    - ``PipeSpec`` (models/gpt2_pipe.py): uniform stages → COMPILED SPMD
      pipeline over the pp mesh axis (pipe/spmd.py). All grad-accum
      micro-batches flow through the pipeline inside ONE jitted step; the
      instruction schedule (schedule.py) is realized by the scan+ppermute
      program and its autodiff transpose.
    - ``PipelineModule`` (layer list): composed into a single fused function
      — correct on pp=1 meshes (heterogeneous per-stage programs don't fit
      one SPMD program; express such models as a PipeSpec instead).
    """

    def __init__(self, args=None, model=None, optimizer=None,
                 model_params=None, training_data=None, lr_scheduler=None,
                 mpu=None, dist_init_required=None, collate_fn=None,
                 config=None, rng=None, mesh=None, num_micro_batches=None):
        from ...models.gpt2_pipe import PipeSpec
        self.pipeline_module = None
        self._pipe_spec = None
        rng0 = rng if rng is not None else jax.random.PRNGKey(0)

        if isinstance(model, PipeSpec):
            self._pipe_spec = model
            # Mesh must exist before the loss fn; build from config if needed.
            mesh = mesh if mesh is not None else self._build_mesh(config)
            pp = int(mesh.shape.get("pipe", 1))
            if pp > 1 and model.num_layers % pp != 0:
                raise ValueError(f"{model.num_layers} layers not divisible "
                                 f"by {pp} pipeline stages")
            from ...parallel.topology import DP_AXIS
            gas = self._peek_gas(config, int(mesh.shape.get(DP_AXIS, 1)))
            m = num_micro_batches or gas
            self._num_micro = m
            # activation_checkpoint_interval (reference pipe/module.py:
            # 292-346 checkpoints every N layers in forward): an EXPLICIT 0
            # disables the per-tick stage remat; >=1 enables it (stage
            # granularity — finer per-layer policy lives in the model's
            # remat_policy). Key absent -> remat stays ON (the memory-safe
            # default this pipeline has always had).
            interval = self._peek_actckpt_interval(config)
            if interval is not None and interval > 1:
                log_dist(
                    f"pipeline.activation_checkpoint_interval={interval} > 1 "
                    "is coarsened to stage-granularity remat on TPU (the "
                    "reference checkpoints every N layers; here the compiled "
                    "stage is the remat unit — use the model's remat_policy "
                    "for per-layer control)", ranks=[0])
            loss_fn = model.loss_fn(num_stages=pp, num_micro=m, mesh=mesh,
                                    remat=interval != 0)
            # pipeline.schedule: "gpipe" (default — autodiff scan, O(M)
            # boundary banks) | "1f1b" (manual interleaved fwd/bwd, O(P)
            # activation memory — the reference TrainSchedule's profile,
            # schedule.py:182-290). Parsed through PipelineConfig so this
            # pre-super peek and config.pipeline_config agree.
            from ..config import PipelineConfig
            sched = str(PipelineConfig(
                self._peek_param_dict(config)).schedule).lower()
            if sched not in ("gpipe", "1f1b"):
                raise ValueError(f"pipeline.schedule must be 'gpipe' or "
                                 f"'1f1b', got '{sched}'")
            gfn = model.grads_fn(num_stages=pp, num_micro=m, mesh=mesh) \
                if sched == "1f1b" else None
            super().__init__(args=args, model=loss_fn, optimizer=optimizer,
                             model_params=model_params or model.params,
                             training_data=training_data,
                             lr_scheduler=lr_scheduler, mpu=mpu,
                             dist_init_required=dist_init_required,
                             collate_fn=collate_fn, config=config, rng=rng,
                             mesh=mesh, param_shardings=model.shardings,
                             grads_fn=gfn)
            log_dist(f"PipelineEngine: compiled SPMD pipeline pp={pp}, "
                     f"micro_batches={m}, layers={model.num_layers}, "
                     f"schedule={sched}", ranks=[0])
            # Telemetry provenance: record the pipeline shape in the run's
            # meta record so TELEMETRY.json can attribute step times.
            self.telemetry.meta.update(pipeline={
                "schedule": sched, "stages": pp, "micro_batches": m,
                "layers": model.num_layers})
            return

        assert isinstance(model, PipelineModule)
        # Validate the schedule key on this branch too: '1f1b' needs the
        # PipeSpec path — silently training un-pipelined would be a trap.
        from ..config import PipelineConfig
        sched = str(PipelineConfig(
            self._peek_param_dict(config)).schedule).lower()
        if sched == "1f1b":
            raise NotImplementedError(
                "pipeline.schedule='1f1b' requires a PipeSpec model "
                "(models/gpt2_pipe.py); PipelineModule layer lists run "
                "composed (pp=1) and have no interleaved schedule")
        self.pipeline_module = model
        if model_params is None:
            model_params = self._init_layer_params(model, training_data, rng0,
                                                   config)

        loss_fn = self._compose_loss_fn(model)
        super().__init__(args=args, model=loss_fn, optimizer=optimizer,
                         model_params=model_params, training_data=training_data,
                         lr_scheduler=lr_scheduler, mpu=mpu,
                         dist_init_required=dist_init_required,
                         collate_fn=collate_fn, config=config, rng=rng, mesh=mesh)
        pp = int(self.mesh.shape.get("pipe", 1))
        if pp > 1:
            raise NotImplementedError(
                "pp>1 needs an SPMD-expressible model: express uniform "
                "stages as a PipeSpec (models/gpt2_pipe.py), or stages "
                "with DIFFERENT programs (e.g. conv stem + transformer "
                "body) via hetero_pipe_spec (runtime/pipe/hetero.py)")
        log_dist(self.pipeline_module.describe(), ranks=[0])

    def _cost_model_extras(self, payload):
        """Per-stage attribution for the cost-model payload, via the
        jaxpr-walk flops profiler (the analytic counter the cost model
        already ran over the pipelined train step). The compiled SPMD
        pipeline is symmetric by construction — every stage device runs
        the same program over num_layers/pp layers — so the per-stage
        split is uniform and exact, embedding/head work included (SPMD
        executes those eqns on every stage, stage-masked)."""
        if self._pipe_spec is None:
            return {}
        paths = payload.get("paths") or {}
        train = paths.get("train_step") or {}
        flops = train.get("analytic_flops")
        if not flops:
            return {}
        pp = int(self.mesh.shape.get("pipe", 1))
        per_stage = float(flops) / max(1, pp)
        section = {
            "stages": pp,
            "micro_batches": self._num_micro,
            "layers": self._pipe_spec.num_layers,
            "schedule": (self.telemetry.meta.get("pipeline") or
                         {}).get("schedule"),
            "flops_per_stage": [per_stage] * pp,
            "attribution": "jaxpr-walk total split across SPMD stages "
                           "(uniform by construction)",
        }
        # Module-level breakdown for the operator reading TELEMETRY.json
        # ("where do the flops go") — captured by the SAME jaxpr walk
        # path_cost already ran; re-tracing the whole pipelined program
        # here would double the build's blocking time.
        if train.get("top_modules"):
            section["top_modules"] = train["top_modules"]
        return {"pipeline": section}

    def _lint_path_meta(self, name):
        """Pipeline provenance for the lint auditor: the pipelined
        train_step IS the registered path (all ticks compile into the one
        scan+ppermute program), so tag it with the schedule shape the
        report reader needs to attribute per-tick boundary permutes."""
        meta = super()._lint_path_meta(name)
        if self._pipe_spec is not None:
            meta["pipeline"] = {
                "schedule": (self.telemetry.meta.get("pipeline") or
                             {}).get("schedule"),
                "stages": int(self.mesh.shape.get("pipe", 1)),
                "micro_batches": self._num_micro,
            }
        return meta

    @staticmethod
    def _peek_param_dict(config):
        """Normalize any accepted config form to its raw param dict, for
        reads that happen before the base engine parses the config."""
        from ..config import DeepSpeedConfig
        from ..config_utils import load_config_json
        if isinstance(config, str):
            return load_config_json(config)
        if isinstance(config, DeepSpeedConfig):
            return getattr(config, "_param_dict", None) or {}
        return config if isinstance(config, dict) else {}

    @classmethod
    def _peek_actckpt_interval(cls, config):
        """pipeline.activation_checkpoint_interval. Returns None when the
        key is absent (caller keeps remat on — the memory-safe default); an
        explicit value (incl. 0 = remat off) is honored."""
        v = cls._peek_param_dict(config).get("pipeline", {}).get(
            "activation_checkpoint_interval")
        return None if v is None else int(v)

    @classmethod
    def _peek_gas(cls, config, dp: int = 1) -> int:
        """gradient_accumulation_steps (the micro-batch count of the
        pipeline), solved from the batch triple if not explicit."""
        d = cls._peek_param_dict(config)
        gas = d.get("gradient_accumulation_steps")
        if gas:
            return int(gas)
        tb, mb = d.get("train_batch_size"), d.get("train_micro_batch_size_per_gpu")
        if tb and mb:
            return max(1, int(tb) // (int(mb) * dp))
        return 1

    def _scan_microbatches(self) -> int:
        # The pipelined loss consumes every micro-batch in one pass.
        return 1 if self._pipe_spec is not None else \
            self.gradient_accumulation_steps()

    # ------------------------------------------------------------------ #
    def _init_layer_params(self, model: PipelineModule, training_data, rng,
                           config) -> Dict[str, Any]:
        assert training_data is not None, \
            "PipelineEngine needs model_params or training_data to infer shapes"
        sample = training_data[0]
        x = sample[0] if isinstance(sample, (tuple, list)) else sample
        import numpy as np
        x = jnp.asarray(np.asarray(x)[None])  # add batch dim
        params: Dict[str, Any] = {}
        for i, layer in enumerate(model.layers):
            lrng = model.layer_rng(i, rng)
            key = model.param_key(i)
            if _is_flax_module(layer):
                if key not in params:  # tied reuse: only first owner inits
                    params[key] = layer.init(lrng, x)
                x = self._apply_layer(model, i, layer, params[key], x, lrng)
            elif callable(layer):
                params.setdefault(key, {})
                x = layer(x)
            else:
                raise TypeError(f"layer {i} ({type(layer)}) is not callable")
        return params

    @staticmethod
    def _apply_layer(model: PipelineModule, idx: int, layer, p, x, rng):
        spec = model.layer_spec(idx)
        if isinstance(spec, TiedLayerSpec) and spec.forward_fn is not None:
            # e.g. unembedding reusing the embedding matrix.
            return spec.forward_fn(layer, p, x)
        if _is_flax_module(layer):
            return layer.apply(p, x, rngs={"dropout": rng})
        return layer(x) if not p else layer(p, x)

    def _compose_loss_fn(self, model: PipelineModule) -> Callable:
        layers = model.layers
        loss_head = model.loss_fn

        apply_layer = self._apply_layer

        def loss_fn(params, batch, rng):
            if isinstance(batch, (tuple, list)):
                x, labels = batch[0], batch[1] if len(batch) > 1 else None
            else:
                x, labels = batch, None
            for i, layer in enumerate(layers):
                lrng = model.layer_rng(i, rng)
                p = params.get(model.param_key(i), {})
                x = apply_layer(model, i, layer, p, x, lrng)
            if loss_head is not None:
                return loss_head(x, labels)
            return x
        return loss_fn

    # ------------------------------------------------------------------ #
    # Per-layer checkpoint files (reference pipe/module.py:510-567:
    # 'layer_NN-model_states.pt' written per layer, tied params once)
    # ------------------------------------------------------------------ #
    LAYER_FILE_FMT = "layer_{:02d}-model_states.msgpack"

    def _snapshot_model_blobs(self, meta, host_param_leaves):
        import numpy as np
        from flax import serialization
        if self.pipeline_module is None:
            return super()._snapshot_model_blobs(meta, host_param_leaves)
        # Host leaves arrive already fetched (the engine's one batched
        # device_get); reassemble the params tree and build one LAZY
        # blob per layer file — tied params: first owner writes it.
        host = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(self.state.params),
            host_param_leaves)
        layer_files = {}
        blobs = []
        for i in range(len(self.pipeline_module.layers)):
            key = self.pipeline_module.param_key(i)
            if key in layer_files:
                continue
            fname = self.LAYER_FILE_FMT.format(i)
            layer_files[key] = fname
            layer_tree = jax.tree_util.tree_map(np.asarray,
                                                host.get(key, {}))
            blobs.append((fname, lambda t=layer_tree:
                          serialization.to_bytes(t)))
        meta["pipeline_layer_files"] = layer_files
        return blobs

    def _load_pipeline_layer_states(self, path, meta, params_target):
        import os
        from flax import serialization
        layer_files = meta["pipeline_layer_files"]
        out = dict(params_target)
        with self.telemetry.span("checkpoint_load",
                                 what="pipeline_layer_states"):
            for key, fname in layer_files.items():
                fp = os.path.join(path, fname)
                if not os.path.isfile(fp):
                    logger.warning(f"pipeline layer checkpoint {fp} missing")
                    return None
                with open(fp, "rb") as f:
                    out[key] = serialization.from_bytes(params_target[key],
                                                        f.read())
        return out
