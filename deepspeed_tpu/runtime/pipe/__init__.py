from .module import PipelineModule, LayerSpec, TiedLayerSpec
