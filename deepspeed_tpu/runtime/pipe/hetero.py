"""Heterogeneous per-stage pipeline programs.

Parity target: the reference partitions ANY layer list across stages —
e.g. a conv stem on stage 0 feeding transformer stages (reference
runtime/pipe/module.py:348-404 builds each rank's own layer sublist).
An SPMD pipeline cannot do that literally: shard_map traces ONE stage
program for every pipe rank.

Design: run-all-and-select. Each tick every rank executes EVERY program
on its input and ``lax.select_n`` keeps the one its stage owns. This is
deliberately NOT a per-rank ``lax.switch``: a rank-dependent branch
around program bodies puts the partitioner-inserted dp/mp collectives on
some ranks' execution paths and not others', which deadlocks the
collective rendezvous — the same failure the 1F1B tick gates had to
design around (see spmd_1f1b.py's module docstring). ``select_n``
executes all branches uniformly, so any program may contain sharded
matmuls/collectives.

Cost model (why this is acceptable, and when it is not): per tick every
rank pays SUM of program costs instead of its own program's cost. With
K programs the waste factor is at most K; for the intended shape — one
cheap stem/adapter program plus one dominant block program — the waste
is the stem's cost, a few percent. For K heavyweight programs an SPMD
pipeline is the wrong tool; split the model into two meshes instead.
Param memory: each program's params are stacked over ALL P stages (zeros
on stages that don't own the program) so each rank stores one stage
slice of every program — overhead (K-1) stage slices, not K full models.

Gradient correctness falls out of autodiff: ``select_n``'s vjp routes
the cotangent only to the selected branch, so the zero-padded slices of
unowned programs receive exactly-zero grads and the optimizer leaves
them at zero. Works under both the GPipe schedule (autodiff) and the
1F1B manual-vjp schedule, since both consume only the stage_fn contract.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...parallel.topology import PP_AXIS


def hetero_pipe_spec(embed_fn: Callable, head_fn: Callable,
                     programs: Sequence[Callable],
                     stage_programs: Sequence[int],
                     stage_params: Sequence[Any],
                     shared_params: Optional[Dict[str, Any]] = None,
                     shared_specs: Optional[Dict[str, Any]] = None,
                     sample_x: Optional[jax.Array] = None,
                     rng: Optional[jax.Array] = None):
    """Build a PipeSpec whose stages run different programs.

    ``programs``: K stage functions ``prog(params, x, rng) -> x`` (each
    must preserve the boundary activation shape). ``stage_programs``:
    length-P list mapping stage -> program index. ``stage_params``:
    length-P list of param trees; stage s's tree must match the
    structure of its program's params (stages sharing a program need
    identical leaf shapes). ``sample_x``: optional boundary-shaped array
    to shape-check every program at build time.
    """
    from ...models.gpt2_pipe import PipeSpec
    from .spmd import pipeline_param_shardings

    K, Pn = len(programs), len(stage_programs)
    if sorted(set(stage_programs)) != list(range(K)):
        raise ValueError(f"stage_programs {list(stage_programs)} must use "
                         f"every program index 0..{K - 1}")
    if len(stage_params) != Pn:
        raise ValueError(f"need one param tree per stage: "
                         f"{len(stage_params)} != {Pn}")

    # Per-program stacked params: [P, ...] with zeros on unowned stages.
    templates: Dict[int, Any] = {}
    for s, p in enumerate(stage_programs):
        t = templates.setdefault(p, stage_params[s])
        if jax.tree_util.tree_structure(stage_params[s]) != \
                jax.tree_util.tree_structure(t):
            raise ValueError(f"stage {s} param structure differs from "
                             f"program {p}'s other stages")
    blocks = {}
    for p in range(K):
        slices = [stage_params[s] if stage_programs[s] == p else
                  jax.tree_util.tree_map(jnp.zeros_like, templates[p])
                  for s in range(Pn)]
        blocks[f"prog{p}"] = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *slices)

    def _check_boundary(p: int, got_shape, got_dtype, want_shape,
                        want_dtype) -> None:
        if got_shape != tuple(want_shape):
            raise ValueError(
                f"program {p} changes the boundary shape {tuple(want_shape)}"
                f" -> {got_shape}; pipeline stages must preserve it (the "
                "ppermute buffer is one uniform array)")
        if got_dtype != want_dtype:
            raise ValueError(
                f"program {p} changes the boundary dtype {want_dtype} -> "
                f"{got_dtype}; pipeline stages must preserve it (the "
                "ppermute buffer is one uniform array)")

    if sample_x is not None:
        key = rng if rng is not None else jax.random.PRNGKey(0)
        # Canonicalize (no-op for jax arrays): a numpy float64 sample must
        # probe as the dtype jax would actually trace it to, or every
        # program would spuriously fail the dtype check under x64-disabled.
        sample = jnp.asarray(sample_x)
        probe = jax.ShapeDtypeStruct(sample.shape, sample.dtype)
        for p in range(K):
            got = jax.eval_shape(programs[p], templates[p], probe, key)
            _check_boundary(p, got.shape, got.dtype, probe.shape,
                            probe.dtype)

    table = jnp.asarray(list(stage_programs), jnp.int32)

    def stage_fn(blocks_local, x, rng):
        # blocks_local leaves carry the [P]-sharded leading dim (length 1
        # per rank under pp=P meshes): drop it to this stage's slice.
        outs = [programs[p](
            jax.tree_util.tree_map(lambda a: a[0],
                                   blocks_local[f"prog{p}"]), x, rng)
            for p in range(K)]
        # Build-time boundary check, sample_x or not: shapes/dtypes are
        # static under trace, so a shape- or dtype-changing program fails
        # HERE with a real message when the pipeline program is built —
        # not as an opaque select_n/ppermute mismatch deep in the trace.
        # (Deliberately before axis_index: the error must surface even in
        # a bare eval_shape outside the mesh.)
        for p, out in enumerate(outs):
            _check_boundary(p, jnp.shape(out), jnp.result_type(out),
                            jnp.shape(x), jnp.result_type(x))
        if K == 1:
            return outs[0]
        r = lax.axis_index(PP_AXIS)
        return lax.select_n(table[r], *outs)

    shardings = pipeline_param_shardings(
        shared_specs=shared_specs or
        jax.tree_util.tree_map(lambda _: P(), shared_params or {}),
        block_specs=jax.tree_util.tree_map(lambda _: P(), blocks))
    return PipeSpec(embed_fn=embed_fn, stage_fn=stage_fn, head_fn=head_fn,
                    params={"shared": shared_params or {}, "blocks": blocks},
                    shardings=shardings, num_layers=Pn,
                    stage_layers=[1] * Pn)
