"""1F1B SPMD pipeline — O(P) activation memory, fwd/bwd interleaved.

The GPipe-profile pipeline (spmd.py) banks O(M) boundary tensors: the
embedded input bank, the last-stage output bank, and — because reverse-mode
autodiff runs ALL forward ticks before ANY backward tick — one saved stage
input per tick. The reference's TrainSchedule instead interleaves: each
stage starts micro i's backward as soon as its forward chain allows, so at
most O(P) activations are ever live (reference runtime/pipe/schedule.py:
182-290, the 1F1B ordering).

Reverse-mode autodiff CANNOT express that interleaving (it is two-phase by
construction), so this module differentiates MANUALLY: one primal
``lax.scan`` over M + 2(P-1) ticks computes loss AND gradients directly.
Each tick every stage runs — uniformly, so no conditional collectives —

  forward sub-tick:  embed (masked to stage 0) → stage_fn → save input in
                     a 2P-slot ring; last stage feeds the tick's output
                     straight into the head's value_and_grad (micro i's
                     backward starts the same tick its forward ends);
  backward sub-tick: re-run the stage under ``jax.vjp`` at the ring-saved
                     input (same per-micro rng), pull the incoming
                     cotangent through, accumulate block grads locally
                     (they stay pipe-sharded — exactly the param layout)
                     and tied/shared grads via an end-of-scan psum (the
                     reference's ReduceTiedGrads, pipe/engine.py:208-227);
  rotate:            activations ppermute up, cotangents ppermute down.

Schedule (micro index as a function of tick t on stage r):
  forward  f = t - r              (stage 0 leads)
  head     h = t - (P-1)          (last stage, same tick as its fwd)
  backward b = t - 2(P-1) + r     (cotangent wavefront back down)
Ring lifetime of a saved input on stage r is 2(P-1-r) ticks, so a ring of
R = 2P slots indexed by micro mod R never collides: O(P), independent of M.

Compute parity with the remat GPipe path: both run fwd twice + bwd once
per layer (here the re-run is inside ``jax.vjp``). Each sub-tick (embed
fwd, stage fwd, head, stage bwd, embed bwd) is ``lax.cond``-gated on a
predicate that is a function of the TICK INDEX ONLY — uniform across
devices — so warmup/drain ticks skip the work they cannot use. Uniformity
is load-bearing: a per-RANK predicate (e.g. ``r == last`` for the head)
puts the partitioner-inserted dp/mp collectives of the branch body on
some devices' execution paths and not others', and the program deadlocks
at the next collective rendezvous (observed on the 8-device dryrun:
ranks waiting on different op_ids of the same scan). Per-rank validity is
therefore applied INSIDE the branch as ``jnp.where`` selects — a select
DISCARDS the masked side, so a warmup/drain tick's inf/NaN (plausible
under fp16: the head/vjp sees stale buffers) cannot poison the
accumulators the way multiplicative ``0*g`` masking could.

Wall-clock: in a lockstep pipeline the off-stage work that remains (the
head on non-last ranks during the M central ticks) runs in PARALLEL with
the real head on the last rank — it wastes chip-FLOPs, not tick latency.
The reclaimable latency is the warmup/drain sub-ticks, which the uniform
gates remove; ablate_1f1b_gate.py measures it. ``gate_offstage=False``
recovers the ungated run-everything-and-select variant.

fp16 loss scaling: the engine passes its (traced) loss scale; the head
loss is multiplied by it inside the tick, so every cotangent flowing down
the pipe — and every accumulated gradient — is scaled exactly as the
autodiff path's scaled-loss trick produces, and the engine's existing
unscale + overflow-vote machinery applies unchanged. The RETURNED loss is
unscaled (scale is a power of two; the division is exact).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ...parallel import comm
from ...parallel.topology import PP_AXIS
from .spmd import _split_batch, _to_micro


def tick_table(num_micro: int, num_stages: int):
    """The scan's schedule AS DATA: ``table[t][r]`` lists the work items
    tick ``t``'s gates admit on stage ``r`` — ``("F", m)`` stage forward,
    ``("H", m)`` head loss + its grad (last stage, same tick as its
    forward), ``("B", m)`` stage backward. Exactly the clock the scan body
    runs (``f = t - r``, ``h = t - (P-1)``, ``b = t - 2(P-1) + r``; module
    docstring), exported so ``runtime/pipe/schedule.py``'s TrainSchedule —
    the reference's instruction-list specification — can be asserted
    against it as the 1F1B oracle (tests/test_pipe_1f1b.py)."""
    M, Pstages = num_micro, num_stages
    last = Pstages - 1
    table = []
    for t in range(M + 2 * last):
        per_stage = []
        for r in range(Pstages):
            evs = []
            f = t - r
            if 0 <= f < M:
                evs.append(("F", f))
            h = t - last
            if r == last and 0 <= h < M:
                evs.append(("H", h))
            b = t - 2 * last + r
            if 0 <= b < M:
                evs.append(("B", b))
            per_stage.append(evs)
        table.append(per_stage)
    return table


def spmd_pipeline_1f1b_grads(embed_fn: Callable, stage_fn: Callable,
                             head_fn: Callable, num_stages: int,
                             num_micro_batches: int, mesh: Mesh,
                             gate_offstage: bool = True) -> Callable:
    """Build ``grads_fn(params, batch, rng, scale=None) ->
    (unscaled_mean_loss, scale-multiplied grads)``.

    Params pytree: ``{"shared": replicated-over-pipe, "blocks": stacked,
    sharded over pipe}`` — same contract as spmd_pipeline_loss; grads come
    back in the same structure/sharding as params. ``scale`` is the fp16
    loss scale (defaults to 1.0, where grads are plain gradients).

    ``gate_offstage``: cond-skip warmup/drain sub-ticks via tick-uniform
    gates (default). False runs every sub-tick everywhere and
    select-masks — only for measuring the gating win
    (ablate_1f1b_gate.py).
    """
    M, Pstages = num_micro_batches, num_stages
    T = M + 2 * (Pstages - 1)
    R = 2 * Pstages                      # ring slots (>= max lifetime + 1)

    def per_stage(blocks_local, shared, micro_tokens, micro_targets, rng,
                  scale, cdtype, xshape):
        """Runs on every pipe rank; returns (loss_sum, dblocks, dshared)."""
        r = lax.axis_index(PP_AXIS)
        last = Pstages - 1

        def mkey(i):
            # Per-MICRO key (not per-tick): the backward sub-tick re-runs
            # the stage under vjp and must regenerate identical dropout.
            return jax.random.fold_in(jax.random.fold_in(rng, i), r)

        def head_loss(sh, y, tgt, key):
            # mean-over-micros normalization AND the fp16 loss scale are
            # folded into the cotangent here — everything downstream
            # (dy, dx, dblocks, dshared) comes out scaled, exactly like
            # the autodiff path's scaled-loss trick.
            return head_fn(sh, y, tgt, key).astype(jnp.float32) * scale / M

        def ugate(pred, true_thunk, false_thunk):
            # ``pred`` MUST be tick-uniform (a function of t, never of the
            # rank): all devices take the same branch, so the collective
            # sequence cannot diverge. Per-rank validity goes INSIDE the
            # branch as selects.
            if gate_offstage:
                return lax.cond(pred, true_thunk, false_thunk)
            out, zero = true_thunk(), false_thunk()
            return jax.tree_util.tree_map(
                lambda a, z: jnp.where(pred, a, z), out, zero)

        zeros_x = jnp.zeros(xshape, cdtype)
        zeros_shared = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, p.dtype), shared)
        zeros_blocks = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, p.dtype), blocks_local)
        carry0 = (
            zeros_x,                                  # fwd_buf
            zeros_x,                                  # bwd_buf (cotangent)
            # R live slots + 1 trash slot for warmup/drain ticks whose
            # clipped micro index must not clobber a live save.
            jnp.zeros((R + 1,) + xshape, cdtype),     # saved-input ring
            jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), blocks_local),
            jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), shared),
            jnp.zeros((), jnp.float32),               # loss sum
        )

        def tick(carry, t):
            fwd_buf, bwd_buf, ring, g_blocks, g_shared, loss_acc = carry

            # Tick-uniform gate windows (functions of t only; see module
            # docstring for why they must not depend on the rank):
            #   embed fwd   stage 0's f = t            → t < M
            #   stage fwd   some rank has 0 ≤ t-r < M  → t < M + last
            #   head        last rank's h = t - last   → last ≤ t < M+last
            #   stage bwd   some rank has valid b      → t ≥ last
            #   embed bwd   rank 0's b = t - 2·last    → t ≥ 2·last
            emb_t = t < M
            fwd_t = t < M + last
            head_t = jnp.logical_and(t >= last, t < M + last)
            bwd_t = t >= last
            embbwd_t = t >= 2 * last

            # ---------------- forward sub-tick ----------------
            f = t - r
            fc = jnp.clip(f, 0, M - 1)
            f_ok = jnp.logical_and(f >= 0, f < M)
            key_f = mkey(fc)
            tok_f = lax.dynamic_index_in_dim(micro_tokens, fc, 0,
                                             keepdims=False)
            x0 = ugate(
                emb_t,
                lambda: embed_fn(shared, tok_f, key_f).astype(cdtype),
                lambda: zeros_x)
            x_in = jnp.where(r == 0, x0, fwd_buf)
            y = ugate(
                fwd_t,
                lambda: stage_fn(blocks_local, x_in, key_f).astype(cdtype),
                lambda: zeros_x)
            ring = lax.dynamic_update_index_in_dim(
                ring, x_in, jnp.where(f_ok, fc % R, R), 0)

            # Head + its grad on the tick's own output (last stage: micro
            # h == f). The gate skips the whole vocab projection + vjp on
            # the 2·last warmup/drain ticks; within the window, off-stage
            # ranks still run it in parallel (latency-free) and the
            # selects below discard their garbage.
            h = t - last
            hc = jnp.clip(h, 0, M - 1)
            tgt_h = lax.dynamic_index_in_dim(micro_targets, hc, 0,
                                             keepdims=False)
            key_h = jax.random.fold_in(rng, M + hc)
            valid_h = jnp.logical_and(jnp.logical_and(h >= 0, h < M),
                                      r == last)

            def run_head():
                l, (gsh, gy) = jax.value_and_grad(
                    head_loss, argnums=(0, 1))(shared, y, tgt_h, key_h)
                return (jnp.where(valid_h, l, 0.0),
                        jax.tree_util.tree_map(
                            lambda g: jnp.where(valid_h, g,
                                                jnp.zeros_like(g)), gsh),
                        jnp.where(valid_h, gy.astype(cdtype), zeros_x))

            loss_h, dsh_head, dy = ugate(
                head_t, run_head,
                lambda: (jnp.zeros((), jnp.float32), zeros_shared, zeros_x))
            loss_acc = loss_acc + loss_h
            g_shared = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32), g_shared, dsh_head)

            # ---------------- backward sub-tick ----------------
            b = t - 2 * last + r
            bc = jnp.clip(b, 0, M - 1)
            b_ok = jnp.logical_and(b >= 0, b < M)
            key_b = mkey(bc)
            x_saved = lax.dynamic_index_in_dim(ring, bc % R, 0,
                                               keepdims=False)
            g_in = jnp.where(r == last, dy, bwd_buf)

            def run_bwd():
                _, vjp = jax.vjp(
                    lambda bl, xi: stage_fn(bl, xi, key_b), blocks_local,
                    x_saved)
                dblocks, dx = vjp(g_in)
                return (jax.tree_util.tree_map(
                            lambda g: jnp.where(b_ok, g,
                                                jnp.zeros_like(g)), dblocks),
                        dx.astype(cdtype))

            dblocks, dx = ugate(
                bwd_t, run_bwd, lambda: (zeros_blocks, zeros_x))
            g_blocks = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32), g_blocks, dblocks)

            # Embedding backward (tied front): stage 0 pulls its input
            # cotangent into the shared params.
            tok_b = lax.dynamic_index_in_dim(micro_tokens, bc, 0,
                                             keepdims=False)
            valid_e = jnp.logical_and(b_ok, r == 0)

            def run_embed_bwd():
                _, evjp = jax.vjp(
                    lambda sh: embed_fn(sh, tok_b, key_b).astype(cdtype),
                    shared)
                (dsh_emb,) = evjp(dx)
                return jax.tree_util.tree_map(
                    lambda g: jnp.where(valid_e, g, jnp.zeros_like(g)),
                    dsh_emb)

            dsh_emb = ugate(embbwd_t, run_embed_bwd, lambda: zeros_shared)
            g_shared = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32), g_shared, dsh_emb)

            # ---------------- rotate (bf16 boundaries, as in spmd.py) ----
            fwd_next = lax.ppermute(
                y, PP_AXIS, [(i, i + 1) for i in range(Pstages - 1)])
            bwd_next = lax.ppermute(
                dx, PP_AXIS, [(i + 1, i) for i in range(Pstages - 1)])
            return (fwd_next, bwd_next, ring, g_blocks, g_shared,
                    loss_acc), None

        (_, _, _, g_blocks, g_shared, loss_sum), _ = lax.scan(
            tick, carry0, jnp.arange(T))
        # Shared/tied grads are partial per stage (embed on 0, head on
        # P-1); the psum is the ReduceTiedGrads collective. Loss lives on
        # the last stage only, so the psum just broadcasts it.
        g_shared = jax.tree_util.tree_map(
            lambda g: lax.psum(g, PP_AXIS), g_shared)
        loss_sum = lax.psum(loss_sum, PP_AXIS)
        return loss_sum, g_blocks, g_shared

    def grads_fn(params, batch, rng, scale=None):
        scale = jnp.asarray(1.0, jnp.float32) if scale is None else scale
        tokens, targets = _split_batch(batch)
        micro_tokens = _to_micro(tokens, M)       # [M, mb, S]
        micro_targets = _to_micro(targets, M)
        shared = params["shared"]

        # Embedded-activation shape (per micro-batch), via eval_shape so no
        # FLOPs run outside the pipeline.
        x_shape = jax.eval_shape(
            lambda sh, tk: embed_fn(sh, tk, jax.random.PRNGKey(0)),
            shared, jax.tree_util.tree_map(lambda a: a[0], micro_tokens))
        cdtype = x_shape.dtype

        mapped = comm.shard_map(
            partial(per_stage, cdtype=cdtype, xshape=x_shape.shape),
            mesh=mesh,
            in_specs=(P(PP_AXIS), P(), P(), P(), P(), P()),
            out_specs=(P(), P(PP_AXIS), P()),
            axis_names={PP_AXIS},
            check_vma=False)
        loss, g_blocks, g_shared = mapped(
            params["blocks"], shared, micro_tokens, micro_targets, rng,
            scale)
        # Grads stay SCALED (the engine unscales + overflow-votes, same as
        # its autodiff path); the reported loss is unscaled — scale is a
        # power of two, so the division is exact.
        return loss / scale, {"shared": g_shared, "blocks": g_blocks}

    return grads_fn
