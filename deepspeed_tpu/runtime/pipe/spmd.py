"""SPMD collective pipeline — the compiled 1F1B/GPipe execution path.

The reference interprets instruction schedules imperatively per rank
(pipe/engine.py:1135-1161 dispatch map) with p2p-as-broadcast transfers
(p2p.py:31-55). The TPU-native execution is a SINGLE jitted collective
program: ``shard_map`` over the ``pipe`` mesh axis holds one stage's
parameters per device; a ``lax.scan`` over ``M + P - 1`` ticks runs
(stage-compute → ppermute-to-next-stage) per tick — the forward wavefront of
the schedule. JAX autodiff through the scan + ppermute generates the reverse
wavefront (grad ticks with ppermute in the opposite direction), i.e. the
backward half of the schedule. Per-tick rematerialization via
``jax.checkpoint`` keeps LAYER-INTERNAL activations bounded (one stage's
worth per tick); the pipeline's boundary tensors — the embedded inputs and
the banked last-stage outputs — are O(M) single hidden states
[M, mb/dp, S, H] per device, the GPipe memory profile rather than 1F1B's
O(P) buffer count (schedule.py:237-242). With remat that bank, not layer
activations, dominates; an out-of-scan per-micro loss emission would
recover O(P) at the cost of conditional collectives (the round-1 design
that crashed XLA — see "Division of labor" below).

Division of labor (the load-bearing design decision):
- INSIDE the manual ``pipe`` region: only the uniform stage body and the
  ``ppermute`` rotation. Every device executes the identical program every
  tick — no data-dependent branches, so no mismatched collective rendezvous
  and no conditional GSPMD collectives.
- OUTSIDE (plain SPMD over the auto dp/mp axes): the embedding front and the
  loss head. Both read the tied/shared parameters through ordinary autodiff,
  so the tied embed/unembed gradient (the reference's ReduceTiedGrads
  instruction, pipe/engine.py:208-227) is an ordinary sum of two paths in
  one differentiated program — no explicit cross-stage psum of parameter
  cotangents is ever constructed.

The pipeline's input bank crosses into the manual region in the compute
dtype (bf16); each tick's slice is routed through fp32 around the pvary so
its transpose-psum over ``pipe`` stays off the XLA bf16 promotion path.
Cross-stage ppermute transfers are bf16 throughout.

Composition: the ``pipe`` axis is *manual* (shard_map ``axis_names``); data/
model/seq axes stay *auto*, so GSPMD still partitions the batch over dp and
the stage matmuls over mp inside the per-stage program — 3D parallelism as
mesh composition (reference topology.py:246-250).

Model contract (uniform stages — the shape of every pipelined transformer):
- ``embed_fn(shared, tokens, rng) -> x``            (computed pre-pipeline)
- ``stage_fn(blocks_local, x, rng) -> x``           (L/P stacked layers)
- ``head_fn(shared, x, targets, rng) -> scalar``    (computed post-pipeline)
Params pytree: ``{"shared": replicated-over-pipe, "blocks": leaf[0] dim
stacked over layers, sharded over pipe}``.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ...parallel import comm
from ...parallel.topology import PP_AXIS


def spmd_pipeline_loss(embed_fn: Callable, stage_fn: Callable,
                       head_fn: Callable, num_stages: int,
                       num_micro_batches: int, mesh: Mesh,
                       remat: bool = True) -> Callable:
    """Build ``loss_fn(params, batch, rng) -> scalar`` running the pipeline.

    ``batch``: (tokens, targets) with leading dim M*mb (micro-stacked by the
    caller) or a single array whose targets are derived next-token style.
    """
    M, Pstages = num_micro_batches, num_stages
    T = M + Pstages - 1

    def per_stage(blocks_local, micro_x, rng, cdtype):
        """One pipeline stage's full schedule: T ticks of compute+rotate.

        ``micro_x``: [M, mb, ...] embedded micro-batches in the COMPUTE
        dtype (the input bank is bf16 — half the GPipe bank memory),
        replicated over pipe. Returns [1, M, mb, ...] — this stage's
        collected outputs; only stage P-1's slice is meaningful.
        """
        r = lax.axis_index(PP_AXIS)
        stage = jax.checkpoint(stage_fn) if remat else stage_fn

        buf0 = comm.pvary(jnp.zeros(micro_x.shape[1:], cdtype), PP_AXIS)
        out0 = comm.pvary(jnp.zeros(micro_x.shape, cdtype), PP_AXIS)

        def tick(carry, t):
            buf, out = carry
            x0 = lax.dynamic_index_in_dim(
                micro_x, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            # fp32-safe boundary on a PER-TICK slice: pvary's transpose is a
            # psum over pipe, and routing it through fp32 keeps that
            # all-reduce off XLA's bf16 AllReducePromotion path (which
            # CHECK-fails on sdy-annotated reduction computations in this
            # XLA build). Only the [mb, ...] tick slice is ever fp32 — the
            # O(M) bank itself stays bf16.
            x0 = comm.pvary(x0.astype(jnp.float32),
                           PP_AXIS).astype(cdtype)
            x_in = jnp.where(r == 0, x0, buf)
            key_t = jax.random.fold_in(rng, t)
            y = stage(blocks_local, x_in, key_t)

            # Drain window: stage P-1 banks micro-batch out_idx = t-(P-1).
            out_idx = t - (Pstages - 1)
            widx = jnp.clip(out_idx, 0, M - 1)
            write = jnp.logical_and(r == Pstages - 1, out_idx >= 0)
            cur = lax.dynamic_index_in_dim(out, widx, 0, keepdims=False)
            out = lax.dynamic_update_index_in_dim(
                out, jnp.where(write, y, cur), widx, 0)

            # Ship activations to the next stage (SendActivation /
            # RecvActivation as one collective-permute; its reverse-mode
            # transpose is the SendGrad/RecvGrad pair in the other
            # direction).
            buf_next = lax.ppermute(
                y, PP_AXIS, [(i, i + 1) for i in range(Pstages - 1)])
            return (buf_next, out), None

        (_, out), _ = lax.scan(tick, (buf0, out0), jnp.arange(T))
        return out[None]

    def loss_fn(params, batch, rng):
        tokens, targets = _split_batch(batch)
        micro_tokens = _to_micro(tokens, M)      # [M, mb, S]
        micro_targets = _to_micro(targets, M)
        shared = params["shared"]

        # Embedding front (pre-pipeline, auto-sharded over dp/mp). Each
        # micro-batch gets its own folded key so dropout masks decorrelate;
        # the fold domains [T, T+M) here and [T+M, T+2M) for the head are
        # disjoint from the in-pipeline tick keys fold_in(rng, t), t < T.
        midx = jnp.arange(M)
        x = jax.vmap(lambda tk, i: embed_fn(
            shared, tk, jax.random.fold_in(rng, T + i)))(micro_tokens, midx)

        mapped = comm.shard_map(
            partial(per_stage, cdtype=x.dtype), mesh=mesh,
            in_specs=(P(PP_AXIS), P(), P()),
            out_specs=P(PP_AXIS),
            axis_names={PP_AXIS})
        stacked = mapped(params["blocks"], x, rng)
        y_last = stacked[-1]                      # [M, mb, ...]

        # Loss head (post-pipeline). Tied params (e.g. wte) contribute here
        # AND in embed_fn; plain autodiff sums both — ReduceTiedGrads parity.
        losses = jax.vmap(
            lambda y, tg, i: head_fn(shared, y, tg, jax.random.fold_in(
                rng, T + M + i)))(y_last, micro_targets, midx)
        return jnp.mean(losses.astype(jnp.float32))

    return loss_fn


def _split_batch(batch):
    if isinstance(batch, (tuple, list)):
        return batch[0], batch[1]
    # single token array [B, S+1]: next-token objective
    return batch[:, :-1], batch[:, 1:]


def _to_micro(x, m: int):
    def reshape(a):
        assert a.shape[0] % m == 0, \
            f"batch dim {a.shape[0]} not divisible by {m} micro-batches"
        return a.reshape((m, a.shape[0] // m) + a.shape[1:])
    return jax.tree_util.tree_map(reshape, x)


def pipeline_param_shardings(shared_specs: Any, block_specs: Any) -> Dict[str, Any]:
    """Compose TP block specs with the pipe axis: the stacked layer dim
    (leading) becomes the pipe dim; shared params replicate over pipe."""
    def add_pipe(spec: P) -> P:
        parts = list(spec)
        if parts and parts[0] is None:
            parts[0] = PP_AXIS
        elif not parts:
            parts = [PP_AXIS]
        else:
            raise ValueError(f"block spec {spec} already shards dim 0")
        return P(*parts)

    return {
        "shared": shared_specs,
        "blocks": jax.tree_util.tree_map(
            add_pipe, block_specs, is_leaf=lambda x: isinstance(x, P)),
    }
