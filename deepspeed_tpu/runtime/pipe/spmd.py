"""SPMD collective pipeline — the compiled 1F1B/GPipe execution path.

The reference interprets instruction schedules imperatively per rank
(pipe/engine.py:1135-1161 dispatch map) with p2p-as-broadcast transfers
(p2p.py:31-55). The TPU-native execution is a SINGLE jitted collective
program: ``shard_map`` over the ``pipe`` mesh axis holds one stage's
parameters per device; a ``lax.scan`` over ``M + P - 1`` ticks runs
(stage-compute → ppermute-to-next-stage) per tick — the forward wavefront of
the schedule. JAX autodiff through the scan + ppermute generates the reverse
wavefront (grad ticks with ppermute in the opposite direction), i.e. the
backward half of the schedule, with per-tick rematerialization via
``jax.checkpoint`` bounding activation memory the way 1F1B's buffer count
does (schedule.py:237-242).

Composition: the ``pipe`` axis is *manual* (shard_map ``axis_names``); data/
model/seq axes stay *auto*, so GSPMD still partitions the batch over dp and
the stage matmuls over mp inside the per-stage program — 3D parallelism as
mesh composition (reference topology.py:246-250).

Model contract (uniform stages — the shape of every pipelined transformer):
- ``embed_fn(shared, tokens, rng) -> x``            (runs logically on stage 0)
- ``stage_fn(blocks_local, x, rng) -> x``           (L/P stacked layers)
- ``head_fn(shared, x, targets, rng) -> scalar``    (runs on stage P-1)
Params pytree: ``{"shared": replicated-over-pipe, "blocks": leaf[0] dim
stacked over layers, sharded over pipe}``. Weight tying (e.g. embedding =
unembedding) is structural: both embed_fn and head_fn read it from
``shared``; shard_map's transpose inserts the cross-stage psum of its grads
(the ReduceTiedGrads instruction, for free).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ...parallel.topology import PP_AXIS


def spmd_pipeline_loss(embed_fn: Callable, stage_fn: Callable,
                       head_fn: Callable, num_stages: int,
                       num_micro_batches: int, mesh: Mesh,
                       remat: bool = True) -> Callable:
    """Build ``loss_fn(params, batch, rng) -> scalar`` running the pipeline.

    ``batch``: (tokens, targets) with leading dim M*mb (micro-stacked by the
    caller) or a single array whose targets are derived by the head_fn.
    """
    M, Pstages = num_micro_batches, num_stages

    def per_stage(shared, blocks_local, micro_tokens, micro_targets, rng):
        r = lax.axis_index(PP_AXIS)
        stage = jax.checkpoint(stage_fn) if remat else stage_fn

        def tick(carry, t):
            buf, loss_acc = carry
            in_idx = jnp.clip(t, 0, M - 1)
            tokens_t = lax.dynamic_index_in_dim(
                micro_tokens, in_idx, 0, keepdims=False)
            key_t = jax.random.fold_in(rng, t)
            x_in = jnp.where(r == 0,
                             embed_fn(shared, tokens_t, key_t).astype(buf.dtype),
                             buf)
            y = stage(blocks_local, x_in, key_t)

            out_idx = t - (Pstages - 1)
            tgt_t = lax.dynamic_index_in_dim(
                micro_targets, jnp.clip(out_idx, 0, M - 1), 0, keepdims=False)
            emit = jnp.logical_and(r == Pstages - 1, out_idx >= 0)
            loss_t = lax.cond(
                emit,
                lambda: head_fn(shared, y, tgt_t, key_t).astype(jnp.float32),
                lambda: lax.pvary(jnp.asarray(0.0, jnp.float32), PP_AXIS))
            loss_acc = loss_acc + loss_t

            # Ship activations to the next stage (the SendActivation /
            # RecvActivation pair as one collective-permute; reverse-mode AD
            # of this is the SendGrad/RecvGrad pair).
            buf_next = lax.ppermute(
                y, PP_AXIS, [(i, i + 1) for i in range(Pstages - 1)])
            return (buf_next, loss_acc), None

        # Probe the embed output shape to size the rotating buffer.
        tok0 = jax.tree_util.tree_map(lambda a: a[0], micro_tokens)
        x0 = jax.eval_shape(lambda s, tk: embed_fn(s, tk, rng), shared, tok0)
        buf0 = lax.pvary(jnp.zeros(x0.shape, x0.dtype), PP_AXIS)

        (_, loss_sum), _ = lax.scan(
            tick, (buf0, lax.pvary(jnp.asarray(0.0, jnp.float32), PP_AXIS)),
            jnp.arange(M + Pstages - 1))
        return lax.psum(loss_sum, PP_AXIS) / M

    mapped = jax.shard_map(
        per_stage, mesh=mesh,
        in_specs=(P(), P(PP_AXIS), P(), P(), P()),
        out_specs=P(),
        axis_names={PP_AXIS})

    def loss_fn(params, batch, rng):
        tokens, targets = _split_batch(batch)
        micro_tokens = _to_micro(tokens, M)
        micro_targets = _to_micro(targets, M)
        return mapped(params["shared"], params["blocks"],
                      micro_tokens, micro_targets, rng)

    return loss_fn


def _split_batch(batch):
    if isinstance(batch, (tuple, list)):
        return batch[0], batch[1]
    # single token array [B, S+1]: next-token objective
    return batch[:, :-1], batch[:, 1:]


def _to_micro(x, m: int):
    def reshape(a):
        assert a.shape[0] % m == 0, \
            f"batch dim {a.shape[0]} not divisible by {m} micro-batches"
        return a.reshape((m, a.shape[0] // m) + a.shape[1:])
    return jax.tree_util.tree_map(reshape, x)


def pipeline_param_shardings(shared_specs: Any, block_specs: Any) -> Dict[str, Any]:
    """Compose TP block specs with the pipe axis: the stacked layer dim
    (leading) becomes the pipe dim; shared params replicate over pipe."""
    def add_pipe(spec: P) -> P:
        parts = list(spec)
        if parts and parts[0] is None:
            parts[0] = PP_AXIS
        elif not parts:
            parts = [PP_AXIS]
        else:
            raise ValueError(f"block spec {spec} already shards dim 0")
        return P(*parts)

    return {
        "shared": shared_specs,
        "blocks": jax.tree_util.tree_map(
            add_pipe, block_specs, is_leaf=lambda x: isinstance(x, P)),
    }
