"""Data loading.

Parity with reference ``runtime/dataloader.py``: ``DeepSpeedDataLoader``
(auto distributed sampling over the dp axis, dataloader.py:33-101) and
``RepeatingLoader`` (dataloader.py:10).

TPU-native design: one JAX process feeds all local chips, so the loader
yields *global per-process* batches as stacked numpy arrays, which the engine
shards over the mesh dp axis via NamedSharding (device layout is the engine's
job, matching how the reference's sampler + ``to(device)`` split duties).
Accepts torch datasets/dataloaders, numpy arrays, or any indexable.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import numpy as np


class RepeatingLoader:
    """Wrap an iterator to restart on StopIteration (dataloader.py:10).

    Fetch-wait instrumented: ``fetch_wait_s`` accumulates the host wall
    spent inside ``__next__`` (monotonic clock only — no device access),
    so the goodput ledger and operators can see data stalls. This
    wrapper's wait already INCLUDES any wrapped loader's own fetch
    time."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self.data_iter = iter(self.loader)
        self.fetch_wait_s = 0.0

    def __iter__(self):
        return self

    def __len__(self):
        return len(self.loader)

    def __next__(self):
        t0 = time.perf_counter()
        try:
            try:
                return next(self.data_iter)
            except StopIteration:
                self.data_iter = iter(self.loader)
                return next(self.data_iter)
        finally:
            self.fetch_wait_s += time.perf_counter() - t0

    def cumulative_fetch_wait_s(self) -> float:
        return self.fetch_wait_s


def default_collate(samples: Sequence[Any]):
    """Stack a list of samples (arrays / tuples / dicts of arrays)."""
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate([s[i] for s in samples])
                           for i in range(len(first)))
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    arrs = [np.asarray(s) for s in samples]
    return np.stack(arrs)


class DeepSpeedDataLoader:
    """Batched, optionally shuffled, per-process-sharded loader.

    Parity with dataloader.py:33-101: the reference builds a
    ``DistributedSampler(rank=dp_rank, num_replicas=dp_size)``; here each
    *process* takes an interleaved shard of the dataset (process boundary =
    host, since one process drives many chips) and yields batches of
    ``batch_size`` = per-process batch (micro_batch × local dp × grad_acc
    as the engine requests).
    """

    def __init__(self, dataset: Any, batch_size: int,
                 collate_fn: Optional[Callable] = None,
                 local_rank: int = -1,
                 num_local_io_workers: Optional[int] = None,
                 data_sampler: Optional[Any] = None,
                 shuffle: bool = False, seed: int = 0, drop_last: bool = True,
                 data_parallel_world_size: Optional[int] = None,
                 data_parallel_rank: Optional[int] = None):
        import jax
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self.dp_world = (data_parallel_world_size if data_parallel_world_size
                         is not None else jax.process_count())
        self.dp_rank = (data_parallel_rank if data_parallel_rank is not None
                        else jax.process_index())
        self.data_sampler = data_sampler
        # Cumulative host wall spent assembling batches (dataset access +
        # collate) — the loader-local data-stall counter.
        self.fetch_wait_s = 0.0
        self._len = self._shard_len() // batch_size if drop_last else \
            -(-self._shard_len() // batch_size)

    def _dataset_len(self) -> int:
        return len(self.dataset)

    def _shard_len(self) -> int:
        n = self._dataset_len()
        return n // self.dp_world if self.drop_last else \
            len(range(self.dp_rank, n, self.dp_world))

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return self._len

    def __iter__(self) -> Iterator[Any]:
        n = self._dataset_len()
        order = np.arange(n)
        epoch = self.epoch
        # Each fresh iterator is a new epoch (set_epoch still overrides).
        self.epoch += 1
        if self.shuffle:
            rng = np.random.default_rng(self.seed + epoch)
            rng.shuffle(order)
        # Interleaved shard per process (DistributedSampler semantics).
        shard = order[self.dp_rank::self.dp_world]
        usable = (len(shard) // self.batch_size) * self.batch_size \
            if self.drop_last else len(shard)
        for start in range(0, usable, self.batch_size):
            t0 = time.perf_counter()
            idxs = shard[start:start + self.batch_size]
            samples = [self.dataset[int(i)] for i in idxs]
            batch = self.collate_fn(samples)
            self.fetch_wait_s += time.perf_counter() - t0
            yield batch

    def cumulative_fetch_wait_s(self) -> float:
        return self.fetch_wait_s


class ArrayDataset:
    """Tuple-of-arrays dataset: sample i = (arr[i] for each array)."""

    def __init__(self, *arrays: np.ndarray):
        assert arrays and all(len(a) == len(arrays[0]) for a in arrays)
        self.arrays = [np.asarray(a) for a in arrays]

    def __len__(self) -> int:
        return len(self.arrays[0])

    def __getitem__(self, i: int):
        if len(self.arrays) == 1:
            return self.arrays[0][i]
        return tuple(a[i] for a in self.arrays)
