"""The core training engine.

Capability parity with reference ``runtime/engine.py`` (DeepSpeedEngine,
engine.py:95): config-driven construction, optimizer selection matrix
(engine.py:588-628), fp16/bf16 precision with dynamic loss scaling and
overflow-skip (engine.py:630-710, 1000-1085), gradient accumulation
boundaries, gradient clipping, data-parallel gradient averaging
(engine.py:1122-1195), LR scheduling tied to successful steps, checkpoint
save/load with tag dirs + ``latest`` pointer (engine.py:1472-1572), timers
and throughput reporting, ``deepspeed_io`` data loading.

TPU-native architecture (NOT a translation):
- One jit-compiled ``train_step`` fuses the whole iteration: a ``lax.scan``
  over grad-accumulation micro-batches computing grads (the reference's
  forward/backward/hook machinery), gradient averaging via XLA SPMD (the
  batch is sharded over the mesh "data" axis, so grads *are born* as partial
  sums that XLA reduces — the bucketed-allreduce engine code path),
  nan/inf-gated optimizer apply via ``jnp.where`` (the overflow-skip path),
  and loss-scale state update. No hooks, no streams: XLA's latency-hiding
  scheduler overlaps the reduction with backward compute.
- ZeRO stages 1/2 are *sharding annotations*: optimizer state (stage >= 1)
  is laid out with a "data"-axis NamedSharding, which makes XLA compile the
  grad reduction as reduce-scatter + sharded update + all-gather — exactly
  the communication schedule stage2.py implements by hand (see zero/
  partition.py for the spec builder).
- fp32 master params live in ``state.params``; compute casts to
  bf16/fp16 per the config (the reference's FP16_Optimizer master-weight
  copy, fused_optimizer.py:17).
- The torch-style ``forward()/backward()/step()`` trio is provided as a
  compatibility layer driving the same jitted paths.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .async_ckpt import (AsyncCheckpointer, CheckpointSnapshot,
                         LATEST_FILE, META_FILE, PreemptSaver,
                         commit_snapshot, crash_point, is_complete)
from .config import DeepSpeedConfig
from .dataloader import DeepSpeedDataLoader, RepeatingLoader
from .fp16.loss_scaler import (LossScaleState, make_loss_scale_state,
                               update_loss_scale)
from .lr_schedules import get_lr_schedule
from .progressive_layer_drop import ProgressiveLayerDrop
from .utils import (clip_coefficient, clip_grad_norm_, global_norm,
                    tree_has_inf_or_nan)
from .zero.partition import zero_shardings
from .. import constants as C
from ..monitor import Telemetry
from ..monitor.memory import analytic_state_bytes
from ..ops.optimizers import build_optimizer
from ..parallel import comm
from ..parallel.topology import (build_mesh, DP_AXIS, EP_AXIS, MP_AXIS,
                                 SLICE_AXIS)
from ..utils.logging import log_dist, logger
from ..utils.timer import SynchronizedWallClockTimer, ThroughputTimer

try:
    from flax import serialization as flax_serialization
except Exception:  # pragma: no cover
    flax_serialization = None

MODEL_FILE = "mp_rank_00_model_states.msgpack"
MODEL_FILE_FMT = "mp_rank_{:02d}_model_states.msgpack"
OPTIM_FILE_FMT = "zero_pp_rank_0_mp_rank_00_optim_states.msgpack"
OPTIM_SHARD_FMT = "zero_pp_rank_{}_mp_rank_00_optim_states.msgpack"


def _spec_axis(sharding, axis_name: str):
    """Index of the dimension a NamedSharding partitions over ``axis_name``
    (None when unsharded on that axis)."""
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    for i, entry in enumerate(spec):
        if entry == axis_name or (isinstance(entry, (tuple, list)) and
                                  axis_name in entry):
            return i
    return None


def _cast_floats(tree: Any, dtype) -> Any:
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree_util.tree_map(cast, tree)


def _tree_select(pred, on_true: Any, on_false: Any) -> Any:
    """Elementwise pytree select (used for overflow-skip)."""
    return jax.tree_util.tree_map(
        lambda t, f: jnp.where(pred, t, f) if hasattr(t, "dtype") else t,
        on_true, on_false)


def _make_raw_scaled_loss(loss_fn, accepts_pld: bool, gas: int):
    """The scaled-loss core every grad builder shares: params arrive
    already in compute form (cast cache / the stage-3 gather's in-flight
    cast / the caller's _cast_floats wrapper). Returns
    ``(scaled_loss_for_backward, (raw_loss, aux))`` — scaled for the
    fp16 backward, divided by gas so accumulation averages; ``aux`` is
    the loss_fn's auxiliary output (None for plain-loss models — the MoE
    stats dict rides here). ONE definition so the main, trio, and
    offload paths cannot diverge on the scaling semantics."""
    import jax.numpy as _jnp

    def raw_scaled_loss(cparams, mb, key, scale, theta):
        out = loss_fn(cparams, mb, key, pld_theta=theta) if accepts_pld \
            else loss_fn(cparams, mb, key)
        loss, aux = (out if isinstance(out, tuple) else (out, None))
        return (loss.astype(_jnp.float32) * scale) / gas, (loss, aux)
    return raw_scaled_loss


def _overflow_resolution(state: "EngineState", overflow, *, fp16: bool,
                         static_scale: bool, scale_window: int,
                         min_scale: float, hysteresis_init: int
                         ) -> Dict[str, Any]:
    """The overflow-vote bookkeeping every train-step builder shares
    (reference engine.py:1000-1085): on overflow hold the step (so LR
    holds) and count the skip; drive the dynamic loss-scale machine either
    way. Returns the ``EngineState.replace`` fields — params/opt-state
    selection stays with the caller (each path has its own apply)."""
    fields: Dict[str, Any] = dict(
        step=state.step + jnp.where(overflow, 0, 1).astype(jnp.int32),
        skipped_steps=state.skipped_steps +
        jnp.where(overflow, 1, 0).astype(jnp.int32))
    if fp16 and not static_scale:
        ls = LossScaleState(
            loss_scale=state.loss_scale, growth_count=state.growth_count,
            hysteresis=state.hysteresis, dynamic=True,
            scale_window=scale_window, min_scale=min_scale,
            hysteresis_init=hysteresis_init, scale_factor=2.0)
        ls = update_loss_scale(ls, overflow)
        fields.update(loss_scale=ls.loss_scale, growth_count=ls.growth_count,
                      hysteresis=ls.hysteresis)
    return fields


def _clipped_update(grads: Any, state: "EngineState", grad_norm, *, tx,
                    fused_apply, clip: float, master_free: bool = False,
                    sr_key=None) -> Tuple[Any, Any]:
    """Global-norm clip + optimizer apply shared by the train-step
    builders: the fused single-pass Pallas kernel (clip coefficient folded
    into its grad read, stochastic rounding on the in-kernel param write)
    or the optax chain. Returns (new_params, new_opt_state)."""
    if fused_apply is not None:
        coeff = clip_coefficient(grad_norm, clip) \
            if (clip and clip > 0) else None
        return fused_apply(grads, state.opt_state, state.params,
                           clip_coeff=coeff, sr_key=sr_key)
    if clip and clip > 0:
        grads, _ = clip_grad_norm_(grads, clip, precomputed_norm=grad_norm)
    updates, new_opt = tx.update(grads, state.opt_state, state.params)
    import optax
    if master_free:
        # Master-free bf16: the f32 update lands on the bf16 param via
        # unbiased stochastic rounding — sub-ulp updates survive in
        # expectation instead of being dropped by round-to-nearest
        # (ops/stochastic_rounding.py).
        from ..ops.stochastic_rounding import tree_stochastic_round_bf16
        summed = jax.tree_util.tree_map(
            lambda p, u: p.astype(jnp.float32) + u, state.params, updates)
        return tree_stochastic_round_bf16(summed, sr_key), new_opt
    return optax.apply_updates(state.params, updates), new_opt


class EngineState:
    """Pytree of everything the jitted step carries. Registered manually to
    stay dependency-light and serialization-friendly."""

    def __init__(self, step, params, opt_state, loss_scale, growth_count, hysteresis,
                 skipped_steps, cast_params=None, dcn_error=None):
        self.step = step
        self.params = params
        self.opt_state = opt_state
        self.loss_scale = loss_scale
        self.growth_count = growth_count
        self.hysteresis = hysteresis
        self.skipped_steps = skipped_steps
        # Persistent compute-dtype copy of ``params`` (None when the
        # engine computes in fp32 / owns no cache): re-reading 3 GB of
        # fp32 masters to cast them every step is pure HBM waste; the
        # train step refreshes this cache in the same fused pass as the
        # optimizer update, and _place_state re-derives it whenever params
        # are replaced from outside (checkpoint load), so it can never
        # serve stale weights.
        self.cast_params = cast_params
        # Multi-slice DCN-compression error feedback (None unless
        # zero_optimization.dcn_compression is live): per-leaf
        # [slices, *shard] f32 buffers — each (slice, dp-rank) carries
        # the residual its 1-bit-compressed inter-slice transmissions
        # have not yet delivered (parallel/multislice.py). Like 1-bit
        # Adam's worker_error, it is genuinely per-member state; unlike
        # it, it is NOT checkpointed (a resume restarts the feedback at
        # zero — a one-step compression bias, self-correcting).
        self.dcn_error = dcn_error

    def replace(self, **kw) -> "EngineState":
        d = dict(step=self.step, params=self.params, opt_state=self.opt_state,
                 loss_scale=self.loss_scale, growth_count=self.growth_count,
                 hysteresis=self.hysteresis, skipped_steps=self.skipped_steps,
                 cast_params=self.cast_params, dcn_error=self.dcn_error)
        d.update(kw)
        return EngineState(**d)


jax.tree_util.register_pytree_node(
    EngineState,
    lambda s: ((s.step, s.params, s.opt_state, s.loss_scale, s.growth_count,
                s.hysteresis, s.skipped_steps, s.cast_params, s.dcn_error),
               None),
    lambda _, ch: EngineState(*ch))


class DeepSpeedEngine:
    """Config-driven training engine over a device mesh."""

    def __init__(self, args=None, model=None, optimizer=None, model_params=None,
                 training_data=None, lr_scheduler=None, mpu=None,
                 dist_init_required=None, collate_fn=None,
                 config: Union[str, Dict[str, Any], None] = None, rng=None,
                 mesh: Optional[Mesh] = None, dont_change_device: bool = False,
                 param_shardings=None, sparse_grad_filter=None,
                 grads_fn=None, zero3_scan=None):
        if dist_init_required is None or dist_init_required:
            comm.init_distributed()

        # Manually-differentiated training path: ``grads_fn(params, batch,
        # rng, scale) -> (unscaled_loss, scale-multiplied grads)`` replaces
        # value_and_grad in the train step (the 1F1B pipeline computes its
        # gradients inside one primal scan — reverse-mode autodiff can't
        # interleave fwd/bwd ticks). ``scale`` is the fp16 loss scale (a
        # traced 1.0 otherwise); a 3-arg fn is accepted for scale-oblivious
        # models (bf16/fp32 only).
        if grads_fn is not None:
            import inspect
            try:
                n_params = len(inspect.signature(grads_fn).parameters)
            except (TypeError, ValueError):
                n_params = 4
            if n_params < 4:
                _inner_grads_fn = grads_fn
                grads_fn = lambda p, b, r, scale: _inner_grads_fn(p, b, r)
        self._direct_grads_fn = grads_fn
        self.mpu = mpu
        self.mesh = mesh if mesh is not None else self._build_mesh(config)
        self.dp_size = int(self.mesh.shape.get(DP_AXIS, 1))
        # MoE expert parallelism: the `expert` axis factors OUT OF data
        # (it reuses the dp devices), so the batch-replica count — the
        # world size the batch solver and throughput accounting see — is
        # ep * dp, while ZeRO keeps sharding over `data` (within-expert-
        # group) and expert weights shard over `expert`.
        self.ep_size = int(self.mesh.shape.get(EP_AXIS, 1))
        # Multi-slice scale-out: the `slice` axis is OUTERMOST (ICI
        # domains joined by DCN); dp factors WITHIN a slice, so the
        # batch-replica count is slices * ep * dp while ZeRO keeps
        # sharding over `data` (within one slice) and gradient sync goes
        # hierarchical (in-slice reduce-scatter over ICI, inter-slice
        # all-reduce of the 1/dp shards over DCN —
        # parallel/multislice.py).
        self.slice_size = int(self.mesh.shape.get(SLICE_AXIS, 1))
        self.replica_size = self.dp_size * self.ep_size * self.slice_size

        self.config = DeepSpeedConfig(config, mpu=mpu,
                                      world_size=self.replica_size) \
            if not isinstance(config, DeepSpeedConfig) else config
        # The `moe` ds_config block: engine-side expert-parallel truth
        # (mesh axis, metrics schema, wire model). The MODEL is built
        # separately (TransformerConfig.moe) — the train step validates
        # at trace time that a configured block actually has an MoE
        # model behind it.
        self._moe = self.config.moe_config \
            if self.config.moe_config.num_experts > 0 else None
        if self._moe is not None and \
                self._moe.expert_parallel_size != self.ep_size:
            raise ValueError(
                f"moe.expert_parallel_size={self._moe.expert_parallel_size}"
                f" but the mesh '{EP_AXIS}' axis has size {self.ep_size} —"
                " build the mesh with build_mesh(ep=...) to match")
        self._dcn_compression = bool(
            self.config.zero_config.dcn_compression)
        self._validate_engine_config()

        self.loss_fn, init_params = self._normalize_model(model, model_params)
        self.module = model  # reference-API alias

        # Precision: fp32 master weights; compute dtype per config.
        if self.config.bf16_enabled:
            self.compute_dtype = jnp.bfloat16
        elif self.config.fp16_enabled:
            self.compute_dtype = jnp.float16
        else:
            self.compute_dtype = jnp.float32
        # Master-free bf16 (bf16.stochastic_rounding): params live in bf16
        # — no fp32 master copy at all, halving param-state HBM — and the
        # optimizer apply rounds stochastically (unbiased), which is what
        # keeps sub-ulp updates from being systematically dropped
        # (reference stochastic_mode, ops/transformer/transformer.py:
        # 39-151; ops/stochastic_rounding.py here).
        self._master_free = bool(self.config.bf16_stochastic_rounding)
        master_params = _cast_floats(
            init_params,
            jnp.bfloat16 if self._master_free else jnp.float32)

        # LR schedule: config scheduler (pure fn of step) or client scheduler.
        self.lr_scheduler = None
        self._schedule_fn = None
        base_lr = float(self.config.optimizer_params.get("lr", 1e-3)) \
            if self.config.optimizer_params else 1e-3
        if lr_scheduler is not None:
            self.lr_scheduler = lr_scheduler
            self._schedule_fn = lr_scheduler.as_schedule_fn() \
                if hasattr(lr_scheduler, "as_schedule_fn") else lr_scheduler
        elif self.config.scheduler_name is not None:
            self.lr_scheduler = get_lr_schedule(self.config.scheduler_name,
                                                dict(self.config.scheduler_params))
            self._schedule_fn = self.lr_scheduler.as_schedule_fn()
        if self._schedule_fn is None:
            self._schedule_fn = lambda step: jnp.asarray(base_lr, jnp.float32)

        # Optimizer (selection matrix parity, engine.py:588-628).
        self.client_optimizer = optimizer
        self._onebit = (optimizer is None and
                        (self.config.optimizer_name or "").lower() ==
                        C.ONEBIT_ADAM_OPTIMIZER)
        # Persistent compute-dtype param cache (EngineState.cast_params):
        # only the main train-step path consumes it; the offload/onebit/
        # sparse paths cast inside their own programs, and fp32 compute
        # needs no cast at all.
        self._use_cast_cache = (
            self.compute_dtype != jnp.float32 and not self._onebit and
            not self.config.zero_config.cpu_offload and
            not self.config.sparse_gradients_enabled and
            not self._master_free and   # params already ARE compute dtype
            # Stage 3 with live dp: a replicated compute-dtype param
            # cache would defeat the sharded-param memory story; the
            # per-layer gather casts the master SHARD instead (1/dp of
            # the cast work, compute-dtype wire — stage3.gather_cast).
            # dp=1 stage-3 configs keep the cache: nothing is sharded
            # there, so losing it would just re-cast the tree per step.
            not (self.zero_optimization_stage() >= 3 and self.dp_size > 1))
        if self._master_free and (
                self._onebit or self.config.zero_config.cpu_offload or
                self.config.sparse_gradients_enabled):
            raise ValueError(
                "bf16.stochastic_rounding (master-free mode) composes with "
                "the main train path only — onebit/offload/sparse_gradients "
                "keep their own master-weight story")
        if self._onebit:
            if self.zero_optimization_stage() >= 1:
                raise ValueError(
                    "OnebitAdam composes with ZeRO stage 0 only (reference: "
                    "it is an fp16-wrapper-level optimizer, not a ZeRO one)")
            # fp16 composes: the loss-scale machinery (static or dynamic)
            # runs through BOTH phases, like the reference's OnebitAdam
            # which keeps overflow checks during compression
            # (onebit_adam.py:104-228). Overflow skips the step without
            # committing error feedback (ops/onebit.py).
            if param_shardings is not None:
                raise NotImplementedError(
                    "OnebitAdam + tensor-parallel param_shardings: the "
                    "compressed step runs params replicated over dp; "
                    "combining with a TP layout would silently all-gather "
                    "every step")
        self.tx = self._configure_optimizer(optimizer)
        if getattr(self.tx, "fused_apply", None) is not None and \
                param_shardings is not None and optimizer is None:
            # Fused apply flattens leaves into contiguous chunk buffers,
            # which would silently all-gather TP-sharded params every
            # step — fall back to the per-leaf optax chain there (parity
            # holds everywhere the fused path stays on).
            logger.info("optimizer.params.fused: disabled under tensor-"
                        "parallel param_shardings (flattened chunks do not "
                        "compose with TP layouts); using the optax apply")
            fallback = dict(self.config.optimizer_params or {})
            fallback[C.OPTIMIZER_FUSED] = False
            self.tx = build_optimizer(
                self.config.optimizer_name or C.ADAM_OPTIMIZER, fallback,
                self._schedule_fn)
        self._fused_apply = getattr(self.tx, "fused_apply", None)
        # One-pass clipped update (ops/fused_update.fused_step): the
        # global-norm reduction, fp16 unscale, overflow vote+skip, clip,
        # and the compute-dtype cast-cache refresh all ride the single
        # HBM pass over optimizer state — param/m/v are read exactly
        # once per step. None => the historical two-pass sequencing
        # (separate norm read before the fused apply).
        self._fused_step = getattr(self.tx, "fused_step", None)

        # ZeRO-Offload: masters + moments live in host RAM, updated by the
        # C++ SIMD Adam; the device holds ONLY compute-dtype params and
        # zero bytes of optimizer state (stage2.py:775-873 parity).
        scaler_cfg = self._loss_scaler_config()
        self._offload: Optional["ZeroOffloadOptimizer"] = None
        if self.config.zero_config.cpu_offload and \
                self.zero_optimization_stage() >= 1:
            from .zero.offload import ZeroOffloadOptimizer
            procs = jax.process_count()
            part_kwargs = {}
            if procs > 1:
                # Multi-host: each process owns host partition
                # process_index/process_count of the masters + moments
                # (reference stage2.py:775-873 each-rank-updates-its-
                # partition). The partition axis follows the dp shard rule
                # (axis_divisor=dp) so it is the same axis the device grads
                # are sharded on; grads/params are explicitly repartitioned
                # to process-local shardings around the host step
                # (_offload_partition_shardings), so no assumption about
                # device order is needed. The clip norm is allreduced
                # across processes via the host channel.
                divisor = self.dp_size if self.dp_size % procs == 0 \
                    else procs
                part_kwargs = dict(
                    partition_rank=jax.process_index(),
                    partition_num=procs, axis_divisor=divisor,
                    sumsq_allreduce=comm.host_allreduce_sum)
            if self._direct_grads_fn is not None:
                # train_batch routes offload configs to the offload grad
                # pass (its own autodiff) — a direct-grads model would be
                # silently ignored, not composed.
                raise ValueError(
                    "pipeline.schedule='1f1b' does not compose with "
                    "zero_optimization.cpu_offload: the offload path "
                    "computes grads via its own autodiff pass (use the "
                    "gpipe schedule)")
            self._offload = ZeroOffloadOptimizer(
                master_params, self.config.optimizer_name,
                dict(self.config.optimizer_params or {}), self._schedule_fn,
                self.compute_dtype,
                gradient_clipping=self.gradient_clipping(),
                fp16=self.config.fp16_enabled, scaler_cfg=scaler_cfg,
                bucket_bytes=self.config.zero_config.offload_bucket_size,
                host_threads=self.config.zero_config.offload_host_threads,
                **part_kwargs)
            # overlap_comm selects the bucketed overlapped pipeline (D2H /
            # host Adam / H2D streamed per bucket through the worker pool).
            # Multi-host keeps the serial path: its D2H/H2D go through
            # whole-tree XLA reshards (_local_offload_grads /
            # _assemble_offload_params), which have no per-bucket handle.
            self._offload_overlap = bool(
                self.config.zero_config.overlap_comm)
            if self._offload_overlap and procs > 1:
                log_dist("zero_optimization.overlap_comm: overlapped "
                         "offload is single-process only for now; "
                         "falling back to the serial offload step",
                         ranks=[0])
                self._offload_overlap = False
            self._offload_down = None   # lazy per-leaf process shardings
            self._offload_down_fn = None
            self._offload_up_fn = None
            self._offload_param_shardings = None  # lazy flat leaf shardings
            # device params = compute-dtype cast; no device moments at all.
            # (Multi-host: master_tree() is partition-local — keep the full
            # init params for the replicated device state; the per-step
            # H2D path assembles from partitions thereafter.)
            if self._offload.partition_num == 1:
                master_params = self._offload.master_tree()

        # State. The optimizer state is *born sharded*: its structure comes
        # from eval_shape (zero bytes), the shardings are computed from that,
        # and tx.init runs inside a jit with out_shardings — at no point do
        # two full copies of the moments exist (a doubled fp32 Adam state
        # for a 774M model is 12 GB and OOMs the init on one chip).
        self._static_loss_scale = scaler_cfg["static"]
        self._scale_window = scaler_cfg["scale_window"]
        self._min_scale = scaler_cfg["min_scale"]
        self._hysteresis = scaler_cfg["hysteresis"]
        # The shared overflow-resolution config every step builder closes
        # over (one source of truth for _overflow_resolution).
        self._scaler_kw = dict(
            fp16=self.config.fp16_enabled,
            static_scale=self._static_loss_scale,
            scale_window=self._scale_window, min_scale=self._min_scale,
            hysteresis_init=self._hysteresis)
        init_scale = scaler_cfg["init_scale"]
        hysteresis = scaler_cfg["hysteresis"]
        device_params = master_params if self._offload is None \
            else _cast_floats(master_params, self.compute_dtype)
        if self._offload is not None:
            opt_init = None
        elif self._onebit:
            from ..ops.onebit import init_state as onebit_init
            dp_ = self.dp_size

            def opt_init(params):
                # worker_error carries a leading [dp] axis (dp-sharded in
                # _make_state_shardings): it is genuinely PER-RANK state, so
                # declaring it replicated would save/restore only rank 0's
                # error feedback across checkpoints.
                st = onebit_init(params)
                werr = jax.tree_util.tree_map(
                    lambda p: jnp.zeros((dp_,) + p.shape, jnp.float32),
                    params)
                return st._replace(worker_error=werr)
        elif self._master_free:
            # bf16 params but f32 optimizer moments: init from an f32 view
            # so Adam's accumulators don't inherit the bf16 storage dtype
            # (updates then stay f32 end-to-end; only the final apply
            # rounds, stochastically).
            base_opt_init = self.tx.init
            opt_init = lambda params: base_opt_init(
                _cast_floats(params, jnp.float32))
        else:
            opt_init = self.tx.init
        opt_shape = () if opt_init is None \
            else jax.eval_shape(opt_init, device_params)
        self._param_specs = param_shardings
        # ZeRO-3: the parameter tree itself is born dp-sharded (same
        # first-divisible-dim rule as grads and moments — element
        # alignment keeps the optimizer apply shard-local). Leaves the
        # model gathers itself per layer (zero3_scan.covers) keep their
        # layer axis (dim 0) unsharded so per-layer slices stay
        # dp-sharded inside the scan.
        self._zero3 = self.zero_optimization_stage() >= 3 \
            and self.dp_size > 1
        self._zero3_scan_spec = zero3_scan
        self._stage3_specs = None
        self._zero3_covered = None
        if self._zero3:
            from .zero.partition import stage3_param_specs
            covers = zero3_scan.covers if zero3_scan is not None else None
            self._stage3_specs = stage3_param_specs(
                device_params, self.dp_size, DP_AXIS,
                param_specs=self._param_specs, scan_paths=covers)
            flat, ptdef = jax.tree_util.tree_flatten_with_path(
                device_params)
            self._zero3_covered = jax.tree_util.tree_unflatten(
                ptdef, [covers(jax.tree_util.keystr(p)) if covers
                        else False for p, _ in flat])
        self._state_shardings = self._make_state_shardings(
            device_params, opt_shape)
        offload = self._offload is not None
        use_cast_cache = self._use_cast_cache
        compute_dtype = self.compute_dtype
        dcn_live = self._dcn_compression and self.slice_size > 1
        n_slices = self.slice_size

        def _init_state(params):
            return EngineState(
                step=jnp.asarray(0, jnp.int32),
                params=params,
                opt_state=() if offload else opt_init(params),
                loss_scale=jnp.asarray(init_scale, jnp.float32),
                growth_count=jnp.asarray(0, jnp.int32),
                hysteresis=jnp.asarray(hysteresis, jnp.int32),
                skipped_steps=jnp.asarray(0, jnp.int32),
                cast_params=_cast_floats(params, compute_dtype)
                if use_cast_cache else None,
                dcn_error=jax.tree_util.tree_map(
                    lambda p: jnp.zeros(
                        (n_slices,) + tuple(getattr(p, "shape", ())),
                        jnp.float32), params) if dcn_live else None,
            )

        self.state = jax.jit(
            _init_state, out_shardings=self._state_shardings)(
            jax.tree_util.tree_map(jnp.asarray, device_params))

        # Host-side counters (reference engine.py:151-158).
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.skipped_steps = 0

        # RNG.
        self._base_rng = rng if rng is not None else jax.random.PRNGKey(42)

        # Data.
        self.collate_fn = collate_fn
        self.training_dataloader = self.deepspeed_io(training_data) \
            if training_data is not None else None
        self._data_iterator = None

        # PLD (reference engine.py:826-827 injects theta into every
        # forward). Detect once whether the loss_fn can consume it; every
        # grad-computing path (train step, offload, onebit, fwd/bwd split)
        # threads theta when it can.
        self.progressive_layer_drop = None
        self._accepts_pld = False
        if self.config.pld_config.enabled:
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=self.config.pld_config.theta,
                gamma=self.config.pld_config.gamma)
            import inspect
            try:
                self._accepts_pld = "pld_theta" in \
                    inspect.signature(self.loss_fn).parameters
            except (TypeError, ValueError):
                self._accepts_pld = False
            if not self._accepts_pld:
                logger.warning("progressive_layer_drop enabled but the "
                               "model's loss_fn takes no pld_theta kwarg — "
                               "layers will not drop")

        # Flops profiler (reference engine.py:801-824 auto-run window):
        # profiled once, analytically, at the configured global step.
        self.flops_profiler = None
        if self.config.flops_profiler_config.enabled:
            from ..profiling.flops_profiler import FlopsProfiler
            self.flops_profiler = FlopsProfiler(
                config=self.config.flops_profiler_config)

        # Observability.
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_micro_batch_size_per_gpu() *
            self.replica_size,
            start_step=2, steps_per_output=self.steps_per_print(),
            synchronized=self.wall_clock_breakdown())

        # Grad buffer for the forward/backward/step compatibility API.
        self._accum_grads = None
        self._stashed_batch = None

        # Jitted paths (built lazily on first use).
        self._train_step_fn = None
        self._eval_step_fn = None
        self._apply_grads_fn = None
        self._sparse_grad_fn = None
        self._sparse_apply_fn = None

        # Sparse (CSR) embedding gradients (reference engine.py:179-186
        # detects torch.nn.Embedding modules; :1197-1253 routes their grads
        # through a values+indices allgather instead of dense allreduce).
        self._sparse_mask = None
        self._sparse_names: List[str] = []
        self.sparse_comm_stats: Dict[str, int] = {}
        if self.config.sparse_gradients_enabled:
            self._init_sparse_gradients(sparse_grad_filter)
        self._grad_step_fn = None
        self._offload_grad_fn = None
        self.offload_timings = None   # last step's device/D2H/host breakdown

        # ZeRO-2 gradient-sync honesty: resolve which lowering this engine
        # actually runs (audited, not assumed) and say so — with the wire
        # bytes each lowering costs per step — instead of treating
        # reduce_scatter/overlap_comm as docstring-advisory knobs.
        self._grad_sync_mode = self._resolve_grad_sync()
        self._prefetch_depth = int(self.config.zero_config.prefetch_depth)
        if self._zero3 and zero3_scan is not None:
            self._bind_zero3_scan(zero3_scan)
        # MoE all-to-all pricing needs the per-device token count, which
        # only the first batch reveals (_maybe_refresh_moe_wire).
        self._moe_tokens_per_device = None
        if self._moe is not None and self.ep_size > 1 and \
                self._param_specs is None:
            logger.warning(
                "moe.expert_parallel_size > 1 without param_shardings: "
                "expert weights stay replicated on every device — pass "
                "deepspeed_tpu.moe.sharding specs (e.g. "
                "gpt2_moe_param_shardings) to born-shard them over the "
                "expert axis")
        self._wire_bytes, self._wire_detail = self._grad_wire_bytes()
        self._log_comm_plan()

        # Telemetry (monitor/): per-step records + spans + recompile
        # sentinel + memory watermarks. Inert when disabled; when enabled,
        # all device access is batched at report boundaries (zero added
        # hot-path syncs — the _maybe_log discipline, subsystem-wide).
        self.telemetry = Telemetry(
            self.config.telemetry_config,
            default_report_steps=self.steps_per_print(),
            meta=dict(
                dp=self.dp_size,
                ep=self.ep_size,
                slices=self.slice_size,
                zero_stage=self.zero_optimization_stage(),
                precision=self.config.precision_dtype,
                cpu_offload=self._offload is not None,
                grad_sync_mode=self._grad_sync_mode,
                wire_bytes_per_step=self._wire_bytes,
                wire_bytes_ici=self._wire_bytes - self._wire_bytes_dcn,
                wire_bytes_dcn=self._wire_bytes_dcn,
                dcn_compression=self._dcn_compression,
                wire_terms=self._wire_terms(),
                wire_detail=self._wire_detail,
                train_batch_size=self.train_batch_size(),
                gradient_accumulation_steps=
                self.gradient_accumulation_steps(),
                **({"moe": dict(
                    num_experts=self._moe.num_experts,
                    top_k=self._moe.top_k,
                    capacity_factor=self._moe.capacity_factor,
                    expert_parallel_size=self.ep_size)}
                   if self._moe is not None else {})))
        # Weakref, not a bound closure: the Telemetry outlives engines via
        # its atexit flush hook, and a strong closure here would pin the
        # engine's entire device state for process lifetime.
        import weakref
        _engine_ref = weakref.ref(self)
        self.telemetry.step_provider = lambda: (
            _engine_ref().global_steps if _engine_ref() is not None else -1)
        # Analytic per-device model-state footprint from the committed
        # shardings (host metadata only) — the watermark baseline. Under
        # stage 3 the params price at their dp-shard (the shardings say
        # so) and the bounded gather working set is ADDED: a healthy
        # stage-3 step legitimately holds prefetch_depth+1 gathered
        # layers (or the compute-dtype leaf-at-use set on generic
        # models) on top of the resident state.
        gather_ws = 0
        if self._zero3:
            from .zero.stage3 import gather_working_set_bytes
            _spec = self._zero3_scan_spec
            gather_ws = gather_working_set_bytes(
                self.state.params, self._stage3_specs, DP_AXIS,
                jnp.dtype(self.compute_dtype).itemsize,
                prefetch_depth=self._prefetch_depth,
                scan_paths=_spec.covers if _spec is not None else None,
                mesh=self.mesh)
            self.telemetry.meta["zero3_prefetch_depth"] = \
                self._prefetch_depth
            self.telemetry.meta["zero3_gather_working_set_bytes"] = \
                int(gather_ws)
        self.telemetry.set_analytic_footprint(
            analytic_state_bytes(self.state,
                                 gather_working_set=gather_ws))
        # Roofline cost model: built ONCE at the first report boundary
        # (every active step path has compiled by then); see
        # _maybe_build_cost_model.
        self._cost_model_built = False

        # Health taps (monitor/health.py): the step programs return one
        # [num_leaves] f32 array of per-leaf grad sum-of-squares that
        # rides the telemetry ring to the batched drain fetch — NaN/Inf
        # provenance (first non-finite leaf + layer) with zero added
        # device syncs. The TapSpec decoding it is host metadata from
        # the params tree.
        self._health_tap_fn = None
        hcfg = getattr(self.config.telemetry_config, "health", None)
        if self.telemetry.enabled and self.telemetry.health is not None \
                and hcfg is not None and hcfg.grad_taps:
            from ..monitor.health import TapSpec, leaf_sq_taps
            self.telemetry.set_tap_spec(TapSpec.from_tree(
                self.state.params))
            self._health_tap_fn = leaf_sq_taps

        # Async / preemption-safe checkpointing (runtime/async_ckpt.py):
        # the writer thread, the auto-save cadence, and the SIGTERM
        # final-save handler. All inert unless the `checkpoint` config
        # block opts in.
        ckcfg = self.config.checkpoint_config
        self._ckpt_dir = ckcfg.save_dir
        self._ckpt_every = int(ckcfg.snapshot_every)
        self._ckpt_max_pending = int(ckcfg.max_pending_snapshots)
        self._ckpt_writer_timeout = float(ckcfg.writer_timeout_s)
        self._ckpt_fsync = bool(ckcfg.fsync)
        self._last_saved_step = -1
        self._async_ckpt = None
        self._preempt_saver = None
        if ckcfg.async_save:
            self._async_ckpt = AsyncCheckpointer(
                telemetry=self.telemetry,
                writer_timeout_s=self._ckpt_writer_timeout,
                dump_dir=self.config.telemetry_config.output_path
                or "./runs")
        if self._ckpt_dir and ckcfg.preempt_save:
            # Installed AFTER Telemetry built its flight recorder: on
            # SIGTERM this handler runs FIRST (last installed wins),
            # commits the final checkpoint, then chains to the flight
            # recorder's handler — which persists FLIGHT.json and
            # re-raises so the exit code stays honest.
            self._preempt_saver = PreemptSaver(self, self._ckpt_dir)
            self._preempt_saver.install()
        if ckcfg.async_save or self._ckpt_every > 0:
            self.telemetry.meta.setdefault("checkpoint", {
                "async": bool(ckcfg.async_save),
                "snapshot_every": self._ckpt_every})

        log_dist(f"DeepSpeedEngine initialized: dp={self.dp_size}, "
                 f"dtype={self.compute_dtype.__name__}, "
                 f"zero_stage={self.zero_optimization_stage()}", ranks=[0])

    # ------------------------------------------------------------------ #
    # Construction helpers
    # ------------------------------------------------------------------ #
    def _build_mesh(self, config) -> Mesh:
        mp = pp = sp = ep = slices = 1
        if isinstance(config, str):
            from .config_utils import load_config_json
            config = load_config_json(config)
        if isinstance(config, DeepSpeedConfig):
            mc = config.mesh_config
            mp, pp, sp = (mc.model_parallel_size or 1, mc.pipe_parallel_size or 1,
                          mc.sequence_parallel_size or 1)
            ep = config.moe_config.expert_parallel_size or 1
            slices = mc.num_slices or 1
        elif isinstance(config, dict):
            mesh_cfg = config.get(C.MESH, {})
            mp = mesh_cfg.get(C.MESH_MODEL_PARALLEL_SIZE, 1) or 1
            pp = mesh_cfg.get(C.MESH_PIPE_PARALLEL_SIZE, 1) or 1
            sp = mesh_cfg.get(C.MESH_SEQUENCE_PARALLEL_SIZE, 1) or 1
            ep = config.get(C.MOE, {}).get(
                C.MOE_EXPERT_PARALLEL_SIZE, 1) or 1
            slices = mesh_cfg.get(C.MESH_NUM_SLICES, 1) or 1
        return build_mesh(mp=mp, pp=pp, sp=sp, ep=ep, slices=slices)

    def _validate_engine_config(self) -> None:
        # Stage 3 (parameter partitioning) goes PAST the reference, which
        # raises for any stage > 2 (engine.py:707-708). Composition
        # limits: the 1F1B pipeline computes grads inside its own primal
        # scan and cannot thread the per-layer gather/scatter schedule.
        if self.config.zero_optimization_stage >= 3 and \
                self._direct_grads_fn is not None:
            raise ValueError(
                "ZeRO stage 3 does not compose with pipeline grads_fn "
                "(1F1B computes grads inside its own primal scan); use "
                "stage <= 2 with the pipeline engine")
        if self.ep_size > 1:
            # Expert parallelism composes with the MAIN train path: the
            # paths below run their own shard_maps/autodiff over `data`
            # only and would silently mis-shard the (expert, data) batch.
            blockers = []
            if self._direct_grads_fn is not None:
                blockers.append("pipeline grads_fn (1F1B)")
            if self.config.zero_config.cpu_offload:
                blockers.append("zero_optimization.cpu_offload")
            if self.config.sparse_gradients_enabled:
                blockers.append("sparse_gradients")
            if (self.config.optimizer_name or "").lower() == \
                    C.ONEBIT_ADAM_OPTIMIZER:
                blockers.append("OnebitAdam")
            if blockers:
                raise ValueError(
                    "moe expert_parallel_size > 1 composes with the main "
                    f"train path only; drop {', '.join(blockers)}")
        if self.slice_size > 1:
            # Multi-slice scale-out composes with the MAIN train path on
            # a (slice, data) mesh under ZeRO stage >= 2 (stages 2 AND
            # 3: the axis-algebra planner places the stage-3 param
            # gathers on `data`/ICI and only the 1/dp residual on DCN).
            # Each remaining refusal is the planner-derived reason: the
            # hierarchical sync's DCN saving IS the in-slice reduce-
            # scatter (dense modes would ship grad-sized trees over
            # DCN), and every other path computes grads without the
            # slice axis in scope (silently missing the inter-slice
            # reduction entirely).
            from ..parallel.axis_algebra import MeshFactorization
            blockers = []
            if self.zero_optimization_stage() < 2:
                blockers.append("zero_optimization.stage >= 2 (got "
                                f"{self.zero_optimization_stage()}; the "
                                "planner's in-slice tier is a reduce-"
                                "scatter — dense grads have no 1/dp "
                                "residual to confine to DCN)")
            if not self.config.zero_config.reduce_scatter:
                blockers.append("reduce_scatter: true")
            try:
                MeshFactorization.from_mesh(self.mesh).outer_axis
            except ValueError as e:
                # slice x expert: the planner supports one outer
                # residual axis — quote its reason verbatim.
                blockers.append(f"expert_parallel_size == 1 ({e})")
            if self._direct_grads_fn is not None:
                blockers.append("no pipeline grads_fn (1F1B)")
            if self.config.zero_config.cpu_offload:
                blockers.append("no zero_optimization.cpu_offload")
            if self.config.sparse_gradients_enabled:
                blockers.append("no sparse_gradients")
            if (self.config.optimizer_name or "").lower() == \
                    C.ONEBIT_ADAM_OPTIMIZER:
                blockers.append("no OnebitAdam (dcn_compression is the "
                                "multislice home of the 1-bit wire)")
            # param_shardings (TP layouts) are re-checked when the grad
            # sync resolves — _param_specs is bound after this runs.
            if getattr(self, "_param_specs", None) is not None:
                blockers.append("no tensor-parallel param_shardings")
            for ax, size in self.mesh.shape.items():
                if ax not in (SLICE_AXIS, DP_AXIS) and int(size) > 1:
                    blockers.append(f"'{ax}' axis of size 1 (got {size})")
            if blockers:
                raise ValueError(
                    f"mesh slices={self.slice_size} (hierarchical "
                    "ICI/DCN gradient sync) requires: "
                    + "; ".join(blockers))
        if self._dcn_compression and self.slice_size <= 1:
            raise ValueError(
                "zero_optimization.dcn_compression requires a multi-"
                "slice mesh (mesh.slices > 1 / build_mesh(slices=...)): "
                "there is no DCN hop to compress on a single slice")

    def _normalize_model(self, model, model_params) -> Tuple[Callable, Any]:
        """Accept a flax module or a loss callable; return loss_fn(params,
        batch, rng) -> loss | (loss, aux) plus initial params."""
        if model is None:
            raise ValueError("deepspeed_tpu requires a model (flax module or "
                             "loss_fn(params, batch, rng))")
        if hasattr(model, "apply") and hasattr(model, "init"):
            if model_params is None:
                raise ValueError("Pass model_params=module.init(...) for flax modules")

            def loss_fn(params, batch, rng):
                inputs = batch if isinstance(batch, (tuple, list)) else (batch,)
                # flax ignores rng collections the module doesn't use.
                return model.apply(params, *inputs, rngs={"dropout": rng})
            return loss_fn, model_params
        if callable(model):
            if model_params is None:
                raise ValueError("Pass model_params with a callable loss_fn model")
            return model, model_params
        raise TypeError(f"Unsupported model type {type(model)}")

    def _configure_optimizer(self, client_optimizer):
        import optax
        if client_optimizer is not None:
            if isinstance(client_optimizer, optax.GradientTransformation):
                return client_optimizer
            if callable(client_optimizer):
                return client_optimizer(self._schedule_fn)
            raise TypeError("optimizer must be an optax.GradientTransformation "
                            "or callable(schedule_fn) -> transformation")
        name = self.config.optimizer_name or C.ADAM_OPTIMIZER
        # ZeRO-shard-local fused apply: on a pure-dp mesh with sharded
        # optimizer state, the fused kernels run under shard_map over dp
        # so the moments are never gathered (each device updates exactly
        # its ZeRO shard). Meshes with live pipe/seq/model axes keep the
        # plain lowering (partial-auto shard_map is outside this jax's
        # capability envelope — tests/capability.py).
        mesh_kw = dict(mesh=self.mesh, shard_axis=DP_AXIS) \
            if self._fused_shard_local() else {}
        return build_optimizer(name, dict(self.config.optimizer_params or {}),
                               self._schedule_fn, **mesh_kw)

    def _fused_shard_local(self) -> bool:
        """True when the fused optimizer kernels run shard-local over dp
        (pure-dp mesh, ZeRO state sharded). The ONE predicate both the
        optimizer construction and the roofline's optimizer_apply
        pricing use — they must agree or the per-device byte figures
        lie."""
        return (self.zero_optimization_stage() >= 1 and self.dp_size > 1
                and all(int(s) == 1 for a, s in self.mesh.shape.items()
                        if a != DP_AXIS))

    def _loss_scaler_config(self) -> Dict[str, Any]:
        cfg = self.config
        if cfg.fp16_enabled:
            if cfg.fp16_loss_scale and cfg.fp16_loss_scale > 0:
                return dict(static=True, init_scale=float(cfg.fp16_loss_scale),
                            scale_window=cfg.fp16_loss_scale_window,
                            min_scale=float(cfg.fp16_min_loss_scale),
                            hysteresis=cfg.fp16_hysteresis)
            return dict(static=False, init_scale=2.0 ** cfg.fp16_initial_scale_power,
                        scale_window=cfg.fp16_loss_scale_window,
                        min_scale=float(cfg.fp16_min_loss_scale),
                        hysteresis=cfg.fp16_hysteresis)
        return dict(static=True, init_scale=1.0, scale_window=1000,
                    min_scale=1.0, hysteresis=2)

    def _resolve_grad_sync(self) -> str:
        """Which ZeRO-2 gradient-sync lowering this engine runs:

        - ``"none"``: stage < 2 or dp == 1 — nothing to scatter;
        - ``"allreduce"``: ``reduce_scatter: false`` — the dense all-reduce
          path (grads stay replicated, reference semantics);
        - ``"declarative"``: declared grad shardings, GSPMD lowers;
        - ``"explicit"``: grads computed under shard_map with
          ``lax.psum_scatter`` — the lowering is guaranteed by
          construction.

        ``grad_sync: auto`` (default) audits the declarative lowering via
        the hlo_audit probe and goes explicit iff the partitioner falls
        back to a full all-reduce + slice (the known declarative-ZeRO
        failure mode: grads materialize unpartitioned, 2x the wire).
        """
        zc = self.config.zero_config
        if self.zero_optimization_stage() < 2 or self.dp_size <= 1:
            return "none"
        if not zc.reduce_scatter:
            return "allreduce"
        # The explicit path wraps the grad computation in a fully-manual
        # shard_map over the REPLICA axes — plain dp, or the factored
        # (slice, data) / (expert, data) meshes (each leaf psum_scatters
        # over `data`, then the residual all-reduces over the outer
        # axis: the hierarchical DCN hop / the cross-expert-group dense
        # sync). Paths with their own grad programs (1F1B direct grads,
        # onebit, sparse-CSR) and meshes with additional live axes
        # (TP/PP/SP, where replica-manual + rest-auto is a partial-auto
        # shard_map) keep the declarative constraint. param_shardings
        # compose iff every spec is expert-only (the MoE layout — the
        # factored path slices those at the shard_map boundary); TP
        # layouts do not. The offload grad pass routes through the same
        # explicit builder since stage 3 landed (its bucket regroup
        # happens OUTSIDE the shard_map) — this is what retired the last
        # lint waiver (collective_placement:offload_grad_step:
        # grad-allreduce).
        replica_axes = (DP_AXIS, SLICE_AXIS, EP_AXIS)
        specs_ok = self._param_specs is None
        if not specs_ok and self.ep_size > 1:
            from ..moe.sharding import is_expert_spec

            def spec_manual_ok(sp) -> bool:
                if not isinstance(sp, P):
                    return False
                if is_expert_spec(sp):
                    return True
                # Entries over size-1 mesh axes are no-op shardings (the
                # gpt2 TP specs name `model` even on an mp=1 mesh).
                for entry in sp:
                    for ax in ((entry,) if isinstance(entry, str)
                               else (entry or ())):
                        if int(self.mesh.shape.get(ax, 1)) > 1:
                            return False
                return True

            spec_leaves = jax.tree_util.tree_leaves(
                self._param_specs, is_leaf=lambda x: isinstance(x, P))
            specs_ok = all(spec_manual_ok(sp) for sp in spec_leaves)
        explicit_ok = (
            specs_ok and not self._onebit
            and not self.config.sparse_gradients_enabled
            and self._direct_grads_fn is None
            and all(int(self.mesh.shape[a]) == 1
                    for a in self.mesh.axis_names
                    if a not in replica_axes))
        mode = zc.grad_sync
        if self.slice_size > 1:
            # Hierarchical sync EXISTS only on the explicit path (a
            # declarative lowering would emit whatever flat collective
            # GSPMD picks over the joint axes — grad-sized DCN traffic).
            if mode == "declarative" or not explicit_ok:
                raise ValueError(
                    "a multi-slice mesh (slices > 1) requires the "
                    "explicit hierarchical gradient path: set "
                    "zero_optimization.grad_sync to 'auto' or "
                    "'explicit' on a (slice, data) mesh with the main "
                    "train/offload path")
            return "explicit"
        if mode == "explicit":
            if not explicit_ok:
                raise ValueError(
                    "zero_optimization.grad_sync='explicit' supports the "
                    "main train and offload paths on a pure-dp (or "
                    "slice/expert-factored) mesh only (no TP/PP/SP axes, "
                    "onebit, sparse_gradients, or pipeline grads_fn) — "
                    "use 'auto' or 'declarative'")
            return "explicit"
        if mode == "declarative" or not explicit_ok:
            return "declarative"
        if self.ep_size > 1:
            # The declarative lowering for the (expert, data)-sharded
            # batch regresses to all-reduce + slice on this backend
            # (audited in COMM_AUDIT.json's moe flagship history) — the
            # factored explicit path closes it; no probe needed.
            return "explicit"
        from ..parallel import hlo_audit
        lowering = hlo_audit.zero2_grad_sync_lowering(self.mesh, DP_AXIS)
        return "declarative" if lowering == "reduce-scatter" else "explicit"

    def _grad_wire_bytes(self) -> Tuple[int, str]:
        """(analytic wire bytes/step, detail) for the RESOLVED gradient
        sync — the PR-3 wire model priced at the lowering this engine
        actually runs. One source of truth for the init log, the
        telemetry meta/records, and bench's dp_comm provenance."""
        self._wire_model = None
        # Two-tier split: everything is ICI wire except the inter-slice
        # hop of the hierarchical multislice sync (the only collective
        # in-tree that rides DCN).
        self._wire_bytes_dcn = 0
        if self.replica_size <= 1:
            return 0, "single replica (no gradient sync)"
        from ..parallel import hlo_audit
        if self.slice_size > 1:
            gas = self._scan_microbatches()
            zero3_kw = {}
            if self._zero3:
                zero3_kw = dict(
                    zero3=True,
                    param_bytes_per_el=jnp.dtype(
                        self.compute_dtype).itemsize,
                    gas=gas, param_specs=self._stage3_specs,
                    mesh=self.mesh)
            model = hlo_audit.grad_sync_wire_model(
                self.state.params, self.dp_size, slices=self.slice_size,
                dcn_compression=self._dcn_compression, **zero3_kw)
            self._wire_model = model
            dcn = model["dcn_wire_bytes_compressed"] \
                if self._dcn_compression else model["dcn_wire_bytes"]
            self._wire_bytes_dcn = int(dcn)
            # The tiers are per-STEP in the same units: the in-slice
            # collectives run once per micro-step inside the gas scan
            # (x gas), the DCN hop once per step on the accumulated
            # shard — summing a per-micro ICI term with a per-step DCN
            # term would misreport which tier binds. Under stage 3 the
            # ici term already includes both param gathers (the planner
            # binds them to `data`: ICI on every factorization).
            ici = int(model["ici_wire_bytes"]) * int(gas)
            comp = (" 1-bit-compressed (packed sign bits + per-chunk "
                    "scales — the DCN wire format; the emulation psums "
                    "decompressed values)") if self._dcn_compression \
                else ""
            z3 = (f" + 2 in-slice param gathers/micro-step "
                  f"({jnp.dtype(self.compute_dtype).name} wire, zero "
                  f"param bytes on DCN)") if self._zero3 else ""
            return int(ici + dcn), \
                (f"hierarchical {self._grad_sync_mode}: in-slice "
                 f"reduce-scatter over ICI (dp={self.dp_size}, "
                 f"x{gas} micro-steps){z3} + inter-slice all-reduce "
                 f"over DCN (slices={self.slice_size}) of the 1/dp "
                 f"residual only{comp} — {int(dcn):,} DCN B/step vs "
                 f"{model['flat_dcn_link_bytes']:,} for a flat joint "
                 f"sync")
        if self.ep_size > 1:
            return self._moe_wire_bytes(hlo_audit)
        if self._sparse_mask is not None:
            # Sparse embedding grads travel the data-dependent CSR
            # exchange (volume ~ nnz_rows/vocab of dense; see
            # sparse_comm_stats) — pricing them at the dense model would
            # overstate wire by orders of magnitude. Model the dense
            # leaves only and say so.
            dense_leaves = [
                l for l, m in zip(
                    jax.tree_util.tree_leaves(self.state.params),
                    jax.tree_util.tree_leaves(self._sparse_mask)) if not m]
            model = hlo_audit.grad_sync_wire_model(dense_leaves,
                                                   self.dp_size)
            self._wire_model = model
            return model["all_reduce_wire_bytes"], \
                ("dense all-reduce over non-sparse leaves only (sparse "
                 "embedding grads use the data-dependent CSR exchange; "
                 "see sparse_comm_stats)")
        if self._zero3:
            # Stage 3: the grads reduce-scatter AND the params cross the
            # wire twice more (fwd gather + bwd re-gather) per
            # micro-step, at the compute dtype.
            model = hlo_audit.grad_sync_wire_model(
                self.state.params, self.dp_size, zero3=True,
                param_bytes_per_el=jnp.dtype(self.compute_dtype).itemsize,
                gas=self._scan_microbatches(),
                param_specs=self._stage3_specs, mesh=self.mesh)
            self._wire_model = model
            return model["zero3_wire_bytes"], \
                (f"{self._grad_sync_mode} ZeRO-3: per micro-step, "
                 f"2 param gathers "
                 f"({jnp.dtype(self.compute_dtype).name} wire) + f32 "
                 f"grad reduce-scatter — "
                 f"{model['param_gather_wire_bytes']:,} gather B/step")
        model = hlo_audit.grad_sync_wire_model(self.state.params,
                                               self.dp_size)
        self._wire_model = model
        if self.zero_optimization_stage() < 2:
            return model["all_reduce_wire_bytes"], \
                "dense all-reduce (grads replicated below ZeRO stage 2)"
        mode = self._grad_sync_mode
        if mode == "allreduce":
            return model["all_reduce_wire_bytes"], \
                "dense all-reduce (reduce_scatter: false)"
        declared = hlo_audit.zero2_grad_sync_lowering(self.mesh, DP_AXIS)
        if mode == "declarative" and declared == "all-reduce":
            # The user pinned the declarative path on a backend whose
            # partitioner regresses it: report the wire it actually
            # costs, not the wire the declaration hoped for.
            return model["all_reduce_wire_bytes"], \
                ("declarative — REGRESSED to all-reduce + slice "
                 "on this backend (grad_sync: auto or explicit "
                 "restores the reduce-scatter)")
        return model["reduce_scatter_wire_bytes"], \
            (f"{mode} reduce-scatter (declared sharding "
             f"lowers to {declared} on this backend)")

    def _wire_terms(self) -> Optional[Dict[str, Dict[str, Any]]]:
        """Per-TERM split of the analytic wire figure on a multi-slice
        mesh, each term tagged with the tier it rides (the planner's
        assignment): the in-scan grad reduce-scatter and — under stage 3
        — both param gathers on ICI, the once-per-step residual
        all-reduce on DCN. None on single-slice meshes (one tier, no
        split to report). Telemetry meta carries it so the roofline's
        comm_tiers can be decomposed per collective, not just per tier."""
        wm = self._wire_model
        if not isinstance(wm, dict) or "ici_wire_bytes" not in wm:
            return None
        gas = int(self._scan_microbatches())
        rs = int(wm["reduce_scatter_wire_bytes"]) * gas
        terms = {
            "grad_reduce_scatter": {"tier": "ici", "bytes": rs,
                                    "placement": "in-scan"},
            "inter_slice_residual": {"tier": "dcn",
                                     "bytes": int(self._wire_bytes_dcn),
                                     "placement": "per-step"},
        }
        gather = int(wm["ici_wire_bytes"]) * gas - rs
        if gather > 0:
            terms["param_gather"] = {"tier": "ici", "bytes": gather,
                                     "placement": "in-scan"}
        return terms

    def _moe_layer_info(self) -> Tuple[int, int]:
        """(n_moe_layers, hidden) read off the expert up-projection leaf
        (path ``moe_fc_kernel``, stacked [n_moe, E, H, F]); (0, 0) when
        the param tree carries none."""
        flat, _ = jax.tree_util.tree_flatten_with_path(self.state.params)
        for path, leaf in flat:
            if "moe_fc_kernel" in jax.tree_util.keystr(path) and \
                    getattr(leaf, "ndim", 0) == 4:
                return int(leaf.shape[0]), int(leaf.shape[2])
        return 0, 0

    def _moe_wire_bytes(self, hlo_audit) -> Tuple[int, str]:
        """Expert-parallel (ep > 1) wire model:

        - DENSE leaves sync over the full ep x dp replica set (under
          ZeRO >= 2: all-reduce across expert groups + reduce-scatter
          within data — the declared dp shard);
        - EXPERT leaves (param spec on the `expert` axis) all-reduce
          their 1/ep shard over `data` ONLY — the moe shard_map
          transpose's within-expert-group psum; they are never
          replicated across experts;
        - the dispatch/combine all-to-alls price per token
          (hlo_audit.moe_alltoall_wire_model); the exact per-step figure
          resolves at the first batch (_maybe_refresh_moe_wire), when
          the engine learns the token count.
        """
        from ..moe.sharding import is_expert_spec
        ring = hlo_audit.ring_wire_bytes
        leaves = jax.tree_util.tree_leaves(self.state.params)
        if self._param_specs is not None:
            spec_leaves = jax.tree_util.tree_structure(
                self.state.params).flatten_up_to(self._param_specs)
        else:
            spec_leaves = [P()] * len(leaves)
        mask = [isinstance(sp, P) and is_expert_spec(sp)
                for sp in spec_leaves]
        dense_leaves = [l for l, m in zip(leaves, mask) if not m]
        expert_full = sum(int(np.prod(l.shape)) * 4
                          for l, m in zip(leaves, mask)
                          if m and hasattr(l, "shape"))
        expert_local = expert_full // self.ep_size
        n_moe, hidden = self._moe_layer_info()
        moe_kw = dict(
            hidden=hidden, num_experts=self._moe.num_experts,
            top_k=self._moe.top_k,
            capacity_factor=self._moe.capacity_factor,
            ep=self.ep_size, n_moe_layers=max(1, n_moe),
            bytes_per_el=jnp.dtype(self.compute_dtype).itemsize,
            tokens_per_device=self._moe_tokens_per_device,
            gas=self._scan_microbatches())
        model = dict(hlo_audit.grad_sync_wire_model(
            dense_leaves, self.dp_size, moe=moe_kw))
        # Only the EXPLICIT factored path earns the hierarchical
        # pricing: RS over data per micro-step, then the cross-group
        # all-reduce carries the 1/dp RESIDUAL only (pricing it at full
        # size would overstate the expert hop dp x). A user-pinned
        # declarative stage-2 keeps the regressed full all-reduce
        # figure — that IS what it compiles to on this backend.
        stage2_rs = self.zero_optimization_stage() >= 2 and \
            self._grad_sync_mode == "explicit"
        if stage2_rs and self.dp_size > 1:
            dense_wire = (
                ring("reduce-scatter", model["scatterable_bytes"],
                     self.dp_size)
                + ring("all-reduce",
                       model["scatterable_bytes"] // self.dp_size,
                       self.ep_size)
                + ring("all-reduce", model["replicated_bytes"],
                       self.dp_size)
                + ring("all-reduce", model["replicated_bytes"],
                       self.ep_size))
            dense_note = (f"dense grads reduce-scatter over data "
                          f"({self.dp_size}) + all-reduce their 1/dp "
                          f"residual across expert groups "
                          f"({self.ep_size})")
        else:
            dense_wire = ring("all-reduce", model["grad_bytes"],
                              self.replica_size)
            dense_note = (f"dense grads all-reduce over expert x data "
                          f"({self.replica_size})")
        # Expert grads sync over data-within-group only; under the
        # stage >= 2 explicit factored path they reduce-scatter there
        # (the declared dp dim layered onto the expert base spec), under
        # dense modes they all-reduce.
        expert_wire = ring("reduce-scatter" if stage2_rs else "all-reduce",
                           expert_local, self.dp_size)
        a2a = int(model.get("moe_alltoall_wire_bytes") or 0)
        # The honest dense-baseline comparator the init log prints: one
        # all-reduce of EVERYTHING (expert grads replicated across
        # experts — the failure mode) over the full replica set.
        model["all_reduce_wire_bytes"] = ring(
            "all-reduce", model["grad_bytes"] + expert_full,
            self.replica_size)
        model.update(expert_grad_bytes_local=int(expert_local),
                     expert_grad_wire_bytes=int(expert_wire),
                     dense_grad_wire_bytes=int(dense_wire))
        self._wire_model = model
        per_tok = model["moe"]["wire_bytes_per_token"]
        expert_sync = "reduce-scatter" if stage2_rs else "all-reduce"
        detail = (
            f"{self._grad_sync_mode} MoE ep={self.ep_size}: {dense_note}; "
            f"expert grads ({expert_local:,} B/device) {expert_sync} over "
            f"data within their expert group only; dispatch/combine "
            f"all-to-all {per_tok:,} B/token"
            + (f" = {a2a:,} B/step" if a2a
               else " (per-step figure resolves at the first batch)"))
        return int(dense_wire + expert_wire + a2a), detail

    def _maybe_refresh_moe_wire(self, micro_batches) -> None:
        """Resolve the MoE all-to-all wire term exactly once the token
        count is visible (first batch): tokens/device/micro-step = the
        per-device sample count x tokens-per-sample (LM token batches
        [gas, B, S+1] route S tokens; other shapes use the trailing-dim
        product). Updates the analytic wire bytes + telemetry meta —
        host metadata only, no device access."""
        if self._moe is None or self.ep_size <= 1 or \
                self._moe_tokens_per_device is not None:
            return
        leaves = [l for l in jax.tree_util.tree_leaves(micro_batches)
                  if hasattr(l, "shape") and getattr(l, "ndim", 0) >= 2]
        if not leaves:
            return
        leaf = leaves[0]
        per_dev = max(1, int(leaf.shape[1]) // max(1, self.replica_size))
        if len(leaves) == 1 and leaf.ndim == 3 and \
                jnp.issubdtype(leaf.dtype, jnp.integer):
            # The combined LM layout [gas, B, S+1] (inputs [:, :-1]):
            # S tokens route. A (tokens, targets) PAIR has two leaves
            # and routes all S — the generic branch below.
            per_sample = max(1, int(leaf.shape[2]) - 1)
        else:
            per_sample = int(np.prod(leaf.shape[2:])) or 1
        self._moe_tokens_per_device = per_dev * per_sample
        self._wire_bytes, self._wire_detail = self._grad_wire_bytes()
        tl = self.telemetry
        if tl.enabled:
            tl.meta["wire_bytes_per_step"] = self._wire_bytes
            tl.meta["wire_bytes_ici"] = \
                self._wire_bytes - self._wire_bytes_dcn
            tl.meta["wire_bytes_dcn"] = self._wire_bytes_dcn
            tl.meta["wire_terms"] = self._wire_terms()
            tl.meta["wire_detail"] = self._wire_detail
            if isinstance(self._wire_model, dict) and \
                    "moe" in self._wire_model:
                tl.meta["moe_alltoall_wire_bytes_per_step"] = \
                    int(self._wire_model["moe_alltoall_wire_bytes"])

    def _log_comm_plan(self) -> None:
        """Init-time communication honesty (audited lowering + analytic
        wire bytes/step) — the knobs act or report, never silently."""
        zc = self.config.zero_config
        if zc.overlap_comm and self._offload is None:
            log_dist(
                "zero_optimization.overlap_comm: device-side collectives "
                "are overlapped by XLA's latency-hiding scheduler "
                "automatically; the knob only selects the bucketed host "
                "pipeline under cpu_offload", ranks=[0])
        if self.slice_size > 1:
            log_dist(f"Multi-slice scale-out: {self._wire_detail}; "
                     f"~{self._wire_bytes:,} wire bytes/step "
                     f"({self._wire_bytes - self._wire_bytes_dcn:,} ICI + "
                     f"{self._wire_bytes_dcn:,} DCN; "
                     f"slices={self.slice_size} x dp={self.dp_size})",
                     ranks=[0])
            return
        if self.ep_size > 1:
            log_dist(f"MoE expert parallelism: {self._wire_detail}; "
                     f"~{self._wire_bytes:,} wire bytes/step "
                     f"(ep={self.ep_size} x dp={self.dp_size})", ranks=[0])
            return
        if self.zero_optimization_stage() < 2 or self.dp_size <= 1:
            return
        log_dist(
            f"ZeRO-{self.zero_optimization_stage()} grad sync: "
            f"{self._wire_detail}; "
            f"~{self._wire_bytes:,} wire bytes/step vs "
            f"{self._wire_model['all_reduce_wire_bytes']:,} for a full "
            f"all-reduce (dp={self.dp_size})", ranks=[0])

    def _grad_shardings(self):
        """ZeRO stage>=2 gradient shardings over dp (None for stage < 2,
        dp=1, or the honest ``reduce_scatter: false`` dense-allreduce
        path)."""
        if getattr(self, "_grad_sync_mode", None) in ("none", "allreduce"):
            return None
        if self.zero_optimization_stage() < 2 or self.dp_size <= 1:
            return None
        if self._zero3:
            # Grads land EXACTLY on the param layout (stage3_param_specs)
            # so the shard-local update consumes them in place.
            return jax.tree_util.tree_map(
                lambda spec: NamedSharding(self.mesh, spec),
                self._stage3_specs, is_leaf=lambda x: isinstance(x, P))
        from .zero.partition import grad_shardings
        return grad_shardings(self.state.params, self.mesh, DP_AXIS,
                              self._param_specs)

    def _bind_zero3_scan(self, spec) -> None:
        """Bind the model's ``Zero3Scan`` contract to this engine's
        resolved stage-3 layout: the gather lowering mode (the same
        honesty split as grad_sync), each covered leaf's gather dim
        AFTER the per-layer slice (the stacked dp dim minus the layer
        axis), the gathered (dp-free) spec for the declarative
        constraint, and the configured prefetch depth. The loss_fn
        traces AFTER engine construction (first train step), so it reads
        the bound spec then."""
        from .zero.partition import spec_dp_dim
        mode = "explicit" if self._grad_sync_mode == "explicit" \
            else "declarative"
        layer_info = {}
        flat, _ = jax.tree_util.tree_flatten_with_path(
            self._stage3_specs, is_leaf=lambda x: isinstance(x, P))
        for path, sp in flat:
            if not spec.covers(jax.tree_util.keystr(path)):
                continue
            name = getattr(path[-1], "key", None) or str(path[-1])
            d = spec_dp_dim(sp, DP_AXIS)
            # stage3_param_specs never puts dp on a covered leaf's layer
            # axis; d >= 1 or None by construction.
            gdim = None if d is None else d - 1
            sliced = [None if e == DP_AXIS else e for e in list(sp)[1:]]
            layer_info[name] = (gdim, P(*sliced))
        spec.bind(mode=mode, mesh=self.mesh, axis_name=DP_AXIS,
                  compute_dtype=self.compute_dtype,
                  prefetch_depth=self._prefetch_depth,
                  layer_info=layer_info)
        # A constructor override on the spec wins over the config knob,
        # and the depth clamps to L-1 (the scan cannot hold more than
        # every layer); adopt the EFFECTIVE depth so the memory
        # watermark, telemetry meta, and the lint materialization
        # budget price the working set the compiled scan actually
        # holds — an unclamped budget would loosen the gate.
        if layer_info:
            leaves = [l for l, cov in zip(
                jax.tree_util.tree_leaves(self.state.params),
                jax.tree_util.tree_leaves(self._zero3_covered)) if cov]
            n_layers = int(leaves[0].shape[0]) if leaves else 1
            spec.prefetch_depth = max(
                0, min(int(spec.prefetch_depth), n_layers - 1))
        self._prefetch_depth = int(spec.prefetch_depth)
        log_dist(f"ZeRO-3 layer scan bound: mode={mode}, "
                 f"prefetch_depth={spec.prefetch_depth}, "
                 f"{len(layer_info)} scanned leaves", ranks=[0])

    def _make_state_shardings(self, params, opt_state) -> EngineState:
        """Params per TP spec (default replicated); ZeRO stage >= 1 shards
        optimizer state over dp, layered on top of the TP spec. ``params`` /
        ``opt_state`` may be shape structs (only shapes are inspected)."""
        def repl(tree):
            return jax.tree_util.tree_map(
                lambda _: NamedSharding(self.mesh, P()), tree)
        if getattr(self, "_zero3", False):
            # Stage 3: params born dp-sharded (stage3_param_specs,
            # already layered over any TP base).
            params_sh = jax.tree_util.tree_map(
                lambda spec: NamedSharding(self.mesh, spec),
                self._stage3_specs, is_leaf=lambda x: isinstance(x, P))
        elif self._param_specs is not None:
            params_sh = jax.tree_util.tree_map(
                lambda spec: NamedSharding(self.mesh, spec),
                self._param_specs, is_leaf=lambda x: isinstance(x, P))
        else:
            params_sh = repl(params)
        if getattr(self, "_onebit", False) and opt_state != ():
            # m/v/server_error replicated; worker_error dp-sharded on its
            # leading [dp] axis (per-rank error feedback).
            opt_sh = repl(opt_state)
            opt_sh = opt_sh._replace(
                worker_error=jax.tree_util.tree_map(
                    lambda _: NamedSharding(self.mesh, P(DP_AXIS)),
                    opt_sh.worker_error))
        elif getattr(self, "_zero3", False):
            # Moments mirror the stage-3 param layout (param-structured
            # subtrees); the fused optimizer's flat buffers keep the
            # plain dp row sharding.
            from .zero.partition import stage3_state_shardings
            opt_sh = stage3_state_shardings(opt_state, self.mesh, DP_AXIS,
                                            params, self._stage3_specs)
        elif self.zero_optimization_stage() >= 1 and self.dp_size > 1:
            opt_sh = zero_shardings(opt_state, self.mesh, DP_AXIS,
                                    params=params,
                                    param_specs=self._param_specs)
        elif self._param_specs is not None:
            # Moments follow the param TP layout; no ZeRO axis.
            opt_sh = zero_shardings(opt_state, self.mesh, None,
                                    params=params,
                                    param_specs=self._param_specs)
        else:
            opt_sh = repl(opt_state)
        scalar = NamedSharding(self.mesh, P())
        # DCN-compression error feedback: per-leaf [slices, *leaf] f32,
        # slice-sharded on the leading axis (genuinely per-slice state)
        # and dp-sharded where the grad shard is (same _leaf_spec rule,
        # shifted one dim right) — each (slice, dp-rank) owns exactly
        # the residual of its own compressed transmissions.
        dcn_sh = None
        if getattr(self, "_dcn_compression", False) and \
                self.slice_size > 1:
            from .zero.partition import _leaf_spec
            # Under stage 3 the error leaf must mirror the STAGE-3 grad
            # spec (covered scanned leaves keep their layer axis
            # unsharded — the plain rule would disagree with the
            # builder's err_specs and force a reshard at the shard_map
            # boundary every step).
            z3_specs = self._stage3_specs \
                if getattr(self, "_zero3", False) else None

            def err_sharding(p, sp=None):
                if not hasattr(p, "shape") or getattr(p, "ndim", 0) < 1:
                    return NamedSharding(self.mesh, P(SLICE_AXIS))
                spec = sp if sp is not None \
                    else _leaf_spec(p.shape, self.dp_size, DP_AXIS)
                return NamedSharding(self.mesh, P(SLICE_AXIS, *spec))
            if z3_specs is not None:
                dcn_sh = jax.tree_util.tree_map(
                    err_sharding, params, z3_specs)
            else:
                dcn_sh = jax.tree_util.tree_map(err_sharding, params)
        return EngineState(step=scalar, params=params_sh, opt_state=opt_sh,
                           loss_scale=scalar, growth_count=scalar,
                           hysteresis=scalar, skipped_steps=scalar,
                           cast_params=(params_sh if self._use_cast_cache
                                        else None),
                           dcn_error=dcn_sh)

    def _metrics_shardings(self, with_taps: bool = False,
                           with_moe: bool = False
                           ) -> Dict[str, NamedSharding]:
        """Replicated shardings for the step-metrics dict. Declared (with
        ``_state_shardings``) as out_shardings on every DONATING step
        program: without declared outputs, jax pairs donated inputs to
        same-aval outputs sharding-blind, and under ZeRO the dp-sharded
        moments share global avals with the replicated params — the
        partitioner then drops the mispaired aliases and every
        param-sized donated buffer is freed-but-never-reused (the lint
        suite's donation finding, a full param-tree of transient HBM).
        ``with_taps`` adds the health tap's [num_leaves] entry (also
        replicated) for paths that emit it."""
        scalar = NamedSharding(self.mesh, P())
        out = {k: scalar for k in ("loss", "grad_norm", "lr",
                                   "loss_scale", "overflow")}
        if with_taps:
            out["health_leaf_sq"] = scalar
        if with_moe:
            # [num_experts] routed counts + scalar drop/aux/z, all
            # replicated — drain material, no hot-path syncs.
            for k in ("moe_expert_tokens", "moe_drop_fraction",
                      "moe_aux_loss", "moe_z_loss"):
                out[k] = scalar
        return out

    def _place_state(self, state: EngineState) -> EngineState:
        # Jitted identity, NOT device_put: device_put may alias caller-owned
        # arrays into the state, and the donated train step would delete the
        # user's model_params out from under them. jit outputs are always
        # fresh buffers.
        state = jax.tree_util.tree_map(jnp.asarray, state)
        if self._use_cast_cache:
            # Always re-derive the compute-dtype cache here: every external
            # params replacement (checkpoint load) funnels through this, so
            # the cache cannot go stale.
            dt = self.compute_dtype

            def place(s):
                return s.replace(cast_params=_cast_floats(s.params, dt))
        else:
            def place(s):
                return s
        return jax.jit(place, out_shardings=self._state_shardings)(state)

    def _batch_sharding(self, batch_tree, leading_dims: int = 1):
        """Shard batch arrays over the replica axes on the (micro-)batch
        dim — (expert, data) jointly when expert parallelism is live
        (expert factors out of data), (slice, data) jointly on a
        multi-slice mesh (slices factor OUTSIDE data, matching the
        outermost mesh axis), plain dp otherwise."""
        if self.slice_size > 1:
            batch_axes = (SLICE_AXIS, DP_AXIS)
        elif self.ep_size > 1:
            batch_axes = (EP_AXIS, DP_AXIS)
        else:
            batch_axes = DP_AXIS

        def spec(x):
            pspec = P(*([None] * (leading_dims - 1) + [batch_axes]))
            return NamedSharding(self.mesh, pspec)
        return jax.tree_util.tree_map(spec, batch_tree)

    # ------------------------------------------------------------------ #
    # Config accessors (reference engine.py getters)
    # ------------------------------------------------------------------ #
    def train_batch_size(self) -> int:
        return self.config.train_batch_size

    def train_micro_batch_size_per_gpu(self) -> int:
        return self.config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self) -> int:
        return self.config.gradient_accumulation_steps

    def zero_optimization_stage(self) -> int:
        return self.config.zero_optimization_stage

    def zero_optimization(self) -> bool:
        return self.config.zero_enabled

    def fp16_enabled(self) -> bool:
        return self.config.fp16_enabled

    def bfloat16_enabled(self) -> bool:
        return self.config.bf16_enabled

    def gradient_clipping(self) -> float:
        return self.config.gradient_clipping

    def steps_per_print(self) -> int:
        return self.config.steps_per_print

    def wall_clock_breakdown(self) -> bool:
        return self.config.wall_clock_breakdown

    def is_gradient_accumulation_boundary(self) -> bool:
        return (self.micro_steps + 1) % self.gradient_accumulation_steps() == 0

    def _scan_microbatches(self) -> int:
        """How many micro-batches the jitted train step scans over. The
        pipeline engine overrides this to 1: its loss_fn consumes ALL
        grad-accum micro-batches in one pipelined pass."""
        return self.gradient_accumulation_steps()

    @property
    def optimizer(self):
        return self.tx

    def get_lr(self) -> List[float]:
        return [float(self._schedule_fn(self.global_steps))]

    def loss_scale(self) -> float:
        if self._offload is not None:
            return float(self._offload.loss_scale)
        return float(jax.device_get(self.state.loss_scale))

    # ------------------------------------------------------------------ #
    # Data path (reference engine.py:717-758)
    # ------------------------------------------------------------------ #
    def deepspeed_io(self, dataset, batch_size=None, route=C.ROUTE_TRAIN,
                     pin_memory=None, data_sampler=None, collate_fn=None,
                     num_local_io_workers=None):
        if dataset is None:
            return None
        if hasattr(dataset, "__iter__") and not hasattr(dataset, "__getitem__"):
            return RepeatingLoader(dataset)
        if batch_size is None:
            # One loader item = one micro step of this process's share of the
            # dp axis (the loader shards the dataset per process).
            local_dp = max(1, self.dp_size // jax.process_count())
            batch_size = self.train_micro_batch_size_per_gpu() * local_dp
        return DeepSpeedDataLoader(
            dataset=dataset, batch_size=batch_size,
            collate_fn=collate_fn or self.collate_fn,
            shuffle=route == C.ROUTE_TRAIN, drop_last=True,
            data_parallel_world_size=jax.process_count(),
            data_parallel_rank=jax.process_index())

    # ------------------------------------------------------------------ #
    # ZeRO-Offload step: device grads -> host SIMD Adam -> device params
    # ------------------------------------------------------------------ #
    def _build_offload_grad_fn(self, bucketed: bool = False):
        """Jitted grad-accumulation pass only (no optimizer apply): returns
        (loss-scaled summed grads, mean_loss). Grads stay dp-sharded under
        stage 2 until the host gather.

        ``bucketed``: emit the grads as a tuple of per-bucket leaf tuples
        (offload bucket order = flatten order) instead of one pytree, so
        the overlapped pipeline can enqueue each bucket's async D2H and
        wait on it independently of the others."""
        gas = self._scan_microbatches()
        loss_fn = self.loss_fn
        compute_dtype = self.compute_dtype
        grad_sh = self._grad_shardings()
        pld, accepts_pld = self.progressive_layer_drop, self._accepts_pld

        def constrain_grads(g):
            return g if grad_sh is None \
                else lax.with_sharding_constraint(g, grad_sh)

        raw_offload_loss = _make_raw_scaled_loss(loss_fn, accepts_pld,
                                                 gas)

        def scaled_loss(params, mb, key, scale, theta):
            return raw_offload_loss(_cast_floats(params, compute_dtype),
                                    mb, key, scale, theta)

        grad_fn = jax.value_and_grad(scaled_loss, has_aux=True)

        # Grad wire dtype: bf16 runs ship compute-dtype grads to the host
        # (half the D2H volume; matches the reference, whose cpu_offload
        # D2H copies the fp16 grads as-is, stage2.py:775-873). The host
        # optimizer upcasts to fp32 before the SIMD Adam. fp32 runs keep
        # the full-precision wire.
        wire_dtype = compute_dtype if compute_dtype == jnp.bfloat16 \
            else jnp.float32
        buckets = self._offload.buckets if bucketed else None

        def regroup(grads):
            if buckets is None:
                return grads
            flat = jax.tree_util.tree_leaves(grads)
            return tuple(tuple(flat[i] for i in b) for b in buckets)

        if self._grad_sync_mode == "explicit" and grad_sh is not None:
            # Guaranteed reduce-scatter for the offload grad pass too —
            # the bucket regroup happens outside the shard_map, so the
            # per-bucket D2H handles are unaffected. This retired the
            # last lint waiver (the offload declarative path regressing
            # to all-reduce + slice on this backend). Stage 3 gets the
            # CAST-FREE loss like the main path: the builder's gather
            # casts uncovered leaves in flight, and Zero3Scan-covered
            # shards must stay in the per_rank-widened f32 so the
            # per-layer grad scatter keeps f32 (a _cast_floats here
            # would narrow them to the compute dtype per layer).
            explicit = self._build_explicit_zero2_grads(
                raw_offload_loss if self._zero3 else scaled_loss,
                grad_sh, gas)

            def explicit_grads_step(params, micro_batches, rng, step,
                                    scale):
                rng = jax.random.fold_in(rng, step)
                theta = pld.theta_at(step.astype(jnp.float32)) \
                    if accepts_pld else None
                keys = jax.random.split(rng, gas)
                grads, mean_loss, _aux, _err = explicit(
                    params, micro_batches, keys, scale, theta)
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(wire_dtype), grads)
                return regroup(grads), mean_loss

            return jax.jit(explicit_grads_step)

        def grads_step(params, micro_batches, rng, step, scale):
            rng = jax.random.fold_in(rng, step)
            theta = pld.theta_at(step.astype(jnp.float32)) \
                if accepts_pld else None
            keys = jax.random.split(rng, gas)

            if gas == 1:
                # No accumulation buffer: saves a full fp32 zero-init +
                # add pass AND the fp32-sized transient (for the 1.5B
                # bench config that transient alone is 6 GB of HBM).
                mb = jax.tree_util.tree_map(lambda x: x[0], micro_batches)
                (_, (raw_loss, _aux)), grads = grad_fn(params, mb, keys[0],
                                                       scale, theta)
                grads = constrain_grads(grads)
                return (regroup(jax.tree_util.tree_map(
                    lambda g: g.astype(wire_dtype), grads)),
                    raw_loss.astype(jnp.float32))

            def accum(carry, xs):
                g_acc, loss_acc = carry
                mb, key = xs
                (_, (raw_loss, _aux)), grads = grad_fn(params, mb, key,
                                                       scale, theta)
                g_acc = constrain_grads(
                    jax.tree_util.tree_map(jnp.add, g_acc, grads))
                return (g_acc, loss_acc + raw_loss.astype(jnp.float32) / gas), None

            zero_grads = constrain_grads(jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32)
                if hasattr(p, "dtype") else p, params))
            (grads, mean_loss), _ = lax.scan(
                accum, (zero_grads, jnp.asarray(0.0, jnp.float32)),
                (micro_batches, keys))
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(wire_dtype), grads)
            return regroup(grads), mean_loss

        return jax.jit(grads_step)

    def _offload_partition_shardings(self, procs: Optional[int] = None):
        """Per-leaf NamedShardings placing each process's host partition on
        its own devices: the partition axis is sharded over a
        process-major mesh axis, everything else replicated. Repartitioning
        grads into these shardings before device_get (and params out of
        them after the host step) makes every host partition
        process-addressable via XLA collectives, with no assumption about
        how the dp shards were laid out."""
        procs = procs or jax.process_count()
        off = self._offload
        # jax.devices() is ordered by device id, which is NOT contiguous
        # per process on all topologies; row r of the proc-mesh must be
        # process r's devices or every host would update another host's
        # partition.
        devs = np.asarray(sorted(jax.devices(),
                                 key=lambda d: (d.process_index, d.id)))
        devs = devs.reshape(procs, -1)
        mesh = Mesh(devs, ("proc", "dev"))
        leaves, treedef = jax.tree_util.tree_flatten(
            jax.tree_util.tree_unflatten(off.treedef,
                                         list(range(len(off.full_shapes)))))
        specs = []
        for i in leaves:
            ax = off._axes[i]
            if ax is None:
                specs.append(NamedSharding(mesh, P()))
            else:
                spec = [None] * len(off.full_shapes[i])
                spec[ax] = "proc"
                specs.append(NamedSharding(mesh, P(*spec)))
        return jax.tree_util.tree_unflatten(treedef, specs)

    def _local_offload_grads(self, grads):
        """Multi-host D2H: repartition grads to the process shardings, then
        read the (now guaranteed-local) partition of each leaf."""
        if self._offload_down is None:
            self._offload_down = self._offload_partition_shardings()
            # jit caches by function identity: keep ONE identity fn per
            # direction or every step would retrace + recompile the
            # whole-tree reshard.
            self._offload_down_fn = jax.jit(
                lambda t: t, out_shardings=self._offload_down)
        grads = self._offload_down_fn(grads)
        return jax.tree_util.tree_map(
            lambda g: np.asarray(g.addressable_shards[0].data), grads)

    def _assemble_offload_params(self):
        """Multi-host H2D: each process contributes its updated partition;
        XLA all-gathers them into the engine's replicated param sharding."""
        off = self._offload
        if self._offload_down is None:
            self._offload_down = self._offload_partition_shardings()
        down_leaves = jax.tree_util.tree_leaves(self._offload_down)
        local = off.local_param_leaves()
        leaves = [jax.make_array_from_process_local_data(
                      sh, np.ascontiguousarray(l))
                  for sh, l in zip(down_leaves, local)]
        tree = jax.tree_util.tree_unflatten(off.treedef, leaves)
        if self._offload_up_fn is None:
            self._offload_up_fn = jax.jit(
                lambda t: t, out_shardings=self._state_shardings.params)
        return self._offload_up_fn(tree)

    def _offload_leaf_shardings(self):
        """Per-leaf target shardings for the bucketed param uploads, flat
        in offload leaf order (the state params tree has the offload
        treedef by construction)."""
        if self._offload_param_shardings is None:
            self._offload_param_shardings = jax.tree_util.tree_leaves(
                self._state_shardings.params)
        return self._offload_param_shardings

    def _train_batch_offload(self, micro_batches):
        import time as _time
        from .zero.offload import grad_to_host, run_bucketed_step
        if self._offload_grad_fn is None:
            self._offload_grad_fn = self.telemetry.instrument_step_fn(
                "offload_grad_step",
                self._build_offload_grad_fn(bucketed=self._offload_overlap))
        off = self._offload
        multihost = jax.process_count() > 1
        t_pre = _time.perf_counter()
        # Fence the PREVIOUS step's async param H2D here, in its own
        # bucket: without this, the upload time lands inside
        # device_step_ms and the recorded breakdown cannot reconcile
        # (round-4 OFFLOAD_BENCH.json's 80.5 s "device step" was ~3 GB of
        # params crossing a 0.035 GB/s tunnel, not compute).
        jax.block_until_ready(self.state.params)
        t0 = _time.perf_counter()
        grads, loss = self._offload_grad_fn(
            self.state.params, micro_batches, self._base_rng,
            jnp.asarray(self.global_steps, jnp.int32),
            jnp.asarray(off.loss_scale, jnp.float32))

        if self._offload_overlap:
            metrics, timings, loss = self._offload_step_overlapped(
                grads, loss, t0)
        else:
            # Serial parity path. The loss read fences the device step;
            # each bucket's device_get after it is then its own D2H fence
            # (nothing else in flight), so the per-bucket d2h timings
            # cannot bleed into one another — only residual device compute
            # this backend's early-returning block_until_ready missed can
            # land in bucket 0 (the documented caveat in OFFLOAD_BENCH).
            loss = jax.device_get(loss)
            t1 = _time.perf_counter()
            reshard_ms = 0.0
            if multihost:
                # Whole-tree XLA reshard makes every partition process-
                # local; the bucket fetches below then index host arrays.
                # The real D2H happens HERE, so time it — otherwise the
                # components stop reconciling with wall_ms on multihost.
                host_leaves = jax.tree_util.tree_leaves(
                    self._local_offload_grads(grads))
                reshard_ms = (_time.perf_counter() - t1) * 1e3
                fetch = lambda b: [host_leaves[i] for i in off.buckets[b]]
            else:
                grad_leaves = jax.tree_util.tree_leaves(grads)

                def fetch(b):
                    got = jax.device_get([grad_leaves[i]
                                          for i in off.buckets[b]])
                    return [off.slice_leaf(i, grad_to_host(g))
                            for i, g in zip(off.buckets[b], got)]

            metrics, timings = run_bucketed_step(off, fetch, overlap=False)
            t3 = _time.perf_counter()
            if not metrics["overflow"]:
                # async H2D of the updated compute-dtype params, whole-tree
                new_params = self._assemble_offload_params() if multihost \
                    else off.device_params(self._state_shardings.params)
                self.state = self.state.replace(
                    params=new_params,
                    step=jnp.asarray(off.step_count, jnp.int32))
            timings["h2d_dispatch_ms"] = (_time.perf_counter() - t3) * 1e3
            timings["device_step_ms"] = (t1 - t0) * 1e3
            if reshard_ms:
                timings["d2h_reshard_ms"] = reshard_ms
                timings["d2h_ms"] += reshard_ms
        metrics["loss"] = loss
        self.skipped_steps = off.skipped_steps
        timings["h2d_wait_ms"] = (t0 - t_pre) * 1e3
        timings["wall_ms"] = (_time.perf_counter() - t_pre) * 1e3
        self.offload_timings = timings
        return metrics

    def _offload_step_overlapped(self, bucket_grads, loss, t0):
        """Overlapped bucket pipeline: enqueue every bucket's async D2H at
        dispatch, stream bucket waits on this thread while the worker pool
        runs the per-bucket norm kernels, resolve the overflow vote, then
        run per-bucket Adam in the pool and device_put each bucket the
        moment its apply lands (all jax dispatch stays on this thread).
        Next step's compute is fenced only by the param uploads
        (block_until_ready at the top of _train_batch_offload), so the
        H2D tail overlaps whatever host work follows train_batch."""
        import time as _time
        from .zero.offload import grad_to_host, run_bucketed_step
        off = self._offload
        for bucket in bucket_grads:
            for leaf in bucket:
                enqueue = getattr(leaf, "copy_to_host_async", None)
                if enqueue is not None:
                    enqueue()
        # Fences device compute (the transfers above are already in
        # flight); in overlap mode the fetch of bucket 0 would fence it
        # anyway — this just attributes the time to the right component.
        loss_val = jax.device_get(loss)
        t1 = _time.perf_counter()

        def fetch(b):
            return [off.slice_leaf(i, grad_to_host(g))
                    for i, g in zip(off.buckets[b], bucket_grads[b])]

        shardings = self._offload_leaf_shardings()
        dev_leaves: list = [None] * len(off.full_shapes)

        def upload(b, host_leaves):
            for i, leaf in zip(off.buckets[b], host_leaves):
                dev_leaves[i] = jax.device_put(leaf, shardings[i])

        metrics, timings = run_bucketed_step(off, fetch, upload,
                                             overlap=True)
        if not metrics["overflow"]:
            self.state = self.state.replace(
                params=jax.tree_util.tree_unflatten(off.treedef, dev_leaves),
                step=jnp.asarray(off.step_count, jnp.int32))
        timings["device_step_ms"] = (t1 - t0) * 1e3
        return metrics, timings, loss_val

    # ------------------------------------------------------------------ #
    # Sparse (CSR) embedding gradients
    # ------------------------------------------------------------------ #
    def _init_sparse_gradients(self, sparse_grad_filter) -> None:
        """Mark the param leaves whose grads travel the CSR path.

        The reference keys on ``torch.nn.Embedding`` instances
        (engine.py:179-186); the functional analogue is a predicate over
        param paths — by default 2-D leaves whose path contains "embed" or
        "wte" (lookup tables). ``sparse_grad_filter(path_str, leaf) -> bool``
        overrides the default.
        """
        if self.zero_optimization_stage() >= 1:
            raise ValueError(
                "sparse_gradients requires ZeRO stage 0: under ZeRO grads "
                "are born dp-sharded and the dense reduce-scatter already "
                "ships 1/dp of every tensor")
        if self._onebit:
            raise ValueError(
                "sparse_gradients does not compose with OnebitAdam (the "
                "compressed momentum exchange replaces the grad allreduce)")

        def default(path, leaf):
            p = path.lower()
            return getattr(leaf, "ndim", 0) == 2 and \
                ("embed" in p or "wte" in p)

        filt = sparse_grad_filter or default
        flat, treedef = jax.tree_util.tree_flatten_with_path(
            self.state.params)
        mask_leaves, names = [], []
        for path, leaf in flat:
            path_str = jax.tree_util.keystr(path)
            is_sparse = bool(filt(path_str, leaf))
            mask_leaves.append(is_sparse)
            if is_sparse:
                names.append(path_str)
        if not names:
            logger.warning("sparse_gradients enabled but no param leaf "
                           "matched the embedding predicate — dense "
                           "allreduce will be used for everything")
            return
        self._sparse_mask = jax.tree_util.tree_unflatten(treedef, mask_leaves)
        self._sparse_names = names
        for n in names:
            log_dist(f"Will convert {n} to sparse (csr) tensor during "
                     "training", ranks=[0])

    def _build_sparse_grad_fn(self):
        """Per-rank grads under shard_map over dp: dense leaves are
        psum-averaged in-graph (ICI, where dense is the fast path); sparse
        embedding leaves come back per-rank [dp, V, H] for the host CSR
        exchange, whose wire volume is nnz_rows/vocab of dense (reference
        engine.py:1197-1253). Under fp16 the loss is scale-multiplied so
        grads come out SCALED (dense and sparse alike); the reported loss
        is the raw mean."""
        shard_map = comm.shard_map
        gas = self._scan_microbatches()
        loss_fn = self.loss_fn
        compute_dtype = self.compute_dtype
        dp, mesh = self.dp_size, self.mesh
        mask = self._sparse_mask
        pld, accepts_pld = self.progressive_layer_drop, self._accepts_pld

        def per_rank(params, step, micro_batches, keys, scale):
            rank = lax.axis_index(DP_AXIS)
            keys = jax.vmap(lambda k: jax.random.fold_in(k, rank))(keys)
            theta = pld.theta_at(step.astype(jnp.float32)) \
                if accepts_pld else None

            def mean_loss_fn(p):
                def one_micro(carry, xs):
                    scaled_acc, raw_acc = carry
                    mb, key = xs
                    cparams = _cast_floats(p, compute_dtype)
                    out = loss_fn(cparams, mb, key, pld_theta=theta) \
                        if accepts_pld else loss_fn(cparams, mb, key)
                    loss = out[0] if isinstance(out, tuple) else out
                    lf = loss.astype(jnp.float32)
                    return (scaled_acc + lf * scale / gas,
                            raw_acc + lf / gas), None

                (scaled, raw), _ = lax.scan(
                    one_micro, (jnp.asarray(0.0, jnp.float32),
                                jnp.asarray(0.0, jnp.float32)),
                    (micro_batches, keys))
                return scaled, raw

            (_, loss_val), grads = jax.value_and_grad(
                mean_loss_fn, has_aux=True)(params)
            grads = jax.tree_util.tree_map(
                lambda g, m: g[None] if m else lax.psum(g, DP_AXIS) / dp,
                grads, mask)
            return grads, lax.psum(loss_val, DP_AXIS) / dp

        def grad_step(params, step, micro_batches, rng, scale):
            rng = jax.random.fold_in(rng, step)
            keys = jax.random.split(rng, gas)
            batch_specs = jax.tree_util.tree_map(
                lambda _: P(None, DP_AXIS), micro_batches)
            grad_specs = jax.tree_util.tree_map(
                lambda m: P(DP_AXIS) if m else P(), mask)
            fn = shard_map(per_rank, mesh=mesh,
                           in_specs=(P(), P(), batch_specs, P(), P()),
                           out_specs=(grad_specs, P()),
                           check_vma=False)
            return fn(params, step, micro_batches, keys, scale)

        return jax.jit(grad_step)

    def _build_sparse_apply_fn(self):
        """Optimizer apply on the CSR-combined (now dense, replicated)
        grads: global-norm clip + tx update, same semantics as the main
        path's step. fp16: the sparse leaves arrive already unscaled (the
        host-side exchange divides by the scale), so only the dense leaves
        are unscaled here; the overflow vote spans BOTH (dense in-graph,
        sparse via the host-computed flag), and overflow skips the step
        and drives the dynamic scale machine exactly like the main path
        (reference engine.py:1000-1085). Returns the step's loss scale as
        a traced output: the donated input state's buffer is deleted on
        return, so the caller must not read it afterwards."""
        tx = self.tx
        fused_apply = self._fused_apply
        clip = self.gradient_clipping()
        schedule_fn = self._schedule_fn
        fp16 = self.config.fp16_enabled
        scaler_kw = self._scaler_kw
        mask = self._sparse_mask
        health_taps = self._health_tap_fn

        def apply_step(state, grads, sparse_overflow):
            scale = state.loss_scale
            tap = None
            if fp16:
                inv = 1.0 / scale
                grads = jax.tree_util.tree_map(
                    lambda g, m: g if m else g * inv, grads, mask)
                overflow = jnp.logical_or(sparse_overflow,
                                          tree_has_inf_or_nan(grads))
            else:
                overflow = jnp.asarray(False)
            # Health tap AFTER the unscale: here the whole tree is in
            # true magnitudes (the CSR exchange already unscaled the
            # sparse leaves host-side), so the reported per-layer norms
            # match grad_norm semantics — and a NaN shipped through the
            # CSR path is attributed too.
            if health_taps is not None:
                tap = health_taps(grads)
            grad_norm = global_norm(grads)
            # Same single-pass apply as the main step, clip folded in
            # (shared _clipped_update helper).
            new_params, new_opt = _clipped_update(
                grads, state, grad_norm, tx=tx, fused_apply=fused_apply,
                clip=clip)
            keep = overflow
            new_params = _tree_select(keep, state.params, new_params)
            new_opt = _tree_select(keep, state.opt_state, new_opt)
            new_state = state.replace(
                params=new_params, opt_state=new_opt,
                **_overflow_resolution(state, overflow, **scaler_kw))
            # ``scale`` is returned as a traced output: the input state is
            # DONATED, so reading state.loss_scale after this call would
            # touch a deleted buffer (the round-5 steps_per_print crash).
            return new_state, grad_norm, schedule_fn(state.step), overflow, \
                scale, tap

        scalar = NamedSharding(self.mesh, P())
        return jax.jit(apply_step, donate_argnums=(0,),
                       out_shardings=(self._state_shardings, scalar,
                                      scalar, scalar, scalar,
                                      scalar if health_taps is not None
                                      else None))

    def _csr_exchange(self, grads, inv_scale: float = 1.0):
        """Replace each sparse leaf's stacked per-rank grads [dp, V, H]
        with the CSR-allreduced dense mean. Mirrors the reference's
        csr_allreduce (engine.py:1212-1253): extract nonzero rows, gather
        values+indices across ranks (padded allgather across hosts),
        coalesce, densify. fp16: the gathered CSR values are unscaled
        HERE (``inv_scale``, nnz elements touched instead of V*H) and
        vetted for inf/NaN — the host half of the overflow vote. Returns
        (grads, shipped_elems, dense_elems, sparse_overflow)."""
        from .csr_tensor import CSRTensor, all_gather_csr
        procs = jax.process_count()
        repl = NamedSharding(self.mesh, P())
        shipped = [0]
        dense_n = [0]
        overflow = [False]

        def combine(g, m):
            if not m:
                return g
            if procs == 1:
                ranks = list(np.asarray(jax.device_get(g)))
            else:
                # Each process holds its local dp ranks; dedupe replicas
                # from other mesh axes by dp slot.
                seen = {}
                for sh in g.addressable_shards:
                    slot = sh.index[0].start or 0
                    if slot not in seen:
                        seen[slot] = np.asarray(sh.data)[0]
                ranks = [seen[k] for k in sorted(seen)]
            csr_shards = [CSRTensor.from_dense(r) for r in ranks]
            shipped[0] += sum(s.sparse_size() for s in csr_shards)
            local = all_gather_csr(csr_shards)
            if procs > 1:
                local = comm.csr_exchange_hosts(local)
            if not np.all(np.isfinite(local.values)):
                overflow[0] = True
            if inv_scale != 1.0:
                local = CSRTensor(local.row_indices,
                                  local.values * inv_scale,
                                  local.dense_shape)
            dense = (local.to_dense() / self.dp_size).astype(np.float32)
            dense_n[0] += local.dense_size
            if procs > 1:
                return jax.make_array_from_process_local_data(repl, dense)
            return jax.device_put(dense, repl)

        new_grads = jax.tree_util.tree_map(combine, grads, self._sparse_mask)
        return new_grads, shipped[0], dense_n[0], overflow[0]

    def _train_batch_sparse(self, micro_batches):
        if self._sparse_grad_fn is None:
            self._sparse_grad_fn = self.telemetry.instrument_step_fn(
                "sparse_grad_step", self._build_sparse_grad_fn())
            self._sparse_apply_fn = self.telemetry.instrument_step_fn(
                "sparse_apply_step", self._build_sparse_apply_fn())
        scale = self.state.loss_scale
        grads, loss = self._sparse_grad_fn(
            self.state.params, jnp.asarray(self.global_steps, jnp.int32),
            micro_batches, self._base_rng, scale)
        inv = 1.0 / float(jax.device_get(scale)) \
            if self.config.fp16_enabled else 1.0
        with self.telemetry.span("grad_sync", path="csr_exchange"):
            grads, shipped, dense_n, sp_overflow = self._csr_exchange(
                grads, inv_scale=inv)
        self.sparse_comm_stats = {"sparse_elements": int(shipped),
                                  "dense_elements": int(dense_n)}
        self.state, grad_norm, lr, overflow, scale_out, tap = \
            self._sparse_apply_fn(self.state, grads, jnp.asarray(sp_overflow))
        metrics = {"loss": loss, "grad_norm": grad_norm, "lr": lr,
                   "loss_scale": scale_out, "overflow": overflow}
        if tap is not None:
            metrics["health_leaf_sq"] = tap
        return metrics

    # ------------------------------------------------------------------ #
    # The jitted train step
    # ------------------------------------------------------------------ #
    def _build_onebit_train_step(self):
        """1-bit Adam step: per-rank local grads inside shard_map over dp,
        error-feedback sign-compressed momentum allreduce (ops/onebit.py;
        reference onebit_adam.py:104-228)."""
        shard_map = comm.shard_map
        from ..ops.onebit import onebit_adam_update
        gas = self._scan_microbatches()
        flat_batch = self.dp_size == 1 and jax.process_count() == 1
        loss_fn = self.loss_fn
        compute_dtype = self.compute_dtype
        schedule_fn = self._schedule_fn
        p = dict(self.config.optimizer_params or {})
        b1, b2 = tuple(p.get("betas", (0.9, 0.999)))
        eps = p.get("eps", 1e-8)
        wd = p.get("weight_decay", 0.0)
        freeze_step = int(p.get("freeze_step", 100000))
        clip = self.gradient_clipping()
        dp, mesh = self.dp_size, self.mesh
        pld, accepts_pld = self.progressive_layer_drop, self._accepts_pld
        fp16 = self.config.fp16_enabled
        scaler_kw = self._scaler_kw

        def per_rank(params, opt_state, step, scale, micro_batches, keys):
            # worker_error arrives [1, ...] (its dp axis split by shard_map)
            opt_state = opt_state._replace(
                worker_error=jax.tree_util.tree_map(
                    lambda w: w[0], opt_state.worker_error))
            if dp > 1:
                # Distinct dropout streams per dp rank (the SPMD path's
                # global-batch masks).
                rank = lax.axis_index(DP_AXIS)
                keys = jax.vmap(lambda k: jax.random.fold_in(k, rank))(keys)

            theta = pld.theta_at(step.astype(jnp.float32)) \
                if accepts_pld else None

            def mean_loss_fn(p):
                def one_micro(loss_acc, xs):
                    mb, key = xs
                    cparams = _cast_floats(p, compute_dtype)
                    out = loss_fn(cparams, mb, key, pld_theta=theta) \
                        if accepts_pld else loss_fn(cparams, mb, key)
                    loss = out[0] if isinstance(out, tuple) else out
                    return loss_acc + loss.astype(jnp.float32) / gas, None

                total, _ = lax.scan(one_micro, jnp.asarray(0.0, jnp.float32),
                                    (micro_batches, keys))
                return total * scale if fp16 else total

            loss_val, grads = jax.value_and_grad(mean_loss_fn)(params)
            if fp16:
                loss_val = loss_val / scale
            lr = schedule_fn(step)
            new_params, new_opt, aux = onebit_adam_update(
                grads, opt_state, params, lr=lr, b1=b1, b2=b2, eps=eps,
                weight_decay=wd, freeze_step=freeze_step,
                axis_name=DP_AXIS if dp > 1 else None, dp=dp, clip=clip,
                loss_scale=scale if fp16 else None)
            new_opt = new_opt._replace(
                worker_error=jax.tree_util.tree_map(
                    lambda w: w[None], new_opt.worker_error))
            loss_out = lax.psum(loss_val, DP_AXIS) / dp if dp > 1 else loss_val
            return (new_params, new_opt, loss_out, lr,
                    aux["grad_norm"], aux["overflow"])

        def train_step(state: EngineState, micro_batches, rng):
            rng = jax.random.fold_in(rng, state.step)
            keys = jax.random.split(rng, gas)
            if flat_batch:
                micro_batches = jax.tree_util.tree_map(
                    lambda x: x.reshape((gas, x.shape[0] // gas) +
                                        x.shape[1:]), micro_batches)
            if dp > 1:
                batch_specs = jax.tree_util.tree_map(
                    lambda _: P(None, DP_AXIS), micro_batches)
                from ..ops.onebit import OnebitState
                opt_specs = OnebitState(
                    step=P(), m=P(), v=P(), worker_error=P(DP_AXIS),
                    server_error=P())
                fn = shard_map(
                    per_rank, mesh=mesh,
                    in_specs=(P(), opt_specs, P(), P(), batch_specs, P()),
                    out_specs=(P(), opt_specs, P(), P(), P(), P()),
                    check_vma=False)
            else:
                fn = per_rank
            new_params, new_opt, loss, lr, gnorm, overflow = fn(
                state.params, state.opt_state, state.step, state.loss_scale,
                micro_batches, keys)
            # Overflow-skip parity with the main path (shared resolution):
            # hold step (LR holds), count the skip, drive the scale
            # machine. Params/opt already held inside the update.
            new_state = state.replace(
                params=new_params, opt_state=new_opt,
                **_overflow_resolution(state, overflow, **scaler_kw))
            metrics = {"loss": loss, "grad_norm": gnorm,
                       "lr": lr, "loss_scale": state.loss_scale,
                       "overflow": overflow}
            return new_state, metrics

        return jax.jit(train_step, donate_argnums=(0,),
                       out_shardings=(self._state_shardings,
                                      self._metrics_shardings()))

    def _build_explicit_zero2_grads(self, scaled_loss, grad_sh, gas: int):
        """The guaranteed ZeRO-2/3 reduce-scatter gradient path: per-rank
        grads under ``shard_map`` over dp, each leaf ``lax.psum_scatter``'d
        at its declared partition dim (non-divisible leaves psum) — the
        collective the declarative path *hopes* GSPMD emits, emitted by
        construction. Selected when ``grad_sync`` resolves to "explicit"
        (the hlo_audit probe caught the declared sharding lowering to a
        full all-reduce + slice on this backend).

        FACTORED replica meshes generalize the schedule hierarchically
        (parallel/multislice.py): the shard_map goes fully manual over
        (outer, data) where outer is the ``slice`` axis (multi-slice
        scale-out) or the ``expert`` axis (MoE), each leaf reduce-
        scatters over ``data`` INSIDE the gas scan exactly as before,
        and the accumulated 1/dp residual crosses the outer axis ONCE
        per step: slices all-reduce it over DCN (optionally 1-bit-
        compressed with carried error feedback —
        ``zero_optimization.dcn_compression``), expert groups all-reduce
        the DENSE leaves across groups while expert-sharded leaves
        (their grads are already per-expert) skip the outer hop
        entirely. The loss-mean correction divides by the FULL replica
        count (outer * dp), exact for power-of-two worlds — which makes
        one 2-slice step on a slice-duplicated batch BIT-identical to
        the single-slice step from the same state
        (tests/test_multislice.py; multi-step trajectories meet the
        usual cross-program few-ulp FMA limit).

        ``scaled_loss(params, mb, key, scale, theta) -> (scaled, raw)``
        is differentiated HERE. Under stage 2 it receives the full
        (replicated / cast-cached) params and the explicit scatter runs
        on the full-shape local grads. Under stage 3 the params ENTER
        the shard_map as their dp shards; ``zero/stage3.gather_cast``
        reconstructs each leaf just-in-time (compute-dtype all-gather of
        the fp32 master shard, wrapped in ``jax.checkpoint`` so backward
        RE-GATHERS instead of saving the gathered tree) and its custom
        transpose IS the reduce-scatter — widened to f32 before the
        collective, so one stage-3 step is bit-identical to the stage-2
        step from the same state. Leaves a bound ``Zero3Scan`` covers
        pass through as shards: the model gathers them per layer inside
        its scan, prefetch_depth layers ahead. On a MULTI-SLICE mesh
        stage 3 composes by the same algebra: the stage-3 specs shard
        over `data` only, so each slice holds the full shard set
        replicated across slices, every gather_cast / layer-scan gather
        binds `data` (ICI — zero param bytes ever cross DCN), the
        in-vjp scatter is the in-slice tier, and the accumulated 1/dp
        residual takes the same once-per-step DCN hop as stage 2.

        Parity with the declarative path (tests/test_hlo_audit.py): one
        step from identical state is BIT-identical — the local per-rank
        computation is the same program (GSPMD partitions the batch the
        same way), the cross-dp reduction is f32 per micro-step in both,
        and the local-vs-global loss-mean correction ``(g·dp)/dp`` is
        exact for power-of-two dp. Multi-step trajectories agree to a few
        f32 ulp: the two lowerings' collectives sum rank partials in
        different orders (ring reduce-scatter rotates each shard's start
        rank), the same cross-program limit PR 1 documented for FMA
        contraction. RNG: per-rank dropout streams via ``fold_in(rank)``
        (the joint replica index on factored meshes), like the onebit/
        sparse shard_map paths.
        Returns ``fn(params, micro_batches, keys, scale, theta,
        dcn_error=None) -> (dp-sharded f32 grads, mean_loss, aux,
        new_dcn_error)`` — ``new_dcn_error`` is None unless DCN
        compression is live.
        """
        from ..parallel.axis_algebra import (MeshFactorization,
                                             plan_grad_sync)
        from ..parallel.multislice import inter_slice_allreduce
        shard_map = comm.shard_map
        mesh, dp = self.mesh, self.dp_size
        accepts_pld = self._accepts_pld
        zero3 = self._zero3
        # The collective schedule is DERIVED from the mesh factorization
        # (parallel/axis_algebra): the single outer replica axis (None
        # on a plain-dp mesh — `slice` rides DCN, `expert` stays ICI),
        # the full replica count, the shard_map scope, and where each
        # collective sits. The lax calls below execute that plan; the
        # wire model prices it; lint/audit check the compiled program
        # against it.
        fact = MeshFactorization.from_mesh(mesh)
        plan = plan_grad_sync(fact, zero3=zero3,
                              dcn_compression=self._dcn_compression)
        outer_axis = fact.outer_axis
        outer = fact.size(outer_axis) if outer_axis is not None else 1
        replicas = fact.replicas
        moe_manual = self.ep_size > 1
        dcn_compress = (self._dcn_compression
                        and plan.residual is not None
                        and plan.residual.tier == "dcn")
        leaves, treedef = jax.tree_util.tree_flatten(grad_sh)
        dims_tree = jax.tree_util.tree_unflatten(
            treedef, [_spec_axis(sh, DP_AXIS) for sh in leaves])
        grad_out_specs = jax.tree_util.tree_unflatten(
            treedef, [sh.spec for sh in leaves])
        # Expert-sharded grads (spec on the `expert` axis) already live
        # per expert group — they take the in-group `data` reduction
        # only, never the outer hop (experts are not replicas).
        outer_skip = jax.tree_util.tree_unflatten(
            treedef, [_spec_axis(sh, EP_AXIS) is not None
                      for sh in leaves])
        if zero3:
            # Params enter AS SHARDS (the stage-3 layout == the grad
            # layout, so the same spec tree serves both directions).
            param_in_specs = grad_out_specs
            covered = self._zero3_covered
            compute_dtype = self.compute_dtype
            from .zero.stage3 import gather_cast

            def gather_params(p):
                def one(leaf, d, cov):
                    if cov or not hasattr(leaf, "dtype") or \
                            not jnp.issubdtype(leaf.dtype, jnp.floating):
                        return leaf     # model self-gathers per layer
                    return gather_cast(leaf, DP_AXIS, d, compute_dtype)
                return jax.tree_util.tree_map(one, p, dims_tree, covered)

            # checkpoint: backward re-gathers (2 gathers + 1 scatter per
            # param per micro-step — the ZeRO-3 3x wire schedule) instead
            # of holding the gathered tree from forward to backward.
            gather_ck = jax.checkpoint(gather_params)

            def loss_for_grad(p, mb, key, scale, theta):
                return scaled_loss(gather_ck(p), mb, key, scale, theta)

            grad_fn = jax.value_and_grad(loss_for_grad, has_aux=True)
        else:
            # MoE factored mesh: expert-sharded params enter AS their
            # expert-axis shards (the fully-manual shard_map slices them
            # at the boundary; moe_ffn detects the in-scope axes via
            # comm.axis_in_scope and runs its collectives bare).
            param_in_specs = self._param_specs \
                if moe_manual and self._param_specs is not None else P()
            grad_fn = jax.value_and_grad(scaled_loss, has_aux=True)

        def scatter_leaf(g, d):
            # f32 BEFORE the collective: the cross-dp reduction then runs
            # in f32 exactly like the declarative path's f32 accumulation
            # carry (a bf16 reduction would break parity AND precision).
            # Stage 3 never reaches here — its scatter IS gather_cast's
            # transpose (same widen-then-scatter, inside the vjp).
            g = g.astype(jnp.float32)
            if d is None:
                return lax.psum(g, DP_AXIS)
            return lax.psum_scatter(g, DP_AXIS, scatter_dimension=d,
                                    tiled=True)

        def reduce_grads(g):
            if zero3:
                # Already reduced: gather_cast's transpose scattered the
                # gathered leaves and psummed the replicated ones; the
                # model's zero3 scan did the same for covered leaves.
                return jax.tree_util.tree_map(
                    lambda x: x.astype(jnp.float32), g)
            return jax.tree_util.tree_map(scatter_leaf, g, dims_tree)

        def reduce_aux(aux):
            # Aux stats computed on each rank's LOCAL tokens (the MoE
            # layer's ep==1 path inside this shard_map): counts sum
            # over EVERY replica axis in scope — dp, plus the slice
            # axis on a multislice mesh (an ep=1 MoE model composes
            # with slices; reducing over dp alone would report one
            # slice's counts as global) — the rest mean. On the
            # FACTORED (expert, data) mesh the layer's manual path
            # already psum/pmean'd its stats over both axes —
            # re-reducing would double-count.
            if not isinstance(aux, dict) or "moe" not in aux:
                return aux
            if moe_manual:
                return aux
            axes = (DP_AXIS,) if outer_axis is None \
                else (DP_AXIS, outer_axis)
            moe = dict(aux["moe"])
            for k, v in moe.items():
                moe[k] = lax.psum(v, axes) if k == "expert_tokens" \
                    else lax.pmean(v, axes)
            return {**aux, "moe": moe}

        skip_leaves = [bool(s) for s in
                       jax.tree_util.tree_leaves(outer_skip)]

        def outer_reduce(g, err, scale):
            """The once-per-step outer hop on the accumulated 1/dp
            residual: slices all-reduce over DCN (optionally 1-bit-
            compressed with error feedback), expert groups all-reduce
            the dense leaves across groups; expert-sharded leaves pass
            through. Compression runs in UNSCALED units: the grads here
            are still loss-scaled (downstream unscales at the update),
            but the carried error feedback must not be denominated in a
            scale that the dynamic scaler changes under it — so the
            shard divides by ``scale`` before compressing and the
            summed result multiplies back (both exact: the loss scale
            is a power of two; a traced 1.0 for non-fp16). Returns
            (reduced grads, new error tree | None)."""
            if outer_axis is None:
                return g, None
            g_leaves = treedef.flatten_up_to(g)
            err_leaves = treedef.flatten_up_to(err) if dcn_compress \
                else [None] * len(g_leaves)
            inv_scale = 1.0 / scale
            out, errs = [], []
            for gl, sk, el in zip(g_leaves, skip_leaves, err_leaves):
                if sk:
                    out.append(gl)
                    errs.append(el)
                    continue
                if dcn_compress:
                    # el enters as this slice's [1, *shard] slab of the
                    # [slices, *shard] error buffer.
                    summed, ne = inter_slice_allreduce(
                        gl * inv_scale, el[0], num_slices=outer,
                        compress=True)
                    out.append(summed * scale)
                    errs.append(ne[None])
                else:
                    out.append(lax.psum(gl, outer_axis))
                    errs.append(None)
            new_err = jax.tree_util.tree_unflatten(treedef, errs) \
                if dcn_compress else None
            return jax.tree_util.tree_unflatten(treedef, out), new_err

        def per_rank(params, micro_batches, keys, scale, theta,
                     dcn_error=None):
            rank = lax.axis_index(DP_AXIS)
            if outer_axis is not None:
                # Joint replica index: distinct dropout streams per
                # (outer member, dp rank), slice-major like the mesh.
                rank = lax.axis_index(outer_axis) * dp + rank
            keys = jax.vmap(lambda k: jax.random.fold_in(k, rank))(keys)
            theta_arg = theta if accepts_pld else None
            if zero3:
                # Widen the SHARDS to f32 OUTSIDE the grad boundary:
                # grads w.r.t. an f32 primal stay f32 after the in-vjp
                # scatter (bf16 master-free primals would otherwise
                # narrow the f32-reduced grads back to bf16 — breaking
                # bit-parity with stage 2, whose scatter runs on widened
                # local grads post-AD). A no-op copy for fp32 masters;
                # shard-sized either way.
                params = jax.tree_util.tree_map(
                    lambda x: x.astype(jnp.float32)
                    if hasattr(x, "dtype") and
                    jnp.issubdtype(x.dtype, jnp.floating) else x, params)
            if gas == 1:
                mb = jax.tree_util.tree_map(lambda x: x[0], micro_batches)
                (_, (raw_loss, aux)), g = grad_fn(params, mb, keys[0],
                                                  scale, theta_arg)
                g = reduce_grads(g)
                loss = raw_loss.astype(jnp.float32)
            else:
                def accum(carry, xs):
                    g_acc, loss_acc = carry
                    mb, key = xs
                    (_, (raw_loss, aux)), g = grad_fn(params, mb, key,
                                                      scale, theta_arg)
                    # Scatter per micro-step and carry only the 1/dp
                    # shards: the accumulation buffer never holds an
                    # unpartitioned gradient (the stage-2 invariant).
                    g_acc = jax.tree_util.tree_map(
                        jnp.add, g_acc, reduce_grads(g))
                    return (g_acc, loss_acc +
                            raw_loss.astype(jnp.float32) / gas), aux

                def zero_shard(p, d):
                    shape = list(p.shape)
                    if d is not None and not zero3:
                        shape[d] //= dp
                    # zero3: params are ALREADY the local shard view.
                    return jnp.zeros(shape, jnp.float32)

                zeros = jax.tree_util.tree_map(zero_shard, params,
                                               dims_tree)
                (g, loss), aux_stack = lax.scan(
                    accum, (zeros, jnp.asarray(0.0, jnp.float32)),
                    (micro_batches, keys))
                # Aux rides as stacked scan outputs; report the
                # micro-step mean (None stays None).
                aux = jax.tree_util.tree_map(
                    lambda a: jnp.mean(a, axis=0), aux_stack)
            # loss_fn normalizes over its LOCAL shard, so the summed
            # grads and losses are replicas x the global-mean values;
            # /replicas is exact for power-of-two worlds (bit-parity
            # with the declarative path, and — via the exact scaling —
            # of a slice-duplicated 2-slice run with the 1-slice run).
            # The outer hop happens AFTER the division, ONCE on the
            # accumulated shard: the DCN hop costs 1/dp of the grads per
            # STEP, not per micro-step.
            g = jax.tree_util.tree_map(lambda x: x / replicas, g)
            g, new_err = outer_reduce(g, dcn_error, scale)
            loss = lax.psum(loss, DP_AXIS)
            if outer_axis is not None:
                loss = lax.psum(loss, outer_axis)
            loss = loss / replicas
            if dcn_compress:
                return g, loss, reduce_aux(aux), new_err
            return g, loss, reduce_aux(aux)

        batch_axes = fact.grad_shard_scope if outer_axis is not None \
            else DP_AXIS
        err_specs = jax.tree_util.tree_unflatten(
            treedef, [P(SLICE_AXIS, *sh.spec) for sh in leaves]) \
            if dcn_compress else None

        def explicit_grads(params, micro_batches, keys, scale, theta,
                           dcn_error=None):
            batch_specs = jax.tree_util.tree_map(
                lambda _: P(None, batch_axes), micro_batches)
            theta_in = theta if theta is not None \
                else jnp.zeros((), jnp.float32)
            in_specs = (param_in_specs, batch_specs, P(), P(), P())
            out_specs = (grad_out_specs, P(), P())
            if dcn_compress:
                if dcn_error is None:
                    raise ValueError(
                        "dcn_compression is live but no error-feedback "
                        "state was passed (state.dcn_error)")
                fn = shard_map(per_rank, mesh=mesh,
                               in_specs=in_specs + (err_specs,),
                               out_specs=out_specs + (err_specs,),
                               check_vma=False)
                g, loss, aux, new_err = fn(params, micro_batches, keys,
                                           scale, theta_in, dcn_error)
                return g, loss, aux, new_err
            fn = shard_map(per_rank, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
            g, loss, aux = fn(params, micro_batches, keys, scale,
                              theta_in)
            return g, loss, aux, None

        return explicit_grads

    def _build_train_step(self):
        if self._onebit:
            if self._direct_grads_fn is not None:
                raise ValueError("grads_fn does not compose with OnebitAdam")
            return self._build_onebit_train_step()
        direct_grads = self._direct_grads_fn
        gas = self._scan_microbatches()
        # Single-chip/single-process: the step consumes the user's flat
        # batch directly and splits micro-batches device-side.
        flat_batch = self.replica_size == 1 and jax.process_count() == 1
        clip = self.gradient_clipping()
        fp16 = self.config.fp16_enabled
        schedule_fn = self._schedule_fn
        loss_fn = self.loss_fn
        compute_dtype = self.compute_dtype
        tx = self.tx
        fused_apply = self._fused_apply
        fused_step = self._fused_step
        scaler_kw = self._scaler_kw
        if float(self.config.gradient_predivide_factor or 1.0) != 1.0:
            # Subsumed by design: grads are accumulated in fp32 as the mean
            # over the global batch, so the fp16 reduction-range motivation
            # for predivide (reference engine.py:1130-1141) does not arise.
            logger.warning("gradient_predivide_factor has no effect on TPU: "
                           "reductions are fp32-accumulated by XLA")

        # ZeRO-2: grads are BORN dp-sharded. Constraining the accumulation
        # carry makes XLA compile the cross-dp gradient reduction as
        # reduce-scatter and keeps only 1/dp of every gradient per chip —
        # the memory story stage2.py:613-738 implements with hooks+buckets.
        # When the hlo_audit probe shows this backend's partitioner
        # regressing the declaration to all-reduce + slice, grad_sync
        # resolves to "explicit" and the psum_scatter path below replaces
        # the declarative grad computation outright.
        grad_sh = self._grad_shardings()
        explicit_grads_fn = None

        def constrain_grads(g):
            if grad_sh is None:
                return g
            return lax.with_sharding_constraint(g, grad_sh)

        pld = self.progressive_layer_drop
        accepts_pld = self._accepts_pld
        use_cache = self._use_cast_cache
        master_free = self._master_free
        health_taps = self._health_tap_fn
        moe_cfg = self._moe

        raw_scaled_loss = _make_raw_scaled_loss(loss_fn, accepts_pld,
                                                gas)

        def scaled_loss(params, mb, key, scale, theta):
            # With the cast cache, ``params`` arrive already in the compute
            # dtype (state.cast_params); grads w.r.t. them equal the grads
            # the cast chain would deliver (the cast vjp is a dtype-widen).
            cparams = params if use_cache \
                else _cast_floats(params, compute_dtype)
            return raw_scaled_loss(cparams, mb, key, scale, theta)

        grad_fn = jax.value_and_grad(scaled_loss, has_aux=True)
        if self._grad_sync_mode == "explicit" and grad_sh is not None \
                and direct_grads is None:
            # Stage 3 hands the builder the CAST-FREE loss: the gather
            # performs the master-shard -> compute-dtype cast in flight,
            # and Zero3Scan-covered leaves must reach the model's layer
            # scan as fp32 shards (its custom transpose widens before the
            # per-layer reduce-scatter).
            explicit_grads_fn = self._build_explicit_zero2_grads(
                raw_scaled_loss if self._zero3 else scaled_loss,
                grad_sh, gas)

        def train_step(state: EngineState, micro_batches, rng):
            # Derive the per-step key INSIDE jit (a host-side fold_in would
            # dispatch eager device ops every step).
            rng = jax.random.fold_in(rng, state.step)
            scale = state.loss_scale
            theta = pld.theta_at(state.step.astype(jnp.float32)) \
                if accepts_pld else None
            keys = jax.random.split(rng, gas)
            if flat_batch:
                # Flat batches are split into [gas, micro, ...] HERE, inside
                # jit — a host-side eager reshape is one dispatch round-trip
                # per step, which stalls the async pipeline on tunneled
                # backends.
                micro_batches = jax.tree_util.tree_map(
                    lambda x: x.reshape((gas, x.shape[0] // gas) + x.shape[1:]),
                    micro_batches)

            loss_params = state.cast_params if use_cache else state.params
            new_dcn_error = None
            if direct_grads is not None:
                # Manual-VJP model (1F1B pipeline): one call yields loss
                # AND grads; it consumes all micro-batches itself. Params
                # arrive in the compute dtype like every other path (the
                # T-tick scan would otherwise re-read fp32 masters each
                # tick).
                mb = jax.tree_util.tree_map(lambda x: x[0], micro_batches)
                mean_loss, grads = direct_grads(
                    loss_params if use_cache else
                    _cast_floats(state.params, compute_dtype), mb, keys[0],
                    scale)
                grads = constrain_grads(_cast_floats(grads, jnp.float32))
                mean_loss = mean_loss.astype(jnp.float32)
                aux = None
            elif explicit_grads_fn is not None:
                # Guaranteed reduce-scatter: grads leave the shard_map
                # already dp-sharded and f32 (no constraint needed — the
                # out_specs ARE the ZeRO-2 layout). On multi-slice
                # meshes this is the HIERARCHICAL path; with DCN
                # compression the error-feedback buffers thread through.
                grads, mean_loss, aux, new_dcn_error = explicit_grads_fn(
                    loss_params, micro_batches, keys, scale, theta,
                    state.dcn_error)
            elif gas == 1:
                # Fast path: no accumulation scan — saves a full zero-init +
                # add pass over the fp32 grad tree every step. Master-free
                # included: grads are promoted to f32 here so the optax
                # fallback's second moment is (f32 g)^2, never a bf16
                # square (the fused kernel promotes on read by
                # construction); XLA folds the widening cast into the
                # consumer, so no extra materialized pass.
                mb = jax.tree_util.tree_map(lambda x: x[0], micro_batches)
                (_, (raw_loss, aux)), grads = grad_fn(
                    loss_params, mb, keys[0], scale, theta)
                grads = constrain_grads(_cast_floats(grads, jnp.float32))
                mean_loss = raw_loss.astype(jnp.float32)
            else:
                def accum(carry, xs):
                    g_acc, loss_acc = carry
                    mb, key = xs
                    (_, (raw_loss, aux)), grads = grad_fn(loss_params, mb,
                                                          key, scale, theta)
                    g_acc = constrain_grads(
                        jax.tree_util.tree_map(jnp.add, g_acc, grads))
                    return (g_acc,
                            loss_acc + raw_loss.astype(jnp.float32) / gas), \
                        aux

                zero_grads = constrain_grads(jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32)
                    if hasattr(p, "dtype") else p, state.params))
                (grads, mean_loss), aux_stack = lax.scan(
                    accum, (zero_grads, jnp.asarray(0.0, jnp.float32)),
                    (micro_batches, keys))
                aux = jax.tree_util.tree_map(
                    lambda a: jnp.mean(a, axis=0), aux_stack)

            # Health tap BEFORE the apply consumes the grads: one small
            # stacked array of per-leaf sum-of-squares (non-finite entry
            # == the overflow vote's information, with provenance). The
            # grads are still loss-scaled here; dividing the tap by
            # scale^2 (one scalar multiply on [L]) reports TRUE norms —
            # anomaly events must match grad_norm semantics, not show
            # 65536x-inflated layers. A finite scale preserves
            # (non-)finiteness either way.
            tap = None
            if health_taps is not None:
                tap = health_taps(grads)
                if fp16:
                    tap = tap / (scale * scale)

            sr_key = jax.random.fold_in(rng, 0x5352) if master_free \
                else None
            if fused_step is not None:
                # One-pass clipped update: the norm reduction (which
                # doubles as the fp16 overflow vote — inf/nan in any grad
                # surfaces as a non-finite sum of squares), the unscale
                # multiply, the clip coefficient, the overflow-skip
                # select, and the compute-dtype cast-cache refresh ALL
                # ride the fused kernels' single read/write of
                # grad+param+m+v. No separate global_norm pass, no
                # full-tree unscale, no post-apply jnp.where select, no
                # standalone cast pass.
                out = fused_step(
                    grads, state.opt_state, state.params, clip=clip,
                    inv_scale=(1.0 / scale) if fp16 else None, fp16=fp16,
                    compute_norm=bool(clip and clip > 0) or fp16,
                    sr_key=sr_key,
                    cast_dtype=compute_dtype if use_cache else None)
                new_params, new_opt_state = out.params, out.state
                new_cast = out.cast_params if use_cache else None
                grad_norm, overflow = out.grad_norm, out.overflow
            else:
                # Two-pass path (optax chain / per-leaf fused ablation):
                # unscale the loss-scaled gradients. Non-fp16 runs at a
                # static scale of 1.0 — skip the full-tree multiply.
                if fp16:
                    inv = 1.0 / scale
                    grads = jax.tree_util.tree_map(lambda g: g * inv, grads)

                overflow = tree_has_inf_or_nan(grads) if fp16 \
                    else jnp.asarray(False)

                if (clip and clip > 0) or fp16:
                    grad_norm = global_norm(grads)
                else:
                    # Full-tree norm is an extra HBM pass; only pay for it
                    # when something consumes it (clipping / overflow
                    # diagnostics).
                    grad_norm = jnp.asarray(-1.0, jnp.float32)
                new_params, new_opt_state = _clipped_update(
                    grads, state, grad_norm, tx=tx, fused_apply=fused_apply,
                    clip=clip, master_free=master_free, sr_key=sr_key)
                # Refresh the compute-dtype cache in the same fused pass as
                # the param update (one extra compute-dtype write instead
                # of next step's full fp32 re-read + cast).
                new_cast = _cast_floats(new_params, compute_dtype) \
                    if use_cache else None

                # Overflow-skip (reference step semantics
                # engine.py:1000-1085): keep old params/opt state, don't
                # advance step (so LR holds).
                keep = overflow
                new_params = _tree_select(keep, state.params, new_params)
                new_opt_state = _tree_select(keep, state.opt_state,
                                             new_opt_state)
                if use_cache:
                    new_cast = _tree_select(keep, state.cast_params,
                                            new_cast)

            # Shared overflow-vote resolution: step/skip bookkeeping +
            # loss-scale state machine. DCN-compression error feedback
            # commits only on a taken step (an overflow must not poison
            # the feedback with garbage residuals — the onebit rule).
            new_dcn = state.dcn_error
            if new_dcn_error is not None:
                new_dcn = _tree_select(overflow, state.dcn_error,
                                       new_dcn_error)
            new_state = state.replace(
                params=new_params, opt_state=new_opt_state,
                cast_params=new_cast, dcn_error=new_dcn,
                **_overflow_resolution(state, overflow, **scaler_kw))
            metrics = {
                "loss": mean_loss,
                "grad_norm": grad_norm,
                "lr": schedule_fn(state.step),
                "loss_scale": scale,
                "overflow": overflow,
            }
            if tap is not None:
                metrics["health_leaf_sq"] = tap
            if moe_cfg is not None:
                # The moe block promises MoE metrics (the out_shardings
                # schema is fixed pre-trace); a dense model behind it is
                # a config error, said plainly.
                if not (isinstance(aux, dict) and "moe" in aux):
                    raise ValueError(
                        "ds_config has a `moe` block but the model's "
                        "loss_fn returned no moe stats — build the model "
                        "with TransformerConfig.moe "
                        "(deepspeed_tpu.moe.MoEConfig) or drop the block")
                st = aux["moe"]
                metrics["moe_expert_tokens"] = \
                    st["expert_tokens"].astype(jnp.float32)
                metrics["moe_drop_fraction"] = st["drop_fraction"]
                metrics["moe_aux_loss"] = st["aux_loss"]
                metrics["moe_z_loss"] = st["z_loss"]
            return new_state, metrics

        return jax.jit(train_step, donate_argnums=(0,),
                       out_shardings=(self._state_shardings,
                                      self._metrics_shardings(
                                          with_taps=health_taps is not None,
                                          with_moe=moe_cfg is not None)))

    def _build_eval_step(self):
        loss_fn = self.loss_fn
        compute_dtype = self.compute_dtype

        def eval_step(params, batch, rng):
            cparams = _cast_floats(params, compute_dtype)
            out = loss_fn(cparams, batch, rng)
            loss, _ = (out if isinstance(out, tuple) else (out, None))
            return loss

        return jax.jit(eval_step)

    # ------------------------------------------------------------------ #
    # Public train/eval API
    # ------------------------------------------------------------------ #
    def _next_rng(self):
        return jax.random.fold_in(self._base_rng, self.global_steps)

    def _check_batch_divisible(self, batch) -> None:
        gas = self._scan_microbatches()
        for x in jax.tree_util.tree_leaves(batch):
            lead = getattr(x, "shape", (0,))[0] if getattr(x, "ndim", 1) else 0
            if lead % gas != 0:
                # ValueError, not assert: under ``python -O`` an assert is
                # stripped and the in-jit reshape fails with an opaque XLA
                # shape error instead.
                raise ValueError(
                    f"batch dim {lead} not divisible by grad-accum {gas}")

    def _stack_micro_batches(self, batch):
        """Reshape to [gas, per_micro_step, ...]. Device arrays stay on
        device (np.asarray on a jax.Array would be a synchronous D2H
        round-trip every step — ruinous over a tunneled backend)."""
        gas = self._scan_microbatches()

        def reshape(x):
            if not isinstance(x, (jax.Array, np.ndarray)):
                x = np.asarray(x)
            lead = x.shape[0]
            if lead % gas != 0:
                raise ValueError(
                    f"batch dim {lead} not divisible by grad-accum {gas}")
            return x.reshape((gas, lead // gas) + x.shape[1:])
        return jax.tree_util.tree_map(reshape, batch)

    def train_batch(self, batch=None, data_iter=None):
        """Run one full training iteration (all grad-accum micro steps + one
        optimizer step). Parity with PipelineEngine.train_batch semantics for
        the non-pipeline engine; the preferred TPU API.

        ``batch``: pytree with leading dim ``gas * micro * dp_local``; or pull
        ``gas`` micro-batches from ``data_iter`` / the engine's dataloader.
        """
        tl = self.telemetry
        t_wall0 = time.perf_counter()
        tl.profiler_tick(self.global_steps)
        sparse_path = self._sparse_mask is not None and self.dp_size > 1
        if self._train_step_fn is None and self._offload is None \
                and not sparse_path:
            # Recompile-sentinel instrumentation (a no-op pass-through
            # when telemetry is off): a jit cache miss after warmup is an
            # unexpected retrace — logged, optionally fatal.
            self._train_step_fn = tl.instrument_step_fn(
                "train_step", self._build_train_step())

        if batch is None:
            it = data_iter
            if it is None:
                if self._data_iterator is None:
                    assert self.training_dataloader is not None, \
                        "train_batch() needs a batch, data_iter, or training_data"
                    self._data_iterator = iter(RepeatingLoader(self.training_dataloader))
                it = self._data_iterator
            gas = self.gradient_accumulation_steps()
            # Fetch-wait accounting for the goodput ledger: host wall the
            # engine spends waiting on the input pipeline (monotonic clock
            # only, no device access). Covers any iterator — the
            # dataloader's own fetch_wait_s counter is the loader-local
            # view of the same stall.
            t_fetch0 = time.perf_counter()
            micro = [next(it) for _ in range(gas)]
            batch = jax.tree_util.tree_map(
                lambda *xs: np.concatenate([np.asarray(x) for x in xs], axis=0),
                *micro)
            if tl.ledger is not None:
                tl.ledger.note("data_stall",
                               time.perf_counter() - t_fetch0)

        if self._offload is None and self.replica_size == 1 \
                and jax.process_count() == 1:
            # Flat fast path: no host-side tree ops at all; the jitted step
            # does the micro-batch split on device.
            self._check_batch_divisible(batch)
            micro_batches = batch
        else:
            micro_batches = self._stack_micro_batches(batch)
        if self.replica_size > 1:
            # Shard the per-micro-step batch dim over dp so XLA partitions
            # the whole forward/backward data-parallel. Multi-process: each
            # process holds only its local dp share, so assemble the global
            # array from per-process shards instead of device_put (which
            # would treat every local array as the full global batch).
            shardings = self._batch_sharding(micro_batches, leading_dims=2)
            if jax.process_count() > 1:
                micro_batches = jax.tree_util.tree_map(
                    lambda x, sh: jax.make_array_from_process_local_data(
                        sh, np.asarray(x)),
                    micro_batches, shardings)
            else:
                micro_batches = jax.device_put(micro_batches, shardings)
        if (self.flops_profiler is not None and
                self.global_steps == self.config.flops_profiler_config.profile_step):
            self._run_flops_profiler(micro_batches)
        if tl.tracer is not None:
            tl.add_span("data_prep", t_wall0,
                        time.perf_counter() - t_wall0)
        self._maybe_refresh_moe_wire(micro_batches)

        self.tput_timer.start()
        t_dispatch = time.perf_counter()
        if self._offload is not None:
            metrics = self._train_batch_offload(micro_batches)
        elif sparse_path:
            metrics = self._train_batch_sparse(micro_batches)
        else:
            self.state, metrics = self._train_step_fn(
                self.state, micro_batches, self._base_rng)

        self.global_steps += 1
        self.micro_steps += self.gradient_accumulation_steps()
        self.global_samples += self.train_batch_size()
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self.global_steps)
        if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "step"):
            self.lr_scheduler.last_batch_iteration = self.global_steps - 1
        self.tput_timer.stop()
        self._record_telemetry(metrics, t_wall0, t_dispatch)
        self._maybe_log(metrics)
        self._maybe_auto_save()
        return metrics["loss"]

    def _maybe_auto_save(self) -> None:
        """Auto-save (checkpoint.snapshot_every): tag global_stepN into
        the configured save_dir — the resume anchor the crash/kill
        harness (tools/crashkill.py) loads from. Shared by every
        optimizer-step boundary: train_batch AND the
        forward/backward/step trio honor the same cadence."""
        if self._ckpt_every > 0 and \
                self.global_steps % self._ckpt_every == 0:
            self.save_checkpoint(self._ckpt_dir)

    # Alias matching common JAX naming.
    train_step = train_batch

    def _record_telemetry(self, metrics, t0: float, t_dispatch: float) -> None:
        """Buffer this step's telemetry record — append-only, no device
        access (the metrics dict's jax scalars ride as futures and sync
        at the next report-boundary drain). ``wall_ms`` is host wall from
        train_batch entry; on the jitted paths that is DISPATCH wall
        (steps pipeline asynchronously — the fenced truth is the
        throughput timer's window average in the report record), on the
        host-synchronous offload path it is true step wall."""
        tl = self.telemetry
        if not tl.enabled:
            return
        # Deferred fail_on_recompile surfaces HERE — after the donated
        # step's returned state was stored, so a caught RecompileError
        # leaves the engine usable (e.g. to checkpoint before dying).
        tl.raise_pending()
        t_now = time.perf_counter()
        host: Dict[str, Any] = {
            "wall_ms": (t_now - t0) * 1e3,
            "wire_bytes": self._wire_bytes,
            "samples": self.train_batch_size(),
        }
        if self._offload is not None and self.offload_timings:
            t = self.offload_timings
            off = {k: round(float(t[k]), 3) for k in (
                "device_step_ms", "d2h_ms", "host_norm_ms", "host_step_ms",
                "h2d_dispatch_ms", "h2d_wait_ms", "wall_ms") if k in t}
            off["overlap_fraction"] = round(
                float(t.get("overlap_fraction", 0.0)), 4)
            off["num_buckets"] = int(t.get("num_buckets", 1))
            off["overlapped"] = bool(t.get("overlapped", False))
            host["offload"] = off
            tl.add_offload_trace(t)
        if tl.tracer is not None:
            name = "offload_step" if self._offload is not None \
                else "step_dispatch"
            tl.add_span(name, t_dispatch, t_now - t_dispatch,
                        args={"step": self.global_steps})
            tl.add_span("train_batch", t0, t_now - t0,
                        args={"step": self.global_steps})
        tl.record_step(self.global_steps, metrics, **host)

    def _report_extra(self) -> Dict[str, Any]:
        """Report-boundary fields for the telemetry drain record. Called
        ONLY at a drain boundary (the skipped_steps read is a sync)."""
        self._maybe_build_cost_model()
        extra: Dict[str, Any] = {
            "global_samples": self.global_samples,
            "samples_per_sec": self.tput_timer.avg_samples_per_sec(),
            "samples_per_sec_valid": self.tput_timer.has_samples(),
        }
        if self._offload is not None:
            extra["skipped_steps"] = self._offload.skipped_steps
        else:
            self.skipped_steps = int(
                jax.device_get(self.state.skipped_steps))
            extra["skipped_steps"] = self.skipped_steps
        return extra

    def profile_window(self, steps: int,
                       start_step: Optional[int] = None) -> Optional[str]:
        """Arm a ``jax.profiler`` capture over ``steps`` hot training
        steps (default: starting at the next ``train_batch``). The
        trace is ingested into the per-step wall decomposition and
        reconciled against the roofline cost model at the next telemetry
        drain (``telemetry.profile`` block); with telemetry off this is
        a no-op returning None. Returns the capture dir. Zero device
        syncs are added when no window is armed — the PR-4 fence
        contract."""
        return self.telemetry.arm_profile_window(
            int(steps), start_step=self.global_steps + 1
            if start_step is None else int(start_step))

    # ------------------------------------------------------------------ #
    # Roofline cost model (monitor/cost_model.py)
    # ------------------------------------------------------------------ #
    def _maybe_build_cost_model(self) -> None:
        """Build the roofline cost model ONCE, at the first report
        boundary — every active step path has compiled by then, and the
        recompile sentinel holds each one's abstract signature. The build
        AOT-relowers each path host-side (no device traffic, no fences);
        any failure degrades to a structured event, never to a dead
        training loop."""
        tl = self.telemetry
        if self._cost_model_built or not tl.enabled \
                or tl.sentinel is None \
                or not getattr(self.config.telemetry_config,
                               "cost_model", True):
            return
        self._cost_model_built = True
        try:
            from ..monitor.cost_model import build_cost_model
            step_paths = self._cost_model_step_paths()
            # Wire bytes are PER STEP; price them on the grad-computing
            # path, split per invocation so the step total reconciles.
            # Two tiers: the inter-slice DCN hop is priced against its
            # own (much lower) bandwidth ceiling — a step can be
            # DCN-bound while ICI idles.
            comm: Dict[str, float] = {}
            dcn: Dict[str, float] = {}
            ici_bytes = self._wire_bytes - self._wire_bytes_dcn
            for p in ("train_step", "offload_grad_step",
                      "sparse_grad_step", "grad_step"):
                if p in step_paths and self._wire_bytes:
                    comm[p] = float(ici_bytes) / step_paths[p]
                    if self._wire_bytes_dcn:
                        dcn[p] = float(self._wire_bytes_dcn) / \
                            step_paths[p]
                    break
            payload = build_cost_model(
                tl.sentinel, comm_bytes_by_path=comm,
                step_paths=step_paths, n_devices=int(self.mesh.size),
                dcn_bytes_by_path=dcn)
            pricing = self._optimizer_apply_pricing()
            if pricing is not None:
                payload["optimizer_apply"] = pricing
            payload.update(self._cost_model_extras(payload))
            tl.set_cost_model(payload,
                              samples_per_step=self.train_batch_size())
            step = payload.get("step", {})
            if step.get("bound"):
                log_dist(
                    "cost model: step is "
                    f"{step['bound']}-bound, analytic floor "
                    f"{step['floor_ms']:.3f} ms/step "
                    f"({payload['chip']['name']} peaks"
                    f"{', ASSUMED' if payload['chip']['assumed'] else ''})",
                    ranks=[0])
        except Exception as e:   # observability must not kill training
            tl.event("cost_model_error",
                     {"error": f"{type(e).__name__}: {e}"[:300]})

    def _optimizer_apply_pricing(self) -> Optional[Dict[str, Any]]:
        """Analytic HBM bytes the optimizer APPLY phase moves per step
        (ops/fused_update.apply_hbm_bytes): the active mode priced
        against the alternative, so the roofline record carries the
        one-pass-vs-two-pass ratio explicitly.  Figures are per replica
        of the full tree; under ZeRO the apply runs shard-local, so
        per-DEVICE bytes divide by ``zero_shard_divisor`` uniformly.
        None for engines whose apply is not the fused family (offload's
        host Adam, onebit's compressed exchange price differently)."""
        if self._fused_apply is None or self._offload is not None \
                or self._onebit:
            return None
        from ..ops.fused_update import apply_hbm_bytes
        # Sparse-gradient engines route the apply through the two-pass
        # sparse_apply_step regardless of fused_step availability.
        one_pass = self._fused_step is not None and \
            self._sparse_mask is None
        pricing = apply_hbm_bytes(
            self.state.params, one_pass=one_pass,
            cast_dtype=(self.compute_dtype if self._use_cast_cache
                        else None),
            fp16=self.config.fp16_enabled,
            clip=bool(self.gradient_clipping()))
        # Per-device bytes divide by dp only where the kernels actually
        # run shard-local — the same predicate that handed the mesh to
        # fused_adam (a live mp/pp axis keeps the plain lowering on
        # full buffers).
        shard = self.dp_size if self._fused_shard_local() else 1
        return {
            "mode": "one_pass" if one_pass else "two_pass",
            "per_replica": pricing,
            "zero_shard_divisor": shard,
            "active_bytes_per_device": int(pricing["active"] // shard),
        }

    def _cost_model_step_paths(self) -> Dict[str, float]:
        """{path_name: invocations per optimizer step} for the paths that
        compose ONE train step in the engine's active mode."""
        if self._offload is not None:
            return {"offload_grad_step": 1.0}
        if self._sparse_mask is not None and self.dp_size > 1:
            return {"sparse_grad_step": 1.0, "sparse_apply_step": 1.0}
        if self._train_step_fn is not None:
            return {"train_step": 1.0}
        # forward/backward/step trio: one grad program per micro-batch,
        # one apply at the accumulation boundary.
        return {"grad_step": float(self.gradient_accumulation_steps()),
                "apply_grads": 1.0}

    def _cost_model_extras(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Subclass hook for extra cost-model payload sections (the
        pipeline engine adds per-stage attribution)."""
        return {}

    # ------------------------------------------------------------------ #
    # Static lint audit (analysis/)
    # ------------------------------------------------------------------ #
    def _lint_path_meta(self, name: str) -> Dict[str, Any]:
        """Engine-truth metadata for the lint passes auditing path
        ``name`` (analysis/passes.py): which paths carry the gradient
        sync, at which DECLARED mode, the per-leaf payload sizes a
        grad-sync collective may legally carry, and the analytic
        per-device state bytes the materialization threshold scales
        from. Host metadata only — no device access."""
        from .zero.partition import _leaf_spec
        grad_paths = ("train_step", "offload_grad_step",
                      "sparse_grad_step", "grad_step")
        param_leaves = [l for l in
                        jax.tree_util.tree_leaves(self.state.params)
                        if hasattr(l, "shape")]
        param_bytes_full = sum(
            int(l.size) * int(l.dtype.itemsize) for l in param_leaves)
        # Largest single UNSHARDED leaf at f32 (grads promote to f32 on
        # every sync path): the materialization pass exempts buffers up
        # to one full leaf — per-leaf transients are inherent to any
        # lowering; the gate is about tree-scale materialization.
        largest_leaf = max(
            (int(l.size) * max(4, int(l.dtype.itemsize))
             for l in param_leaves), default=0)
        scatterable: set = set()
        if self.dp_size > 1:
            wire_itemsize = jnp.dtype(self.compute_dtype).itemsize
            # Under ZeRO >= 2 only the partitionable leaves reduce-
            # scatter; dense modes ("none"/"allreduce") sync EVERY grad
            # leaf — the pass still needs those payload sizes to judge
            # placement (an all-reduce trapped inside the gas scan).
            partitioned_only = self.zero_optimization_stage() >= 2
            for l in param_leaves:
                if partitioned_only and not any(
                        s is not None for s in
                        _leaf_spec(l.shape, self.dp_size, DP_AXIS)):
                    continue
                n = int(l.size)
                # Grads sync in f32 on the main paths; the offload
                # wire dtype is the compute dtype under bf16.
                scatterable.add(n * 4)
                scatterable.add(n * int(wire_itemsize))
        # Stage 3: the materialization gate's budget is the declared
        # (sharded) per-device state PLUS the bounded gather working set
        # — generic paths gather leaf-at-use (full tree at COMPUTE
        # dtype, transient), the layer-scan path holds prefetch_depth+1
        # gathered layers. Never the fp32 master tree.
        gather_ws = 0
        if self._zero3:
            from .zero.stage3 import gather_working_set_bytes
            spec = self._zero3_scan_spec
            gather_ws = gather_working_set_bytes(
                self.state.params, self._stage3_specs, DP_AXIS,
                jnp.dtype(self.compute_dtype).itemsize,
                prefetch_depth=self._prefetch_depth,
                scan_paths=spec.covers if spec is not None else None,
                mesh=self.mesh)
        # Expert-sharded leaves (MoE, ep > 1): the payload sizes an
        # expert-grad collective may legally carry (the per-device 1/ep
        # shard, and its per-layer slice inside the block scan) — any
        # all-reduce of one with replica groups WIDER than the data axis
        # spans the expert axis, i.e. treats experts as replicas: the
        # seeded-violation case collective_placement catches.
        expert_bytes: set = set()
        if self.ep_size > 1 and self._param_specs is not None:
            from ..moe.sharding import is_expert_spec
            all_leaves = jax.tree_util.tree_leaves(self.state.params)
            spec_leaves = jax.tree_util.tree_structure(
                self.state.params).flatten_up_to(self._param_specs)
            itemsizes = (4, int(jnp.dtype(self.compute_dtype).itemsize))
            from jax.sharding import PartitionSpec as _P

            def payloads(nelems, ndim, lead):
                # Full local buffer + its per-layer slice inside the
                # block scan, at f32 and the wire dtype.
                out = set()
                for b in itemsizes:
                    out.add(nelems * b)
                    if ndim >= 3 and lead > 0:
                        out.add(nelems // lead * b)
                return out

            dense_payloads: set = set()
            for l, sp in zip(all_leaves, spec_leaves):
                if not hasattr(l, "shape") or \
                        (isinstance(sp, _P) and is_expert_spec(sp)):
                    continue
                dense_payloads |= payloads(
                    int(l.size), getattr(l, "ndim", 0),
                    int(l.shape[0]) if getattr(l, "ndim", 0) else 0)
            for l, sp in zip(all_leaves, spec_leaves):
                if not hasattr(l, "shape") or \
                        not (isinstance(sp, _P) and is_expert_spec(sp)):
                    continue
                for payload in payloads(
                        int(l.size) // self.ep_size,
                        getattr(l, "ndim", 0),
                        int(l.shape[0]) if getattr(l, "ndim", 0) else 0):
                    # The check is a payload-size heuristic, so two
                    # guards against false positives: a 64 KiB floor
                    # (bias-sized expert leaves are byte-identical to
                    # small dense grads — a [H, E] router grad matches
                    # an expert-bias slice) and exclusion of any size a
                    # DENSE leaf could legally all-reduce at across the
                    # full replica set. A colliding size loses coverage
                    # for that one leaf, never CI.
                    if payload >= 64 * 1024 and \
                            payload not in dense_payloads:
                        expert_bytes.add(payload)
        # Factored replica meshes: the per-rank payloads the OUTER-axis
        # hop may legally carry — the 1/dp shard of every scatterable
        # dense leaf and the full replicated tail (f32; the compressed
        # DCN emulation psums the same shapes). collective_placement
        # whitelists outer-group all-reduces of these (a shard payload
        # can coincide byte-for-byte with a smaller leaf's full size)
        # and, on multislice meshes, flags anything grad-sized spanning
        # the slice axis (a flat joint sync over DCN). Expert-sharded
        # leaves are excluded — they never take the outer hop and have
        # their own check.
        dcn_shard_bytes: set = set()
        outer_factored = self.slice_size > 1 or (
            self.ep_size > 1 and
            getattr(self, "_grad_sync_mode", "none") == "explicit")
        if outer_factored:
            all_leaves = jax.tree_util.tree_leaves(self.state.params)
            if self._param_specs is not None and self.ep_size > 1:
                from ..moe.sharding import is_expert_spec
                spec_l = jax.tree_util.tree_structure(
                    self.state.params).flatten_up_to(self._param_specs)
            else:
                is_expert_spec = None
                spec_l = [None] * len(all_leaves)
            for l, sp in zip(all_leaves, spec_l):
                if not hasattr(l, "shape"):
                    continue
                if sp is not None and is_expert_spec is not None \
                        and is_expert_spec(sp):
                    continue
                n = int(l.size)
                if any(s is not None for s in
                       _leaf_spec(l.shape, self.dp_size, DP_AXIS)):
                    dcn_shard_bytes.add(n // self.dp_size * 4)
                else:
                    dcn_shard_bytes.add(n * 4)
        # Stage 3: the per-leaf GATHERED payload sizes (full leaf at the
        # wire dtypes, plus the per-layer slice for scanned leaves) — on
        # a multislice mesh collective_placement flags any all-gather of
        # one whose groups are wider than dp (param bytes over DCN; the
        # planner binds every stage-3 gather to `data`/ICI).
        z3_gather_leaf: set = set()
        if self._zero3 and self.dp_size > 1:
            from .zero.partition import spec_dp_dim
            wire_itemsize = int(jnp.dtype(self.compute_dtype).itemsize)
            leaves = jax.tree_util.tree_leaves(self.state.params)
            spec_l = jax.tree_util.tree_structure(
                self.state.params).flatten_up_to(self._stage3_specs)
            cov_l = jax.tree_util.tree_leaves(self._zero3_covered)
            for l, sp, cov in zip(leaves, spec_l, cov_l):
                if not hasattr(l, "shape"):
                    continue
                if spec_dp_dim(sp, DP_AXIS) is None:
                    continue
                n = int(l.size)
                for b in (wire_itemsize, 4):
                    z3_gather_leaf.add(n * b)
                    if cov and getattr(l, "ndim", 0) >= 1 and \
                            int(l.shape[0]) > 0:
                        z3_gather_leaf.add(n // int(l.shape[0]) * b)
        # The derived collective schedule (axis_algebra) the explicit
        # path executes — serialized for lint/audit consumers.
        plan_meta = None
        if getattr(self, "_grad_sync_mode", "none") == "explicit" and \
                self.replica_size > 1:
            from ..parallel.axis_algebra import (MeshFactorization,
                                                 plan_grad_sync)
            try:
                plan_meta = plan_grad_sync(
                    MeshFactorization.from_mesh(self.mesh),
                    zero3=bool(self._zero3),
                    dcn_compression=bool(self._dcn_compression)).to_meta()
            except ValueError:
                plan_meta = None
        return {
            "grad_sync_path": name in grad_paths,
            "grad_sync_mode": getattr(self, "_grad_sync_mode", "none"),
            # The trio's grad_step is one micro-batch per invocation; the
            # fused paths scan gas micro-batches inside one program.
            "gas": 1 if name == "grad_step" else self._scan_microbatches(),
            "scatterable_leaf_bytes": sorted(scatterable),
            "declared_state_bytes": int(analytic_state_bytes(self.state)),
            "param_bytes_full": int(param_bytes_full),
            "largest_leaf_bytes": int(largest_leaf),
            "dp": self.dp_size,
            "ep": self.ep_size,
            "slices": self.slice_size,
            "dcn_shard_bytes": sorted(dcn_shard_bytes),
            "expert_leaf_bytes": sorted(expert_bytes),
            "expert_group_size": self.dp_size,
            "zero_stage": self.zero_optimization_stage(),
            "zero3": bool(self._zero3),
            "zero3_gather_bytes": int(gather_ws),
            "zero3_gather_leaf_bytes": sorted(z3_gather_leaf),
            "collective_plan": plan_meta,
        }

    def lint_audit(self, config=None, waivers=None, passes=None):
        """Run the compile-time lint suite (analysis/) over every step
        path this engine has compiled — host-side re-lower from the
        recompile sentinel's recorded abstract signatures; zero device
        fences. Returns an ``analysis.findings.LintReport``."""
        from ..analysis.auditor import lint_engine
        return lint_engine(self, config=config, waivers=waivers,
                           passes=passes)

    def eval_batch(self, batch, rng=None):
        if self._eval_step_fn is None:
            self._eval_step_fn = self._build_eval_step()
        rng = rng if rng is not None else self._next_rng()
        return self._eval_step_fn(self.state.params, batch, rng)

    def _run_flops_profiler(self, micro_batches) -> None:
        """Trace the full train step and print the per-module FLOPs table
        (reference engine.py:801-824 runs its hook profiler over one forward
        at flops_profiler.profile_step; here the jaxpr walk covers
        forward+backward+optimizer in one analytic pass, no monkey-patching)."""
        from ..profiling.flops_profiler import profile_fn
        cfg = self.config.flops_profiler_config
        # The sentinel wrapper keeps the raw jitted fn on __wrapped__;
        # profile the raw fn so the jaxpr walk sees the same callable
        # either way (and the profiling trace is not counted as a call).
        step_fn = self._train_step_fn
        step_fn = getattr(step_fn, "__wrapped__", step_fn)
        if step_fn is None:     # offload path: profile the grad function
            if self._offload_grad_fn is None:
                self._offload_grad_fn = self.telemetry.instrument_step_fn(
                    "offload_grad_step",
                    self._build_offload_grad_fn(
                        bucketed=self._offload_overlap))
            grad_fn = getattr(self._offload_grad_fn, "__wrapped__",
                              self._offload_grad_fn)
            res = profile_fn(
                grad_fn, self.state.params, micro_batches,
                self._base_rng, jnp.asarray(self.global_steps, jnp.int32),
                jnp.asarray(self._offload.loss_scale, jnp.float32),
                params=self.state.params, run=False)
        else:
            res = profile_fn(step_fn, self.state, micro_batches,
                             self._base_rng, params=self.state.params,
                             run=False)
        self.flops_profiler.result = res
        if jax.process_index() == 0:
            self.flops_profiler.print_model_profile(
                module_depth=cfg.module_depth, top_modules=cfg.top_modules,
                detailed=cfg.detailed)

    def _maybe_log(self, metrics) -> None:
        """Log at steps_per_print boundaries ONLY — any device_get here is a
        host↔device sync that would stall the async dispatch pipeline (the
        TPU analogue of the reference keeping cuda.synchronize behind
        wall_clock_breakdown). skipped_steps syncs lazily from state. The
        telemetry drain rides the same boundary discipline (its own
        report_steps cadence, defaulting to steps_per_print)."""
        if self.global_steps % max(1, self.steps_per_print()) == 0:
            # Scalars only: the health tap rides metrics as a
            # [num_leaves] array and is drain/event material, not a
            # print-line field.
            m = {k: (float(jax.device_get(v)) if hasattr(v, "dtype") else v)
                 for k, v in metrics.items()
                 if getattr(v, "ndim", 0) == 0 or not hasattr(v, "dtype")}
            if m.get("grad_norm", 0.0) < 0:
                # Sentinel: norm computation skipped (no clipping, no fp16) —
                # don't surface a bogus value to logs/monitors.
                m.pop("grad_norm", None)
            if self._offload is None:
                self.skipped_steps = int(
                    jax.device_get(self.state.skipped_steps))
            gn = f"grad_norm={m['grad_norm']:.4f} " if "grad_norm" in m else ""
            off = ""
            if self._offload is not None and self.offload_timings:
                # The offload breakdown used to die as an undocumented
                # engine attribute; surface it where the operator looks.
                t = self.offload_timings
                host_ms = t.get("host_norm_ms", 0.0) + \
                    t.get("host_step_ms", 0.0)
                off = (f" offload[d2h={t.get('d2h_ms', 0.0):.0f}ms "
                       f"host={host_ms:.0f}ms "
                       f"h2d={t.get('h2d_dispatch_ms', 0.0):.0f}ms "
                       f"overlap={t.get('overlap_fraction', 0.0):.2f}]")
            log_dist(
                f"step={self.global_steps} loss={m['loss']:.6f} "
                f"lr={m['lr']:.3e} {gn}"
                f"loss_scale={m['loss_scale']:.1f} "
                f"overflow={bool(m['overflow'])}{off}",
                ranks=[0])
        self.telemetry.maybe_drain(self.global_steps,
                                   extra_fn=self._report_extra)

    # ------------------------------------------------------------------ #
    # torch-style compatibility trio (forward → backward → step)
    # ------------------------------------------------------------------ #
    def forward(self, batch):
        """Compute loss *and* grads in one jitted pass; grads are stashed for
        backward(). One forward execution per micro-batch, unlike a literal
        forward/backward split which would run the model twice."""
        if self._onebit:
            raise NotImplementedError(
                "OnebitAdam supports train_batch() only: the compressed "
                "allreduce lives inside the fused step, which the "
                "forward/backward/step split cannot drive")
        if self._dcn_compression:
            raise NotImplementedError(
                "zero_optimization.dcn_compression supports train_batch()"
                " only: the error-feedback buffers thread through the "
                "fused step, which the forward/backward/step split "
                "cannot drive")
        if self._grad_step_fn is None:
            self._build_grad_paths()
        if getattr(self, "_trio_t0", None) is None:
            # Start of an accumulation window: step()'s telemetry wall_ms
            # must cover forward+backward+apply, not just the apply.
            self._trio_t0 = time.perf_counter()
        theta = jnp.asarray(
            self.progressive_layer_drop.theta_at(self.global_steps),
            jnp.float32) if self._accepts_pld else None
        with self.telemetry.span("grad_compute"):
            grads, raw_loss = self._grad_step_fn(
                self.state.cast_params if self._use_cast_cache
                else self.state.params,
                batch, self._next_rng(), self.state.loss_scale, theta)
        self._stashed_grads = grads
        return raw_loss

    def backward(self, loss=None, allreduce_gradients: bool = True):
        """Accumulate the grads computed in forward()."""
        assert getattr(self, "_stashed_grads", None) is not None, \
            "call forward() before backward()"
        grads = self._stashed_grads
        self._stashed_grads = None
        if self._accum_grads is None:
            self._accum_grads = grads
        else:
            self._accum_grads = jax.tree_util.tree_map(
                jnp.add, self._accum_grads, grads)
        self.micro_steps += 1
        return loss

    def step(self):
        """Apply the optimizer at a grad-accum boundary (engine.py:1000-1085)."""
        if self.micro_steps % self.gradient_accumulation_steps() != 0:
            return  # not at boundary; parity with reference gating
        assert self._accum_grads is not None, "no gradients accumulated"
        t_apply = time.perf_counter()
        # Window wall from the first forward() of this accumulation cycle
        # (fallback: apply-only, when step() is driven without forward).
        t0 = getattr(self, "_trio_t0", None) or t_apply
        self._trio_t0 = None
        with self.telemetry.span("optimizer_apply"):
            self.state, metrics = self._apply_grads_fn(self.state,
                                                       self._accum_grads)
        self._accum_grads = None
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        self._record_telemetry(metrics, t0, t_apply)
        self._maybe_log(metrics)
        self._maybe_auto_save()

    def _build_grad_paths(self):
        gas = self.gradient_accumulation_steps()
        loss_fn = self.loss_fn
        compute_dtype = self.compute_dtype
        fp16 = self.config.fp16_enabled
        clip = self.gradient_clipping()
        tx = self.tx
        schedule_fn = self._schedule_fn
        scaler_kw = self._scaler_kw

        pld, accepts_pld = self.progressive_layer_drop, self._accepts_pld
        use_cache = self._use_cast_cache

        raw_scaled_loss = _make_raw_scaled_loss(loss_fn, accepts_pld,
                                                gas)

        def scaled_loss(params, mb, key, scale, theta):
            # forward() hands in state.cast_params when the cache is on.
            cparams = params if use_cache \
                else _cast_floats(params, compute_dtype)
            return raw_scaled_loss(cparams, mb, key, scale, theta)

        vg = jax.value_and_grad(scaled_loss, has_aux=True)

        grad_sh = self._grad_shardings()
        # Resolved-explicit engines route the trio's backward through the
        # same guaranteed psum_scatter path as the fused train step: the
        # declarative out_shardings below regress to a full all-reduce +
        # slice on this backend (the lint suite's grad-materialization
        # finding — grads would cross the wire unpartitioned at 2x the
        # reduce-scatter bytes, every micro-step).
        explicit_fn = None
        if self._grad_sync_mode == "explicit" and grad_sh is not None:
            explicit_fn = self._build_explicit_zero2_grads(
                raw_scaled_loss if self._zero3 else scaled_loss,
                grad_sh, gas=1)

        def grad_step(params, mb, key, scale, theta=None):
            if explicit_fn is not None:
                # One micro-batch per trio call: wrap in the [gas=1]
                # leading axis the explicit path scans over. The trio
                # has no metrics dict for MoE stats to ride — aux drops
                # (the aux LOSS is already inside raw_loss).
                mb1 = jax.tree_util.tree_map(lambda x: x[None], mb)
                g, loss, _aux, _err = explicit_fn(params, mb1, key[None],
                                                  scale, theta)
                return g, loss
            (_, (raw_loss, _aux)), grads = vg(params, mb, key, scale,
                                              theta)
            # fp32 grads regardless of compute dtype: backward() accumulates
            # micro-batches in these, and apply_grads clips/updates in fp32.
            return _cast_floats(grads, jnp.float32), raw_loss

        # ZeRO-2: grads leave the jitted backward already dp-sharded.
        grad_step = jax.jit(grad_step, out_shardings=(
            grad_sh, NamedSharding(self.mesh, P()))) \
            if grad_sh is not None else jax.jit(grad_step)

        fused_apply = self._fused_apply
        fused_step = self._fused_step
        use_cache = self._use_cast_cache
        health_taps = self._health_tap_fn

        def apply_grads(state: EngineState, grads):
            scale = state.loss_scale
            # Same in-graph health tap as the main train step — the trio
            # applies the ACCUMULATED (still loss-scaled) grads, so
            # provenance covers the whole window; unscale the tap so the
            # reported norms are true magnitudes (scale traces as 1.0
            # when not fp16).
            tap = None
            if health_taps is not None:
                tap = health_taps(grads) / (scale * scale)
            if fused_step is not None:
                # One-pass clipped update, same contract as the main
                # train step: unscale (scale is a traced 1.0 when not
                # fp16 — the kernel's scalar multiply replaces the
                # historical full-tree g/scale pass either way), norm,
                # overflow vote, clip, skip-select and cast-cache
                # refresh inside the single optimizer-state HBM pass.
                out = fused_step(
                    grads, state.opt_state, state.params, clip=clip,
                    inv_scale=1.0 / scale, fp16=fp16, compute_norm=True,
                    cast_dtype=compute_dtype if use_cache else None)
                new_params, new_opt = out.params, out.state
                new_cast = out.cast_params if use_cache else None
                grad_norm, overflow = out.grad_norm, out.overflow
            else:
                grads = jax.tree_util.tree_map(lambda g: g / scale, grads)
                overflow = tree_has_inf_or_nan(grads) if fp16 \
                    else jnp.asarray(False)
                grad_norm = global_norm(grads)
                new_params, new_opt = _clipped_update(
                    grads, state, grad_norm, tx=tx, fused_apply=fused_apply,
                    clip=clip)
                # Same cache refresh as the fused train step: the next
                # train_batch reads state.cast_params.
                new_cast = None
                if state.cast_params is not None:
                    new_cast = _tree_select(
                        overflow, state.cast_params,
                        _cast_floats(new_params, compute_dtype))
                new_params = _tree_select(overflow, state.params, new_params)
                new_opt = _tree_select(overflow, state.opt_state, new_opt)
            new_state = state.replace(
                params=new_params, opt_state=new_opt, cast_params=new_cast,
                **_overflow_resolution(state, overflow, **scaler_kw))
            metrics = {"loss": raw_metric_placeholder(), "grad_norm": grad_norm,
                       "lr": schedule_fn(state.step), "loss_scale": scale,
                       "overflow": overflow}
            if tap is not None:
                metrics["health_leaf_sq"] = tap
            return new_state, metrics

        def raw_metric_placeholder():
            return jnp.asarray(0.0, jnp.float32)

        self._grad_step_fn = self.telemetry.instrument_step_fn(
            "grad_step", grad_step)
        self._apply_grads_fn = self.telemetry.instrument_step_fn(
            "apply_grads",
            jax.jit(apply_grads, donate_argnums=(0,),
                    out_shardings=(self._state_shardings,
                                   self._metrics_shardings(
                                       with_taps=health_taps is not None))))
        return self._grad_step_fn

    # ------------------------------------------------------------------ #
    # Checkpointing (reference engine.py:1472-1572, §3.5)
    # ------------------------------------------------------------------ #
    def _get_ckpt_name(self, checkpoints_path: str, tag: str) -> str:
        return os.path.join(checkpoints_path, str(tag), MODEL_FILE)

    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[Dict[str, Any]] = None,
                        save_latest: bool = True) -> bool:
        """Save a checkpoint. With ``checkpoint.async`` the call returns
        after the in-step-window SNAPSHOT (one batched device fetch) and
        a background thread serializes + commits; otherwise the whole
        save runs inline. Both routes share the snapshot builder and the
        two-phase atomic commit (runtime/async_ckpt.py), so the written
        artifact is byte-identical either way."""
        if self._async_ckpt is not None:
            return self._save_checkpoint_async(save_dir, tag, client_state,
                                               save_latest)
        with self.telemetry.span("checkpoint_save",
                                 tag=str(tag) if tag is not None else "auto"):
            return self._save_checkpoint(save_dir, tag, client_state,
                                         save_latest)

    def _save_checkpoint_async(self, save_dir: str, tag: Optional[str],
                               client_state: Optional[Dict[str, Any]],
                               save_latest: bool) -> bool:
        """Async save: the exposed cost is the ``checkpoint_snapshot``
        span below (snapshot fetch + any blocking wait for writer-queue
        room); serialization and the commit happen on the writer thread
        and are priced into the ledger's background bucket."""
        err = self._async_ckpt.last_error
        if err is not None:
            # Surface a failed background write on the NEXT save, where
            # a caller can react — not silently in a daemon thread.
            self._async_ckpt.last_error = None
            raise RuntimeError(
                "a previous background checkpoint write failed "
                f"({type(err).__name__}: {err}); the checkpoint it was "
                "writing is lost (latest still names the prior one)") \
                from err
        with self.telemetry.span(
                "checkpoint_snapshot",
                tag=str(tag) if tag is not None else "auto"):
            # Bound host memory: each pending snapshot is a full host
            # copy of the state. Waiting here is exposed wall and lands
            # in the checkpoint bucket — honest accounting of a writer
            # that cannot keep up with snapshot_every. A writer still
            # wedged after writer_timeout_s fails the save LOUDLY:
            # queueing another full-state copy would break the
            # max_pending_snapshots bound, and the guard watchdog's
            # stack dump already names what it is stuck on.
            if not self._async_ckpt.wait_below(
                    self._ckpt_max_pending,
                    timeout=self._ckpt_writer_timeout):
                raise RuntimeError(
                    "checkpoint writer still busy after "
                    f"{self._ckpt_writer_timeout:.0f}s — refusing to "
                    "queue another full-state host snapshot past "
                    f"max_pending_snapshots={self._ckpt_max_pending} "
                    "(see the writer watchdog's stack dump)")
            snap = self._snapshot_checkpoint(save_dir, tag, client_state,
                                             save_latest)
            crash_point("after_snapshot")
            self._async_ckpt.submit(snap)
        self._note_saved(save_dir, save_latest)
        log_dist(f"checkpoint snapshot {snap.path} taken "
                 "(background write queued)", ranks=[0])
        return True

    def _save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                         client_state: Optional[Dict[str, Any]] = None,
                         save_latest: bool = True) -> bool:
        """Synchronous save: snapshot + inline commit."""
        snap = self._snapshot_checkpoint(save_dir, tag, client_state,
                                         save_latest)
        path = commit_snapshot(snap)
        self._note_saved(save_dir, save_latest)
        log_dist(f"saved checkpoint {path}", ranks=[0])
        return True

    def _note_saved(self, save_dir: str, save_latest: bool) -> None:
        """Track the last step whose state reached the AUTO-SAVE dir's
        ``latest`` — the preemption handler's dedup key. Saves into other
        dirs (or without the latest flip) don't count: a final SIGTERM
        save must still land in ``checkpoint.save_dir``."""
        if save_latest and self._ckpt_dir and \
                os.path.abspath(save_dir) == os.path.abspath(self._ckpt_dir):
            self._last_saved_step = self.global_steps

    def _snapshot_checkpoint(self, save_dir: str, tag: Optional[str],
                             client_state: Optional[Dict[str, Any]],
                             save_latest: bool) -> CheckpointSnapshot:
        """Capture the engine state into a host-side CheckpointSnapshot
        with the reference's sharded layout (engine.py:1472-1572, §3.5):

        - ``mp_rank_XX_model_states.msgpack`` — model params, one file per
          TP rank when mp > 1 (each holds only that rank's slice).
        - ``zero_pp_rank_D_mp_rank_00_optim_states.msgpack`` — one file per
          dp rank with that rank's ZeRO shard of the optimizer state; no
          host ever materializes the full unsharded moments. When
          multislice DCN compression is live, the error-feedback buffers
          ride these files under ``dcnN`` keys, sharded the same way.
        - ``latest`` pointer + ``engine_meta.json`` (counters + shard map;
          the meta file doubles as the commit's completeness seal).

        The device fetch is ONE batched ``jax.device_get`` over every
        leaf the checkpoint needs — the telemetry drain's batched-fetch
        discipline (fence-asserted in tier-1); serialization is deferred
        to lazy blob builders so the async writer pays it, not the step
        window. Load re-assembles full arrays from the shards and
        re-partitions for the CURRENT mesh, so dp-resize-on-load
        (stage1.py:848-1106 elastic checkpoints) works across any dp
        sizes.
        """
        if tag is None:
            tag = f"global_step{self.global_steps}"
        self._checkpoint_tag_validation(tag)
        # Non-array metadata goes in a JSON sidecar: msgpack restore is
        # target-structured and would drop arbitrary client_state shapes.
        meta: Dict[str, Any] = {
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "skipped_steps": self.skipped_steps,
            "dp_world_size": self.dp_size,
            "ds_config_precision": self.config.precision_dtype,
            "client_state": client_state or {},
        }
        if type(getattr(self.state, "opt_state", None)).__name__ == \
                "FusedAdamState":
            # Moment-buffer layout version: 2 = V-interleaved shard-local
            # rows (ISSUE 8). Pre-v2 checkpoints stored end-to-end leaf
            # concatenation — same flat dtype, sometimes the same padded
            # SIZE, so a silent restore would scramble moments across
            # leaves; the load path refuses them instead.
            meta["fused_moment_layout"] = 2
        if self.lr_scheduler is not None and \
                hasattr(self.lr_scheduler, "state_dict"):
            meta["lr_scheduler"] = self.lr_scheduler.state_dict()

        blobs: List[Any] = []
        if self._offload is not None:
            # Host masters ARE canonical; host-resident state saves
            # whole. COPY the arrays: the background writer serializes
            # this instant's values while the next steps mutate the
            # buffers in place.
            def _host_copy(x):
                return np.array(x, copy=True) if isinstance(
                    x, np.ndarray) else np.asarray(x)
            host_params = jax.tree_util.tree_map(
                _host_copy, self._offload.master_tree())
            off_state = jax.tree_util.tree_map(
                lambda x: np.array(x, copy=True)
                if isinstance(x, np.ndarray) else x,
                self._offload.state_dict())
            blobs.append((MODEL_FILE,
                          lambda hp=host_params:
                          flax_serialization.to_bytes({"module": hp})))
            blobs.append((OPTIM_FILE_FMT,
                          lambda st=off_state:
                          flax_serialization.to_bytes({"offload": st})))
        else:
            # THE batched fetch: every device leaf the checkpoint needs,
            # in one device_get (params + moments + scalars + DCN error
            # feedback). The host counter refresh rides it too — the
            # old separate skipped_steps sync is gone.
            param_leaves = jax.tree_util.tree_leaves(self.state.params)
            opt_leaves = jax.tree_util.tree_leaves(self.state.opt_state)
            scalars = [self.state.step, self.state.loss_scale,
                       self.state.growth_count, self.state.hysteresis,
                       self.state.skipped_steps]
            dcn_leaves = [] if self.state.dcn_error is None else \
                jax.tree_util.tree_leaves(self.state.dcn_error)
            fetched = [np.asarray(x) for x in jax.device_get(
                param_leaves + opt_leaves + scalars + dcn_leaves)]
            n_p, n_o = len(param_leaves), len(opt_leaves)
            host_param_leaves = fetched[:n_p]
            host_opt_leaves = fetched[n_p:n_p + n_o]
            step_v, scale_v, growth_v, hyst_v, skipped_v = \
                fetched[n_p + n_o:n_p + n_o + 5]
            host_dcn_leaves = fetched[n_p + n_o + 5:]
            self.skipped_steps = int(skipped_v)
            meta["skipped_steps"] = self.skipped_steps
            blobs += self._snapshot_model_blobs(meta, host_param_leaves)
            scalars_blob = {"__scalars__": {
                "step": step_v, "loss_scale": scale_v,
                "growth_count": growth_v, "hysteresis": hyst_v,
                "skipped": skipped_v}}
            blobs += self._snapshot_optim_blobs(
                meta, host_opt_leaves, scalars_blob, host_dcn_leaves)
        return CheckpointSnapshot(
            save_dir=save_dir, tag=str(tag), save_latest=save_latest,
            meta=meta, blobs=blobs,
            is_writer=jax.process_index() == 0, fsync=self._ckpt_fsync)

    def preempt_save(self, reason: str = "SIGTERM") -> bool:
        """Final snapshot+commit for a dying run — the PreemptSaver's
        SIGTERM entry (callable directly). When a background write is
        already in flight, WAIT for it instead of snapshotting again:
        that commit IS the final checkpoint. When the current step is
        already saved, do nothing. True when ``latest`` names a
        checkpoint of the current step on return."""
        if not self._ckpt_dir:
            return False
        ck = self._async_ckpt
        awaited_ok = True
        if ck is not None and ck.in_flight:
            awaited_ok = bool(ck.wait(timeout=self._ckpt_writer_timeout))
            self.telemetry.event("preempt_save", {
                "reason": reason, "mode": "awaited_inflight",
                "ok": awaited_ok})
        # _last_saved_step is stamped at SUBMIT time; only trust it when
        # the writer actually committed — a failed (or still-wedged)
        # background write means `latest` never flipped, and skipping
        # here would lose up to snapshot_every steps on the exact event
        # this handler exists for. Fall through to the inline save
        # instead.
        write_failed = ck is not None and ck.last_error is not None
        if awaited_ok and not write_failed and \
                self._last_saved_step == self.global_steps:
            return True
        # Inline save even under async config: the process is dying and
        # a queued write would die with it.
        with self.telemetry.span("checkpoint_save", tag="preempt"):
            snap = self._snapshot_checkpoint(self._ckpt_dir, None, None,
                                             True)
            commit_snapshot(snap)
        if write_failed:
            # The inline commit just superseded the lost write: latest
            # now names the CURRENT step, so the stale error must not
            # fail a later save for an already-recovered checkpoint.
            ck.last_error = None
        self._last_saved_step = self.global_steps
        self.telemetry.event("preempt_save", {
            "reason": reason, "mode": "saved", "tag": snap.tag,
            "step": self.global_steps})
        log_dist(f"preemption save: committed {snap.path}", ranks=[0])
        return True

    @staticmethod
    def _effective_axes(leaves, sh_leaves, axis_name: str, n: int):
        """Per-leaf shard axis, demoted to None (replicated in the files)
        when the leaf can't be split evenly."""
        axes = []
        for leaf, sh in zip(leaves, sh_leaves):
            ax = _spec_axis(sh, axis_name)
            if ax is not None and (not hasattr(leaf, "ndim") or leaf.ndim == 0
                                   or leaf.shape[ax] % n != 0):
                ax = None
            axes.append(ax)
        return axes

    @staticmethod
    def _shard_blob_builders(fmt: str, n: int, leaves, axes,
                             extras_shard0: Optional[Dict[str, Any]] = None,
                             groups: Optional[Dict[str, Any]] = None):
        """One LAZY msgpack builder per rank with that rank's slices of
        the already-fetched HOST leaves; replicated leaves and extras
        ride shard 0 only. Slicing host arrays is views — the expensive
        serialization happens when the builder runs, on the writer
        thread under async saving. ``groups`` adds key-prefixed leaf
        families to every shard file (the DCN error-feedback buffers
        ride the optim shards under ``dcnN`` keys)."""
        groups = groups or {}

        def build(r: int) -> bytes:
            blob: Dict[str, Any] = {}

            def put(prefix, lvs, axs):
                for i, (leaf, ax) in enumerate(zip(lvs, axs)):
                    if ax is None:
                        if r == 0:
                            blob[f"{prefix}{i}"] = np.asarray(leaf)
                        continue
                    c = leaf.shape[ax] // n
                    sl = [slice(None)] * leaf.ndim
                    sl[ax] = slice(r * c, (r + 1) * c)
                    blob[f"{prefix}{i}"] = np.ascontiguousarray(
                        leaf[tuple(sl)])

            put("", leaves, axes)
            for prefix, (glvs, gaxs) in groups.items():
                put(prefix, glvs, gaxs)
            if r == 0 and extras_shard0:
                blob.update(extras_shard0)
            return flax_serialization.msgpack_serialize(blob)

        return [(fmt.format(r), lambda r=r: build(r)) for r in range(n)]

    def _snapshot_model_blobs(self, meta: Dict[str, Any],
                              host_param_leaves):
        """Model blob builders from the already-fetched host leaves:
        single mp_rank_00 file, or per-TP-rank slice files when mp > 1
        (reference mp_rank_XX naming, engine.py:1275-1280)."""
        mp = int(self.mesh.shape.get(MP_AXIS, 1))
        sh_leaves = jax.tree_util.tree_leaves(self._state_shardings.params)
        axes = self._effective_axes(host_param_leaves, sh_leaves, MP_AXIS, mp)
        if mp > 1 and any(ax is not None for ax in axes):
            meta["mp_shards"] = mp
            meta["param_shard_axes"] = axes
            return self._shard_blob_builders(MODEL_FILE_FMT, mp,
                                             host_param_leaves, axes)
        treedef = jax.tree_util.tree_structure(self.state.params)
        host_params = jax.tree_util.tree_unflatten(treedef,
                                                   host_param_leaves)
        return [(MODEL_FILE,
                 lambda hp=host_params:
                 flax_serialization.to_bytes({"module": hp}))]

    def _snapshot_optim_blobs(self, meta: Dict[str, Any], host_opt_leaves,
                              scalars_blob: Dict[str, Any],
                              host_dcn_leaves):
        """One optim blob per dp rank holding that rank's ZeRO shard
        (zero_pp_rank_D naming, engine.py:1262-1268). Scalars and
        replicated leaves ride shard 0; the multislice DCN
        error-feedback buffers (when compression is live) ride every
        shard under ``dcnN`` keys, dp-sliced like the moments — so a
        resume no longer restarts the feedback at zero (the old
        documented one-step bias)."""
        dp = self.dp_size
        sh_leaves = jax.tree_util.tree_leaves(self._state_shardings.opt_state)
        axes = self._effective_axes(host_opt_leaves, sh_leaves, DP_AXIS, dp)
        meta["optim_shards"] = dp
        meta["optim_shard_axes"] = axes
        groups: Dict[str, Any] = {}
        if host_dcn_leaves:
            dcn_sh = jax.tree_util.tree_leaves(
                self._state_shardings.dcn_error)
            dcn_axes = self._effective_axes(host_dcn_leaves, dcn_sh,
                                            DP_AXIS, dp)
            meta["dcn_error_shard_axes"] = dcn_axes
            groups["dcn"] = (host_dcn_leaves, dcn_axes)
        return self._shard_blob_builders(OPTIM_SHARD_FMT, dp,
                                         host_opt_leaves, axes,
                                         extras_shard0=scalars_blob,
                                         groups=groups)

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_module_strict: bool = True,
                        load_optimizer_states: bool = True,
                        load_lr_scheduler_states: bool = True):
        """Telemetry-spanned entry; see ``_load_checkpoint``."""
        with self.telemetry.span("checkpoint_load", dir=str(load_dir)):
            return self._load_checkpoint(load_dir, tag, load_module_strict,
                                         load_optimizer_states,
                                         load_lr_scheduler_states)

    def _load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                         load_module_strict: bool = True,
                         load_optimizer_states: bool = True,
                         load_lr_scheduler_states: bool = True):
        if tag is None:
            latest = os.path.join(load_dir, LATEST_FILE)
            if not os.path.isfile(latest):
                logger.warning(f"no 'latest' file at {load_dir}; nothing loaded")
                return None, {}
            with open(latest) as f:
                tag = f.read().strip()
        path = os.path.join(load_dir, str(tag))
        if not os.path.isdir(path):
            logger.warning(f"checkpoint {path} not found; nothing loaded")
            return None, {}
        if not is_complete(path):
            # Torn tag: the commit protocol writes engine_meta.json LAST
            # (inside the tmp dir, before the atomic rename), so a tag
            # dir without it was produced by an interrupted pre-protocol
            # writer. Refuse cleanly BEFORE touching any engine state —
            # a half-restored engine is worse than no restore.
            logger.warning(
                f"checkpoint {path} is INCOMPLETE (no engine_meta.json "
                "completeness seal) — a torn/interrupted save; refusing "
                "to load it. Delete the tag dir (and repoint 'latest' at "
                "an intact tag) to clear this.")
            return None, {}
        meta_file = os.path.join(path, META_FILE)
        meta = {}
        if os.path.isfile(meta_file):
            with open(meta_file) as f:
                meta = json.load(f)

        # cast_params is re-derived by _place_state; dcn_error restores
        # from its own shard keys using tree STRUCTURE only — fetching
        # either here would pull full-model-sized trees device-to-host
        # for nothing (the skip-fetch survives whether or not
        # compression is on).
        host_state = jax.device_get(self.state.replace(cast_params=None,
                                                       dcn_error=None))
        if load_optimizer_states and \
                type(host_state.opt_state).__name__ == "FusedAdamState" \
                and int(meta.get("fused_moment_layout", 1)) != 2:
            # The fused moment buffers changed layout (end-to-end leaf
            # concatenation -> V-interleaved rows, ISSUE 8). The flat
            # sizes can coincide, so a structural restore would SILENTLY
            # scramble Adam moments across leaves — refuse loudly,
            # BEFORE any engine state (params, counters) is touched so a
            # caller catching the error keeps a consistent engine.
            raise ValueError(
                f"checkpoint {path} stores fused optimizer moments in the "
                "pre-ISSUE-8 flat layout (no fused_moment_layout=2 marker "
                "in engine_meta.json) which is incompatible with the "
                "V-interleaved buffers this engine runs; load with "
                "load_optimizer_states=False (params restore fine, "
                "moments re-initialize) or re-save from the writing "
                "version")
        params_target = host_state.params if self._offload is None \
            else jax.device_get(self._offload.master_tree())
        if meta.get("pipeline_layer_files"):
            new_params = self._load_pipeline_layer_states(
                path, meta, params_target)
            if new_params is None:
                return None, {}
        elif meta.get("mp_shards"):
            new_params = self._assemble_shards(
                path, MODEL_FILE_FMT, int(meta["mp_shards"]),
                meta["param_shard_axes"], params_target)
            if new_params is None:
                return None, {}
        else:
            model_file = os.path.join(path, MODEL_FILE)
            if not os.path.isfile(model_file):
                logger.warning(f"checkpoint {model_file} not found")
                return None, {}
            with open(model_file, "rb") as f:
                raw_model = f.read()
            probe = flax_serialization.msgpack_restore(raw_model)
            if not (isinstance(probe, dict) and "module" in probe):
                # mp-sharded shard 0 reuses the legacy filename; without the
                # sidecar we can't know the shard axes.
                raise ValueError(
                    f"{model_file} is a SHARDED (mp_rank) model checkpoint "
                    "but engine_meta.json is missing/unreadable — restore "
                    "the sidecar to load it")
            model_blob = flax_serialization.from_state_dict(
                {"module": params_target}, probe)
            new_params = model_blob["module"]
        self.global_steps = int(meta.get("global_steps", 0))
        self.global_samples = int(meta.get("global_samples", 0))
        self.skipped_steps = int(meta.get("skipped_steps", 0))
        self.micro_steps = self.global_steps * self.gradient_accumulation_steps()

        updates: Dict[str, Any] = {"params": new_params}
        if self._offload is not None:
            # masters are canonical; device params re-derive from them.
            # set_masters refreshes the bf16 staging buffers — without it,
            # device_params() at step_count>0 would serve the PRE-load
            # staging weights on the load_optimizer_states=False path.
            self._offload.set_masters(jax.tree_util.tree_leaves(new_params))
            if load_optimizer_states:
                optim_file = os.path.join(path, OPTIM_FILE_FMT)
                if os.path.isfile(optim_file):
                    with open(optim_file, "rb") as f:
                        blob = flax_serialization.from_bytes(
                            {"offload": self._offload.state_dict()}, f.read())
                    self._offload.load_state_dict(blob["offload"])
                    self.skipped_steps = self._offload.skipped_steps
            if load_lr_scheduler_states and self.lr_scheduler is not None \
                    and "lr_scheduler" in meta \
                    and hasattr(self.lr_scheduler, "load_state_dict"):
                self.lr_scheduler.load_state_dict(meta["lr_scheduler"])
            updates["params"] = self._offload.device_params()
            updates["step"] = jnp.asarray(self._offload.step_count, jnp.int32)
            self.state = self._place_state(self.state.replace(**updates))
            log_dist(f"loaded offload checkpoint {path} at "
                     f"global_step={self.global_steps}", ranks=[0])
            return path, meta.get("client_state", {})
        if load_optimizer_states and meta.get("optim_shards"):
            # Sharded layout: re-assemble the full state from every saved
            # dp rank's file; _place_state re-partitions for the CURRENT
            # mesh — elastic dp-resize (stage1.py:848-1106).
            saved_dp = int(meta["optim_shards"])
            # One parse of the shard files feeds the optim state, the
            # scalars, AND the dcn error family — these are the largest
            # blobs in the checkpoint; deserializing them twice would
            # double the load's heaviest phase.
            shard_blobs = self._read_shard_blobs(path, OPTIM_SHARD_FMT,
                                                 saved_dp)
            assembled = self._assemble_shards(
                path, OPTIM_SHARD_FMT, saved_dp, meta["optim_shard_axes"],
                host_state.opt_state, blobs=shard_blobs)
            if assembled is not None:
                scalars = shard_blobs[0]["__scalars__"]
                updates.update(
                    opt_state=assembled,
                    step=jnp.asarray(scalars["step"]),
                    loss_scale=jnp.asarray(scalars["loss_scale"]),
                    growth_count=jnp.asarray(scalars["growth_count"]),
                    hysteresis=jnp.asarray(scalars["hysteresis"]),
                    skipped_steps=jnp.asarray(scalars["skipped"]))
            if self.state.dcn_error is not None:
                # DCN-compression error feedback: restore the carried
                # residuals (dp/slice-elastic like everything else — a
                # slice-count change shape-mismatches per leaf and keeps
                # the fresh zeros with a warning). Skipped entirely when
                # compression is off.
                if meta.get("dcn_error_shard_axes"):
                    dcn = self._assemble_shards(
                        path, OPTIM_SHARD_FMT, saved_dp,
                        meta["dcn_error_shard_axes"],
                        self.state.dcn_error, key_prefix="dcn",
                        blobs=shard_blobs)
                    if dcn is not None:
                        updates["dcn_error"] = dcn
                else:
                    logger.warning(
                        f"checkpoint {path} carries no dcn_error "
                        "buffers (pre-resilience save); DCN error "
                        "feedback restarts at zero — a one-step "
                        "compression bias, self-correcting")
        elif load_optimizer_states:
            optim_file = os.path.join(path, OPTIM_FILE_FMT)
            if os.path.isfile(optim_file):
                with open(optim_file, "rb") as f:
                    raw = f.read()
                # New sharded files reuse the legacy rank-0 name; without
                # engine_meta.json we can't know the shard axes — fail with
                # a real message, not a flax structure explosion.
                probe = flax_serialization.msgpack_restore(raw)
                if isinstance(probe, dict) and "__scalars__" in probe:
                    raise ValueError(
                        f"{optim_file} is a SHARDED optimizer checkpoint "
                        "but engine_meta.json is missing/unreadable — "
                        "restore the sidecar to load it")
                optim_blob = flax_serialization.from_state_dict(
                    {"opt_state": host_state.opt_state,
                     "step": np.asarray(host_state.step),
                     "loss_scale": np.asarray(host_state.loss_scale),
                     "growth_count": np.asarray(host_state.growth_count),
                     "hysteresis": np.asarray(host_state.hysteresis),
                     "skipped": np.asarray(host_state.skipped_steps)}, probe)
                updates.update(
                    opt_state=optim_blob["opt_state"],
                    step=jnp.asarray(optim_blob["step"]),
                    loss_scale=jnp.asarray(optim_blob["loss_scale"]),
                    growth_count=jnp.asarray(optim_blob["growth_count"]),
                    hysteresis=jnp.asarray(optim_blob["hysteresis"]),
                    skipped_steps=jnp.asarray(optim_blob["skipped"]))
        if load_lr_scheduler_states and self.lr_scheduler is not None and \
                "lr_scheduler" in meta and \
                hasattr(self.lr_scheduler, "load_state_dict"):
            self.lr_scheduler.load_state_dict(meta["lr_scheduler"])

        self.state = self._place_state(self.state.replace(**updates))
        log_dist(f"loaded checkpoint {path} at global_step={self.global_steps}",
                 ranks=[0])
        return path, meta.get("client_state", {})

    @staticmethod
    def _read_shard_blobs(path: str, fmt: str, n: int):
        """Deserialize all ``n`` shard files once (the heaviest part of
        a load — full Adam moment shards); None if any is missing.
        Callers assembling multiple leaf families from the same files
        (optim state + dcn error feedback) share one parse."""
        blobs = []
        for r in range(n):
            fp = os.path.join(path, fmt.format(r))
            if not os.path.isfile(fp):
                logger.warning(f"checkpoint shard {fp} not found")
                return None
            with open(fp, "rb") as f:
                blobs.append(flax_serialization.msgpack_restore(f.read()))
        return blobs

    def _assemble_shards(self, path: str, fmt: str, n: int, axes,
                         target_tree, key_prefix: str = "",
                         blobs=None):
        """Read ``n`` shard files (or reuse pre-parsed ``blobs``) and
        concatenate each leaf along its recorded axis (replicated leaves
        come from shard 0). Returns the full tree with ``target_tree``'s
        structure, or None if files are missing. ``key_prefix`` selects
        a prefixed leaf family riding the same files (the DCN error
        buffers' ``dcnN`` keys)."""
        if blobs is None:
            blobs = self._read_shard_blobs(path, fmt, n)
        if blobs is None:
            return None
        leaves, treedef = jax.tree_util.tree_flatten(target_tree)
        if len(leaves) != len(axes):
            raise ValueError(
                f"checkpoint shard layout has {len(axes)} leaves but the "
                f"current state has {len(leaves)} — the optimizer/model "
                "structure changed since this checkpoint was saved")
        out = []
        for i, (leaf, ax) in enumerate(zip(leaves, axes)):
            if ax is None:
                val = blobs[0][f"{key_prefix}{i}"]
            else:
                val = np.concatenate([b[f"{key_prefix}{i}"] for b in blobs],
                                     axis=int(ax))
            if hasattr(leaf, "shape") and np.shape(val) != np.shape(leaf):
                # Elastic-incompatible leaf (e.g. onebit worker_error's
                # per-rank [dp] axis under a different dp): keep the current
                # (fresh) value rather than loading a wrong-shaped one.
                logger.warning(
                    f"checkpoint leaf {i}: saved shape {np.shape(val)} != "
                    f"current {np.shape(leaf)}; keeping current value")
                val = leaf
            out.append(val)
        return jax.tree_util.tree_unflatten(treedef, out)

    def _load_pipeline_layer_states(self, path, meta, params_target):
        raise NotImplementedError(
            "checkpoint has pipeline per-layer files; load it through a "
            "PipelineEngine")

    def _checkpoint_tag_validation(self, tag: str) -> None:
        """Cross-host tag consistency vote (engine.py:1455-1470): under SPMD
        all hosts run the same program so mismatch can only come from
        client-supplied tags; verify by hashing when multi-host."""
        if jax.process_count() == 1 or not self.config.checkpoint_tag_validation_enabled:
            return
        import hashlib
        h = int(hashlib.sha1(tag.encode()).hexdigest()[:8], 16)
        arr = jnp.asarray([h], jnp.int32)
        # max == min across hosts iff all tags equal.
        mx = jax.device_get(comm.all_reduce_host(arr, op="max")) \
            if hasattr(comm, "all_reduce_host") else arr
        mn = jax.device_get(comm.all_reduce_host(arr, op="min")) \
            if hasattr(comm, "all_reduce_host") else arr
        if int(mx[0]) != int(mn[0]):
            msg = f"checkpoint tag '{tag}' differs across hosts"
            if self.config.checkpoint_tag_validation_fail:
                raise ValueError(msg)
            logger.warning(msg)


# The engine's old private ``_Monitor`` (tensorboard-gated JSONL sink that
# every process appended to and never closed) is subsumed by the telemetry
# subsystem: ``monitor/telemetry.py::JsonlSink`` is the process-0-guarded,
# close()/atexit-managed successor, and the ``tensorboard`` config block
# is an alias for a telemetry sink (runtime/config.py::TelemetryConfig).
