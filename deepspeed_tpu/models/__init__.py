"""Model families shipped with the framework.

The reference ships fused BERT kernels (csrc/transformer/) and drives GPT-2 /
BERT through external example repos (tests/model/Megatron_GPT2, BingBertSquad).
Here the models are first-class: pure-functional JAX transformers with
mesh-axis sharding specs (Megatron-style TP), scan-over-layers compilation,
and remat policies standing in for the reference's memory knobs.
"""
from .transformer import TransformerConfig, layer_norm, dense
from .gpt2 import (GPT2Config, gpt2_init, gpt2_apply, gpt2_logits_at,
                   gpt2_loss_fn, gpt2_param_shardings, GPT2_CONFIGS)
from .bert import (BertConfig, bert_init, bert_apply, bert_mlm_loss_fn,
                   bert_param_shardings, BERT_CONFIGS)

__all__ = [
    "TransformerConfig", "layer_norm", "dense",
    "GPT2Config", "gpt2_init", "gpt2_apply", "gpt2_logits_at",
    "gpt2_loss_fn", "gpt2_param_shardings", "GPT2_CONFIGS",
    "BertConfig", "bert_init", "bert_apply", "bert_mlm_loss_fn",
    "bert_param_shardings", "BERT_CONFIGS",
]
