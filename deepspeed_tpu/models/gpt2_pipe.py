"""GPT-2 expressed for the SPMD pipeline (pipe/spmd.py model contract).

The reference pipelines GPT-2 via Megatron's PipelineModule layer lists
(docs/_tutorials/pipeline.md); here the pipelined form is derived directly
from the same param pytree as models.gpt2: shared (embeddings + final LN,
replicated over pp — the tied embed/unembed pair, TiedLayerSpec parity) and
the stacked transformer blocks (sharded over pp on the layer dim).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .gpt2 import GPT2Config, gpt2_init
from .transformer import apply_blocks, block_param_shardings, layer_norm
from ..runtime.pipe.spmd import pipeline_param_shardings


@dataclasses.dataclass
class PipeSpec:
    """Uniform-stage pipeline model: funcs + params + shardings.

    The PipelineEngine consumes this for compiled pp>1 execution; see
    pipe/spmd.py for the contract.
    """
    embed_fn: Any
    stage_fn: Any
    head_fn: Any
    params: Dict[str, Any]
    shardings: Dict[str, Any]
    num_layers: int

    def loss_fn(self, num_stages: int, num_micro: int, mesh,
                remat: bool = True):
        from ..runtime.pipe.spmd import spmd_pipeline_loss
        return spmd_pipeline_loss(self.embed_fn, self.stage_fn, self.head_fn,
                                  num_stages, num_micro, mesh, remat=remat)


def gpt2_pipe_spec(cfg: GPT2Config, rng=None,
                   mp_axis: str = "model") -> PipeSpec:
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    flat = gpt2_init(rng, cfg)
    params = {
        "shared": {"wte": flat["wte"], "wpe": flat["wpe"],
                   "ln_f_scale": flat["ln_f_scale"],
                   "ln_f_bias": flat["ln_f_bias"]},
        "blocks": flat["blocks"],
    }
    shardings = pipeline_param_shardings(
        shared_specs={"wte": P(mp_axis, None), "wpe": P(None, None),
                      "ln_f_scale": P(None), "ln_f_bias": P(None)},
        block_specs=block_param_shardings(mp_axis))

    def embed_fn(shared, tokens, rng):
        S = tokens.shape[-1]
        return shared["wte"].astype(cfg.dtype)[tokens] + \
            shared["wpe"].astype(cfg.dtype)[None, :S]

    def stage_fn(blocks_local, x, rng):
        return apply_blocks(blocks_local, x, cfg, rng=rng,
                            deterministic=cfg.hidden_dropout == 0.0)

    def head_fn(shared, x, targets, rng):
        from ..ops.cross_entropy import chunked_softmax_xent
        x = layer_norm(x, shared["ln_f_scale"], shared["ln_f_bias"],
                       cfg.layer_norm_eps)
        B, S, H = x.shape
        return chunked_softmax_xent(x.reshape(B * S, H),
                                    shared["wte"].astype(cfg.dtype),
                                    targets.reshape(-1))

    return PipeSpec(embed_fn=embed_fn, stage_fn=stage_fn, head_fn=head_fn,
                    params=params, shardings=shardings,
                    num_layers=cfg.num_layers)
