"""GPT-2 expressed for the SPMD pipeline (pipe/spmd.py model contract).

The reference pipelines GPT-2 via Megatron's PipelineModule layer lists
(docs/_tutorials/pipeline.md); here the pipelined form is derived directly
from the same param pytree as models.gpt2: shared (embeddings + final LN,
replicated over pp — the tied embed/unembed pair, TiedLayerSpec parity) and
the stacked transformer blocks (sharded over pp on the layer dim).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .gpt2 import GPT2Config, gpt2_init
from .transformer import apply_blocks, block_param_shardings, layer_norm
from ..runtime.pipe.spmd import pipeline_param_shardings


@dataclasses.dataclass
class PipeSpec:
    """Uniform-stage pipeline model: funcs + params + shardings.

    The PipelineEngine consumes this for compiled pp>1 execution; see
    pipe/spmd.py for the contract.
    """
    embed_fn: Any
    stage_fn: Any
    head_fn: Any
    params: Dict[str, Any]
    shardings: Dict[str, Any]
    num_layers: int
    # Set when the spec was built with explicit per-stage layer counts
    # (identity-padded stages); the pipeline stage count is then fixed.
    stage_layers: Any = None

    def _check_stages(self, num_stages: int) -> None:
        if self.stage_layers is not None and \
                len(self.stage_layers) != num_stages:
            raise ValueError(
                f"this PipeSpec was built for {len(self.stage_layers)} "
                f"stages (stage_layers={list(self.stage_layers)}) but the "
                f"mesh has pp={num_stages}")

    def loss_fn(self, num_stages: int, num_micro: int, mesh,
                remat: bool = True):
        self._check_stages(num_stages)
        from ..runtime.pipe.spmd import spmd_pipeline_loss
        return spmd_pipeline_loss(self.embed_fn, self.stage_fn, self.head_fn,
                                  num_stages, num_micro, mesh, remat=remat)

    def grads_fn(self, num_stages: int, num_micro: int, mesh):
        """1F1B interleaved pipeline: returns (loss, grads) directly —
        O(P) activation memory instead of the GPipe O(M) banks."""
        self._check_stages(num_stages)
        from ..runtime.pipe.spmd_1f1b import spmd_pipeline_1f1b_grads
        return spmd_pipeline_1f1b_grads(self.embed_fn, self.stage_fn,
                                        self.head_fn, num_stages, num_micro,
                                        mesh)


def pad_stacked_blocks(blocks, num_layers: int, stage_layers):
    """Non-uniform pipeline cuts: re-stack [L, ...] blocks as
    [P * Lmax, ...] where stage s owns slice [s*Lmax, (s+1)*Lmax) holding
    its ``stage_layers[s]`` real layers followed by identity padding
    (zeros; skipped at run time via the validity mask). Returns
    (padded_blocks, valid [P*Lmax] f32) — the reference's analogue is
    partition_balanced boundaries feeding per-rank layer builds
    (pipe/module.py:348-404); here the padded stack keeps ONE uniform SPMD
    stage program."""
    stage_layers = list(stage_layers)
    if sum(stage_layers) != num_layers:
        raise ValueError(f"stage_layers {stage_layers} must sum to "
                         f"{num_layers}")
    Pn, Lmax = len(stage_layers), max(stage_layers)
    bounds = np.cumsum([0] + stage_layers)

    def pad_leaf(leaf):
        out = jnp.zeros((Pn * Lmax,) + leaf.shape[1:], leaf.dtype)
        for s in range(Pn):
            lo, hi = int(bounds[s]), int(bounds[s + 1])
            out = out.at[s * Lmax: s * Lmax + (hi - lo)].set(leaf[lo:hi])
        return out

    valid = np.zeros((Pn * Lmax,), np.float32)
    for s in range(Pn):
        valid[s * Lmax: s * Lmax + stage_layers[s]] = 1.0
    return (jax.tree_util.tree_map(pad_leaf, blocks),
            jnp.asarray(valid))


def gpt2_pipe_spec(cfg: GPT2Config, rng=None, mp_axis: str = "model",
                   stage_layers=None) -> PipeSpec:
    """``stage_layers``: optional per-stage layer counts (non-uniform
    pipeline cuts, e.g. [10, 9, 9, 8] for an embedding-heavy stage 0).
    Stages are padded to max(stage_layers) with identity blocks that
    lax.cond-skip at run time, keeping the SPMD stage program uniform."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    flat = gpt2_init(rng, cfg)
    blocks = flat["blocks"]
    stage_valid = None       # [P, Lmax] 0/1, a CONSTANT (not a param leaf:
    #                          weight decay must never touch it)
    if stage_layers is not None:
        blocks, flat_valid = pad_stacked_blocks(blocks, cfg.num_layers,
                                                stage_layers)
        stage_valid = jnp.reshape(flat_valid,
                                  (len(stage_layers), max(stage_layers)))
    params = {
        "shared": {"wte": flat["wte"], "wpe": flat["wpe"],
                   "ln_f_scale": flat["ln_f_scale"],
                   "ln_f_bias": flat["ln_f_bias"]},
        "blocks": blocks,
    }
    shardings = pipeline_param_shardings(
        shared_specs={"wte": P(mp_axis, None), "wpe": P(None, None),
                      "ln_f_scale": P(None), "ln_f_bias": P(None)},
        block_specs=block_param_shardings(mp_axis))

    def embed_fn(shared, tokens, rng):
        S = tokens.shape[-1]
        return shared["wte"].astype(cfg.dtype)[tokens] + \
            shared["wpe"].astype(cfg.dtype)[None, :S]

    def stage_fn(blocks_local, x, rng):
        if cfg.moe is not None:
            raise NotImplementedError(
                "MoE blocks do not compose with the pipeline stage path "
                "yet (apply_blocks would return a stats tuple the stage "
                "fn cannot thread) — ROADMAP item 4c")
        valid = None
        if stage_valid is not None:
            # Inside the shard_map'd pipe region: pick this stage's mask.
            from jax import lax as _lax
            from ..parallel.topology import PP_AXIS
            valid = stage_valid[_lax.axis_index(PP_AXIS)]
        return apply_blocks(blocks_local, x, cfg, rng=rng,
                            deterministic=cfg.hidden_dropout == 0.0,
                            layer_valid=valid)

    def head_fn(shared, x, targets, rng):
        from ..ops.cross_entropy import chunked_softmax_xent
        x = layer_norm(x, shared["ln_f_scale"], shared["ln_f_bias"],
                       cfg.layer_norm_eps)
        B, S, H = x.shape
        return chunked_softmax_xent(x.reshape(B * S, H),
                                    shared["wte"].astype(cfg.dtype),
                                    targets.reshape(-1))

    return PipeSpec(embed_fn=embed_fn, stage_fn=stage_fn, head_fn=head_fn,
                    params=params, shardings=shardings,
                    num_layers=(cfg.num_layers if stage_layers is None else
                                len(stage_layers) * max(stage_layers)),
                    stage_layers=stage_layers)
