"""BERT encoder family with MLM head.

The reference's flagship kernel workload is BERT pretraining (fused encoder
layer csrc/transformer/ds_transformer_cuda.cpp; numerical references in
tests/unit/modeling.py, modelingpreln.py — post-LN and pre-LN variants).
This module is both variants, driven by ``pre_layer_norm``: token + position
(+ segment) embeddings → embedding LN → N blocks → MLM head over tied
embeddings.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .transformer import (TransformerConfig, apply_blocks, block_param_shardings,
                          dense, dense_attention, gelu, init_block_params,
                          layer_norm)


@dataclasses.dataclass(frozen=True)
class BertConfig(TransformerConfig):
    causal: bool = False
    pre_layer_norm: bool = False        # original BERT; preln variant = True
    max_seq_length: int = 512
    vocab_size: int = 30528             # bert-large vocab padded to 64
    type_vocab_size: int = 2


BERT_CONFIGS: Dict[str, BertConfig] = {
    "bert-base":  BertConfig(hidden_size=768, num_heads=12, num_layers=12),
    "bert-large": BertConfig(hidden_size=1024, num_heads=16, num_layers=24),
    "bert-large-preln": BertConfig(hidden_size=1024, num_heads=16,
                                   num_layers=24, pre_layer_norm=True),
    "bert-tiny":  BertConfig(hidden_size=128, num_heads=4, num_layers=2,
                             max_seq_length=128, vocab_size=512),
}


def bert_init(rng: jax.Array, cfg: BertConfig) -> Dict[str, Any]:
    ks = jax.random.split(rng, 5)
    std = cfg.initializer_range
    H = cfg.hidden_size
    params = {
        "wte": jax.random.normal(ks[0], (cfg.vocab_size, H), jnp.float32) * std,
        "wpe": jax.random.normal(ks[1], (cfg.max_seq_length, H), jnp.float32) * std,
        "emb_ln_scale": jnp.ones((H,), jnp.float32),
        "emb_ln_bias": jnp.zeros((H,), jnp.float32),
        "blocks": init_block_params(ks[2], cfg),
        # MLM head: dense + LN + tied-embedding decoder bias.
        "mlm_kernel": jax.random.normal(ks[3], (H, H), jnp.float32) * std,
        "mlm_bias": jnp.zeros((H,), jnp.float32),
        "mlm_ln_scale": jnp.ones((H,), jnp.float32),
        "mlm_ln_bias": jnp.zeros((H,), jnp.float32),
        "decoder_bias": jnp.zeros((cfg.vocab_size,), jnp.float32),
    }
    if cfg.type_vocab_size:
        params["wse"] = jax.random.normal(
            ks[4], (cfg.type_vocab_size, H), jnp.float32) * std
    return params


def bert_param_shardings(cfg: BertConfig, mp_axis: str = "model") -> Dict[str, Any]:
    sh = {
        "wte": P(mp_axis, None),
        "wpe": P(None, None),
        "emb_ln_scale": P(None), "emb_ln_bias": P(None),
        "blocks": block_param_shardings(mp_axis),
        "mlm_kernel": P(None, None), "mlm_bias": P(None),
        "mlm_ln_scale": P(None), "mlm_ln_bias": P(None),
        "decoder_bias": P(mp_axis),
    }
    if cfg.type_vocab_size:
        sh["wse"] = P(None, None)
    return sh


def bert_apply(params: Dict[str, Any], tokens: jnp.ndarray, cfg: BertConfig,
               segment_ids: Optional[jnp.ndarray] = None,
               attention_mask: Optional[jnp.ndarray] = None,
               rng: Optional[jax.Array] = None, deterministic: bool = True,
               attention_fn=None) -> jnp.ndarray:
    """tokens [B, S] → final hidden states [B, S, H].

    ``attention_mask`` [B, S] with 1 = attend: converted to the additive
    [B, 1, 1, S] form (the reference's fused softmax consumes the same,
    transformer.py:208-216).
    """
    B, S = tokens.shape
    x = params["wte"].astype(cfg.dtype)[tokens] + \
        params["wpe"].astype(cfg.dtype)[None, :S]
    if cfg.type_vocab_size and segment_ids is not None:
        x = x + params["wse"].astype(cfg.dtype)[segment_ids]
    if cfg.moe is not None:
        raise NotImplementedError(
            "MoE blocks are wired for the GPT-2 training path only "
            "(models/gpt2.py threads the stats tuple); BERT keeps the "
            "dense FFN")
    x = layer_norm(x, params["emb_ln_scale"], params["emb_ln_bias"],
                   cfg.layer_norm_eps)
    add_mask = None
    if attention_mask is not None:
        add_mask = (1.0 - attention_mask[:, None, None, :].astype(jnp.float32)) \
            * -1e9
    return apply_blocks(params["blocks"], x, cfg, mask=add_mask, rng=rng,
                        deterministic=deterministic, attention_fn=attention_fn)


def bert_mlm_logits(params: Dict[str, Any], hidden: jnp.ndarray,
                    cfg: BertConfig) -> jnp.ndarray:
    h = gelu(dense(hidden, params["mlm_kernel"], params["mlm_bias"]))
    h = layer_norm(h, params["mlm_ln_scale"], params["mlm_ln_bias"],
                   cfg.layer_norm_eps)
    return h @ params["wte"].astype(h.dtype).T + \
        params["decoder_bias"].astype(h.dtype)


def bert_mlm_loss_fn(cfg: BertConfig, attention_fn=None):
    """loss_fn(params, batch, rng); batch = (tokens, labels[, attention_mask])
    with labels == -100 at unmasked positions (HF convention)."""
    def loss_fn(params, batch, rng):
        tokens, labels = batch[0], batch[1]
        attn_mask = batch[2] if len(batch) > 2 else None
        hidden = bert_apply(params, tokens, cfg, attention_mask=attn_mask,
                            rng=rng, deterministic=False,
                            attention_fn=attention_fn)
        logits = bert_mlm_logits(params, hidden, cfg).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        valid = labels >= 0
        safe_labels = jnp.where(valid, labels, 0)
        nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
        denom = jnp.maximum(jnp.sum(valid), 1)
        return jnp.sum(jnp.where(valid, nll, 0.0)) / denom
    return loss_fn
