"""GPT-2 causal language model family.

The reference trains GPT-2 through Megatron-LM examples
(tests/model/Megatron_GPT2/, docs/_tutorials/megatron.md); here it is a
built-in model: token+position embeddings → N pre-LN blocks → final LN →
tied-embedding logits → next-token cross-entropy. Sizes cover the benchmark
ladder in BASELINE.json (small → 1.5B).

Sharding story (Megatron TP via GSPMD): block kernels column/row-sharded on
the "model" axis (transformer.block_param_shardings); the token embedding is
vocab-sharded so the tied logits matmul is column-parallel and the CE loss
reduces over the sharded vocab axis with an XLA-inserted all-reduce.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .transformer import (TransformerConfig, apply_blocks, block_param_shardings,
                          count_params, dense_attention, init_block_params,
                          layer_norm, layer_norm_fn)


@dataclasses.dataclass(frozen=True)
class GPT2Config(TransformerConfig):
    causal: bool = True
    pre_layer_norm: bool = True
    max_seq_length: int = 1024
    vocab_size: int = 50304            # padded to a multiple of 128 for MXU tiling

    @property
    def name(self) -> str:
        return f"gpt2-h{self.hidden_size}-l{self.num_layers}"


GPT2_CONFIGS: Dict[str, GPT2Config] = {
    # Benchmark ladder (BASELINE.json configs).
    "gpt2-small":  GPT2Config(hidden_size=768,  num_heads=12, num_layers=12),
    "gpt2-medium": GPT2Config(hidden_size=1024, num_heads=16, num_layers=24),
    "gpt2-large":  GPT2Config(hidden_size=1280, num_heads=20, num_layers=36),
    "gpt2-xl":     GPT2Config(hidden_size=1600, num_heads=25, num_layers=48),  # 1.5B
    "gpt2-tiny":   GPT2Config(hidden_size=128,  num_heads=4,  num_layers=2,
                              max_seq_length=128, vocab_size=512),  # tests
}


def gpt2_init(rng: jax.Array, cfg: GPT2Config) -> Dict[str, Any]:
    k_emb, k_pos, k_blocks = jax.random.split(rng, 3)
    std = cfg.initializer_range
    return {
        "wte": jax.random.normal(k_emb, (cfg.vocab_size, cfg.hidden_size),
                                 jnp.float32) * std,
        "wpe": jax.random.normal(k_pos, (cfg.max_seq_length, cfg.hidden_size),
                                 jnp.float32) * std,
        "blocks": init_block_params(k_blocks, cfg),
        "ln_f_scale": jnp.ones((cfg.hidden_size,), jnp.float32),
        "ln_f_bias": jnp.zeros((cfg.hidden_size,), jnp.float32),
    }


def gpt2_param_shardings(cfg: GPT2Config, mp_axis: str = "model") -> Dict[str, Any]:
    """PartitionSpec tree matching gpt2_init's structure."""
    return {
        "wte": P(mp_axis, None),          # vocab-sharded (column-parallel logits)
        "wpe": P(None, None),
        "blocks": block_param_shardings(mp_axis),
        "ln_f_scale": P(None),
        "ln_f_bias": P(None),
    }


def gpt2_hidden(params: Dict[str, Any], tokens: jnp.ndarray, cfg: GPT2Config,
                rng: Optional[jax.Array] = None, deterministic: bool = True,
                attention_fn=None, pld_theta=None, zero3=None, mesh=None,
                with_moe_stats: bool = False):
    """tokens [B, S] int32 → final hidden states [B, S, H] (post ln_f).

    ``zero3``: a bound ``Zero3Scan`` — the stacked block params arrive
    as ZeRO-3 dp shards and are gathered per layer inside the scan
    (prefetch-overlapped); see models/transformer.apply_blocks.

    ``with_moe_stats=True`` returns ``(hidden, moe_stats_or_None)`` —
    the training loss path consumes the stats; serving/eval callers
    keep the plain return (the stats are dropped, the routed compute is
    identical). ``mesh`` feeds the MoE ep > 1 shard_map."""
    B, S = tokens.shape
    x = params["wte"].astype(cfg.dtype)[tokens] + \
        params["wpe"].astype(cfg.dtype)[None, :S]
    out = apply_blocks(params["blocks"], x, cfg, mask=None, rng=rng,
                       deterministic=deterministic, attention_fn=attention_fn,
                       pld_theta=pld_theta, zero3=zero3, mesh=mesh)
    x, moe_stats = out if cfg.moe is not None else (out, None)
    h = layer_norm_fn(cfg)(x, params["ln_f_scale"], params["ln_f_bias"])
    if with_moe_stats:
        return h, moe_stats
    return h


def gpt2_apply(params: Dict[str, Any], tokens: jnp.ndarray, cfg: GPT2Config,
               rng: Optional[jax.Array] = None, deterministic: bool = True,
               attention_fn=None) -> jnp.ndarray:
    """tokens [B, S] int32 → logits [B, S, V]."""
    x = gpt2_hidden(params, tokens, cfg, rng=rng, deterministic=deterministic,
                    attention_fn=attention_fn)
    # Tied unembedding (the reference ties via TiedLayerSpec in pipeline
    # models; here it is structural).
    logits = x @ params["wte"].astype(cfg.dtype).T
    return logits


def gpt2_logits_at(params: Dict[str, Any], tokens: jnp.ndarray,
                   cfg: GPT2Config, index: Union[int, jnp.ndarray] = -1,
                   rng: Optional[jax.Array] = None,
                   deterministic: bool = True,
                   attention_fn=None) -> jnp.ndarray:
    """Logits at ONE sequence position: tokens [B, S] → [B, V].

    Runs the full hidden stack but projects only position ``index``
    through the tied unembedding, so the [B, S, vocab] logits tensor never
    materializes — the serving-side memory contract (the training-side
    equivalent is ops/cross_entropy's chunked projection). ``index`` may
    be a Python int (negative = from the end) or a traced scalar (the
    inference prefill path indexes the prompt's final token inside a
    jitted program).
    """
    x = gpt2_hidden(params, tokens, cfg, rng=rng, deterministic=deterministic,
                    attention_fn=attention_fn)
    if isinstance(index, int):
        if index < 0:
            index += tokens.shape[1]
    else:
        # Traced scalar: dynamic_index_in_dim would CLAMP a negative
        # index to 0 (silent wrong position) — normalize in-graph.
        index = jnp.where(index < 0, index + tokens.shape[1], index)
    h = lax.dynamic_index_in_dim(x, index, axis=1, keepdims=False)  # [B, H]
    return h @ params["wte"].astype(h.dtype).T


def gpt2_loss_fn(cfg: GPT2Config, attention_fn=None, zero3=None, mesh=None):
    """Returns loss_fn(params, batch, rng) for the engine.

    batch: tokens [B, S+1] (inputs are [:, :-1], targets [:, 1:]) or a
    (tokens, targets) tuple.

    The CE head runs through ops.cross_entropy.chunked_softmax_xent, so the
    [tokens, vocab] fp32 logits tensor is never materialized (chunked
    recompute in backward — see that module's docstring).

    ``zero3``: pass the SAME ``Zero3Scan`` object here and to
    ``deepspeed_tpu.initialize(..., zero3_scan=...)`` — the engine binds
    the stage-3 layout at construction, the loss reads it at trace time
    and gathers the stacked block params per layer inside the scan.

    ``cfg.moe``: the loss gains the weighted load-balance aux loss and
    router z-loss, and the fn returns ``(loss, {"moe": stats})`` — the
    engine rides the stats on the telemetry drain. ``mesh`` is required
    when ``expert_parallel_size > 1`` (the all-to-all shard_map).
    """
    from ..ops.cross_entropy import chunked_softmax_xent

    if cfg.moe is not None and cfg.moe.expert_parallel_size > 1 and \
            mesh is None:
        # Without the mesh the MoE layer would silently take its
        # no-collective fallback inside the jit — GSPMD then all-gathers
        # the full expert-sharded weight tree every step, the exact
        # failure expert parallelism exists to avoid. The TRAINING entry
        # point refuses; eval on fetched params (gpt2_apply) keeps the
        # fallback.
        raise ValueError(
            "cfg.moe.expert_parallel_size > 1 requires "
            "gpt2_loss_fn(cfg, mesh=mesh) — the all-to-all shard_map "
            "cannot infer the mesh")

    def loss_fn(params, batch, rng, pld_theta=None):
        if isinstance(batch, (tuple, list)):
            tokens, targets = batch[0], batch[1]
        else:
            tokens, targets = batch[:, :-1], batch[:, 1:]
        x, moe_stats = gpt2_hidden(params, tokens, cfg, rng=rng,
                                   deterministic=False,
                                   attention_fn=attention_fn,
                                   pld_theta=pld_theta, zero3=zero3,
                                   mesh=mesh, with_moe_stats=True)
        B, S = tokens.shape
        loss = chunked_softmax_xent(x.reshape(B * S, -1),
                                    params["wte"].astype(cfg.dtype),
                                    targets.reshape(-1))
        if moe_stats is None:
            return loss
        moe = cfg.moe
        loss = loss + moe.aux_loss_weight * moe_stats["aux_loss"] \
            + moe.z_loss_weight * moe_stats["z_loss"]
        return loss, {"moe": moe_stats}
    return loss_fn


def gpt2_num_params(cfg: GPT2Config) -> int:
    H, L, F, V, S = (cfg.hidden_size, cfg.num_layers, cfg.ffn_size,
                     cfg.vocab_size, cfg.max_seq_length)
    per_block = 4 * H + 3 * H * H + 3 * H + H * H + H + 2 * H * F + F + H
    return V * H + S * H + L * per_block + 2 * H


def gpt2_flops_per_token(cfg: GPT2Config, seq_len: Optional[int] = None) -> float:
    """Training FLOPs/token = 6·N_matmul + attention term (PaLM appendix B
    counting). N_matmul includes the tied unembedding (V·H): its logits
    projection is a real trained-weight matmul executed fwd+bwd every step
    (standard MFU accounting includes the vocab projection). Excluded:
    embedding/position lookups (gathers, ~0 FLOPs) and remat recompute
    (not useful work)."""
    S = seq_len or cfg.max_seq_length
    H, L = cfg.hidden_size, cfg.num_layers
    n = gpt2_num_params(cfg) - cfg.vocab_size * H - cfg.max_seq_length * H
    n += cfg.vocab_size * H    # tied unembedding matmul
    return 6.0 * n + 12.0 * L * H * S
