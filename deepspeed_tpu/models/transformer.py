"""Transformer building blocks — pure-functional, shard-annotated.

Capability parity with the reference's fused transformer layer
(ops/transformer/transformer.py:468 DeepSpeedTransformerLayer and its CUDA
backend csrc/transformer/ds_transformer_cuda.cpp): QKV projection, scaled
masked softmax attention, output projection, residual + LayerNorm (pre- or
post-LN), GELU FFN, dropout — with the memory knobs
(attn_dropout_checkpoint / normalize_invertible / gelu_checkpoint,
transformer.py:39-151) expressed as jax.checkpoint remat policies instead of
hand-managed saved-tensor lists.

TPU-native design decisions:
- Params are plain dict pytrees; per-layer tensors are STACKED on a leading
  layer axis and the block is applied with ``lax.scan`` — one compilation of
  one block regardless of depth (XLA unrolls nothing).
- Attention math runs in fp32 (softmax stability) while matmuls stay in the
  compute dtype so they hit the MXU at full rate.
- Tensor parallelism is Megatron-style column→row sharding, expressed purely
  as PartitionSpec trees over the weights; GSPMD inserts the all-reduces.
- The attention inner product is pluggable (``attention_fn``) so dense, flash
  (Pallas), and block-sparse attention share the surrounding layer.
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """Shared transformer hyperparameters.

    Mirrors DeepSpeedTransformerConfig (reference transformer.py:39-151):
    batch/seq/hidden/heads/pre_layer_norm/dropout knobs; the checkpointing
    booleans map onto ``remat_policy``.
    """
    hidden_size: int = 768
    num_heads: int = 12
    num_layers: int = 12
    intermediate_size: int = 0          # 0 → 4*hidden
    max_seq_length: int = 1024
    vocab_size: int = 50257
    type_vocab_size: int = 0            # >0 → BERT-style segment embeddings
    pre_layer_norm: bool = True         # GPT-2: True; original BERT: False
    hidden_dropout: float = 0.1
    attn_dropout: float = 0.1
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    # remat policy: "none" | "full" | "dots" | "attn" (≈ attn_dropout_checkpoint
    # + gelu_checkpoint territory in the reference)
    remat_policy: str = "none"
    causal: bool = False
    dtype: Any = jnp.bfloat16
    # scan_layers=True compiles one block and lax.scans it (fast compiles,
    # small code); False unrolls the layer loop, which lets XLA overlap
    # weight loads with compute across layer boundaries (better step time,
    # slower compile) — the usual TPU tradeoff.
    scan_layers: bool = True
    # True = erf-form GELU (HF BERT "gelu"); False = tanh approximation
    # (GPT-2 gelu_new, and what the reference's gelu_kernels.cu computes).
    gelu_exact: bool = False
    # Mixture-of-Experts: a ``deepspeed_tpu.moe.MoEConfig`` swaps the
    # dense FFN for the expert-parallel MoE FFN on every
    # ``moe_layer_freq``-th block (freq 1 = every block — the only form
    # the scanned layer stack supports; freq > 1 needs
    # ``scan_layers=False``, since mixed block programs cannot share one
    # scan body). None = dense everywhere (unchanged).
    # ``MoEConfig.grouped_gemm`` picks the expert-FFN program with the
    # same contract as ``fused_kernels`` below: "auto"/True/False,
    # DS_GROUPED_GEMM override, grouped Pallas kernel vs einsum pair
    # (ops/grouped_gemm) — cfg-static, resolved inside _moe_tokens.
    moe: Any = None
    moe_layer_freq: int = 1
    # Fused elementwise Pallas kernels (ops/fused_elementwise): residual-
    # add+LayerNorm and the bias+GELU FFN epilogue. "auto" = on when the
    # backend is TPU (DS_FUSED_ELEMENTWISE=0/1 overrides); True/False
    # force — True on CPU runs interpret-mode Pallas (how the dp=8
    # tier-1 mesh tests them). Static per config: flipping it changes
    # the program, not the compiled signature.
    fused_kernels: Any = "auto"

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        assert self.hidden_size % self.num_heads == 0
        return self.hidden_size // self.num_heads


# --------------------------------------------------------------------- #
# Primitive ops
# --------------------------------------------------------------------- #
def layer_norm(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
               eps: float = 1e-5) -> jnp.ndarray:
    """LayerNorm in fp32 (the reference's normalize_kernels.cu does the same
    accumulation in fp32 even for fp16 activations)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


def dense(x: jnp.ndarray, kernel: jnp.ndarray, bias: Optional[jnp.ndarray]) -> jnp.ndarray:
    y = x @ kernel.astype(x.dtype)
    if bias is not None:
        y = y + bias.astype(x.dtype)
    return y


def gelu(x: jnp.ndarray) -> jnp.ndarray:
    # tanh approximation — same curve the reference's gelu_kernels.cu uses.
    return jax.nn.gelu(x, approximate=True)


# --------------------------------------------------------------------- #
# cfg-resolved fused-kernel dispatch (ops/fused_elementwise)
# --------------------------------------------------------------------- #
def use_fused_kernels(cfg: "TransformerConfig") -> bool:
    from ..ops.fused_elementwise import fused_elementwise_enabled
    return fused_elementwise_enabled(getattr(cfg, "fused_kernels", "auto"))


def layer_norm_fn(cfg: "TransformerConfig") -> Callable:
    """``(x, scale, bias) -> y``: the fused Pallas LayerNorm when the
    config enables it, the jnp reference otherwise.  The choice is
    static per config, so every caller (training block, serving
    decode/prefill) keeps ONE compiled signature either way."""
    if use_fused_kernels(cfg):
        from ..ops.fused_elementwise import fused_layer_norm
        return lambda x, scale, bias: fused_layer_norm(
            x, scale, bias, cfg.layer_norm_eps)
    return lambda x, scale, bias: layer_norm(
        x, scale, bias, cfg.layer_norm_eps)


def residual_layer_norm_fn(cfg: "TransformerConfig") -> Callable:
    """``(x, delta, scale, bias) -> (s, y)`` with ``s = x + delta`` and
    ``y = LN(s)`` — fused into one pass when enabled."""
    if use_fused_kernels(cfg):
        from ..ops.fused_elementwise import fused_residual_layer_norm
        return lambda x, delta, scale, bias: fused_residual_layer_norm(
            x, delta, scale, bias, cfg.layer_norm_eps)

    def unfused(x, delta, scale, bias):
        s = x + delta
        return s, layer_norm(s, scale, bias, cfg.layer_norm_eps)
    return unfused


def gelu_dense_fn(cfg: "TransformerConfig") -> Callable:
    """``(h, kernel, bias) -> gelu(h @ kernel + bias)`` — the FFN
    up-projection with its bias+GELU epilogue fused when enabled (the
    matmul stays with XLA's MXU GEMM; the kernel fuses everything
    after it into one elementwise pass)."""
    if use_fused_kernels(cfg):
        from ..ops.fused_elementwise import fused_bias_gelu
        return lambda h, kernel, bias: fused_bias_gelu(
            h @ kernel.astype(h.dtype), bias, cfg.gelu_exact)
    return lambda h, kernel, bias: jax.nn.gelu(
        dense(h, kernel, bias), approximate=not cfg.gelu_exact)


def dropout(x: jnp.ndarray, rate: float, rng: Optional[jax.Array],
            deterministic: bool) -> jnp.ndarray:
    if deterministic or rate == 0.0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))


def dense_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    mask: Optional[jnp.ndarray], causal: bool,
                    attn_dropout: float = 0.0,
                    rng: Optional[jax.Array] = None,
                    deterministic: bool = True) -> jnp.ndarray:
    """Reference attention: QK^T → scale → mask → softmax → AV.

    q,k,v: [B, S, nH, dH]. mask: broadcastable to [B, 1, S, S] additive.
    Softmax in fp32 (csrc softmax_kernels.cu accumulates fp32 likewise).
    """
    dh = q.shape[-1]
    qt = jnp.einsum("bsnd,btnd->bnst", q, k).astype(jnp.float32)
    qt = qt / math.sqrt(dh)
    if causal:
        s, t = qt.shape[-2], qt.shape[-1]
        cmask = jnp.tril(jnp.ones((s, t), jnp.bool_))
        qt = jnp.where(cmask[None, None], qt, jnp.float32(-1e9))
    if mask is not None:
        qt = qt + mask.astype(jnp.float32)
    w = jax.nn.softmax(qt, axis=-1)
    w = dropout(w, attn_dropout, rng, deterministic)
    out = jnp.einsum("bnst,btnd->bsnd", w.astype(v.dtype), v)
    return out


AttentionFn = Callable[..., jnp.ndarray]


# --------------------------------------------------------------------- #
# One transformer block (stack-friendly)
# --------------------------------------------------------------------- #
def init_block_params(rng: jax.Array, cfg: TransformerConfig,
                      num_layers: Optional[int] = None) -> Dict[str, jnp.ndarray]:
    """Initialize STACKED block params: every tensor has a leading layer
    axis — [L] for the shared attention/LN tensors; with ``cfg.moe`` the
    FFN tensors split into a dense stack ([n_dense]) and an expert stack
    ([n_moe, E, ...]), each covering only its own layers (no dead
    parameters on either side)."""
    L = num_layers if num_layers is not None else cfg.num_layers
    H, F = cfg.hidden_size, cfg.ffn_size
    std = cfg.initializer_range
    # GPT-2-style scaled init for residual-ending projections.
    proj_std = std / math.sqrt(2.0 * L)
    ks = jax.random.split(rng, 6)

    def norm(key, shape, s):
        return (jax.random.normal(key, shape, jnp.float32) * s)

    params = {
        "ln1_scale": jnp.ones((L, H), jnp.float32),
        "ln1_bias": jnp.zeros((L, H), jnp.float32),
        "qkv_kernel": norm(ks[0], (L, H, 3 * H), std),
        "qkv_bias": jnp.zeros((L, 3 * H), jnp.float32),
        "proj_kernel": norm(ks[1], (L, H, H), proj_std),
        "proj_bias": jnp.zeros((L, H), jnp.float32),
        "ln2_scale": jnp.ones((L, H), jnp.float32),
        "ln2_bias": jnp.zeros((L, H), jnp.float32),
    }
    if cfg.moe is None:
        n_dense, n_moe = L, 0
    else:
        from ..moe.layer import moe_layer_indices
        n_moe = len(moe_layer_indices(L, cfg.moe_layer_freq))
        n_dense = L - n_moe
        if n_moe == 0:
            raise ValueError(
                f"cfg.moe is set but moe_layer_freq={cfg.moe_layer_freq} "
                f"selects no MoE layer out of {L} — use freq <= num_layers "
                "or drop cfg.moe")
    if n_dense > 0:
        params.update({
            "fc_kernel": norm(ks[2], (n_dense, H, F), std),
            "fc_bias": jnp.zeros((n_dense, F), jnp.float32),
            "fc_out_kernel": norm(ks[3], (n_dense, F, H), proj_std),
            "fc_out_bias": jnp.zeros((n_dense, H), jnp.float32),
        })
    if n_moe > 0:
        E = cfg.moe.num_experts
        params.update({
            "router_kernel": norm(ks[4], (n_moe, H, E), std),
            "moe_fc_kernel": norm(ks[5], (n_moe, E, H, F), std),
            "moe_fc_bias": jnp.zeros((n_moe, E, F), jnp.float32),
            "moe_out_kernel": norm(
                jax.random.fold_in(ks[5], 1), (n_moe, E, F, H), proj_std),
            "moe_out_bias": jnp.zeros((n_moe, E, H), jnp.float32),
        })
    return params


def block_param_shardings(mp_axis: str = "model") -> Dict[str, P]:
    """Megatron column→row TP over the stacked block params.

    QKV and FFN-in kernels are column-sharded (output features over mp);
    proj and FFN-out are row-sharded (input features over mp). GSPMD turns
    the row-sharded matmuls into partial sums + all-reduce — exactly the
    hand-written Megatron pattern the reference's mpu contract assumes
    (engine.py:79-80).
    """
    # Expert-FFN leaves (cfg.moe) get their specs from
    # deepspeed_tpu.moe.sharding.expert_block_shardings (the `expert`
    # axis on the E dim), merged by gpt2_moe_param_shardings.
    return {
        "ln1_scale": P(None, None), "ln1_bias": P(None, None),
        "qkv_kernel": P(None, None, mp_axis), "qkv_bias": P(None, mp_axis),
        "proj_kernel": P(None, mp_axis, None), "proj_bias": P(None, None),
        "ln2_scale": P(None, None), "ln2_bias": P(None, None),
        "fc_kernel": P(None, None, mp_axis), "fc_bias": P(None, mp_axis),
        "fc_out_kernel": P(None, mp_axis, None), "fc_out_bias": P(None, None),
    }


def transformer_block(params: Dict[str, jnp.ndarray], x: jnp.ndarray,
                      cfg: TransformerConfig,
                      mask: Optional[jnp.ndarray] = None,
                      rng: Optional[jax.Array] = None,
                      deterministic: bool = True,
                      attention_fn: Optional[AttentionFn] = None,
                      mesh=None):
    """One (unstacked) block: params here have NO leading layer axis.

    Pre-LN (GPT-2/Megatron) or post-LN (original BERT) per
    cfg.pre_layer_norm — the reference's fused layer supports both
    (transformer.py:458-462 normalize_invertible interplay).

    With ``cfg.moe`` the FFN sublayer routes through the expert-parallel
    MoE FFN whenever this layer's params carry the expert tensors
    (``moe_fc_kernel`` et al. — every ``moe_layer_freq``-th block), and
    the block returns ``(x, moe_stats_or_None)`` instead of ``x``;
    ``mesh`` feeds the ep > 1 all-to-all shard_map.
    """
    if attention_fn is None:
        from ..ops.flash_attention import auto_attention
        attention_fn = auto_attention
    B, S, H = x.shape
    nH, dH = cfg.num_heads, cfg.head_dim
    r1 = r2 = r3 = None
    if rng is not None:
        r1, r2, r3 = jax.random.split(rng, 3)
    # cfg-resolved elementwise ops: the fused Pallas kernels when the
    # config enables them, the reference jnp chain otherwise (identical
    # math — the fused residual+LN pass computes s = x + delta then
    # LN(s) exactly like the two separate ops below would).
    ln = layer_norm_fn(cfg)
    res_ln = residual_layer_norm_fn(cfg)
    gelu_up = gelu_dense_fn(cfg)

    # --- attention sublayer ---
    h = ln(x, params["ln1_scale"], params["ln1_bias"]) \
        if cfg.pre_layer_norm else x
    qkv = dense(h, params["qkv_kernel"], params["qkv_bias"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, S, nH, dH)
    k = k.reshape(B, S, nH, dH)
    v = v.reshape(B, S, nH, dH)
    attn = attention_fn(q, k, v, mask=mask, causal=cfg.causal,
                        attn_dropout=cfg.attn_dropout, rng=r1,
                        deterministic=deterministic)
    attn = attn.reshape(B, S, H)
    attn = dense(attn, params["proj_kernel"], params["proj_bias"])
    attn = dropout(attn, cfg.hidden_dropout, r2, deterministic)
    if cfg.pre_layer_norm:
        # Fused residual-add + next sublayer's LN: x continues the
        # residual stream from s, h feeds the FFN.
        x, h = res_ln(x, attn, params["ln2_scale"], params["ln2_bias"])
    else:
        # Post-LN: the normalized value IS the residual stream.
        _, x = res_ln(x, attn, params["ln1_scale"], params["ln1_bias"])
        h = x

    # --- FFN sublayer (dense, or the expert-parallel MoE FFN) ---
    moe_stats = None
    if "moe_fc_kernel" in params:
        from ..moe.layer import moe_ffn
        h, moe_stats = moe_ffn(params, h, cfg, mesh=mesh)
    else:
        h = gelu_up(h, params["fc_kernel"], params["fc_bias"])
        h = dense(h, params["fc_out_kernel"], params["fc_out_bias"])
    h = dropout(h, cfg.hidden_dropout, r3, deterministic)
    if cfg.pre_layer_norm:
        x = x + h
    else:
        _, x = res_ln(x, h, params["ln2_scale"], params["ln2_bias"])
    if cfg.moe is not None:
        return x, moe_stats
    return x


def _remat_policy(name: str):
    if name == "none":
        return None
    if name == "full":
        return jax.checkpoint_policies.nothing_saveable
    if name == "dots":
        return jax.checkpoint_policies.checkpoint_dots
    if name == "dots_flash":
        # dots + the flash-attention kernel's (out, lse) residuals (tagged
        # in ops.flash_attention._tag_residuals). Without the names the
        # pallas forward kernel re-runs inside backward (+1/3 attention
        # FLOPs); saving them costs B*S*H bf16 + B*nH*S f32 per layer.
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.checkpoint_dots,
            jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse"))
    if name == "attn":
        # Save only matmul outputs that feed the residual stream; recompute
        # softmax/dropout — the attn_dropout_checkpoint + gelu_checkpoint
        # territory of the reference (transformer.py:120-135).
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    raise ValueError(f"unknown remat policy '{name}'")


def apply_blocks(stacked: Dict[str, jnp.ndarray], x: jnp.ndarray,
                 cfg: TransformerConfig,
                 mask: Optional[jnp.ndarray] = None,
                 rng: Optional[jax.Array] = None,
                 deterministic: bool = True,
                 attention_fn: Optional[AttentionFn] = None,
                 pld_theta: Optional[jnp.ndarray] = None,
                 layer_valid: Optional[jnp.ndarray] = None,
                 zero3=None, mesh=None):
    """Run all L layers via lax.scan over the stacked leading axis.

    With ``cfg.moe`` the return value is ``(x, moe_stats)`` — the
    per-MoE-layer stats aggregated over layers (moe/layer.py), ``mesh``
    feeding the ep > 1 all-to-all shard_map. MoE does not compose with
    ``pld_theta``/``layer_valid`` (a skipped layer has no fixed-shape
    stats) or the ``zero3`` layer scan (use the generic stage-3
    leaf-at-use gather instead); ``moe_layer_freq > 1`` requires
    ``scan_layers=False`` (mixed dense/MoE blocks cannot share one scan
    body — the dense and expert FFN stacks cover different layers).

    ``zero3`` (a bound ``runtime.zero.stage3.Zero3Scan``) reroutes the
    layer loop through the ZeRO-3 prefetched scan: the stacked params
    arrive as dp SHARDS, each layer's slice is all-gathered
    ``prefetch_depth`` layers ahead of use inside the scan (the gather
    overlaps the previous layer's compute), dropped right after its
    fwd/bwd consumption, and its grads reduce-scattered back to the
    owning shard inside the backward scan. Does not compose with
    ``pld_theta``/``layer_valid`` (the manual-VJP scan has no per-layer
    skip) or ``scan_layers=False``; ``remat_policy`` is subsumed — the
    backward re-gathers and recomputes each layer by construction.

    ``pld_theta`` (traced scalar in (0, 1]) enables progressive layer drop
    (reference progressive_layer_drop.py:29-37 + the PLD paper's
    depth-scaled schedule): layer l is KEPT with probability
    ``1 - (l+1)/L * (1 - theta)`` — deeper layers drop more often — via
    ``lax.cond``, so a dropped layer's compute is actually skipped at run
    time, not just masked. Requires ``rng``; ignored when deterministic.

    ``layer_valid`` ([L] 0/1): identity-skip for PADDING layers — the
    non-uniform-pipeline-stage mechanism (stages padded to the max layer
    count run their pad slots as ``lax.cond`` no-ops; see
    gpt2_pipe.gpt2_pipe_spec(stage_layers=...)).
    """
    L = stacked["ln1_scale"].shape[0]
    if rng is None:
        keys = jnp.zeros((L, 2), jnp.uint32)
        use_rng = False
    else:
        keys = jax.random.split(rng, L)
        use_rng = True

    has_moe = cfg.moe is not None
    if has_moe:
        from ..moe.layer import (MOE_PARAM_KEYS, aggregate_moe_stats,
                                 moe_layer_indices)
        moe_layers = moe_layer_indices(L, cfg.moe_layer_freq)
        if not moe_layers:
            raise ValueError(
                f"cfg.moe is set but moe_layer_freq={cfg.moe_layer_freq} "
                f"selects no MoE layer out of {L}")
        if pld_theta is not None or layer_valid is not None:
            raise ValueError(
                "moe blocks do not compose with progressive layer drop "
                "or padded layer_valid slots (a skipped layer has no "
                "fixed-shape expert stats)")
        if zero3 is not None and getattr(zero3, "bound", False):
            raise ValueError(
                "moe blocks do not compose with the zero3 layer scan — "
                "use the generic stage-3 leaf-at-use gather (no "
                "zero3_scan)")
        if cfg.scan_layers and len(moe_layers) != L:
            raise ValueError(
                "moe_layer_freq > 1 requires scan_layers=False (mixed "
                "dense/MoE blocks cannot share one scan body)")

    block = partial(transformer_block, cfg=cfg, mask=mask,
                    deterministic=deterministic, attention_fn=attention_fn,
                    mesh=mesh)

    if zero3 is not None and getattr(zero3, "bound", False):
        if pld_theta is not None or layer_valid is not None:
            raise ValueError(
                "zero3 layer scan does not compose with progressive "
                "layer drop or padded layer_valid slots")
        if not cfg.scan_layers:
            raise ValueError("zero3 layer scan requires scan_layers=True")
        from ..runtime.zero.stage3 import zero3_block_scan

        def block_fn(p, h, key):
            return block(p, h, rng=key if use_rng else None)
        return zero3_block_scan(block_fn, stacked, x, keys, zero3)
    policy = _remat_policy(cfg.remat_policy)
    if cfg.remat_policy != "none":
        block = jax.checkpoint(
            block, policy=policy, static_argnums=())

    use_pld = pld_theta is not None and not deterministic and use_rng

    def maybe_dropped(p, h, key, layer_idx, valid):
        # One combined run predicate: padding-slot validity AND the PLD
        # keep draw; run through a single lax.cond so skipped layers cost
        # nothing at run time.
        run = None if valid is None else valid != 0
        if use_pld:
            drop_key, key = jax.random.split(key)
            keep_prob = 1.0 - (layer_idx.astype(jnp.float32) + 1.0) / L * \
                (1.0 - pld_theta)
            keep = jax.random.bernoulli(drop_key, keep_prob)
            run = keep if run is None else jnp.logical_and(run, keep)
        if run is None:
            return block(p, h, rng=key if use_rng else None)
        return lax.cond(run,
                        lambda hh: block(p, hh, rng=key if use_rng else None),
                        lambda hh: hh, h)

    if not cfg.scan_layers:
        stats_list = []
        if has_moe:
            moe_pos = {li: p for p, li in enumerate(moe_layers)}
            dense_pos = {li: p for p, li in enumerate(
                i for i in range(L) if i not in moe_pos)}
            ffn_keys = MOE_PARAM_KEYS | {"fc_kernel", "fc_bias",
                                         "fc_out_kernel", "fc_out_bias"}
        for i in range(L):
            if not has_moe:
                p_i = jax.tree_util.tree_map(lambda t: t[i], stacked)
            else:
                # Dense and expert FFN stacks cover DIFFERENT layer
                # subsets; slice each key group at its own position.
                p_i = {}
                for name, t in stacked.items():
                    if name not in ffn_keys:
                        p_i[name] = t[i]
                    elif name in MOE_PARAM_KEYS:
                        if i in moe_pos:
                            p_i[name] = t[moe_pos[i]]
                    elif i in dense_pos:
                        p_i[name] = t[dense_pos[i]]
            v_i = None if layer_valid is None else layer_valid[i]
            out = maybe_dropped(p_i, x, keys[i], jnp.asarray(i), v_i)
            if has_moe:
                x, st = out
                if st is not None:
                    stats_list.append(st)
            else:
                x = out
        if has_moe:
            stacked_stats = jax.tree_util.tree_map(
                lambda *a: jnp.stack(a), *stats_list)
            return x, aggregate_moe_stats(stacked_stats)
        return x

    def body(h, layer):
        if layer_valid is None:
            p, key, idx = layer
            out = maybe_dropped(p, h, key, idx, None)
        else:
            p, key, idx, v = layer
            out = maybe_dropped(p, h, key, idx, v)
        if has_moe:
            return out[0], out[1]
        return out, None

    xs = (stacked, keys, jnp.arange(L)) if layer_valid is None else \
        (stacked, keys, jnp.arange(L), layer_valid)
    x, ys = lax.scan(body, x, xs)
    if has_moe:
        return x, aggregate_moe_stats(ys)
    return x


def count_params(params: Any) -> int:
    return sum(int(math.prod(l.shape)) for l in jax.tree_util.tree_leaves(params)
               if hasattr(l, "shape"))
