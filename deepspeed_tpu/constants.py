"""Central registry of config keys and defaults.

Capability parity with the reference's ``runtime/constants.py`` (326 LoC of key
names/defaults) and ``runtime/zero/constants.py``: every knob a ``ds_config.json``
file may contain is named here, with its default, so config handling stays
table-driven and existing DeepSpeed-style JSON configs parse unmodified.

TPU-native deltas: ``bf16`` is first-class (the natural TPU dtype); ``fp16``
keys are retained for parity configs and drive the dynamic loss scaler.
"""

#############################################
# Routes
#############################################
ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"

#############################################
# Batch size
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer and lr scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False

# optimizer.params.fused: route Adam/AdamW through the single-pass Pallas
# multi-tensor apply (ops/fused_update.py). On by default where parity
# holds; false restores the optax chain.
OPTIMIZER_FUSED = "fused"
OPTIMIZER_FUSED_DEFAULT = True

SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"

MAX_GRAD_NORM = "max_grad_norm"

# Optimizer names understood by the engine's selection matrix.
ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"
RMSPROP_OPTIMIZER = "rmsprop"
LION_OPTIMIZER = "lion"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER,
    ADAMW_OPTIMIZER,
    LAMB_OPTIMIZER,
    ONEBIT_ADAM_OPTIMIZER,
    SGD_OPTIMIZER,
    ADAGRAD_OPTIMIZER,
    RMSPROP_OPTIMIZER,
    LION_OPTIMIZER,
]

#############################################
# Precision
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 32
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1

BF16 = "bf16"
BF16_ENABLED = "enabled"
# TPU-native default: bf16 on unless a parity config says otherwise.
BF16_ENABLED_DEFAULT = False
# Master-free bf16: params live in bf16 and the optimizer apply rounds
# stochastically (the reference transformer kernel's stochastic_mode,
# ops/transformer/transformer.py:39-151, re-done as a TPU bit trick).
BF16_STOCHASTIC_ROUNDING = "stochastic_rounding"
BF16_STOCHASTIC_ROUNDING_DEFAULT = False

PRECISION_DEFAULT = "fp32"

AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

ALLREDUCE_ALWAYS_FP32 = "fp32_allreduce"
ALLREDUCE_ALWAYS_FP32_DEFAULT = False

#############################################
# Steps / logging
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_ENABLED_DEFAULT = False
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_OUTPUT_PATH_DEFAULT = ""
TENSORBOARD_JOB_NAME = "job_name"
TENSORBOARD_JOB_NAME_DEFAULT = "DeepSpeedJobName"

#############################################
# Telemetry (monitor/ subsystem)
#############################################
# The "telemetry" block subsumes "tensorboard" (which stays as an alias:
# a config with only a tensorboard block gets a telemetry sink with the
# same output_path/job_name). All collection is report-boundary batched —
# the monitor/ subsystem adds zero host<->device syncs on the hot path.
TELEMETRY = "telemetry"
TELEMETRY_ENABLED = "enabled"
TELEMETRY_ENABLED_DEFAULT = False
TELEMETRY_OUTPUT_PATH = "output_path"
TELEMETRY_OUTPUT_PATH_DEFAULT = ""
TELEMETRY_JOB_NAME = "job_name"
TELEMETRY_JOB_NAME_DEFAULT = "DeepSpeedJobName"
# Ring-buffer capacity for per-step records between drains; overflow drops
# the OLDEST records and the drain reports how many were dropped.
TELEMETRY_BUFFER_SIZE = "buffer_size"
TELEMETRY_BUFFER_SIZE_DEFAULT = 1024
# Drain cadence in global steps; 0 = follow steps_per_print.
TELEMETRY_REPORT_STEPS = "report_steps"
TELEMETRY_REPORT_STEPS_DEFAULT = 0
# Host-side span tracing: path of the Chrome-trace/Perfetto JSON to write
# ("" = tracing off; span collection costs nothing when off).
TELEMETRY_TRACE_PATH = "trace_path"
TELEMETRY_TRACE_PATH_DEFAULT = ""
# Recompile sentinel: a jit cache miss on an instrumented step function
# after its warmup calls logs a structured event naming the function and
# the abstract-signature delta; fail_on_recompile raises instead.
TELEMETRY_FAIL_ON_RECOMPILE = "fail_on_recompile"
TELEMETRY_FAIL_ON_RECOMPILE_DEFAULT = False
# Default 2: call 0 is the cold compile, and call 1 may legitimately
# recompile once when the donated output state (whose shardings/layouts
# the compiler chose) becomes the next call's input — steady state starts
# at call 2.
TELEMETRY_RECOMPILE_WARMUP = "recompile_warmup_calls"
TELEMETRY_RECOMPILE_WARMUP_DEFAULT = 2
# Device-memory watermarks, sampled at report boundaries across ALL local
# devices and compared against the analytic ZeRO-partitioned model-state
# footprint: peak > analytic * ratio + slack emits a watermark event.
TELEMETRY_MEMORY_WATERMARKS = "memory_watermarks"
TELEMETRY_MEMORY_WATERMARKS_DEFAULT = True
TELEMETRY_WATERMARK_RATIO = "watermark_ratio"
TELEMETRY_WATERMARK_RATIO_DEFAULT = 2.0
TELEMETRY_WATERMARK_SLACK_BYTES = "watermark_slack_bytes"
TELEMETRY_WATERMARK_SLACK_BYTES_DEFAULT = 256 * 2 ** 20
# Optional jax.profiler device-trace window: capture num_steps starting at
# start_step into profile_dir (default: <output_path>/jax_trace).
# start_step -1 = off.
TELEMETRY_PROFILE_START_STEP = "profile_start_step"
TELEMETRY_PROFILE_START_STEP_DEFAULT = -1
TELEMETRY_PROFILE_NUM_STEPS = "profile_num_steps"
TELEMETRY_PROFILE_NUM_STEPS_DEFAULT = 1
TELEMETRY_PROFILE_DIR = "profile_dir"
TELEMETRY_PROFILE_DIR_DEFAULT = ""
# --- telemetry.profile: trace capture + ingestion + reconciliation -----
# The nested block form (the flat profile_* keys above stay as aliases).
# start_step >= 0 arms a jax.profiler window of window_steps hot steps;
# after the window closes, the capture is ingested
# (monitor/profile_ingest.py) into the per-step wall decomposition,
# reconciled against the cost model's floors (monitor/reconcile.py), and
# drained into the JSONL as the ``profile`` report section. Components
# measuring more than divergence_threshold x their analytic floor (or,
# for the zero-floor host bucket, more than host_frac of the step wall)
# fire ``reconcile_divergence`` events.
TELEMETRY_PROFILE = "profile"
TELEMETRY_PROFILE_BLOCK_START = "start_step"
TELEMETRY_PROFILE_BLOCK_START_DEFAULT = -1
TELEMETRY_PROFILE_BLOCK_STEPS = "window_steps"
TELEMETRY_PROFILE_BLOCK_STEPS_DEFAULT = 2
TELEMETRY_PROFILE_BLOCK_DIR = "out_dir"
TELEMETRY_PROFILE_BLOCK_DIR_DEFAULT = ""
TELEMETRY_PROFILE_THRESHOLD = "divergence_threshold"
TELEMETRY_PROFILE_THRESHOLD_DEFAULT = 3.0
TELEMETRY_PROFILE_HOST_FRAC = "host_frac"
TELEMETRY_PROFILE_HOST_FRAC_DEFAULT = 0.10
# Roofline cost model: at the FIRST report boundary, AOT-relower every
# compiled step path from its recorded abstract signature, pull XLA's
# cost_analysis() (flops + bytes accessed), fuse it with the jaxpr-walk
# analytic flops and the grad-sync wire model, and emit per-path
# compute/HBM/interconnect-bound verdicts + per-step MFU (one-time
# host-side compile at the boundary; no device traffic, no fences).
TELEMETRY_COST_MODEL = "cost_model"
TELEMETRY_COST_MODEL_DEFAULT = True
# Multi-host: rank 0 writes the primary JSONL; with per_host_shards every
# other SPMD process writes its own ``<job>.rankK.jsonl`` shard (and
# ``<trace>.rankK.json`` when tracing) instead of silently discarding its
# ring records. tools/telemetry_report.py aggregates the shards:
# per-host step-wall skew (straggler detection) and step-count/loss-hash
# desync checks.
TELEMETRY_PER_HOST = "per_host_shards"
TELEMETRY_PER_HOST_DEFAULT = False

# --- telemetry.health: anomaly detection, hang watchdog, flight recorder
# The forensic layer (monitor/health.py + monitor/flight.py). All
# detection is drain-time host work on already-fetched scalars; the only
# in-graph piece is the per-leaf grad tap below.
TELEMETRY_HEALTH = "health"
TELEMETRY_HEALTH_ENABLED = "enabled"
TELEMETRY_HEALTH_ENABLED_DEFAULT = True
# In-graph per-leaf grad sum-of-squares tap ([num_leaves] f32, riding
# the ring to the batched drain fetch — zero added device syncs, one
# extra read of the grad tree per step). Gives NaN/Inf provenance: the
# first non-finite leaf and its layer. Wired on the main train step, the
# forward/backward trio, and the sparse apply; the offload path's host
# Adam and onebit's in-shard_map update keep their own overflow
# machinery (grad_norm still feeds the spike detector there).
TELEMETRY_HEALTH_GRAD_TAPS = "grad_taps"
TELEMETRY_HEALTH_GRAD_TAPS_DEFAULT = True
# EWMA z-score spike detection on loss and grad_norm: flag |z| above the
# threshold after warmup_steps finite samples.
TELEMETRY_HEALTH_Z_THRESHOLD = "z_threshold"
TELEMETRY_HEALTH_Z_THRESHOLD_DEFAULT = 6.0
TELEMETRY_HEALTH_EWMA_ALPHA = "ewma_alpha"
TELEMETRY_HEALTH_EWMA_ALPHA_DEFAULT = 0.1
TELEMETRY_HEALTH_WARMUP_STEPS = "warmup_steps"
TELEMETRY_HEALTH_WARMUP_STEPS_DEFAULT = 20
# Hang watchdog (off by default: it is a per-engine daemon thread):
# fires when no step completes within max(watchdog_min_s,
# watchdog_factor * p95(recent step walls)) — all-thread stack dump
# (faulthandler), device memory_stats sample, pending step signature.
TELEMETRY_HEALTH_WATCHDOG = "watchdog"
TELEMETRY_HEALTH_WATCHDOG_DEFAULT = False
TELEMETRY_HEALTH_WATCHDOG_FACTOR = "watchdog_factor"
TELEMETRY_HEALTH_WATCHDOG_FACTOR_DEFAULT = 10.0
TELEMETRY_HEALTH_WATCHDOG_MIN_S = "watchdog_min_s"
TELEMETRY_HEALTH_WATCHDOG_MIN_S_DEFAULT = 120.0
# Crash flight recorder: SIGTERM/SIGINT/atexit handlers persist the last
# flight_window drained step records, the unsettled goodput window,
# anomaly events, and a config/mesh/env snapshot to FLIGHT.json
# (atomically; flight_path "" = <output_path>/FLIGHT.json, per-host
# shards get FLIGHT.rankK.json).
TELEMETRY_HEALTH_FLIGHT = "flight_recorder"
TELEMETRY_HEALTH_FLIGHT_DEFAULT = True
TELEMETRY_HEALTH_FLIGHT_PATH = "flight_path"
TELEMETRY_HEALTH_FLIGHT_PATH_DEFAULT = ""
TELEMETRY_HEALTH_FLIGHT_WINDOW = "flight_window"
TELEMETRY_HEALTH_FLIGHT_WINDOW_DEFAULT = 64

#############################################
# Inference / serving (inference/ subsystem)
#############################################
# The "inference" block configures the batched autoregressive serving
# tier (deepspeed_tpu/inference/): the slot count of the static KV
# cache, the cache sequence capacity, weight quantization, and the
# prefill chunking. All of it is STATIC program shape — the continuous-
# batching scheduler inserts/evicts requests without changing any
# compiled signature (the recompile sentinel is the regression gate).
INFERENCE = "inference"
# Number of concurrent request slots in the KV cache. Must be divisible
# by the mesh dp-axis size (slots are the data-parallel dimension of
# serving).
INFERENCE_MAX_SLOTS = "max_slots"
INFERENCE_MAX_SLOTS_DEFAULT = 8
# KV-cache sequence capacity per slot; 0 = the model's max_seq_length.
INFERENCE_MAX_SEQ_LEN = "max_seq_len"
INFERENCE_MAX_SEQ_LEN_DEFAULT = 0
# Weight quantization applied at engine construction: "none" keeps the
# checkpoint dtype, "bf16" stochastically rounds fp32 weights to bf16
# (ops/stochastic_rounding.py — the master-free training machinery),
# "int8" stores per-output-channel symmetric int8 (stochastic rounding
# onto the integer grid) and dequantizes inside the compiled step.
INFERENCE_QUANTIZE = "quantize"
INFERENCE_QUANTIZE_DEFAULT = "none"
INFERENCE_QUANTIZE_MODES = ("none", "bf16", "int8")
# Prefill chunk length: prompts are right-padded to a multiple and run
# chunk-by-chunk against the cache (static shapes at every prompt
# length). 0 = whole-prompt single-shot prefill padded to max_seq_len —
# the long-context path that composes with ring attention when the mesh
# has a sequence axis.
INFERENCE_PREFILL_CHUNK = "prefill_chunk"
INFERENCE_PREFILL_CHUNK_DEFAULT = 32
# Paged KV cache (the PagedAttention design): the cache is a pool of
# fixed-size blocks and a slot holds a list of block ids, so short and
# long requests share HBM and common prompt prefixes are shared
# copy-on-write across requests (full-block granularity, chain-hashed).
# block_size is the tokens-per-block page size; 0 = the PR-7 slot-major
# layout (one max_seq_len row per slot, no sharing). Must divide
# max_seq_len.
INFERENCE_BLOCK_SIZE = "block_size"
INFERENCE_BLOCK_SIZE_DEFAULT = 16
# Total blocks in the pool; 0 = full provisioning (max_slots *
# max_seq_len / block_size — every slot can reach max_seq_len, so
# admission never blocks on HBM). Smaller pools oversubscribe: the
# scheduler's admission gate then accounts free blocks, and the HBM
# saved is what SERVE_BENCH.json's hbm_bytes_per_token measures. Must
# be divisible by the mesh dp-axis size (blocks are born sharded over
# dp alongside the slots they serve).
INFERENCE_NUM_BLOCKS = "num_blocks"
INFERENCE_NUM_BLOCKS_DEFAULT = 0
# Speculative decoding (draft-then-verify, Leviathan et al. 2023):
# spec_k > 0 proposes k tokens per live slot from the self-drafting
# n-gram cache (prompt-lookup decoding — no drafter model) and one
# batched verify step accepts the longest agreeing prefix plus one
# corrected token. Greedy output is bit-identical to non-speculative
# greedy decode; the scheduler falls back to plain decode when
# temperature > 0 (exact rejection sampling is not implemented).
# Requires the paged cache (block_size > 0).
INFERENCE_SPEC_K = "spec_k"
INFERENCE_SPEC_K_DEFAULT = 0
# n-gram context length the drafter matches against the slot's token
# history (it tries n, n-1, ..., 1 and proposes the continuation of the
# most recent prior occurrence; repeat-last-token when nothing matches).
INFERENCE_SPEC_NGRAM = "spec_ngram"
INFERENCE_SPEC_NGRAM_DEFAULT = 3
# KV-pool storage dtype: "model" stores blocks at the model compute
# dtype; "bf16" halves fp32 KV HBM at rest (scores are fp32 either way).
INFERENCE_KV_DTYPE = "kv_cache_dtype"
INFERENCE_KV_DTYPE_DEFAULT = "model"
INFERENCE_KV_DTYPE_MODES = ("model", "bf16")
# Replica label stamped on this engine's telemetry + aggregator
# snapshots ("" = unlabeled single replica). The multi-replica router
# (inference/router.py) sets it so telemetry_report can keep replicas'
# percentile streams apart.
INFERENCE_REPLICA = "replica"
INFERENCE_REPLICA_DEFAULT = ""
# Pallas paged-attention kernel for the paged decode/verify/prefill
# attends (ops/paged_attention.py): table-driven block slices do
# O(context) work instead of the one-hot contraction's O(pool). True /
# False force it; "auto" enables on TPU only (the DS_PAGED_KERNEL env
# var overrides "auto"). Forced on without a TPU the kernel runs in
# interpret mode — same program, pure XLA — which is how the CPU-mesh
# tier-1 proves logit parity. Ignored by slot-major engines
# (block_size == 0).
INFERENCE_PAGED_KERNEL = "paged_kernel"
INFERENCE_PAGED_KERNEL_DEFAULT = "auto"
# inference.slo — serving SLO targets (monitor/serving_slo.py). A
# request is "good" when its TTFT and TPOT are both inside target; an
# unset target (0) always passes, and with both unset the tracker is
# off (snapshots omit the slo section). availability is the target
# good-fraction whose complement is the error budget the burn rate is
# measured against (burn_rate > 1 = budget consumed faster than the
# SLO allows); window_s is the trailing window for the windowed
# attainment/burn view.
INFERENCE_SLO = "slo"
INFERENCE_SLO_TTFT_MS = "ttft_ms"
INFERENCE_SLO_TTFT_MS_DEFAULT = 0.0
INFERENCE_SLO_TPOT_MS = "tpot_ms"
INFERENCE_SLO_TPOT_MS_DEFAULT = 0.0
INFERENCE_SLO_AVAILABILITY = "availability"
INFERENCE_SLO_AVAILABILITY_DEFAULT = 0.99
INFERENCE_SLO_WINDOW_S = "window_s"
INFERENCE_SLO_WINDOW_S_DEFAULT = 60.0

#############################################
# ZeRO
#############################################
ZERO_OPTIMIZATION = "zero_optimization"
ZERO_OPTIMIZATION_DISABLED = 0
ZERO_OPTIMIZATION_OPTIMIZER_STATES = 1
ZERO_OPTIMIZATION_GRADIENTS = 2
ZERO_OPTIMIZATION_WEIGHTS = 3
MAX_STAGE_ZERO_OPTIMIZATION = ZERO_OPTIMIZATION_WEIGHTS
ZERO_OPTIMIZATION_DEFAULT = ZERO_OPTIMIZATION_DISABLED

ZERO_STAGE = "stage"
ZERO_STAGE_DEFAULT = ZERO_OPTIMIZATION_DISABLED
ZERO_CONTIGUOUS_GRADIENTS = "contiguous_gradients"
ZERO_CONTIGUOUS_GRADIENTS_DEFAULT = False
ZERO_REDUCE_BUCKET_SIZE = "reduce_bucket_size"
ZERO_REDUCE_BUCKET_SIZE_DEFAULT = 500_000_000
ZERO_REDUCE_SCATTER = "reduce_scatter"
ZERO_REDUCE_SCATTER_DEFAULT = True
# How the stage-2 reduce-scatter is obtained when reduce_scatter is on:
# "declarative" trusts the GSPMD partitioner to lower the declared grad
# sharding; "explicit" computes grads under shard_map with lax.psum_scatter
# (guaranteed lowering); "auto" probes the compiled lowering once per
# backend (parallel/hlo_audit.py) and goes explicit iff the declarative
# path regresses to a full all-reduce + slice.
ZERO_GRAD_SYNC = "grad_sync"
ZERO_GRAD_SYNC_DEFAULT = "auto"
ZERO_GRAD_SYNC_MODES = ("auto", "declarative", "explicit")
# ZeRO-3 layer-gather prefetch: how many layers ahead the per-layer
# param all-gather is issued inside the model's layer scan (runtime/zero/
# stage3.py). 0 = gather at use (the parity baseline: no overlap
# structure); k >= 1 = the scan carries k gathered layers so layer i+k's
# gather overlaps layer i's compute. Only the stacked-layer scan path
# consumes the knob; unstacked models gather leaf-at-use regardless.
ZERO_PREFETCH_DEPTH = "prefetch_depth"
ZERO_PREFETCH_DEPTH_DEFAULT = 1
# Multi-slice DCN compression: 1-bit (error-feedback sign + per-chunk
# scale) compression of the INTER-SLICE gradient hop only — the slow
# DCN tier is where the 1-bit wire format (ops/onebit.py) pays; the
# in-slice ICI reduce-scatter is never compressed. Requires a mesh with
# slices > 1 (parallel/multislice.py) and the explicit hierarchical
# grad path (ZeRO stage >= 2).
ZERO_DCN_COMPRESSION = "dcn_compression"
ZERO_DCN_COMPRESSION_DEFAULT = False
ZERO_OVERLAP_COMM = "overlap_comm"
ZERO_OVERLAP_COMM_DEFAULT = False
ZERO_ALLGATHER_PARTITIONS = "allgather_partitions"
ZERO_ALLGATHER_PARTITIONS_DEFAULT = True
ZERO_ALLGATHER_BUCKET_SIZE = "allgather_bucket_size"
ZERO_ALLGATHER_BUCKET_SIZE_DEFAULT = 500_000_000
ZERO_LOAD_FROM_FP32_WEIGHTS = "load_from_fp32_weights"
ZERO_LOAD_FROM_FP32_WEIGHTS_DEFAULT = True
ZERO_CPU_OFFLOAD = "cpu_offload"
ZERO_CPU_OFFLOAD_DEFAULT = False
# Offload overlap pipeline: the host masters are split into ~bucket_size-
# byte groups (fp32 master bytes) so D2H, host Adam, and H2D stream
# per-bucket; overlap_comm toggles the concurrent executor, host_threads
# sizes its worker pool (0 = os.cpu_count()).
ZERO_OFFLOAD_BUCKET_SIZE = "offload_bucket_size"
ZERO_OFFLOAD_BUCKET_SIZE_DEFAULT = 64 * 2 ** 20
ZERO_OFFLOAD_HOST_THREADS = "offload_host_threads"
ZERO_OFFLOAD_HOST_THREADS_DEFAULT = 0
ZERO_ELASTIC_CHECKPOINT = "elastic_checkpoint"
ZERO_ELASTIC_CHECKPOINT_DEFAULT = True
ZERO_MAX_ELEMENTS_PER_COMM = "max_elements_per_comm"
ZERO_MAX_ELEMENTS_PER_COMM_DEFAULT = 500_000_000

#############################################
# Activation checkpointing
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
ACT_CHKPT_PARTITION_ACTIVATIONS = "partition_activations"
ACT_CHKPT_PARTITION_ACTIVATIONS_DEFAULT = False
ACT_CHKPT_NUMBER_CHECKPOINTS = "number_checkpoints"
ACT_CHKPT_NUMBER_CHECKPOINTS_DEFAULT = None
ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION = "contiguous_memory_optimization"
ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT = False
ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY = "synchronize_checkpoint_boundary"
ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT = False
ACT_CHKPT_CPU_CHECKPOINTING = "cpu_checkpointing"
ACT_CHKPT_CPU_CHECKPOINTING_DEFAULT = False
ACT_CHKPT_PROFILE = "profile"
ACT_CHKPT_PROFILE_DEFAULT = False

#############################################
# Sparse attention
#############################################
SPARSE_ATTENTION = "sparse_attention"
SPARSE_MODE = "mode"
SPARSE_MODE_DEFAULT = "fixed"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_BLOCK = "block"
SPARSE_BLOCK_DEFAULT = 16
SPARSE_DIFFERENT_LAYOUT_PER_HEAD = "different_layout_per_head"
SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT = False
SPARSE_NUM_LOCAL_BLOCKS = "num_local_blocks"
SPARSE_NUM_LOCAL_BLOCKS_DEFAULT = 4
SPARSE_NUM_GLOBAL_BLOCKS = "num_global_blocks"
SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT = 1
SPARSE_ATTENTION_TYPE = "attention"
SPARSE_ATTENTION_TYPE_DEFAULT = "bidirectional"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION = "horizontal_global_attention"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT = False
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS = "num_different_global_patterns"
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT = 1
SPARSE_NUM_RANDOM_BLOCKS = "num_random_blocks"
SPARSE_NUM_RANDOM_BLOCKS_DEFAULT = 0
SPARSE_LOCAL_WINDOW_BLOCKS = "local_window_blocks"
SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT = [4]
SPARSE_GLOBAL_BLOCK_INDICES = "global_block_indices"
SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT = [0]
SPARSE_GLOBAL_BLOCK_END_INDICES = "global_block_end_indices"
SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT = None
SPARSE_NUM_SLIDING_WINDOW_BLOCKS = "num_sliding_window_blocks"
SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT = 3

#############################################
# Pipeline
#############################################
PIPELINE = "pipeline"
PIPELINE_STAGES = "stages"
PIPELINE_STAGES_DEFAULT = None
PIPELINE_PARTITION = "partition"
PIPELINE_PARTITION_DEFAULT = "best"
PIPELINE_SEED_LAYERS = "seed_layers"
PIPELINE_SEED_LAYERS_DEFAULT = False
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL = "activation_checkpoint_interval"
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT = 0
PIPELINE_SCHEDULE = "schedule"
PIPELINE_SCHEDULE_DEFAULT = "gpipe"

#############################################
# Gradient noise scale / progressive layer drop
#############################################
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
PLD_ENABLED = "enabled"
PLD_ENABLED_DEFAULT = False
PLD_THETA = "theta"
PLD_THETA_DEFAULT = 1.0
PLD_GAMMA = "gamma"
PLD_GAMMA_DEFAULT = 0.001

#############################################
# Flops profiler
#############################################
FLOPS_PROFILER = "flops_profiler"
FLOPS_PROFILER_ENABLED = "enabled"
FLOPS_PROFILER_ENABLED_DEFAULT = False
FLOPS_PROFILER_PROFILE_STEP = "profile_step"
FLOPS_PROFILER_PROFILE_STEP_DEFAULT = 1
FLOPS_PROFILER_MODULE_DEPTH = "module_depth"
FLOPS_PROFILER_MODULE_DEPTH_DEFAULT = -1
FLOPS_PROFILER_TOP_MODULES = "top_modules"
FLOPS_PROFILER_TOP_MODULES_DEFAULT = 1
FLOPS_PROFILER_DETAILED = "detailed"
FLOPS_PROFILER_DETAILED_DEFAULT = True

#############################################
# Elasticity
#############################################
ELASTICITY = "elasticity"
ENABLED = "enabled"
ENABLED_DEFAULT = False
MAX_ACCEPTABLE_BATCH_SIZE = "max_train_batch_size"
MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT = 2000
MICRO_BATCHES = "micro_batch_sizes"
MICRO_BATCHES_DEFAULT = [2, 4, 6]
MIN_GPUS = "min_gpus"
MIN_GPUS_DEFAULT = 1
MAX_GPUS = "max_gpus"
MAX_GPUS_DEFAULT = 10000
MIN_TIME = "min_time"
MIN_TIME_DEFAULT = 0
VERSION = "version"
VERSION_DEFAULT = 0.1
LATEST_ELASTICITY_VERSION = 0.1
IGNORE_NON_ELASTIC_BATCH_INFO = "ignore_non_elastic_batch_info"
IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT = False
PREFER_LARGER_BATCH = "prefer_larger_batch"
PREFER_LARGER_BATCH_DEFAULT = True

#############################################
# MoE expert parallelism (moe/ subsystem)
#############################################
# The "moe" block configures the engine side of expert parallelism:
# the `expert` mesh axis size (factors out of data — reuses the dp
# devices), the metrics schema (per-expert token counts / drop fraction
# / aux loss ride the telemetry drain), and the all-to-all wire model.
# The MODEL side is TransformerConfig.moe (deepspeed_tpu.moe.MoEConfig
# — build it with MoEConfig.from_ds_config so the two cannot drift).
MOE = "moe"
# 0 = MoE disabled (the block is inert).
MOE_NUM_EXPERTS = "num_experts"
MOE_NUM_EXPERTS_DEFAULT = 0
# Router top-k (1 or 2 — Switch vs GShard gating).
MOE_TOP_K = "top_k"
MOE_TOP_K_DEFAULT = 2
# Per-expert slot count C = ceil(capacity_factor * k * T / E) per
# device; tokens beyond capacity drop to the residual path. One
# compiled shape regardless of routing.
MOE_CAPACITY_FACTOR = "capacity_factor"
MOE_CAPACITY_FACTOR_DEFAULT = 1.25
# Load-balance aux loss weight (Switch: E * sum(f_e * P_e)).
MOE_AUX_LOSS_WEIGHT = "aux_loss_weight"
MOE_AUX_LOSS_WEIGHT_DEFAULT = 1e-2
# Router z-loss weight (mean(logsumexp(logits)^2) — logit drift guard).
MOE_Z_LOSS_WEIGHT = "z_loss_weight"
MOE_Z_LOSS_WEIGHT_DEFAULT = 1e-3
# The `expert` mesh axis size (must divide num_experts AND the device
# count alongside the other axes). 1 = no expert axis: experts run
# data-parallel-replicated, no all-to-all (the dev/CI path).
MOE_EXPERT_PARALLEL_SIZE = "expert_parallel_size"
MOE_EXPERT_PARALLEL_SIZE_DEFAULT = 1
# Expert-FFN compute path: the grouped-GEMM Pallas kernel
# (ops/grouped_gemm.py) vs the batched einsum. "auto" = kernel on TPU,
# einsum on CPU (DS_GROUPED_GEMM=0/1 overrides); True/False force —
# the same contract as TransformerConfig.fused_kernels.
MOE_GROUPED_GEMM = "grouped_gemm"
MOE_GROUPED_GEMM_DEFAULT = "auto"

#############################################
# Mesh / parallelism (TPU-native extension keys)
#############################################
MESH = "mesh"
MESH_DATA_PARALLEL_SIZE = "data_parallel_size"
MESH_MODEL_PARALLEL_SIZE = "model_parallel_size"
MESH_PIPE_PARALLEL_SIZE = "pipe_parallel_size"
MESH_SEQUENCE_PARALLEL_SIZE = "sequence_parallel_size"
# Multi-slice scale-out: how many ICI domains (slices) the mesh spans —
# the OUTERMOST mesh axis; dp factors within a slice and only the
# `slice`-axis collectives cross DCN (parallel/multislice.py).
MESH_NUM_SLICES = "slices"

#############################################
# Checkpoint
#############################################
CHECKPOINT = "checkpoint"
CHECKPOINT_TAG_VALIDATION = "tag_validation"
CHECKPOINT_TAG_VALIDATION_DEFAULT = "Warn"
CHECKPOINT_TAG_VALIDATION_MODES = ["Warn", "Ignore", "Fail"]
# Async checkpointing (runtime/async_ckpt.py): save_checkpoint() runs a
# fast in-step-window SNAPSHOT (one batched device_get into host
# buffers) and hands serialization + the two-phase atomic commit to a
# background writer thread. Sync and async paths share the commit
# byte-for-byte; both flip `latest` via tmp + os.replace.
CHECKPOINT_ASYNC = "async"
CHECKPOINT_ASYNC_DEFAULT = False
# Auto-save cadence: > 0 saves a checkpoint (tag global_stepN) into
# `save_dir` every N completed steps from inside train_batch.
CHECKPOINT_SNAPSHOT_EVERY = "snapshot_every"
CHECKPOINT_SNAPSHOT_EVERY_DEFAULT = 0
# Directory for auto-saves and the SIGTERM final save. Required when
# snapshot_every > 0; enables the preemption handler when set.
CHECKPOINT_SAVE_DIR = "save_dir"
CHECKPOINT_SAVE_DIR_DEFAULT = ""
# SIGTERM handler (chains with the flight recorder's): requests a final
# snapshot+commit when one isn't already in flight, then re-raises so
# the exit code stays honest. Effective only with a save_dir.
CHECKPOINT_PREEMPT_SAVE = "preempt_save"
CHECKPOINT_PREEMPT_SAVE_DEFAULT = True
# Writer knobs: max snapshots allowed in the writer queue before the
# NEXT save blocks (each pending snapshot is a full host copy of the
# state — this bounds host memory; the blocking wait is exposed and
# priced into the goodput checkpoint bucket, honestly), and the
# hang-watchdog timeout guarding each background write.
CHECKPOINT_MAX_PENDING = "max_pending_snapshots"
CHECKPOINT_MAX_PENDING_DEFAULT = 1
CHECKPOINT_WRITER_TIMEOUT_S = "writer_timeout_s"
CHECKPOINT_WRITER_TIMEOUT_S_DEFAULT = 300.0
# fsync blobs + dirs at commit: required for durability across MACHINE
# crashes; a plain process kill (preemption) never needs it, and the
# CPU-mesh test tier keeps it off for speed.
CHECKPOINT_FSYNC = "fsync"
CHECKPOINT_FSYNC_DEFAULT = False
