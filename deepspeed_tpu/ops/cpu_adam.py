"""DeepSpeedCPUAdam — host-resident Adam/AdamW for ZeRO-Offload.

Parity with reference ``ops/adam/cpu_adam.py:12`` (per-instance native
optimizer state keyed by opt_id, ``step`` with optional fused fp16 param
copy) on top of the C++ SIMD kernel in ``csrc/cpu_adam.cpp`` (reference
``csrc/adam/cpu_adam.cpp:21-147``). Falls back to a vectorized numpy
implementation of identical math when no compiler is available, so offload
works everywhere and the native path is a pure speedup.

All state is numpy fp32 in host RAM: masters (owned by the engine), moments
(owned here). The step optionally emits a bf16 staging copy in the same
pass — that buffer is what ``jax.device_put`` ships back to HBM.
"""
from __future__ import annotations

import ctypes
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .op_builder import cpu_adam_builder
from ..utils.logging import logger

_f32p = ctypes.POINTER(ctypes.c_float)
_u16p = ctypes.POINTER(ctypes.c_uint16)


def host_f32(x) -> np.ndarray:
    """Owned, writable, C-contiguous fp32 host copy of ``x``.

    np.asarray of a CPU-backend jax array is a ZERO-COPY read-only view of
    the jax buffer — handing that to the in-place SIMD kernel would mutate
    the caller's arrays behind XLA's back. Likewise the axon backend
    returns F-ordered views whose flat layout must not leak into kernel
    state (flat-index pairing breaks across a serialization round-trip).
    """
    a = np.asarray(x, np.float32)
    if a.base is not None or not a.flags["OWNDATA"] \
            or not a.flags["C_CONTIGUOUS"] or not a.flags["WRITEABLE"]:
        a = np.array(a, np.float32, order="C")
    return a


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.ds_adam_step.argtypes = [
        _f32p, _f32p, _f32p, _f32p, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ctypes.c_float, ctypes.c_int32, ctypes.c_float]
    lib.ds_adam_step.restype = None
    lib.ds_adam_step_plus_copy.argtypes = [
        _f32p, _f32p, _f32p, _f32p, _u16p, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ctypes.c_float, ctypes.c_int32, ctypes.c_float]
    lib.ds_adam_step_plus_copy.restype = None
    lib.ds_grad_norm_sq.argtypes = [_f32p, ctypes.c_int64, ctypes.c_float]
    lib.ds_grad_norm_sq.restype = ctypes.c_double
    lib.ds_adam_step_bf16g.argtypes = [
        _f32p, _u16p, _f32p, _f32p, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ctypes.c_float, ctypes.c_int32, ctypes.c_float]
    lib.ds_adam_step_bf16g.restype = None
    lib.ds_adam_step_plus_copy_bf16g.argtypes = [
        _f32p, _u16p, _f32p, _f32p, _u16p, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ctypes.c_float, ctypes.c_int32, ctypes.c_float]
    lib.ds_adam_step_plus_copy_bf16g.restype = None
    lib.ds_grad_norm_sq_bf16.argtypes = [_u16p, ctypes.c_int64,
                                         ctypes.c_float]
    lib.ds_grad_norm_sq_bf16.restype = ctypes.c_double
    return lib


def _is_bf16(a) -> bool:
    """ml_dtypes.bfloat16 ndarray."""
    d = getattr(a, "dtype", None)
    return d is not None and getattr(d, "name", "") == "bfloat16"


_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False


def _native_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_FAILED
    if _LIB is None and not _LIB_FAILED:
        builder = cpu_adam_builder()
        if not builder.is_compatible():
            _LIB_FAILED = True
            logger.warning("cpu_adam: no C++ compiler; using numpy fallback")
        else:
            try:
                _LIB = _bind(builder.jit_load())
            except Exception as e:  # pragma: no cover
                _LIB_FAILED = True
                logger.warning(f"cpu_adam native build failed ({e}); "
                               "using numpy fallback")
    return _LIB


def _ptr(a: np.ndarray, ty=_f32p):
    return a.ctypes.data_as(ty)


class DeepSpeedCPUAdam:
    """Host Adam over a pytree of fp32 numpy masters (updated in place)."""

    def __init__(self, params: Dict[str, Any], lr: float = 1e-3,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True):
        import jax
        self.lr = float(lr)
        self.betas = (float(betas[0]), float(betas[1]))
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.adamw_mode = bool(adamw_mode)
        self.step_count = 0
        leaves, self.treedef = jax.tree_util.tree_flatten(params)
        # Plain C-ordered zeros — zeros_like would inherit the (possibly
        # F-ordered) layout of backend views, see host_f32.
        self.exp_avg = [np.zeros(np.shape(l), np.float32) for l in leaves]
        self.exp_avg_sq = [np.zeros(np.shape(l), np.float32) for l in leaves]
        self._lib = _native_lib()

    @property
    def native(self) -> bool:
        return self._lib is not None

    def state_dict(self) -> Dict[str, Any]:
        return {"step": self.step_count, "exp_avg": list(self.exp_avg),
                "exp_avg_sq": list(self.exp_avg_sq)}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.step_count = int(sd["step"])
        self.exp_avg = [host_f32(a) for a in sd["exp_avg"]]
        self.exp_avg_sq = [host_f32(a) for a in sd["exp_avg_sq"]]

    # ------------------------------------------------------------------ #
    def step(self, master_leaves, grad_leaves, lr: Optional[float] = None,
             grad_scale: float = 1.0, bf16_out: Optional[list] = None) -> None:
        """One optimizer step over flat leaf lists, in place.

        ``grad_scale`` folds the loss-scale inverse and clip coefficient
        into the kernel's gradient read (single pass). With ``bf16_out``
        (list of uint16 arrays, same shapes) the updated masters are also
        down-cast in the same pass (ds_adam_step_plus_copy parity).
        """
        self.step_count += 1
        self.step_leaves(master_leaves, grad_leaves,
                         range(len(master_leaves)), lr=lr,
                         grad_scale=grad_scale, bf16_out=bf16_out,
                         step=self.step_count)

    def step_leaves(self, master_leaves, grad_leaves, indices,
                    lr: Optional[float] = None, grad_scale: float = 1.0,
                    bf16_out: Optional[list] = None,
                    step: Optional[int] = None) -> None:
        """Per-bucket Adam: update ``master_leaves[i]`` for ``i`` in
        ``indices`` from ``grad_leaves[j]`` (the j-th grad pairs with the
        j-th index), in place.

        ``step`` is the bias-correction tick, passed EXPLICITLY so
        concurrent per-bucket callers share one optimizer step without
        racing on ``step_count`` — the bucketed offload pipeline updates
        ``step_count`` once, after every bucket has applied. Leaves are
        disjoint per bucket, so calls for different buckets are thread-safe
        (the native kernels and numpy both release the GIL for the heavy
        loops)."""
        t = int(self.step_count if step is None else step)
        lr = self.lr if lr is None else float(lr)
        b1, b2 = self.betas
        for j, i in enumerate(indices):
            p, g = master_leaves[i], grad_leaves[j]
            assert p.dtype == np.float32 and p.flags["C_CONTIGUOUS"], \
                "masters must be contiguous fp32"
            m, v = self.exp_avg[i], self.exp_avg_sq[i]
            if self._lib is not None and _is_bf16(g):
                # BF16 grads straight into the kernel: no host-side cast
                # pass, half the gradient read traffic.
                gb = np.ascontiguousarray(g).view(np.uint16)
                if bf16_out is not None:
                    self._lib.ds_adam_step_plus_copy_bf16g(
                        _ptr(p), _ptr(gb, _u16p), _ptr(m), _ptr(v),
                        _ptr(bf16_out[i], _u16p), p.size, t,
                        lr, b1, b2, self.eps, self.weight_decay,
                        int(self.adamw_mode), grad_scale)
                else:
                    self._lib.ds_adam_step_bf16g(
                        _ptr(p), _ptr(gb, _u16p), _ptr(m), _ptr(v), p.size,
                        t, lr, b1, b2, self.eps,
                        self.weight_decay, int(self.adamw_mode), grad_scale)
                continue
            g = np.ascontiguousarray(np.asarray(g, np.float32))
            if self._lib is not None:
                if bf16_out is not None:
                    self._lib.ds_adam_step_plus_copy(
                        _ptr(p), _ptr(g), _ptr(m), _ptr(v),
                        _ptr(bf16_out[i], _u16p), p.size, t,
                        lr, b1, b2, self.eps, self.weight_decay,
                        int(self.adamw_mode), grad_scale)
                else:
                    self._lib.ds_adam_step(
                        _ptr(p), _ptr(g), _ptr(m), _ptr(v), p.size,
                        t, lr, b1, b2, self.eps,
                        self.weight_decay, int(self.adamw_mode), grad_scale)
            else:
                self._numpy_step(p, g, m, v, lr, grad_scale, t)
                if bf16_out is not None:
                    bf16_out[i][...] = _f32_to_bf16_np(p)

    def _numpy_step(self, p, g, m, v, lr, grad_scale, t) -> None:
        b1, b2 = self.betas
        g = g * grad_scale
        if not self.adamw_mode and self.weight_decay:
            g = g + self.weight_decay * p
        m *= b1
        m += (1 - b1) * g
        v *= b2
        v += (1 - b2) * np.square(g)
        bc1 = 1 - b1 ** t
        bc2 = 1 - b2 ** t
        denom = np.sqrt(v) / np.sqrt(bc2) + self.eps
        if self.adamw_mode and self.weight_decay:
            p -= lr * self.weight_decay * p
        p -= (lr / bc1) * (m / denom)

    def grad_norm_sq(self, grad_leaves, grad_scale: float = 1.0) -> float:
        """Squared L2 norm of the (scaled) gradients, accumulated per leaf
        in list order (float64 partials). The per-bucket entry point: the
        bucketed offload path sums these partials in bucket-index order, so
        overlapped and serial execution of the SAME bucketing produce the
        identical double — the overflow vote and clip coefficient cannot
        diverge between the two modes."""
        acc = 0.0
        for g in grad_leaves:
            if self._lib is not None and _is_bf16(g):
                gb = np.ascontiguousarray(g).view(np.uint16)
                acc += float(self._lib.ds_grad_norm_sq_bf16(
                    _ptr(gb, _u16p), gb.size, grad_scale))
                continue
            g = np.ascontiguousarray(np.asarray(g, np.float32))
            if self._lib is not None:
                acc += float(self._lib.ds_grad_norm_sq(
                    _ptr(g), g.size, grad_scale))
            else:
                gd = g.astype(np.float64) * grad_scale
                acc += float(np.sum(gd * gd))
        return acc

    def grad_norm(self, grad_leaves, grad_scale: float = 1.0) -> float:
        """Global L2 norm of the (scaled) gradients, host-side."""
        return float(np.sqrt(self.grad_norm_sq(grad_leaves, grad_scale)))


def _f32_to_bf16_np(a: np.ndarray) -> np.ndarray:
    """fp32 -> bf16 bits with round-to-nearest-even (numpy fallback)."""
    x = a.view(np.uint32)
    lsb = (x >> 16) & 1
    rounded = x + 0x7FFF + lsb
    return (rounded >> 16).astype(np.uint16)
