"""Fused Pallas multi-tensor optimizer apply — one HBM pass per step.

Parity target: the reference's multi-tensor fused Adam
(``csrc/adam/multi_tensor_adam.cu:123``) — ONE kernel pass per chunk that
reads grad+param+m+v and writes param+m+v, with the chunked multi-tensor
front end amortizing thousands of small leaves into a handful of launches.

Why this exists on TPU at all (given XLA already fuses elementwise ops):
XLA fuses *within* a leaf, but the optax apply is still one fusion per
param leaf — ~450 kernel launches for an unrolled GPT-2, each re-paying
launch + pipeline-warmup overhead — and the engine's clip multiply,
unscale, bias correction and stochastic-rounding write are separate
HBM passes when XLA's fusion heuristics split them. The Pallas kernel
makes the single-pass property structural instead of heuristic.

Two entry points share the kernel:

- ``fused_apply`` (PR-1 API, kept verbatim): the caller has already
  resolved the clip coefficient and the overflow vote; the kernel folds
  the coefficient into its grad read. The engine's historical "two-pass"
  path: a separate full-tree norm read precedes the apply.
- ``fused_step`` (the one-pass path): the global-norm reduction, fp16
  unscale, overflow vote, clip, overflow-skip select, and the
  compute-dtype cast-cache refresh ALL ride inside the fused pass:

      kernel 1 (per chunk): sq-norm partials of the flat grads
      scalar carry:         norm = sqrt(psum partials) / scale
                            overflow = !isfinite(norm)   [fp16]
                            coeff = min(1, clip/(norm+1e-6))
      kernel 2 (per chunk): read g,p,m,v; g = (g*inv)*coeff
                            m',v' Adam update (f32 moments)
                            skip-select (overflow holds the step)
                            write p' (+ optional compute-dtype cast copy,
                            + optional in-kernel bf16 stochastic round)

  so optimizer state (param+m+v) is read and written exactly ONCE per
  step: no separate norm pass, no full-tree unscale multiply, no
  post-apply jnp.where overflow select, no post-apply cast pass.

Multi-tensor layout (V-interleaved, ZeRO-shard-local)
-----------------------------------------------------

The pytree's float leaves flatten into contiguous same-dtype buffers.
PR-1 concatenated leaves end to end, which made every per-device flat
chunk a FULL-tree buffer under ZeRO sharding (GSPMD gathered the
dp-sharded moments around the opaque kernel — COMM_AUDIT.json's
``fused_chunk_gather`` finding). The layout is now *virtual-shard
interleaved*: each leaf is padded to a multiple of ``V`` virtual shards
and reshaped to ``[V, r_leaf]``; leaves concatenate along axis 1 into a
``[V, L]`` group buffer (stored flat as ``[V*L]``). Row v holds the
v-th 1/V slice of every leaf, so:

- a contiguous 1/dp range of the flat buffer == ``V/dp`` whole rows ==
  the dp-shard of every leaf (any dp dividing V);
- the kernels run under ``shard_map`` over the dp axis on LOCAL rows —
  the moments are never gathered, each device updates exactly its ZeRO
  shard, and the updated params leave the region dp-sharded (the
  engine's replicated out_shardings turn that into the per-leaf ZeRO-2
  param all-gather);
- the layout does not depend on dp (``V`` is a constant 8, widened to
  dp only above 8 devices), so checkpoints stay elastic across dp
  resizes exactly like PR-1's;
- under ZeRO-3 (params THEMSELVES dp-sharded, runtime/zero/stage3.py)
  the apply needs NO new gather: a leaf sharded on its leading dim over
  dp owns contiguous flat ranges, which are exactly whole virtual rows
  (``V/dp`` rows = the d-th 1/dp of every leaf), so the
  ``_flatten_group`` row constraint is a local reshape and the kernels
  consume grad, param AND moments as the same dp shard — verified by
  COMM_AUDIT.json's zero3 config (zero apply-time collectives). Leaves
  the stage-3 layer scan shards on a non-leading dim relayout at region
  entry (still 1/dp per device, never a gather to full).

The deterministic math is bit-exact with ``optax.adamw`` / the engine's
coupled-Adam chain: every multiply-add is written in optax's association
order (see ``tests/test_fused_update.py``). The one-pass norm is the
same sum-of-squares at a different association (chunk partials instead
of per-leaf sums), so clip coefficients agree to f32 ulp — the same
cross-program tolerance class PR-1 documented for FMA contraction.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU backend bits are importable everywhere; interpret=True runs on CPU
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from . import autotune

ScheduleOrFloat = Union[Callable, float]

# Kernel geometry: W lanes wide (128-multiple), up to _R sublane rows per
# grid step. One (128, 1024) f32 block is 512 KiB; with 4 inputs + up to
# 4 outputs double buffered that is ~8 MiB of VMEM — inside the ~16
# MiB/core budget.
_W = 1024
_R = 128
# Group rows pad to a multiple of 8*_W elements so the per-shard row
# count is always a multiple of the f32 minimum sublane tile (8).
_ROW_QUANTUM = 8 * _W

# Virtual shard count: the flat layout interleaves every leaf over _V
# rows, so any dp <= _V owns whole rows (= contiguous flat ranges) and
# the layout itself never depends on the live dp size (checkpoint
# elasticity). Meshes wider than _V widen V to dp — sizes above 8 are
# beyond this repo's test envelope and noted in docs/tutorials/kernels.md.
_V = 8


class FusedAdamState(NamedTuple):
    """Fused optimizer state: one flat f32 moment buffer per dtype group,
    stored in the V-interleaved layout (see module docstring). ZeRO
    shardings (zero/partition.py) split the flat axis over dp; any dp
    dividing V lands on whole virtual rows, so shards are element-aligned
    with the grads/params the kernel reads and checkpoint shards stay
    elastic across dp resizes."""
    count: jax.Array                 # int32 scalar, number of updates
    m: Tuple[jax.Array, ...]
    v: Tuple[jax.Array, ...]


class FusedStepOut(NamedTuple):
    """Everything the one-pass ``fused_step`` produces."""
    params: Any
    state: "FusedAdamState"
    cast_params: Any                 # compute-dtype copy (None when unused)
    grad_norm: jax.Array             # unscaled global norm (-1.0 = skipped)
    overflow: jax.Array              # bool (False when not fp16)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def virtual_shards(dp: int = 1) -> int:
    return max(_V, int(dp))


def _float_groups(leaves):
    """Deterministic dtype-grouping of float leaves: [(dtype, [leaf idx])],
    sorted by dtype name. Non-float leaves bypass the kernel entirely."""
    groups = {}
    for i, leaf in enumerate(leaves):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            groups.setdefault(jnp.dtype(leaf.dtype), []).append(i)
    return sorted(groups.items(), key=lambda kv: kv[0].name)


def _leaf_rows(n: int, shards: int) -> int:
    """Per-virtual-shard row length of a leaf (leaf padded to V|n)."""
    return -(-int(n) // shards)


def _group_row_len(sizes, shards: int) -> int:
    """Padded per-row length L of a group buffer: sum of leaf rows,
    padded so every 1/V row is a whole number of (8, _W) f32 tiles."""
    L = sum(_leaf_rows(n, shards) for n in sizes)
    return max(_ROW_QUANTUM, -(-L // _ROW_QUANTUM) * _ROW_QUANTUM)


def group_nbytes(sizes, shards: int = _V, itemsize: int = 4) -> int:
    """Padded group-buffer bytes (one moment buffer) — the analytic
    footprint tools use."""
    return virtual_shards(shards) * _group_row_len(sizes, shards) * itemsize


def _flatten_group(leaves, idxs, dtype, shards: int, Lpad: int,
                   constrain=None) -> jax.Array:
    """Leaves -> the [shards, Lpad] V-interleaved group buffer.

    Each leaf reshapes to [shards, r_leaf] and the rows concatenate along
    axis 1 — the concat axis is NOT the sharded axis, so GSPMD partitions
    the assembly row-locally (no full-buffer materialization; the per-
    leaf reshard is bounded by that leaf's size). ``constrain`` is the
    optional NamedSharding pinning rows to the dp axis."""
    cols = []
    for i in idxs:
        f = leaves[i].reshape(-1).astype(dtype)
        r = _leaf_rows(f.size, shards)
        if r * shards > f.size:
            f = jnp.concatenate([f, jnp.zeros((r * shards - f.size,),
                                              dtype)])
        a = f.reshape(shards, r)
        if constrain is not None:
            a = lax.with_sharding_constraint(a, constrain)
        cols.append(a)
    L = sum(a.shape[1] for a in cols)
    if Lpad > L:
        tail = jnp.zeros((shards, Lpad - L), dtype)
        if constrain is not None:
            tail = lax.with_sharding_constraint(tail, constrain)
        cols.append(tail)
    buf = jnp.concatenate(cols, axis=1) if len(cols) > 1 else cols[0]
    if constrain is not None:
        buf = lax.with_sharding_constraint(buf, constrain)
    return buf


def _unflatten_group(buf: jax.Array, like_leaves, idxs,
                     shards: int) -> Dict[int, jax.Array]:
    """[shards, Lpad] group buffer -> {leaf idx: leaf-shaped array}.
    Slices stay on the (sharded-safe) row axis; each leaf re-gathers at
    most its own size downstream."""
    out: Dict[int, jax.Array] = {}
    off = 0
    for i in idxs:
        n = int(like_leaves[i].size)
        r = _leaf_rows(n, shards)
        piece = lax.slice(buf, (0, off), (shards, off + r)).reshape(-1)
        out[i] = piece[:n].reshape(like_leaves[i].shape)
        off += r
    return out


def leaf_moment_views(state: "FusedAdamState", params: Any,
                      shards: int = _V) -> Tuple[Any, Any]:
    """Per-leaf views of the fused moment buffers (tests / debugging):
    returns (m_tree, v_tree) shaped like ``params``' float leaves (None
    at non-float positions)."""
    p_leaves, treedef = jax.tree_util.tree_flatten(params)
    shards = virtual_shards(shards)
    m_out: List[Any] = [None] * len(p_leaves)
    v_out: List[Any] = [None] * len(p_leaves)
    for gi, (dt, idxs) in enumerate(_float_groups(p_leaves)):
        Lpad = _group_row_len([p_leaves[i].size for i in idxs], shards)
        m2 = state.m[gi].reshape(shards, Lpad)
        v2 = state.v[gi].reshape(shards, Lpad)
        for i, a in _unflatten_group(m2, p_leaves, idxs, shards).items():
            m_out[i] = a
        for i, a in _unflatten_group(v2, p_leaves, idxs, shards).items():
            v_out[i] = a
    return (jax.tree_util.tree_unflatten(treedef, m_out),
            jax.tree_util.tree_unflatten(treedef, v_out))


def _hash_u32(x: jax.Array) -> jax.Array:
    """murmur3 finalizer: a stateless counter hash good enough for the
    rounding noise (16 low bits used), identical on TPU and interpret."""
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> jnp.uint32(16))


# --------------------------------------------------------------------- #
# Kernels
# --------------------------------------------------------------------- #
def _sqnorm_kernel(g_ref, out_ref):
    """Per-chunk squared-norm partial: replaces the separate full-tree
    ``global_norm`` read (and, via isfinite(norm), the full-tree
    ``tree_has_inf_or_nan`` read) of the two-pass path. Pad regions are
    zero by construction and contribute nothing."""
    g = g_ref[...].astype(jnp.float32)
    s = jnp.sum(g * g)
    out_ref[...] = jnp.broadcast_to(s, out_ref.shape)


def _fused_adam_kernel(scal_ref, seed_ref, g_ref, p_ref, m_ref, v_ref,
                       *out_refs, b1: float, b2: float, eps: float,
                       wd: float, coupled: bool, use_inv: bool,
                       use_coeff: bool, one_pass: bool, sr: bool,
                       cast: bool, out_dtype, cast_dtype):
    """One chunk of the fused apply.

    scal_ref (SMEM, f32 [1,8]): [neg_lr, bias_corr1, bias_corr2, coeff,
    inv_scale, skip, 0, 0]; seed_ref (SMEM, int32 [1,2]): [sr seed,
    global base element index]. Math follows optax's association order
    exactly (bit parity on the deterministic path); the fp16 unscale and
    the clip multiply are SEPARATE multiplies, preserving the historical
    ``(g*inv)*coeff`` association of the two-pass engine path."""
    p_out = out_refs[0]
    m_out, v_out = out_refs[1], out_refs[2]
    cast_out = out_refs[3] if cast else None
    g = g_ref[...].astype(jnp.float32)
    if use_inv:
        g = g * scal_ref[0, 4]
    if use_coeff:
        g = g * scal_ref[0, 3]
    p32 = p_ref[...].astype(jnp.float32)
    if coupled and wd:
        # Classic (coupled L2) Adam: decay folded into the gradient
        # BEFORE the moment update (optax.add_decayed_weights first in
        # the chain; reference FusedAdam adam_w_mode=False).
        g = g + wd * p32
    m = (1 - b1) * g + b1 * m_ref[...]
    v = (1 - b2) * (g * g) + b2 * v_ref[...]
    u = (m / scal_ref[0, 1]) / (jnp.sqrt(v / scal_ref[0, 2]) + eps)
    if (not coupled) and wd:
        u = u + wd * p32
    new_p = p32 + u * scal_ref[0, 0]
    if one_pass:
        # Overflow-skip folded into the pass: the old params/moments are
        # already in VMEM, so holding the step costs a register select
        # instead of the engine's post-apply full-tree jnp.where pass.
        keep_old = scal_ref[0, 5] > 0.0
        new_p = jnp.where(keep_old, p32, new_p)
        m = jnp.where(keep_old, m_ref[...], m)
        v = jnp.where(keep_old, v_ref[...], v)
    m_out[...] = m
    v_out[...] = v
    if cast:
        cast_out[...] = new_p.astype(cast_dtype)
    if sr:
        # In-kernel unbiased stochastic rounding to bf16 (the master-free
        # mode): add uniform 16-bit noise to the f32 mantissa tail, then
        # truncate — E[round(x)] == x (see ops/stochastic_rounding.py).
        # Noise comes from a counter hash of the GLOBAL element index
        # (seed_ref[0,1] carries the shard's base offset), so it costs
        # zero HBM traffic and is reproducible per (seed, index).
        R, W = new_p.shape
        rows = lax.broadcasted_iota(jnp.uint32, (R, W), 0)
        cols = lax.broadcasted_iota(jnp.uint32, (R, W), 1)
        idx = seed_ref[0, 1].astype(jnp.uint32) + \
            (pl.program_id(0).astype(jnp.uint32) * jnp.uint32(R) + rows) \
            * jnp.uint32(W) + cols
        noise = _hash_u32(idx ^ seed_ref[0, 0].astype(jnp.uint32)) \
            & jnp.uint32(0xFFFF)
        bits = lax.bitcast_convert_type(new_p, jnp.uint32)
        rounded = (bits + noise) & jnp.uint32(0xFFFF0000)
        out = lax.bitcast_convert_type(rounded, jnp.float32) \
            .astype(jnp.bfloat16)
        # inf/nan must stay put (the carry could walk an inf into nan
        # space); overflow handling belongs to the loss-scale machinery.
        p_out[...] = jnp.where(jnp.isfinite(new_p), out,
                               new_p.astype(jnp.bfloat16))
    else:
        p_out[...] = new_p.astype(out_dtype)


def _smem_spec(shape):
    if pltpu is not None and jax.default_backend() == "tpu":
        return pl.BlockSpec(memory_space=pltpu.SMEM)
    return pl.BlockSpec(shape, lambda i: (0, 0))


def _chunk_spec(rb: int):
    if pltpu is not None and jax.default_backend() == "tpu":
        return pl.BlockSpec((rb, _W), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    return pl.BlockSpec((rb, _W), lambda i: (i, 0))


def _block_rows(rows: int, kernel: str = None, runner=None) -> int:
    """Largest power-of-two row count <= _R dividing ``rows`` (rows is
    always a multiple of 8 by the _ROW_QUANTUM padding).  With ``kernel``
    set, the pick routes through ``ops.autotune`` — candidates are the
    dividing powers of two up to _R, heuristic the largest (today's
    choice bit-for-bit under DS_AUTOTUNE=0 / on CPU)."""
    rb = _R
    while rb > 8 and rows % rb:
        rb //= 2
    assert rows % rb == 0, (rows, rb)
    if kernel is not None:
        cands = autotune.pow2_candidates(8, _R, lambda c: rows % c == 0)
        measure = autotune.measure_from_runner(runner) \
            if (runner is not None and autotune.search_allowed()) else None
        rb = autotune.resolve(kernel, (rows, _W), "float32", rb, cands,
                              measure)
        assert rows % rb == 0, (rows, rb)
    return rb


def _run_sqnorm(gflat: jax.Array, _rb: int = None) -> jax.Array:
    """Squared norm of one flat group buffer via per-chunk partials."""
    rows = gflat.size // _W

    def runner(rb_):
        return _run_sqnorm(jnp.zeros((rows * _W,), gflat.dtype), _rb=rb_)

    rb = _rb or _block_rows(rows, kernel="fused_update_sqnorm",
                            runner=runner)
    grid = rows // rb
    out = pl.pallas_call(
        _sqnorm_kernel,
        grid=(grid,),
        in_specs=[_chunk_spec(rb)],
        out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((grid, 128), jnp.float32),
        interpret=_interpret(),
    )(gflat.reshape(rows, _W))
    return jnp.sum(out[:, 0])


def _run_group(gflat, pflat, m, v, scalars, seed, *, b1, b2, eps, wd,
               coupled, use_inv, use_coeff, one_pass, sr, cast,
               out_dtype, cast_dtype, _rb: int = None):
    """Run the fused kernel over one flat group buffer (local shard when
    shard-mapped). Returns (p_new, m_new, v_new, cast_new_or_None)."""
    rows = gflat.size // _W

    def runner(rb_):
        return _run_group(
            jnp.zeros(gflat.shape, gflat.dtype),
            jnp.zeros(pflat.shape, pflat.dtype),
            jnp.zeros(m.shape, m.dtype), jnp.zeros(v.shape, v.dtype),
            jnp.zeros(scalars.shape, scalars.dtype),
            jnp.zeros(seed.shape, seed.dtype),
            b1=b1, b2=b2, eps=eps, wd=wd, coupled=coupled,
            use_inv=use_inv, use_coeff=use_coeff, one_pass=one_pass,
            sr=sr, cast=cast, out_dtype=out_dtype,
            cast_dtype=cast_dtype, _rb=rb_)

    rb = _rb or _block_rows(rows, kernel="fused_update_apply",
                            runner=runner)
    shape2 = (rows, _W)
    kernel = functools.partial(
        _fused_adam_kernel, b1=b1, b2=b2, eps=eps, wd=wd, coupled=coupled,
        use_inv=use_inv, use_coeff=use_coeff, one_pass=one_pass, sr=sr,
        cast=cast, out_dtype=out_dtype, cast_dtype=cast_dtype)
    out_specs = [_chunk_spec(rb)] * (4 if cast else 3)
    out_shape = [
        jax.ShapeDtypeStruct(shape2, out_dtype),
        jax.ShapeDtypeStruct(shape2, jnp.float32),
        jax.ShapeDtypeStruct(shape2, jnp.float32),
    ]
    if cast:
        out_shape.append(jax.ShapeDtypeStruct(shape2, cast_dtype))
    outs = pl.pallas_call(
        kernel,
        grid=(rows // rb,),
        in_specs=[_smem_spec((1, 8)), _smem_spec((1, 2)),
                  _chunk_spec(rb), _chunk_spec(rb), _chunk_spec(rb),
                  _chunk_spec(rb)],
        out_specs=out_specs,
        out_shape=out_shape,
        # In-place update: p/m/v inputs alias the outputs (same
        # shape+dtype when the param dtype matches; m/v always), so the
        # kernel never holds two copies of the moments in HBM.
        input_output_aliases=(
            {3: 0, 4: 1, 5: 2} if pflat.dtype == out_dtype
            else {4: 1, 5: 2}),
        interpret=_interpret(),
    )(scalars, seed, gflat.reshape(shape2), pflat.reshape(shape2),
      m.reshape(shape2), v.reshape(shape2))
    p_new, m_new, v_new = outs[0], outs[1], outs[2]
    cast_new = outs[3] if cast else None
    return (p_new.reshape(-1), m_new.reshape(-1), v_new.reshape(-1),
            None if cast_new is None else cast_new.reshape(-1))


def apply_hbm_bytes(params: Any, *, one_pass: bool = True,
                    cast_dtype=None, fp16: bool = False,
                    clip: bool = True) -> Dict[str, int]:
    """Analytic HBM bytes one optimizer step's APPLY phase moves, per
    replica (monitor/cost_model.py prices the apply path with this; the
    roofline record carries both modes).

    Honest accounting — only passes the historical two-pass engine
    REALLY paid are priced, and the one-pass side pays for what it
    really runs:

    - Both modes share the apply kernel's read g(f32)+p+m+v, write
      p+m+v (+ the compute-dtype cast-copy write).
    - When a norm is needed (``clip`` or ``fp16``), BOTH modes re-read
      the grads once more: the two-pass path as the separate
      ``global_norm`` pass, the one-pass path as the ``_run_sqnorm``
      kernel — a wash in bytes (the one-pass win there is launches and
      the scalar plumbing, not HBM).
    - fp16 only: the two-pass path's unscale (read+write g), the
      ``tree_has_inf_or_nan`` re-read of g, and the post-apply overflow
      select (read old p+m+v, read new p+m+v, write the selection) are
      real traced passes.  For non-fp16 runs ``overflow`` was a
      compile-time constant and XLA folded the select to nothing — no
      saving is claimed there.
    - cast_dtype only: the standalone cast pass re-READS the updated
      params (the cast write itself exists in both modes).

    Consequence: the drop is ~2.5x for fp16 configs, ~1.1x for
    fp32-master + cast-cache bf16 configs, and ~1.0x for master-free
    bf16 (where the one-pass path's value is fewer launches, not fewer
    bytes) — stated plainly in docs/tutorials/kernels.md.
    """
    leaves = [l for l in jax.tree_util.tree_leaves(params)
              if hasattr(l, "dtype") and
              jnp.issubdtype(l.dtype, jnp.floating)]
    n = sum(int(l.size) for l in leaves)
    p_bytes = sum(int(l.size) * jnp.dtype(l.dtype).itemsize
                  for l in leaves)
    g_bytes = 4 * n                       # grads flatten in f32
    mv_bytes = 2 * 4 * n                  # f32 moments
    cast_bytes = (n * jnp.dtype(cast_dtype).itemsize) if cast_dtype else 0
    kernel = g_bytes + p_bytes + mv_bytes + p_bytes + mv_bytes + cast_bytes
    need_norm = bool(clip) or fp16
    norm_read = g_bytes if need_norm else 0
    one = kernel + norm_read
    two = kernel + norm_read
    if fp16:
        two += 2 * g_bytes                # unscale: read + write g
        two += g_bytes                    # tree_has_inf_or_nan re-read
        # overflow select (REAL only under fp16): read old + new p/m/v,
        # write the selected state
        two += 3 * (p_bytes + mv_bytes)
    if cast_dtype:
        two += p_bytes                    # cast pass re-reads new params
    out = {"one_pass": one, "two_pass": two}
    out["active"] = one if one_pass else two
    out["ratio_two_over_one"] = round(two / max(1, one), 3)
    return out


def fused_adam(learning_rate: ScheduleOrFloat, b1: float = 0.9,
               b2: float = 0.999, eps: float = 1e-8,
               weight_decay: float = 0.0, adam_w_mode: bool = True,
               multi_tensor: bool = True, mesh=None,
               shard_axis: Optional[str] = None
               ) -> "FusedGradientTransformation":
    """Build the fused-apply transformation.

    ``adam_w_mode=True`` matches ``optax.adamw`` (decoupled decay);
    ``False`` matches the engine's coupled-L2 chain (decay folded into
    the gradient before the moments). ``multi_tensor=False`` runs one
    kernel launch per leaf instead of chunked fused buffers — kept for
    the ablation ladder (``ablate_fused_update.py``), not production.

    ``mesh`` + ``shard_axis`` (engine-provided under ZeRO stage >= 1 on
    a pure-dp mesh) run the kernels under ``shard_map`` over the dp
    axis: every buffer enters as its LOCAL virtual-shard rows, the
    moments are never gathered, and the norm partials ``psum`` into the
    global norm. Without them the kernels run on the full buffers (dp=1,
    or bare transform use).

    Returned object is optax-compatible (``init``/``update``) and
    carries two fused entry points: ``fused_apply`` (PR-1 API: caller
    resolves clip/overflow) and ``fused_step`` (one-pass: norm, clip,
    fp16 unscale, overflow vote+skip, cast-cache refresh all inside the
    single HBM pass — see module docstring).
    """
    sched = learning_rate if callable(learning_rate) else None
    base_lr = None if sched is not None else float(learning_rate)
    dp = int(mesh.shape[shard_axis]) if (mesh is not None and
                                         shard_axis is not None) else 1
    shards = virtual_shards(dp)
    use_shard_map = dp > 1 and shards % dp == 0

    def _row_sharding():
        if mesh is None or shard_axis is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(mesh, P(shard_axis, None))

    def _leaves(params):
        return jax.tree_util.tree_flatten(params)

    def init_fn(params):
        leaves, _ = _leaves(params)
        groups = _float_groups(leaves)
        bufs = []
        for _, idxs in groups:
            sizes = [int(leaves[i].size) for i in idxs]
            if multi_tensor:
                Lpad = _group_row_len(sizes, shards)
                bufs.append(jnp.zeros((shards * Lpad,), jnp.float32))
            else:
                # per-leaf mode: one moment buffer per leaf, each padded
                # to its own whole-row quantum (tiny leaves burn a full
                # quantum — the launch-amortization problem multi-tensor
                # mode fixes).
                bufs.append(tuple(
                    jnp.zeros((shards * _group_row_len([n], shards),),
                              jnp.float32) for n in sizes))
        return FusedAdamState(count=jnp.zeros([], jnp.int32),
                              m=tuple(bufs),
                              v=jax.tree_util.tree_map(jnp.zeros_like,
                                                       tuple(bufs)))

    def _base_scalars(count, inv_scale):
        """The scalar carry every path shares: [neg_lr, bc1, bc2, inv].
        Bit parity: these are the exact expressions optax evaluates
        (python-float ** int32 array -> f32 power; see
        optax.tree_utils.tree_bias_correction)."""
        count_inc = count + 1
        bc1 = (1 - b1 ** count_inc).astype(jnp.float32)
        bc2 = (1 - b2 ** count_inc).astype(jnp.float32)
        lr = sched(count) if sched is not None else base_lr
        neg_lr = jnp.asarray(-1.0, jnp.float32) * jnp.asarray(
            lr, jnp.float32)
        inv = jnp.asarray(1.0, jnp.float32) if inv_scale is None \
            else jnp.asarray(inv_scale, jnp.float32)
        return jnp.stack([neg_lr, bc1, bc2, inv])

    def _group_plan(p_leaves):
        """[(group idx, dtype, leaf idxs, sizes, Lpad)] for the tree."""
        plan = []
        for gi, (dt, idxs) in enumerate(_float_groups(p_leaves)):
            sizes = [int(p_leaves[i].size) for i in idxs]
            plan.append((gi, dt, idxs, sizes,
                         _group_row_len(sizes, shards)))
        return plan

    def _kernel_region(base, seed0, pre_coeff, extra_skip, gbufs, pbufs,
                       ms, vs, *, plan, clip, fp16, use_inv, one_pass,
                       compute_norm, has_pre_coeff, use_extra_skip,
                       sr_groups, cast_groups, cast_dtype, local):
        """Norm + apply kernels over (possibly shard-local) group
        buffers. Runs inside shard_map when ``local``; all inputs are
        then the device's own virtual rows. ``cast_groups`` marks which
        groups emit a compute-dtype cast output (static, so the cast
        tuple's pytree shape is fixed)."""
        axis = shard_axis if local else None
        if compute_norm:
            nsq = jnp.float32(0.0)
            for g in gbufs:
                nsq = nsq + _run_sqnorm(g.reshape(-1))
            if axis is not None:
                nsq = lax.psum(nsq, axis)
            # norm of the UNSCALED grads: ||g*inv|| == inv * ||g||.
            grad_norm = jnp.sqrt(nsq) * base[3]
        else:
            grad_norm = jnp.asarray(-1.0, jnp.float32)
        if fp16:
            # inf/nan anywhere in the grads surfaces as a non-finite
            # sum of squares — the norm read doubles as the overflow
            # vote (reference CheckOverflow semantics, one pass).
            overflow = jnp.logical_not(jnp.isfinite(grad_norm))
        else:
            overflow = jnp.asarray(False)
        if use_extra_skip:
            overflow = jnp.logical_or(overflow, extra_skip)
        if compute_norm and clip and clip > 0:
            # Same expression as runtime.utils.clip_coefficient (kept
            # textually identical so the paths cannot diverge).
            coeff = jnp.minimum(1.0, clip / (grad_norm + 1e-6))
            use_coeff = True
        elif has_pre_coeff:
            coeff = pre_coeff.astype(jnp.float32)
            use_coeff = True
        else:
            coeff = jnp.asarray(1.0, jnp.float32)
            use_coeff = False
        skip = jnp.where(overflow, 1.0, 0.0).astype(jnp.float32)
        # SMEM scalar row: [neg_lr, bc1, bc2, coeff, inv, skip, 0, 0].
        scalars = jnp.stack(
            [base[0], base[1], base[2], coeff, base[3], skip,
             jnp.float32(0.0), jnp.float32(0.0)])[None]
        new_p, new_m, new_v, new_cast = [], [], [], []
        for k, (gi, dt, idxs, sizes, Lpad) in enumerate(plan):
            sr = sr_groups[k]
            nloc = int(gbufs[k].size)
            if axis is not None:
                off = lax.axis_index(axis).astype(jnp.int32) * \
                    jnp.int32(nloc)
            else:
                off = jnp.int32(0)
            seed = jnp.stack([seed0 + jnp.int32(gi), off])[None]
            pf, mn, vn, cf = _run_group(
                gbufs[k].reshape(-1), pbufs[k].reshape(-1),
                ms[k].reshape(-1), vs[k].reshape(-1), scalars, seed,
                b1=b1, b2=b2, eps=eps, wd=weight_decay,
                coupled=not adam_w_mode, use_inv=use_inv,
                use_coeff=use_coeff, one_pass=one_pass, sr=sr,
                cast=cast_groups[k], out_dtype=dt, cast_dtype=cast_dtype)
            shape = gbufs[k].shape
            new_p.append(pf.reshape(shape))
            new_m.append(mn.reshape(shape))
            new_v.append(vn.reshape(shape))
            if cast_groups[k]:
                new_cast.append(cf.reshape(shape))
        return (tuple(new_p), tuple(new_m), tuple(new_v),
                tuple(new_cast), grad_norm, overflow)

    def _apply_impl(grads, state, params, *, pre_coeff=None,
                    inv_scale=None, clip=0.0, fp16=False,
                    compute_norm=False, extra_skip=None, one_pass=False,
                    sr_key=None, cast_dtype=None):
        if params is None:
            raise ValueError("fused_adam requires params")
        if not multi_tensor:
            return _apply_per_leaf(grads, state, params,
                                   pre_coeff=pre_coeff, sr_key=sr_key)
        p_leaves, treedef = _leaves(params)
        g_leaves = treedef.flatten_up_to(grads)
        plan = _group_plan(p_leaves)
        base = _base_scalars(state.count, inv_scale)
        seed0 = jax.random.bits(sr_key, (), jnp.uint32).astype(jnp.int32) \
            if sr_key is not None else jnp.zeros((), jnp.int32)
        constrain = _row_sharding() if use_shard_map else None
        gbufs, pbufs, ms, vs = [], [], [], []
        sr_groups, cast_groups = [], []
        for gi, dt, idxs, sizes, Lpad in plan:
            # Grads flatten in f32, NOT the param dtype: master-free
            # engines hand in f32-accumulated grads over bf16 params,
            # and truncating them here would defeat the kernel's
            # f32-second-moment guarantee before it ever reads them.
            gbufs.append(_flatten_group(g_leaves, idxs, jnp.float32,
                                        shards, Lpad, constrain))
            pbufs.append(_flatten_group(p_leaves, idxs, dt, shards,
                                        Lpad, constrain))
            m2 = state.m[gi].reshape(shards, Lpad)
            v2 = state.v[gi].reshape(shards, Lpad)
            if constrain is not None:
                m2 = lax.with_sharding_constraint(m2, constrain)
                v2 = lax.with_sharding_constraint(v2, constrain)
            ms.append(m2)
            vs.append(v2)
            sr = sr_key is not None and dt == jnp.dtype(jnp.bfloat16)
            sr_groups.append(sr)
            cast_groups.append(cast_dtype is not None and not sr and
                               jnp.dtype(cast_dtype) != dt)
        pre_coeff_arr = jnp.asarray(
            1.0 if pre_coeff is None else pre_coeff, jnp.float32)
        extra_skip_arr = jnp.asarray(
            False if extra_skip is None else extra_skip)
        region = functools.partial(
            _kernel_region, plan=plan, clip=clip, fp16=fp16,
            use_inv=inv_scale is not None, one_pass=one_pass,
            compute_norm=compute_norm,
            has_pre_coeff=pre_coeff is not None,
            use_extra_skip=extra_skip is not None,
            sr_groups=tuple(sr_groups), cast_groups=tuple(cast_groups),
            cast_dtype=cast_dtype, local=use_shard_map)
        if use_shard_map:
            from jax.sharding import PartitionSpec as P
            from ..parallel.comm import shard_map
            row = P(shard_axis, None)
            nbuf = len(plan)
            ncast = sum(1 for c in cast_groups if c)
            fn = shard_map(
                region, mesh=mesh,
                in_specs=(P(), P(), P(), P(),
                          (row,) * nbuf, (row,) * nbuf,
                          (row,) * nbuf, (row,) * nbuf),
                out_specs=((row,) * nbuf, (row,) * nbuf, (row,) * nbuf,
                           (row,) * ncast, P(), P()),
                axis_names={shard_axis}, check_vma=False)
            out = fn(base, seed0, pre_coeff_arr, extra_skip_arr,
                     tuple(gbufs), tuple(pbufs), tuple(ms), tuple(vs))
        else:
            out = region(base, seed0, pre_coeff_arr, extra_skip_arr,
                         tuple(gbufs), tuple(pbufs), tuple(ms),
                         tuple(vs))
        new_pb, new_mb, new_vb, new_cb, grad_norm, overflow = out

        new_leaves = list(p_leaves)
        cast_leaves = list(p_leaves) if cast_dtype is not None else None
        ci = 0
        for k, (gi, dt, idxs, sizes, Lpad) in enumerate(plan):
            for i, a in _unflatten_group(new_pb[k], p_leaves, idxs,
                                         shards).items():
                new_leaves[i] = a
            if cast_leaves is not None:
                if cast_groups[k]:
                    src = new_cb[ci]
                    ci += 1
                    for i, a in _unflatten_group(src, p_leaves, idxs,
                                                 shards).items():
                        cast_leaves[i] = a
                else:
                    # Same dtype (or SR bf16 write): the param output IS
                    # the compute-dtype value — alias, don't copy.
                    for i, a in _unflatten_group(new_pb[k], p_leaves,
                                                 idxs, shards).items():
                        cast_leaves[i] = a
        if cast_leaves is not None:
            # Non-float leaves mirror _cast_floats: passed through as-is.
            cast_params = jax.tree_util.tree_unflatten(
                treedef, cast_leaves)
        else:
            cast_params = None
        new_params = jax.tree_util.tree_unflatten(treedef, new_leaves)
        if one_pass:
            count_inc = state.count + \
                jnp.where(overflow, 0, 1).astype(jnp.int32)
        else:
            count_inc = state.count + 1
        new_state = FusedAdamState(
            count=count_inc,
            m=tuple(b.reshape(-1) for b in new_mb),
            v=tuple(b.reshape(-1) for b in new_vb))
        return new_params, new_state, cast_params, grad_norm, overflow

    def _apply_per_leaf(grads, state, params, *, pre_coeff=None,
                       sr_key=None):
        """Ablation mode: one kernel launch per leaf."""
        p_leaves, treedef = _leaves(params)
        g_leaves = treedef.flatten_up_to(grads)
        base = _base_scalars(state.count, None)
        seed0 = jax.random.bits(sr_key, (), jnp.uint32).astype(jnp.int32) \
            if sr_key is not None else jnp.zeros((), jnp.int32)
        coeff = jnp.asarray(1.0 if pre_coeff is None else pre_coeff,
                            jnp.float32)
        scalars = jnp.stack(
            [base[0], base[1], base[2], coeff, base[3],
             jnp.float32(0.0), jnp.float32(0.0), jnp.float32(0.0)])[None]
        new_leaves = list(p_leaves)
        new_m, new_v = [], []
        for gi, (dt, idxs) in enumerate(_float_groups(p_leaves)):
            sr = sr_key is not None and dt == jnp.dtype(jnp.bfloat16)
            ms, vs = [], []
            for j, i in enumerate(idxs):
                n = int(p_leaves[i].size)
                Lpad = _group_row_len([n], shards)
                seed = jnp.stack([seed0 + jnp.int32(gi),
                                  jnp.int32(0)])[None]
                gf = _flatten_group(g_leaves, [i], jnp.float32, shards,
                                    Lpad)
                pf = _flatten_group(p_leaves, [i], dt, shards, Lpad)
                pn, mn, vn, _ = _run_group(
                    gf.reshape(-1), pf.reshape(-1), state.m[gi][j],
                    state.v[gi][j], scalars, seed, b1=b1, b2=b2,
                    eps=eps, wd=weight_decay, coupled=not adam_w_mode,
                    use_inv=False, use_coeff=pre_coeff is not None,
                    one_pass=False, sr=sr, cast=False, out_dtype=dt,
                    cast_dtype=None)
                new_leaves[i] = _unflatten_group(
                    pn.reshape(shards, Lpad), p_leaves, [i], shards)[i]
                ms.append(mn)
                vs.append(vn)
            new_m.append(tuple(ms))
            new_v.append(tuple(vs))
        new_params = jax.tree_util.tree_unflatten(treedef, new_leaves)
        return new_params, FusedAdamState(count=state.count + 1,
                                          m=tuple(new_m),
                                          v=tuple(new_v)), None, \
            jnp.asarray(-1.0, jnp.float32), jnp.asarray(False)

    def _apply(grads, state, params, clip_coeff=None, sr_key=None):
        """PR-1 two-pass API: the caller resolved clip/overflow."""
        new_params, new_state, _, _, _ = _apply_impl(
            grads, state, params, pre_coeff=clip_coeff, sr_key=sr_key)
        return new_params, new_state

    def _step(grads, state, params, *, clip=0.0, inv_scale=None,
              fp16=False, compute_norm=True, extra_skip=None,
              sr_key=None, cast_dtype=None) -> FusedStepOut:
        """One-pass clipped update (module docstring): grads may still
        carry the fp16 loss scale (``inv_scale`` unscales in-kernel);
        norm/overflow/clip/skip/cast all ride the single HBM pass."""
        new_params, new_state, cast_params, grad_norm, overflow = \
            _apply_impl(grads, state, params, inv_scale=inv_scale,
                        clip=clip, fp16=fp16, compute_norm=compute_norm,
                        extra_skip=extra_skip, one_pass=True,
                        sr_key=sr_key, cast_dtype=cast_dtype)
        return FusedStepOut(new_params, new_state, cast_params,
                            grad_norm, overflow)

    def update_fn(updates, state, params=None):
        """optax-compatible wrapper: returns delta-style updates so generic
        callers (``optax.apply_updates``) keep working. The engine's train
        steps call ``fused_step``/``fused_apply`` instead for the true
        single-pass write."""
        new_params, new_state = _apply(updates, state, params)
        deltas = jax.tree_util.tree_map(
            lambda np_, p: (np_.astype(jnp.float32) -
                            p.astype(jnp.float32)).astype(np_.dtype)
            if hasattr(p, "dtype") and jnp.issubdtype(p.dtype, jnp.floating)
            else jnp.zeros_like(p) if hasattr(p, "dtype") else p,
            new_params, params)
        return deltas, new_state

    # Per-leaf ablation mode has no one-pass story (it ignores the
    # norm/clip/overflow/cast machinery) — expose fused_step=None so the
    # engine falls back to the two-pass apply instead of silently
    # dropping clipping.
    return FusedGradientTransformation(init=init_fn, update=update_fn,
                                       fused_apply=_apply,
                                       fused_step=_step if multi_tensor
                                       else None)


class FusedGradientTransformation(NamedTuple):
    """optax.GradientTransformation duck-type + the fused entry points."""
    init: Callable[[Any], FusedAdamState]
    update: Callable[..., Tuple[Any, FusedAdamState]]
    fused_apply: Callable[..., Tuple[Any, FusedAdamState]]
    fused_step: Callable[..., FusedStepOut]
