"""Fused Pallas multi-tensor optimizer apply.

Parity target: the reference's multi-tensor fused Adam
(``csrc/adam/multi_tensor_adam.cu:123``) — ONE kernel pass per chunk that
reads grad+param+m+v and writes param+m+v, with the chunked multi-tensor
front end amortizing thousands of small leaves into a handful of launches.

Why this exists on TPU at all (given XLA already fuses elementwise ops):
XLA fuses *within* a leaf, but the optax apply is still one fusion per
param leaf — ~450 kernel launches for an unrolled GPT-2, each re-paying
launch + pipeline-warmup overhead — and the engine's clip multiply,
unscale, bias correction and stochastic-rounding write are separate
HBM passes when XLA's fusion heuristics split them. The Pallas kernel
makes the single-pass property structural instead of heuristic:

    read  grad, param, m, v          (one chunk per grid step, VMEM)
    g  = grad * clip_coeff           (global-clip folded in, no clip pass)
    m' = (1-b1)*g + b1*m             (f32, even for bf16 grads — the
    v' = (1-b2)*g^2 + b2*v            second moment is never squared in
                                      bf16; reference fp32 accumulators)
    u  = -lr * (m'/bc1 / (sqrt(v'/bc2) + eps) + wd*p)
    write param+u (optionally via unbiased stochastic rounding to bf16
    — the master-free mode of ops/stochastic_rounding.py, done in-kernel
    from a hash-counter PRNG so no noise tensor ever touches HBM), m', v'

The multi-tensor front end flattens the pytree's float leaves into
contiguous same-dtype chunk buffers (the moral equivalent of the CUDA
chunked apply); the optimizer state stores the moments *already fused*
(one f32 buffer per dtype group), so only grads/params pay the
flatten/unflatten passes.

The deterministic path is bit-exact with ``optax.adamw`` / the engine's
coupled-Adam chain: every multiply-add is written in optax's association
order (see ``tests/test_fused_update.py``).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU backend bits are importable everywhere; interpret=True runs on CPU
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

ScheduleOrFloat = Union[Callable, float]

# Chunk geometry: W lanes wide (128-multiple), R sublane rows per grid
# step. One (R, W) f32 block is 512 KiB; with 4 inputs + 3 outputs double
# buffered that is ~7 MiB of VMEM — inside the ~16 MiB/core budget.
_W = 1024
_R = 128
_CHUNK = _R * _W   # elements per grid step; buffers pad to a multiple


class FusedAdamState(NamedTuple):
    """Fused optimizer state: one f32 moment buffer per dtype group.

    The moments live *pre-flattened* — only grads and params pay the
    per-step flatten/unflatten. Buffers are padded to a _CHUNK multiple,
    which keeps them divisible by any practical dp size so ZeRO
    shardings (zero/partition.py) split them on axis 0 and checkpoint
    shards stay elastic across dp resizes.
    """
    count: jax.Array                 # int32 scalar, number of updates
    m: Tuple[jax.Array, ...]
    v: Tuple[jax.Array, ...]


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _float_groups(leaves):
    """Deterministic dtype-grouping of float leaves: [(dtype, [leaf idx])],
    sorted by dtype name. Non-float leaves bypass the kernel entirely."""
    groups = {}
    for i, leaf in enumerate(leaves):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            groups.setdefault(jnp.dtype(leaf.dtype), []).append(i)
    return sorted(groups.items(), key=lambda kv: kv[0].name)


def _pad_to_chunk(n: int) -> int:
    return max(_CHUNK, ((n + _CHUNK - 1) // _CHUNK) * _CHUNK)


def _flatten_group(leaves, idxs, dtype, npad: int) -> jax.Array:
    flats = [leaves[i].reshape(-1).astype(dtype) for i in idxs]
    n = sum(f.size for f in flats)
    if npad > n:
        flats.append(jnp.zeros((npad - n,), dtype))
    return jnp.concatenate(flats) if len(flats) > 1 else flats[0]


def _hash_u32(x: jax.Array) -> jax.Array:
    """murmur3 finalizer: a stateless counter hash good enough for the
    rounding noise (16 low bits used), identical on TPU and interpret."""
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> jnp.uint32(16))


def _fused_adam_kernel(scal_ref, seed_ref, g_ref, p_ref, m_ref, v_ref,
                       p_out, m_out, v_out, *, b1: float, b2: float,
                       eps: float, wd: float, coupled: bool,
                       scale_grads: bool, sr: bool, out_dtype):
    """One chunk of the fused apply. scal_ref (SMEM, f32 [1,4]):
    [neg_lr, bias_corr1, bias_corr2, grad_scale]; seed_ref (SMEM, int32
    [1,1]): stochastic-rounding seed. Math follows optax's association
    order exactly (bit parity on the deterministic path)."""
    g = g_ref[...].astype(jnp.float32)
    if scale_grads:
        g = g * scal_ref[0, 3]
    p32 = p_ref[...].astype(jnp.float32)
    if coupled and wd:
        # Classic (coupled L2) Adam: decay folded into the gradient
        # BEFORE the moment update (optax.add_decayed_weights first in
        # the chain; reference FusedAdam adam_w_mode=False).
        g = g + wd * p32
    m = (1 - b1) * g + b1 * m_ref[...]
    v = (1 - b2) * (g * g) + b2 * v_ref[...]
    u = (m / scal_ref[0, 1]) / (jnp.sqrt(v / scal_ref[0, 2]) + eps)
    if (not coupled) and wd:
        u = u + wd * p32
    new_p = p32 + u * scal_ref[0, 0]
    m_out[...] = m
    v_out[...] = v
    if sr:
        # In-kernel unbiased stochastic rounding to bf16 (the master-free
        # mode): add uniform 16-bit noise to the f32 mantissa tail, then
        # truncate — E[round(x)] == x (see ops/stochastic_rounding.py).
        # Noise comes from a counter hash of the global element index, so
        # it costs zero HBM traffic and is reproducible per (seed, index).
        R, W = new_p.shape
        rows = lax.broadcasted_iota(jnp.uint32, (R, W), 0)
        cols = lax.broadcasted_iota(jnp.uint32, (R, W), 1)
        idx = (pl.program_id(0).astype(jnp.uint32) * jnp.uint32(R) + rows) \
            * jnp.uint32(W) + cols
        noise = _hash_u32(idx ^ seed_ref[0, 0].astype(jnp.uint32)) \
            & jnp.uint32(0xFFFF)
        bits = lax.bitcast_convert_type(new_p, jnp.uint32)
        rounded = (bits + noise) & jnp.uint32(0xFFFF0000)
        out = lax.bitcast_convert_type(rounded, jnp.float32) \
            .astype(jnp.bfloat16)
        # inf/nan must stay put (the carry could walk an inf into nan
        # space); overflow handling belongs to the loss-scale machinery.
        p_out[...] = jnp.where(jnp.isfinite(new_p), out,
                               new_p.astype(jnp.bfloat16))
    else:
        p_out[...] = new_p.astype(out_dtype)


def _smem_spec(shape):
    if pltpu is not None and jax.default_backend() == "tpu":
        return pl.BlockSpec(memory_space=pltpu.SMEM)
    return pl.BlockSpec(shape, lambda i: (0, 0))


def _chunk_spec():
    if pltpu is not None and jax.default_backend() == "tpu":
        return pl.BlockSpec((_R, _W), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    return pl.BlockSpec((_R, _W), lambda i: (i, 0))


def _run_group(gflat, pflat, m, v, scalars, seed, *, b1, b2, eps, wd,
               coupled, scale_grads, sr, out_dtype):
    """Run the kernel over one fused dtype-group buffer [Npad]."""
    npad = gflat.size
    rows = npad // _W
    shape2 = (rows, _W)
    kernel = functools.partial(
        _fused_adam_kernel, b1=b1, b2=b2, eps=eps, wd=wd, coupled=coupled,
        scale_grads=scale_grads, sr=sr, out_dtype=out_dtype)
    p_new, m_new, v_new = pl.pallas_call(
        kernel,
        grid=(rows // _R,),
        in_specs=[_smem_spec((1, 4)), _smem_spec((1, 1)),
                  _chunk_spec(), _chunk_spec(), _chunk_spec(),
                  _chunk_spec()],
        out_specs=[_chunk_spec(), _chunk_spec(), _chunk_spec()],
        out_shape=[
            jax.ShapeDtypeStruct(shape2, out_dtype),
            jax.ShapeDtypeStruct(shape2, jnp.float32),
            jax.ShapeDtypeStruct(shape2, jnp.float32),
        ],
        # In-place update: p/m/v inputs alias the outputs (same
        # shape+dtype when the param dtype matches; m/v always), so the
        # kernel never holds two copies of the moments in HBM.
        input_output_aliases=(
            {3: 0, 4: 1, 5: 2} if pflat.dtype == out_dtype
            else {4: 1, 5: 2}),
        interpret=_interpret(),
    )(scalars, seed, gflat.reshape(shape2), pflat.reshape(shape2),
      m.reshape(shape2), v.reshape(shape2))
    return p_new.reshape(-1), m_new.reshape(-1), v_new.reshape(-1)


def fused_adam(learning_rate: ScheduleOrFloat, b1: float = 0.9,
               b2: float = 0.999, eps: float = 1e-8,
               weight_decay: float = 0.0, adam_w_mode: bool = True,
               multi_tensor: bool = True) -> "FusedGradientTransformation":
    """Build the fused-apply transformation.

    ``adam_w_mode=True`` matches ``optax.adamw`` (decoupled decay);
    ``False`` matches the engine's coupled-L2 chain (decay folded into
    the gradient before the moments). ``multi_tensor=False`` runs one
    kernel launch per leaf instead of chunked fused buffers — kept for
    the ablation ladder (``ablate_fused_update.py``), not production.

    Returned object is optax-compatible (``init``/``update``) and carries
    the single-pass entry point ``fused_apply(grads, state, params,
    clip_coeff=None, sr_key=None) -> (new_params, new_state)`` that the
    engine's train steps call directly: it folds the global-clip
    coefficient into the kernel (no separate clip pass) and, given
    ``sr_key``, rounds bf16 params stochastically in-kernel.
    """
    sched = learning_rate if callable(learning_rate) else None
    base_lr = None if sched is not None else float(learning_rate)

    def _leaves(params):
        return jax.tree_util.tree_flatten(params)

    def init_fn(params):
        leaves, _ = _leaves(params)
        groups = _float_groups(leaves)
        bufs = []
        for _, idxs in groups:
            n = sum(int(leaves[i].size) for i in idxs)
            npad = _pad_to_chunk(n) if multi_tensor else None
            if multi_tensor:
                bufs.append(jnp.zeros((npad,), jnp.float32))
            else:
                # per-leaf mode: one moment buffer per leaf, each padded
                # to a whole chunk (tiny leaves burn a full chunk — the
                # launch-amortization problem multi-tensor mode fixes).
                bufs.append(tuple(
                    jnp.zeros((_pad_to_chunk(int(leaves[i].size)),),
                              jnp.float32) for i in idxs))
        return FusedAdamState(count=jnp.zeros([], jnp.int32),
                              m=tuple(bufs),
                              v=jax.tree_util.tree_map(jnp.zeros_like,
                                                       tuple(bufs)))

    def _scalars(count, clip_coeff):
        count_inc = count + 1
        # Bit parity: these are the exact expressions optax evaluates
        # (python-float ** int32 array → f32 power; see
        # optax.tree_utils.tree_bias_correction).
        bc1 = (1 - b1 ** count_inc).astype(jnp.float32)
        bc2 = (1 - b2 ** count_inc).astype(jnp.float32)
        lr = sched(count) if sched is not None else base_lr
        neg_lr = jnp.asarray(-1.0, jnp.float32) * jnp.asarray(
            lr, jnp.float32)
        gscale = jnp.asarray(1.0, jnp.float32) if clip_coeff is None \
            else jnp.asarray(clip_coeff, jnp.float32)
        return jnp.stack([neg_lr, bc1, bc2, gscale]).reshape(1, 4), count_inc

    def _apply(grads, state, params, clip_coeff=None, sr_key=None):
        if params is None:
            raise ValueError("fused_adam requires params")
        p_leaves, treedef = _leaves(params)
        g_leaves = treedef.flatten_up_to(grads)
        groups = _float_groups(p_leaves)
        scalars, count_inc = _scalars(state.count, clip_coeff)
        seed0 = jax.random.bits(sr_key, (), jnp.uint32).astype(jnp.int32) \
            if sr_key is not None else jnp.zeros((), jnp.int32)
        new_leaves = list(p_leaves)
        new_m, new_v = [], []
        for gi, (dt, idxs) in enumerate(groups):
            sr = sr_key is not None and dt == jnp.dtype(jnp.bfloat16)
            seed = (seed0 + jnp.int32(gi)).reshape(1, 1)
            run = functools.partial(
                _run_group, scalars=scalars, seed=seed, b1=b1, b2=b2,
                eps=eps, wd=weight_decay, coupled=not adam_w_mode,
                scale_grads=clip_coeff is not None, sr=sr, out_dtype=dt)
            if multi_tensor:
                sizes = [int(p_leaves[i].size) for i in idxs]
                npad = _pad_to_chunk(sum(sizes))
                # Grads flatten in f32, NOT the param dtype: master-free
                # engines hand in f32-accumulated grads over bf16 params,
                # and truncating them here would defeat the kernel's
                # f32-second-moment guarantee before it ever reads them.
                pflat, mn, vn = run(
                    _flatten_group(g_leaves, idxs, jnp.float32, npad),
                    _flatten_group(p_leaves, idxs, dt, npad),
                    state.m[gi], state.v[gi])
                off = 0
                for i, sz in zip(idxs, sizes):
                    new_leaves[i] = \
                        pflat[off:off + sz].reshape(p_leaves[i].shape)
                    off += sz
                new_m.append(mn)
                new_v.append(vn)
            else:
                ms, vs = [], []
                for j, i in enumerate(idxs):
                    sz = int(p_leaves[i].size)
                    npad = _pad_to_chunk(sz)
                    pf, mn, vn = run(
                        _flatten_group(g_leaves, [i], jnp.float32, npad),
                        _flatten_group(p_leaves, [i], dt, npad),
                        state.m[gi][j], state.v[gi][j])
                    new_leaves[i] = pf[:sz].reshape(p_leaves[i].shape)
                    ms.append(mn)
                    vs.append(vn)
                new_m.append(tuple(ms))
                new_v.append(tuple(vs))
        new_params = jax.tree_util.tree_unflatten(treedef, new_leaves)
        return new_params, FusedAdamState(count=count_inc, m=tuple(new_m),
                                          v=tuple(new_v))

    def update_fn(updates, state, params=None):
        """optax-compatible wrapper: returns delta-style updates so generic
        callers (``optax.apply_updates``) keep working. The engine's train
        steps call ``fused_apply`` instead for the true single-pass write."""
        new_params, new_state = _apply(updates, state, params)
        deltas = jax.tree_util.tree_map(
            lambda np_, p: (np_.astype(jnp.float32) -
                            p.astype(jnp.float32)).astype(np_.dtype)
            if hasattr(p, "dtype") and jnp.issubdtype(p.dtype, jnp.floating)
            else jnp.zeros_like(p) if hasattr(p, "dtype") else p,
            new_params, params)
        return deltas, new_state

    return FusedGradientTransformation(init=init_fn, update=update_fn,
                                       fused_apply=_apply)


class FusedGradientTransformation(NamedTuple):
    """optax.GradientTransformation duck-type + the fused entry point."""
    init: Callable[[Any], FusedAdamState]
    update: Callable[..., Tuple[Any, FusedAdamState]]
    fused_apply: Callable[..., Tuple[Any, FusedAdamState]]
