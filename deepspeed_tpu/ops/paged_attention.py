"""Pallas paged-attention: decode attend does O(context) work, not O(pool).

The serving tier's paged KV cache (inference/kv_cache.py) stores K/V in a
block pool ``[G, B, nH, bs, D]`` and the baseline ``paged_attend`` scores
each query against ALL B pool blocks, then routes through the one-hot
block-table selector — per-token attend FLOPs and HBM bytes scale with
pool CAPACITY, not the request's live context. This module is the real
kernel the one-hot contraction stood in for: the host-built block tables
ride in as scalar-prefetch indices (the sparse_flash.py flattened-LUT
pattern) and the grid iterates, per (stream, head block), only that
stream's ceil(context/bs) live blocks — each step a dynamic-slice load of
one ``[bs, D]`` K/V tile straight from the pool, online-softmax
accumulation in fp32 scratch, and an inclusive position mask so the final
partial block contributes exactly its written rows.

Shapes follow the one-hot path exactly: q is ``[G, Q, K, nH, D]`` where K
is the query rows PER STREAM — 1 for plain decode, k+1 for speculative
verify, the chunk width for chunked prefill. All K rows of a stream share
its block table; ``positions[g, q, k]`` is each row's inclusive last
attendable position (per-row causal offsets), so all three serving paths
run the SAME kernel with no specialization.

Static-shape discipline: the grid is ``(G*Q, nH/bh, J)`` with J the block-
table WIDTH (max_blocks_per_slot) — a compile-time constant — and steps
beyond a stream's live count are predicated off with ``pl.when`` while
their index maps clamp to the last live block (the TPU pipeline elides
the repeated copy). Compute and HBM traffic scale with ceil(context/bs);
the compiled shape never changes, so the serving engine's zero-recompile
sentinel holds. bf16 pools (``kv_cache_dtype: bf16``) dequantize in-VMEM:
tiles are upcast to fp32 at the register level, accumulation is fp32, and
only the final output drops back to q's dtype.

The head-block tile ``bh`` resolves through the PR-16 autotuner
(``resolve("paged_attn", ...)``); on CPU the heuristic answers and the
kernel runs in interpret mode — which is how the dp=8 CPU-mesh tier-1
proves logit parity against the one-hot baseline.
"""
from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.sharding import PartitionSpec as P

from . import autotune
from .flash_attention import NEG_INF, _interpret
from ..parallel import comm
from ..parallel.topology import DP_AXIS, MP_AXIS

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

_ENV_KNOB = "DS_PAGED_KERNEL"


def paged_kernel_enabled(flag="auto") -> bool:
    """Resolve the ``inference.paged_kernel`` knob (the established
    gating contract — see fused_elementwise_enabled): True/False force;
    ``DS_PAGED_KERNEL=0/1`` overrides "auto"; otherwise on for TPU, off
    for CPU/GPU. Forced-on off-TPU runs the kernel in interpret mode —
    bit-for-bit the same program, pure XLA execution — which is how the
    CPU-mesh tier-1 tests the kernel paths."""
    if flag is True or flag is False:
        return bool(flag)
    env = os.environ.get(_ENV_KNOB)
    if env in ("0", "1"):
        return env == "1"
    return jax.default_backend() == "tpu"


# --------------------------------------------------------------------- #
# Analytic attend cost model (the structural ratio SERVE_BENCH reports)
# --------------------------------------------------------------------- #

def _attend_keys(block_size: int, context: Optional[int] = None,
                 pool_blocks: Optional[int] = None) -> int:
    """Key rows one attend touches. Pass ``pool_blocks`` for the one-hot
    contraction's pool-capacity term (B*bs — every pool row, every
    token) or ``context`` for the kernel's live-context term
    (ceil(ctx/bs)*bs — the stream's own blocks, final one padded)."""
    if (context is None) == (pool_blocks is None):
        raise ValueError("pass exactly one of context= / pool_blocks=")
    if pool_blocks is not None:
        return int(pool_blocks) * int(block_size)
    ctx = max(1, int(context))
    return -(-ctx // int(block_size)) * int(block_size)


def attend_flops_per_token(num_heads: int, head_dim: int, block_size: int,
                           *, context: Optional[int] = None,
                           pool_blocks: Optional[int] = None,
                           num_layers: int = 1) -> int:
    """Analytic attend FLOPs to decode ONE token: 2*nH*D per key row for
    the QK^T scores plus the same for the PV combine. Dominant terms
    only (softmax and the one-hot selector contractions are excluded on
    both sides, so the kernel/one-hot ratio is conservative)."""
    keys = _attend_keys(block_size, context, pool_blocks)
    return 4 * int(num_heads) * int(head_dim) * keys * int(num_layers)


def attend_hbm_bytes_per_token(num_heads: int, head_dim: int,
                               block_size: int, *,
                               context: Optional[int] = None,
                               pool_blocks: Optional[int] = None,
                               kv_itemsize: int = 4,
                               num_layers: int = 1) -> int:
    """Analytic K+V HBM bytes one decode attend streams: 2 (K and V)
    planes of ``keys * nH * D`` elements per layer. The one-hot side
    reads the whole pool; the kernel reads ceil(ctx/bs) tiles."""
    keys = _attend_keys(block_size, context, pool_blocks)
    return (2 * keys * int(num_heads) * int(head_dim)
            * int(kv_itemsize) * int(num_layers))


# --------------------------------------------------------------------- #
# Kernel
# --------------------------------------------------------------------- #

def _pattn_kernel(bt_ref, pos_ref, nlive_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, scale, bs, bh, K):
    """One grid step = one (stream, head block, table slot j). Scratch
    rows are [bh, K] flattened — head h2's K query rows live at
    ``h2*K:(h2+1)*K`` — and persist across the j sweep (innermost grid
    axis), the standard online-softmax carry."""
    s_idx = pl.program_id(0)
    j = pl.program_id(2)
    nlive = nlive_ref[s_idx, 0]
    active = jnp.logical_and(j < nlive, bt_ref[s_idx, j] >= 0)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(active)
    def _compute():
        # Inclusive per-row position mask: key column t of this block is
        # position j*bs + t; row k attends it iff it is <= pos[k]. The
        # final partial block contributes exactly its written rows, and
        # verify's K=k+1 rows get their per-row causal offsets here.
        col = jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1) + j * bs
        allowed = jnp.concatenate(
            [col <= pos_ref[s_idx, kk] for kk in range(K)], axis=0)
        qs = q_ref[0]       # [K, bh, D]
        ks = k_ref[0, 0]    # [bh, bs, D]
        vs = v_ref[0, 0]
        for h2 in range(bh):
            # In-VMEM dequant: bf16 pool tiles upcast at the registers,
            # scores and the accumulator stay fp32 throughout.
            q_h = qs[:, h2, :].astype(jnp.float32)
            k_h = ks[h2].astype(jnp.float32)
            v_h = vs[h2].astype(jnp.float32)
            s = jax.lax.dot_general(
                q_h, k_h, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            s = jnp.where(allowed, s, NEG_INF)
            rows = slice(h2 * K, (h2 + 1) * K)
            m_prev = m_scr[rows, 0:1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            l_new = (l_scr[rows, 0:1] * alpha
                     + jnp.sum(p, axis=1, keepdims=True))
            pv = jax.lax.dot_general(
                p, v_h, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            acc_scr[rows] = acc_scr[rows] * alpha + pv
            m_scr[rows, 0:1] = m_new
            l_scr[rows, 0:1] = l_new

    @pl.when(j == pl.num_programs(2) - 1)
    def _finalize():
        # Streams with no live blocks (dead table rows — inactive slots
        # in the uniform group-batched program) keep l == 0 and emit
        # zeros, matching the one-hot baseline's all-masked selector.
        for h2 in range(bh):
            rows = slice(h2 * K, (h2 + 1) * K)
            l_fin = l_scr[rows, 0:1]
            l_safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
            o_ref[0, :, h2, :] = (acc_scr[rows] / l_safe).astype(
                o_ref.dtype)


def _heuristic_bh(num_heads: int, K: int) -> int:
    """Head-block tile default: fold heads into one grid step while the
    fp32 scratch stays within one sublane tile (bh*K <= 8 rows) — small
    K (plain decode) amortizes per-step sequencing across heads, large K
    (chunked prefill) already fills the step."""
    bh = 1
    while (bh * 2 <= num_heads and num_heads % (bh * 2) == 0
           and bh * 2 * K <= 8):
        bh *= 2
    return bh


def _paged_call(q, pool_k, pool_v, bt, pos, nlive, *, scale, bh):
    """The pallas_call on flattened streams: q [GQ, K, nH, D], pools
    [G, B, nH, bs, D], scalar-prefetch bt [GQ, J] / pos [GQ, K] /
    nlive [GQ, 1] (all int32, group-LOCAL block ids)."""
    GQ, K, nH, D = q.shape
    G, B, _, bs, _ = pool_k.shape
    J = bt.shape[1]
    Q = GQ // G

    def _kv_map(s, h, j, bt_p, pos_p, nl_p):
        # Steps past the live count clamp to the LAST live block — the
        # revisited index lets the TPU pipeline skip the HBM copy, so
        # masked steps cost sequencing only, not bandwidth. max(.., 0)
        # guards dead rows (nlive == 0 streams never compute anyway).
        jj = jnp.minimum(j, jnp.maximum(nl_p[s, 0] - 1, 0))
        return (s // Q, jnp.maximum(bt_p[s, jj], 0), h, 0, 0)

    grid = (GQ, nH // bh, J)
    out = pl.pallas_call(
        functools.partial(_pattn_kernel, scale=scale, bs=bs, bh=bh, K=K),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, K, bh, D),
                             lambda s, h, j, bt_p, pos_p, nl_p:
                             (s, 0, h, 0)),
                pl.BlockSpec((1, 1, bh, bs, D), _kv_map),
                pl.BlockSpec((1, 1, bh, bs, D), _kv_map),
            ],
            out_specs=[
                pl.BlockSpec((1, K, bh, D),
                             lambda s, h, j, bt_p, pos_p, nl_p:
                             (s, 0, h, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((bh * K, 128), jnp.float32),
                pltpu.VMEM((bh * K, 128), jnp.float32),
                pltpu.VMEM((bh * K, D), jnp.float32),
            ]),
        out_shape=[jax.ShapeDtypeStruct((GQ, K, nH, D), q.dtype)],
        interpret=_interpret(),
    )(bt, pos, nlive, q, pool_k, pool_v)
    return out[0]


def _paged_local(q, pool_k, pool_v, block_tables, positions, *, scale,
                 block_heads):
    """Per-shard kernel entry: shapes are LOCAL (G = groups this shard
    owns, nH = heads this shard owns). Block-table ids are group-local
    by construction (the allocator only hands a slot blocks from its own
    group), so no cross-shard indexing exists to fix up."""
    G, Q, K, nH, D = q.shape
    B, bs = pool_k.shape[1], pool_k.shape[3]
    J = block_tables.shape[2]
    GQ = G * Q
    q2 = q.reshape(GQ, K, nH, D)
    bt2 = block_tables.reshape(GQ, J).astype(jnp.int32)
    pos2 = positions.reshape(GQ, K).astype(jnp.int32)
    # Live block count per stream: the table's rows are a dense prefix
    # (blocks append in order), so ceil((max pos + 1)/bs) of them are
    # live; a dead leading entry marks the whole stream inactive.
    nblk = jnp.clip(jnp.max(pos2, axis=1) // bs + 1, 0, J)
    nlive = jnp.where(bt2[:, 0] < 0, 0, nblk)[:, None].astype(jnp.int32)

    if block_heads:
        bh = int(block_heads)
    else:
        heur = _heuristic_bh(nH, K)
        cands = [c for c in (1, 2, 4, 8, 16)
                 if c <= nH and nH % c == 0 and c * K <= 512]
        measure = None
        if autotune.search_allowed():
            def run_at(v):
                return _paged_call(q2, pool_k, pool_v, bt2, pos2, nlive,
                                   scale=scale, bh=v)
            measure = autotune.measure_from_runner(run_at)
        bh = autotune.resolve("paged_attn", (GQ, K, nH, D, B, bs, J),
                              str(q.dtype), heur, cands, measure)
    out = _paged_call(q2, pool_k, pool_v, bt2, pos2, nlive, scale=scale,
                      bh=bh)
    return out.reshape(G, Q, K, nH, D)


def paged_attention(q, pool_k, pool_v, block_tables, positions, *, scale,
                    block_heads: int = 0, mesh=None):
    """Table-driven paged attention over the block pool.

    q:            [G, Q, K, nH, D] — Q streams per group, K query rows
                  per stream (1 decode / k+1 verify / chunk prefill).
    pool_k/v:     [G, B, nH, bs, D] one layer's block pool.
    block_tables: [G, Q, J] int32 group-local block ids (DEAD_BLOCK for
                  unallocated tail entries).
    positions:    [G, Q, K] int32 inclusive last attendable position per
                  query row.

    Returns [G, Q, K, nH, D] in q's dtype. When ``mesh`` spans dp/mp the
    call runs under shard_map (manual over ALL mesh axes): GSPMD cannot
    partition a pallas_call, and group-local block ids make each shard's
    kernel self-contained — zero communication, the same locality
    argument the one-hot contraction relied on."""
    if pltpu is None:  # pragma: no cover - pallas TPU support missing
        raise RuntimeError("pallas TPU backend unavailable; run with "
                           "inference.paged_kernel=false")
    if mesh is not None and math.prod(mesh.shape.values()) > 1:
        dpn = DP_AXIS if DP_AXIS in mesh.axis_names else None
        mpn = MP_AXIS if MP_AXIS in mesh.axis_names else None
        fn = comm.shard_map(
            functools.partial(_paged_local, scale=scale,
                              block_heads=block_heads),
            mesh=mesh,
            in_specs=(P(dpn, None, None, mpn, None),
                      P(dpn, None, mpn, None, None),
                      P(dpn, None, mpn, None, None),
                      P(dpn), P(dpn)),
            out_specs=P(dpn, None, None, mpn, None),
            axis_names=set(mesh.axis_names))
        return fn(q, pool_k, pool_v, block_tables, positions)
    return _paged_local(q, pool_k, pool_v, block_tables, positions,
                        scale=scale, block_heads=block_heads)


__all__ = ["paged_attention", "paged_kernel_enabled",
           "attend_flops_per_token", "attend_hbm_bytes_per_token"]
