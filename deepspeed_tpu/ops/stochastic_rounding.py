"""TPU-native stochastic rounding for master-free bf16 training.

Parity target: the reference transformer kernel's ``stochastic_mode``
(ops/transformer/transformer.py:39-151), which trades a little per-step
rounding noise for running without fp32 master weights. The CUDA kernels
implement it inside fused elementwise updates; on TPU it is a two-op bit
trick XLA fuses into the optimizer apply.

Why it works: a bf16 value is the top 16 bits of an f32. Truncating an f32
to bf16 always rounds toward zero magnitude; ADDING a uniform random
16-bit integer to the f32's low mantissa bits before truncation makes the
carry into bit 16 fire with probability exactly equal to the fractional
distance to the next representable bf16 — i.e. unbiased stochastic
rounding: E[round(x)] == x. Round-to-nearest instead loses every update
smaller than half a ulp, which is how bf16 master-free SGD stalls; the
unbiasedness is what lets hundreds of tiny updates accumulate correctly
(the same argument the reference makes for fp16 stochastic mode).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def stochastic_round_bf16(x: jax.Array, key: jax.Array) -> jax.Array:
    """Round f32 ``x`` to bf16 stochastically (unbiased). ``key`` is a
    PRNG key; every call site must fold a distinct key per step/leaf."""
    x = x.astype(jnp.float32)
    bits = lax.bitcast_convert_type(x, jnp.uint32)
    noise = jax.random.bits(key, x.shape, jnp.uint32) & jnp.uint32(0xFFFF)
    rounded = (bits + noise) & jnp.uint32(0xFFFF0000)
    out = lax.bitcast_convert_type(rounded, jnp.float32).astype(jnp.bfloat16)
    # inf/nan must stay put (the carry could walk an inf into nan space);
    # overflow handling belongs to the loss-scale machinery, not here.
    return jnp.where(jnp.isfinite(x), out, x.astype(jnp.bfloat16))


def tree_stochastic_round_bf16(tree, key: jax.Array):
    """Apply ``stochastic_round_bf16`` to every float leaf with a distinct
    per-leaf key; non-float leaves pass through."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = []
    for i, leaf in enumerate(leaves):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            out.append(stochastic_round_bf16(leaf,
                                             jax.random.fold_in(key, i)))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)
