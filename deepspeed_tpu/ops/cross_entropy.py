"""Chunked softmax cross-entropy over a tied unembedding — the loss-head op.

Replaces the naive ``log_softmax(x @ wte.T)`` head, whose fp32 logits
[tokens, vocab] tensor (824 MB at GPT-2-large bench shapes) is pure HBM
pressure: XLA materializes it forward AND saves it for backward. Here the
head is a custom-VJP op that computes the loss chunk-by-chunk over tokens,
saving only the per-token logsumexp (4 bytes/token); the backward pass
recomputes each chunk's logits once and contracts them immediately into
``dx`` / ``dwte``. Net cost: one extra logits matmul; net saving: the full
logits tensor never exists. This is the same memory-for-FLOPs trade the
reference's fused kernels make with ``gelu_checkpoint``/
``attn_dropout_checkpoint`` (csrc/transformer/ds_transformer_cuda.cpp
memory knobs), applied to the vocabulary projection.

Chunks are unrolled (not ``lax.scan``) so XLA overlaps chunk k's backward
matmuls with chunk k+1's recompute.

All ops are plain jnp/lax, so under ``jit`` + GSPMD a vocab-sharded
``wte`` (Megatron column-parallel logits, gpt2.py shardings) lowers to
partial logsumexps + an all-reduce, matching the hand-written
vocab-parallel CE loss Megatron uses.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

# Target fp32-logits bytes per chunk; chunks are sized so the transient
# [chunk, vocab] block stays bounded. 512 MB measured fastest on v5e
# (ablation: 64M/128M/256M/512M/1G -> 88.6/91.4/92.9/93.3/92.7 TFLOPs on
# the gpt2-large bench); DS_CE_CHUNK_BYTES overrides for tight-memory runs.
try:
    _CHUNK_BYTES = int(os.environ.get("DS_CE_CHUNK_BYTES",
                                      512 * 1024 * 1024))
except ValueError as e:
    raise ValueError(
        "DS_CE_CHUNK_BYTES must be a plain integer byte count "
        f"(got {os.environ.get('DS_CE_CHUNK_BYTES')!r})") from e


_MAX_CHUNKS = 64    # chunks are Python-unrolled; bound the traced graph


def pick_chunks(n_tokens: int, vocab: int) -> int:
    """Smallest divisor of n_tokens >= the memory-target chunk count,
    bounded at _MAX_CHUNKS. Falls back to the largest divisor under the
    bound (possibly 1 = unchunked) when n_tokens has awkward factors —
    correctness and bounded compile time over memory optimality."""
    total = n_tokens * vocab * 4
    target = max(1, -(-total // _CHUNK_BYTES))
    best = 1
    for c in range(1, min(_MAX_CHUNKS, n_tokens) + 1):
        if n_tokens % c == 0:
            best = c
            if c >= target:
                return c
    if target > 1:
        import logging
        logging.getLogger(__name__).warning(
            "chunked cross-entropy: n_tokens=%d has no divisor >= %d under "
            "%d chunks; falling back to %d chunk(s) — the full [%d, %d] "
            "fp32 logits block (%.1f MB) will materialize. Pad the token "
            "dim to a rounder multiple to restore the memory bound.",
            n_tokens, target, _MAX_CHUNKS, best, n_tokens // best, vocab,
            (n_tokens // best) * vocab * 4 / 2**20)
    return best


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def chunked_softmax_xent(x: jnp.ndarray, wte: jnp.ndarray,
                         targets: jnp.ndarray, n_chunks: int = 0) -> jnp.ndarray:
    """Mean next-token CE of ``x @ wte.T`` vs targets.

    x: [N, H] activations (compute dtype); wte: [V, H] tied embedding
    (compute dtype); targets: [N] int. Returns scalar fp32 mean NLL.
    """
    loss, _ = _fwd_impl(x, wte, targets, n_chunks)
    return loss


def _resolve(n_chunks: int, N: int, V: int) -> int:
    return n_chunks if n_chunks > 0 else pick_chunks(N, V)


def _fwd_impl(x, wte, targets, n_chunks):
    N, H = x.shape
    V = wte.shape[0]
    C = _resolve(n_chunks, N, V)
    xs = x.reshape(C, N // C, H)
    ts = targets.reshape(C, N // C)

    def one(xc, tc):
        logits = jax.lax.dot_general(
            xc, wte, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [c, V] fp32
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
        return jnp.sum(lse - tgt), lse

    total = jnp.asarray(0.0, jnp.float32)
    lses = []
    for i in range(C):
        s, lse = one(xs[i], ts[i])
        total = total + s
        lses.append(lse)
    return total / N, jnp.stack(lses)


def _vjp_fwd(x, wte, targets, n_chunks):
    loss, lses = _fwd_impl(x, wte, targets, n_chunks)
    return loss, (x, wte, targets, lses)


def _vjp_bwd(n_chunks, res, g):
    x, wte, targets, lses = res
    N, H = x.shape
    V = wte.shape[0]
    C = _resolve(n_chunks, N, V)
    xs = x.reshape(C, N // C, H)
    ts = targets.reshape(C, N // C)
    gn = (g / N).astype(jnp.float32)

    def one(xc, tc, lse):
        logits = jax.lax.dot_general(
            xc, wte, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        p = jnp.exp(logits - lse[:, None])               # softmax [c, V]
        dl = (p - jax.nn.one_hot(tc, V, dtype=jnp.float32)) * gn
        dlc = dl.astype(x.dtype)
        dx = jax.lax.dot_general(dlc, wte, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        dw = jax.lax.dot_general(dlc, xc, (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        return dx.astype(x.dtype), dw

    dwte = jnp.zeros(wte.shape, jnp.float32)
    dxs = []
    for i in range(C):
        dx, dw = one(xs[i], ts[i], lses[i])
        dwte = dwte + dw
        dxs.append(dx)
    return (jnp.stack(dxs).reshape(N, H), dwte.astype(wte.dtype), None)


chunked_softmax_xent.defvjp(_vjp_fwd, _vjp_bwd)
