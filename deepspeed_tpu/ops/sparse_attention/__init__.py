"""Block-sparse attention.

Capability parity with reference ``deepspeed/ops/sparse_attention/``
(SparsityConfig hierarchy sparsity_config.py:9-663, Triton SDD/DSD/DDS
matmul + masked softmax kernels, SparseSelfAttention composition) —
re-designed for TPU: the layout generators are pure numpy, and the kernel is
a layout-gated Pallas flash-attention (never materializes the [S,S] scores;
skips masked blocks), cf. the splash-attention pattern.
"""
from .sparsity_config import (SparsityConfig, DenseSparsityConfig,
                              FixedSparsityConfig, VariableSparsityConfig,
                              BigBirdSparsityConfig, BSLongformerSparsityConfig)
from .sparse_self_attention import SparseSelfAttention, sparse_attention
from .config_factory import (normalize_sparse_attention,
                             sparsity_config_from_dict)
from .sparse_attention_utils import SparseAttentionUtils

__all__ = [
    "SparsityConfig", "DenseSparsityConfig", "FixedSparsityConfig",
    "VariableSparsityConfig", "BigBirdSparsityConfig",
    "BSLongformerSparsityConfig", "SparseSelfAttention", "sparse_attention",
    "normalize_sparse_attention", "sparsity_config_from_dict",
    "SparseAttentionUtils",
]
