"""ds_config ``sparse_attention`` section → SparsityConfig objects.

Parity with reference ``runtime/config.py:192-362`` (get_sparse_attention +
the five per-mode normalizers): the mode string selects the config class and
the section's keys become its constructor arguments, with the reference's
defaults filled in. The normalized dict round-trips (it is what
``DeepSpeedConfig.sparse_attention`` stores); ``sparsity_config_from_dict``
turns it into the layout-generating object consumed by
``SparseSelfAttention`` / ``SparseAttentionUtils``.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from ... import constants as C
from .sparsity_config import (BigBirdSparsityConfig, BSLongformerSparsityConfig,
                              DenseSparsityConfig, FixedSparsityConfig,
                              SparsityConfig, VariableSparsityConfig)

# mode → (config class, [(json key, default)] beyond block/layout-per-head)
_MODE_KEYS = {
    C.SPARSE_DENSE_MODE: (DenseSparsityConfig, []),
    C.SPARSE_FIXED_MODE: (FixedSparsityConfig, [
        (C.SPARSE_NUM_LOCAL_BLOCKS, C.SPARSE_NUM_LOCAL_BLOCKS_DEFAULT),
        (C.SPARSE_NUM_GLOBAL_BLOCKS, C.SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT),
        (C.SPARSE_ATTENTION_TYPE, C.SPARSE_ATTENTION_TYPE_DEFAULT),
        (C.SPARSE_HORIZONTAL_GLOBAL_ATTENTION,
         C.SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT),
        (C.SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS,
         C.SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT),
    ]),
    C.SPARSE_VARIABLE_MODE: (VariableSparsityConfig, [
        (C.SPARSE_NUM_RANDOM_BLOCKS, C.SPARSE_NUM_RANDOM_BLOCKS_DEFAULT),
        (C.SPARSE_LOCAL_WINDOW_BLOCKS, C.SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT),
        (C.SPARSE_GLOBAL_BLOCK_INDICES, C.SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT),
        (C.SPARSE_GLOBAL_BLOCK_END_INDICES,
         C.SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT),
        (C.SPARSE_ATTENTION_TYPE, C.SPARSE_ATTENTION_TYPE_DEFAULT),
        (C.SPARSE_HORIZONTAL_GLOBAL_ATTENTION,
         C.SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT),
    ]),
    C.SPARSE_BIGBIRD_MODE: (BigBirdSparsityConfig, [
        (C.SPARSE_NUM_RANDOM_BLOCKS, C.SPARSE_NUM_RANDOM_BLOCKS_DEFAULT),
        (C.SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
         C.SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT),
        (C.SPARSE_NUM_GLOBAL_BLOCKS, C.SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT),
    ]),
    C.SPARSE_BSLONGFORMER_MODE: (BSLongformerSparsityConfig, [
        (C.SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
         C.SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT),
        (C.SPARSE_GLOBAL_BLOCK_INDICES, C.SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT),
        (C.SPARSE_GLOBAL_BLOCK_END_INDICES,
         C.SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT),
    ]),
}


def normalize_sparse_attention(section: Optional[Dict[str, Any]]
                               ) -> Optional[Dict[str, Any]]:
    """Fill mode-specific defaults, reject unknown modes — the dict shape
    ``get_sparse_attention`` (reference config.py:192-212) returns."""
    if section is None:
        return None
    mode = section.get(C.SPARSE_MODE, C.SPARSE_MODE_DEFAULT)
    if mode not in _MODE_KEYS:
        raise NotImplementedError(
            f"Given sparsity mode, {mode}, has not been implemented yet!")
    _, keys = _MODE_KEYS[mode]
    out = {C.SPARSE_MODE: mode,
           C.SPARSE_BLOCK: section.get(C.SPARSE_BLOCK, C.SPARSE_BLOCK_DEFAULT)}
    if mode != C.SPARSE_DENSE_MODE:
        out[C.SPARSE_DIFFERENT_LAYOUT_PER_HEAD] = section.get(
            C.SPARSE_DIFFERENT_LAYOUT_PER_HEAD,
            C.SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT)
    for key, default in keys:
        out[key] = section.get(key, default)
    unknown = set(section) - set(out) - {C.SPARSE_MODE}
    if unknown:
        raise ValueError(f"sparse_attention mode '{mode}' does not accept "
                         f"key(s) {sorted(unknown)}")
    return out


def sparsity_config_from_dict(section: Dict[str, Any],
                              num_heads: int) -> SparsityConfig:
    """Normalized section dict → layout-generating SparsityConfig."""
    section = normalize_sparse_attention(section)
    mode = section[C.SPARSE_MODE]
    cls, keys = _MODE_KEYS[mode]
    kwargs = {k: section[k] for k, _ in keys}
    if mode != C.SPARSE_DENSE_MODE:
        kwargs[C.SPARSE_DIFFERENT_LAYOUT_PER_HEAD] = \
            section[C.SPARSE_DIFFERENT_LAYOUT_PER_HEAD]
    return cls(num_heads=num_heads, block=section[C.SPARSE_BLOCK], **kwargs)
