"""Utilities for adapting pretrained transformers to sparse self-attention.

Parity with reference ``ops/sparse_attention/sparse_attention_utils.py:13-210``
(SparseAttentionUtils: extend_position_embedding, tokenizer max-length
update, self-attention swap for HF BERT/RoBERTa, pad/unpad to block size).

TPU-native shape: HF Flax models are immutable pytrees, so "replacing the
attention module" becomes building a functional encoder — the HF encoder
params are re-stacked through ``module_inject`` and run with a
sparse ``attention_fn`` (layout-gated Pallas flash kernel) instead of the
dense one. Position-embedding extension and sequence padding are pure
array ops on the param/input pytrees.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .sparse_self_attention import SparseSelfAttention, sparse_attention
from .sparsity_config import FixedSparsityConfig, SparsityConfig


def _find_embeddings(params: Dict[str, Any]) -> Dict[str, Any]:
    """HF Flax BERT/RoBERTa param trees keep tables under ``embeddings``."""
    if "embeddings" not in params:
        raise ValueError(
            'Please extend "extend_position_embedding" to support your '
            'model type. It currently only supports HF Flax "bert" & '
            '"roberta" param trees (an "embeddings" collection).')
    return params["embeddings"]


class SparseAttentionUtils:
    """Reference-parity utility surface (sparse_attention_utils.py:13)."""

    @staticmethod
    def extend_position_embedding(params: Dict[str, Any], max_position: int,
                                  model_type: str = "bert"
                                  ) -> Dict[str, Any]:
        """Tile the position-embedding table of a pretrained checkpoint up
        to ``max_position`` (reference :19-66). RoBERTa reserves positions
        0 & 1, so its table is ``max_position + 2`` rows and the tiling
        starts at row 2. Returns a NEW param tree (input is not mutated)."""
        emb = _find_embeddings(params)
        table = np.asarray(emb["position_embeddings"]["embedding"])
        if model_type == "bert":
            orig = table.shape[0]
            if max_position <= orig:
                raise ValueError(f"new max position {max_position} must "
                                 f"exceed the original {orig}")
            reps = max(1, max_position // orig)
            new_table = np.tile(table, (reps, 1))
        elif model_type == "roberta":
            orig = table.shape[0] - 2
            if max_position <= orig:
                raise ValueError(f"new max position {max_position} must "
                                 f"exceed the original {orig}")
            reps = max(1, max_position // orig)
            new_table = np.empty((reps * orig + 2, table.shape[1]),
                                 table.dtype)
            new_table[:2] = table[:2]
            for i in range(reps):
                new_table[2 + i * orig: 2 + (i + 1) * orig] = table[2:]
        else:
            raise ValueError(
                'Please extend "extend_position_embedding" to support '
                f'model type "{model_type}" (bert / roberta supported)')

        out = jax.tree_util.tree_map(lambda x: x, params)  # shallow clone
        out["embeddings"] = dict(emb)
        out["embeddings"]["position_embeddings"] = dict(
            emb["position_embeddings"])
        out["embeddings"]["position_embeddings"]["embedding"] = \
            jnp.asarray(new_table)
        return out

    @staticmethod
    def update_tokenizer_model_max_length(tokenizer, max_position: int):
        """Reference :68-83 — framework-agnostic."""
        tokenizer.model_max_length = max_position
        tokenizer.init_kwargs["model_max_length"] = max_position
        return tokenizer

    @staticmethod
    def replace_model_self_attention_with_sparse_self_attention(
            hf_config, hf_params: Dict[str, Any],
            sparsity_config: Optional[SparsityConfig] = None,
            max_position: Optional[int] = None):
        """The functional form of the reference's module swap (:85-148).

        Returns ``(encoder_fn, stacked_params, cfg)``:
        ``encoder_fn(stacked_params, hidden_states, key_padding_mask=None,
        rng=None, deterministic=True)`` runs the HF encoder weights through
        the fused TPU blocks with block-sparse attention.
        ``hidden_states``' sequence length must be a multiple of the
        sparsity block size — use ``pad_to_block_size``.
        """
        from ...models.transformer import apply_blocks
        from ...module_inject.policy import detect_policy
        # Architecture dispatch through the injection-policy registry
        # (reference :96-107 dispatches on BertModel/RobertaModel types;
        # here any registered encoder policy — bert, roberta, or a
        # user-registered one — resolves the weight mapping).
        pol = detect_policy(hf_config)
        cfg = pol.config_from_hf(hf_config)
        if sparsity_config is None:
            sparsity_config = FixedSparsityConfig(num_heads=cfg.num_heads)
        if sparsity_config.num_heads != cfg.num_heads:
            raise ValueError(
                f"sparsity_config.num_heads={sparsity_config.num_heads} "
                f"does not match the model's {cfg.num_heads}")
        if max_position is not None:
            import dataclasses
            cfg = dataclasses.replace(cfg, max_seq_length=max_position)
        stacked = pol.extract(hf_params)
        ssa = SparseSelfAttention(sparsity_config)

        def attention_fn(q, k, v, mask=None, causal=False, attn_dropout=0.0,
                         rng=None, deterministic=True):
            layout = ssa.get_layout(q.shape[1])
            return sparse_attention(q, k, v, layout, causal=causal,
                                    mask=mask, attn_dropout=attn_dropout,
                                    rng=rng, deterministic=deterministic)

        def encoder_fn(params, hidden_states, key_padding_mask=None,
                       rng=None, deterministic=True):
            if cfg.moe is not None:
                raise NotImplementedError(
                    "MoE blocks are not supported on the sparse-"
                    "attention encoder path (dense FFN only)")
            mask = None
            if key_padding_mask is not None:
                pad = 1.0 - key_padding_mask.astype(jnp.float32)
                mask = pad[:, None, None, :] * -1e30
            return apply_blocks(params, hidden_states, cfg, mask=mask,
                                rng=rng, deterministic=deterministic,
                                attention_fn=attention_fn)

        return encoder_fn, stacked, cfg

    @staticmethod
    def pad_to_block_size(block_size: int,
                          input_ids: Optional[jnp.ndarray] = None,
                          attention_mask: Optional[jnp.ndarray] = None,
                          token_type_ids: Optional[jnp.ndarray] = None,
                          position_ids: Optional[jnp.ndarray] = None,
                          inputs_embeds: Optional[jnp.ndarray] = None,
                          pad_token_id: int = 0,
                          model_embeddings=None) -> Tuple[int, ...]:
        """Pad the sequence dim to a multiple of the sparsity block size
        (reference :150-195). Returns ``(pad_len, input_ids,
        attention_mask, token_type_ids, position_ids, inputs_embeds)`` —
        arrays that were given come back padded, others come back None.

        ``model_embeddings``: callable mapping padded token ids ->
        embeddings; used to fill the pad region of ``inputs_embeds`` like
        the reference does with the model's embedding module.
        """
        if input_ids is not None:
            seq_len = input_ids.shape[1]
        elif inputs_embeds is not None:
            seq_len = inputs_embeds.shape[1]
        else:
            raise ValueError("need input_ids or inputs_embeds")
        pad_len = (block_size - seq_len % block_size) % block_size
        if pad_len > 0:
            def pad_tokens(x, value):
                return jnp.pad(x, ((0, 0), (0, pad_len)),
                               constant_values=value)
            if inputs_embeds is not None:
                bsz = inputs_embeds.shape[0]
                pad_ids = jnp.full((bsz, pad_len), pad_token_id, jnp.int32)
                if model_embeddings is None:
                    pad_emb = jnp.zeros(
                        (bsz, pad_len, inputs_embeds.shape[-1]),
                        inputs_embeds.dtype)
                else:
                    pad_emb = model_embeddings(pad_ids)
                inputs_embeds = jnp.concatenate([inputs_embeds, pad_emb],
                                                axis=1)
            if input_ids is not None:
                input_ids = pad_tokens(input_ids, pad_token_id)
            if position_ids is not None:
                position_ids = pad_tokens(position_ids, pad_token_id)
            if attention_mask is not None:
                attention_mask = pad_tokens(attention_mask, 0)
            if token_type_ids is not None:
                token_type_ids = pad_tokens(token_type_ids, 0)
        return (pad_len, input_ids, attention_mask, token_type_ids,
                position_ids, inputs_embeds)

    @staticmethod
    def unpad_sequence_output(pad_len: int,
                              sequence_output: jnp.ndarray) -> jnp.ndarray:
        """Drop the pad region added by pad_to_block_size (reference
        :197-210)."""
        if pad_len > 0:
            sequence_output = sequence_output[:, :-pad_len]
        return sequence_output
