"""Sparsity layout generators.

Capability parity with reference ``sparsity_config.py`` (classes at
sparsity_config.py:9,57,163,333,467,583): each config emits a per-head
block-level boolean layout [num_heads, num_blocks, num_blocks] where
layout[h, i, j] == 1 means query block i attends to key block j for head h.
Re-implemented from the published semantics of each pattern (Sparse
Transformer fixed patterns, BigBird, Longformer) — not a code translation.

TPU note: the reference's default block is 16 (Triton warp tiles); on TPU
the natural block is 128 (MXU/lane width), so ``block=128`` is the default
here. Layouts are plain numpy and feed the Pallas kernel's block gate.
"""
from __future__ import annotations

import random
from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base: layout allocation + helpers (reference sparsity_config.py:9)."""

    def __init__(self, num_heads: int, block: int = 128,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"sequence length {seq_len} must be divisible by block "
                f"{self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), np.int64)

    def check_and_propagate_first_head_layout(self, layout: np.ndarray) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All-ones layout; lets dense run through the sparse path
    (reference sparsity_config.py:57)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformer-style fixed pattern (reference
    sparsity_config.py:163): local windows of ``num_local_blocks`` blocks +
    global attention to the last ``num_global_blocks`` block(s) of each
    window. ``num_different_global_patterns`` rotates which sub-block of the
    window is global across head groups (requires different_layout_per_head).
    """

    def __init__(self, num_heads: int, block: int = 128,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError("num_local_blocks must be divisible by "
                             "num_global_blocks")
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(f"attention mode {attention}")
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal global attention requires "
                             "bidirectional attention")
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError("num_different_global_patterns > 1 needs "
                             "different_layout_per_head=True")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError("too many global patterns for the window size")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def _set_local(self, layout: np.ndarray, h: int) -> None:
        nB = layout.shape[1]
        for start in range(0, nB, self.num_local_blocks):
            end = min(start + self.num_local_blocks, nB)
            for i in range(start, end):
                hi = (i + 1) if self.attention == "unidirectional" else end
                layout[h, i, start:hi] = 1

    def _global_cols(self, h: int, nB: int) -> List[int]:
        # Head group selects which stripe of each window is global.
        pattern = (h // max(1, self.num_heads //
                            self.num_different_global_patterns)) \
            % self.num_different_global_patterns
        first = self.num_local_blocks - (1 + pattern) * self.num_global_blocks
        cols = []
        for w in range(first, nB, self.num_local_blocks):
            cols.extend(range(w, min(w + self.num_global_blocks, nB)))
        return cols

    def _set_global(self, layout: np.ndarray, h: int) -> None:
        nB = layout.shape[1]
        for c in self._global_cols(h, nB):
            if self.attention == "unidirectional":
                layout[h, c:, c] = 1          # later queries see the global col
            else:
                layout[h, :, c] = 1
            if self.horizontal_global_attention:
                layout[h, c, :] = 1
        return None

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        heads = range(self.num_heads) if self.different_layout_per_head else [0]
        for h in heads:
            self._set_local(layout, h)
            self._set_global(layout, h)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Variable local windows + explicit global block indices + random
    blocks (reference sparsity_config.py:333). ``local_window_blocks`` lists
    consecutive window sizes; the last size repeats to cover the sequence.
    ``global_block_indices``/``global_block_end_indices`` give single blocks
    or [start, end) ranges of global columns.
    """

    def __init__(self, num_heads: int, block: int = 128,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False):
        super().__init__(num_heads, block, different_layout_per_head)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(f"attention mode {attention}")
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal global attention requires "
                             "bidirectional attention")
        # Random blocks without different_layout_per_head are valid: the
        # layout is sampled once for head 0 and propagated to all heads
        # (reference sparsity_config.py num_layout_heads=1 behavior).
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        if global_block_end_indices is not None:
            if len(global_block_end_indices) != len(self.global_block_indices):
                raise ValueError("global start/end index lists must align")
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def _set_local(self, layout: np.ndarray, h: int) -> None:
        nB = layout.shape[1]
        start = 0
        sizes = list(self.local_window_blocks)
        while start < nB:
            size = sizes.pop(0) if sizes else self.local_window_blocks[-1]
            end = min(start + size, nB)
            for i in range(start, end):
                hi = (i + 1) if self.attention == "unidirectional" else end
                layout[h, i, start:hi] = 1
            start = end

    def _set_global(self, layout: np.ndarray, h: int) -> None:
        nB = layout.shape[1]
        if self.global_block_end_indices is None:
            ranges = [(i, i + 1) for i in self.global_block_indices]
        else:
            ranges = list(zip(self.global_block_indices,
                              self.global_block_end_indices))
        for lo, hi in ranges:
            for c in range(lo, min(hi, nB)):
                if self.attention == "unidirectional":
                    layout[h, c:, c] = 1
                else:
                    layout[h, :, c] = 1
                if self.horizontal_global_attention:
                    layout[h, c, :] = 1

    def _set_random(self, layout: np.ndarray, h: int) -> None:
        nB = layout.shape[1]
        for i in range(nB):
            for c in random.sample(range(nB), min(self.num_random_blocks, nB)):
                if self.attention == "unidirectional" and c > i:
                    c = i - (c - i) if i - (c - i) >= 0 else i
                layout[h, i, c] = 1

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        heads = range(self.num_heads) if self.different_layout_per_head else [0]
        for h in heads:
            self._set_local(layout, h)
            self._set_global(layout, h)
            if self.num_random_blocks:
                self._set_random(layout, h)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird: random + sliding window + global-first blocks
    (reference sparsity_config.py:467)."""

    def __init__(self, num_heads: int, block: int = 128,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(f"attention mode {attention}")
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nB = layout.shape[1]
        if nB < max(self.num_sliding_window_blocks, self.num_global_blocks,
                    self.num_random_blocks):
            raise ValueError(f"sequence of {nB} blocks too short for the "
                             "BigBird pattern")
        heads = range(self.num_heads) if self.different_layout_per_head else [0]
        w = self.num_sliding_window_blocks // 2
        uni = self.attention == "unidirectional"
        for h in heads:
            # sliding window
            for i in range(nB):
                lo, hi = max(0, i - w), (i + 1 if uni else min(nB, i + w + 1))
                layout[h, i, lo:hi] = 1
            # global: first blocks as rows+cols (col only below diag if uni)
            g = self.num_global_blocks
            layout[h, :, :g] = 1
            if not uni:
                layout[h, :g, :] = 1
            # random
            for i in range(nB):
                pool = range(0, i + 1) if uni else range(nB)
                for c in random.sample(list(pool),
                                       min(self.num_random_blocks, len(list(pool)))):
                    layout[h, i, c] = 1
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + global indices as rows+cols
    (reference sparsity_config.py:583)."""

    def __init__(self, num_heads: int, block: int = 128,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        if global_block_end_indices is not None and \
                len(global_block_end_indices) != len(self.global_block_indices):
            raise ValueError("global start/end index lists must align")
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nB = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        uni = self.attention == "unidirectional"
        heads = range(self.num_heads) if self.different_layout_per_head else [0]
        if self.global_block_end_indices is None:
            ranges = [(i, i + 1) for i in self.global_block_indices]
        else:
            ranges = list(zip(self.global_block_indices,
                              self.global_block_end_indices))
        for h in heads:
            for i in range(nB):
                lo, hi = max(0, i - w), (i + 1 if uni else min(nB, i + w + 1))
                layout[h, i, lo:hi] = 1
            for lo, hi in ranges:
                for c in range(lo, min(hi, nB)):
                    if uni:
                        layout[h, c:, c] = 1
                    else:
                        layout[h, :, c] = 1
                        layout[h, c, :] = 1
        return self.check_and_propagate_first_head_layout(layout)
