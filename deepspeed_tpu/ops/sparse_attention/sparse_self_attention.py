"""SparseSelfAttention — layout-driven attention composition.

Parity with reference ``sparse_self_attention.py:105-164`` (QK^T → masked
block-sparse softmax → AV over a SparsityConfig layout) and the Triton
MatMul/Softmax pair it composes. Here the whole pipeline is ONE layout-gated
Pallas flash kernel (ops/flash_attention.py): no LUT building, no SDD/DSD/
DDS decomposition — the layout gates (q-block, k-block) pairs directly and
masked blocks are skipped.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..flash_attention import flash_attention, _layout_to_mask
from .sparsity_config import FixedSparsityConfig, SparsityConfig


def sparse_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     layout: jnp.ndarray, causal: bool = False,
                     mask: Optional[jnp.ndarray] = None,
                     rpe: Optional[jnp.ndarray] = None,
                     attn_dropout: float = 0.0, rng=None,
                     deterministic: bool = True) -> jnp.ndarray:
    """q,k,v: [B, S, nH, dH]; layout: [nH, S//block, S//block] int.

    The layout must give every query row at least one visible block (all
    five shipped SparsityConfigs do — local windows include the diagonal),
    otherwise that row's softmax denominator is empty.

    ``rpe``: additive relative-position bias, [nH, S, S] or [S, S]
    (broadcast over batch), added to the scores pre-softmax like the
    reference's sparse softmax RPE input (softmax.py:259-291). Treated as
    a constant (no gradient flows to it), matching the reference kernel.
    """
    if rpe is not None:
        if rpe.ndim == 2:
            rpe = rpe[None]
        bias = lax.stop_gradient(rpe)[None]        # [1, nH, S, S]
        mask = bias if mask is None else mask + bias
    return flash_attention(q, k, v, mask=mask, causal=causal,
                           attn_dropout=attn_dropout, rng=rng,
                           deterministic=deterministic, layout=layout)


def sparse_attention_reference(q, k, v, layout, causal: bool = False):
    """Dense-masked reference implementation (for tests; the reference's
    own tests compare the Triton path against a dense torch softmax the
    same way, test_sparse_attention.py:16-97)."""
    from ...models.transformer import dense_attention
    S = q.shape[1]
    return dense_attention(q, k, v, mask=_layout_to_mask(layout, S, None),
                           causal=causal)


class SparseSelfAttention:
    """Module-style wrapper owning a SparsityConfig and a layout cache
    (reference sparse_self_attention.py:24-58 master-layout caching)."""

    def __init__(self, sparsity_config: Optional[SparsityConfig] = None,
                 key_padding_mask_mode: str = "add",
                 attn_mask_mode: str = "mul",
                 max_seq_length: int = 2048):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(num_heads=4)
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self._layout_cache: Dict[int, np.ndarray] = {}

    @classmethod
    def from_config(cls, sparse_attention_section: Dict, num_heads: int,
                    **kwargs) -> "SparseSelfAttention":
        """Build from a ds_config ``sparse_attention`` section (the dict
        DeepSpeedConfig.sparse_attention stores) — the consumption side of
        reference config.py:192-362."""
        from .config_factory import sparsity_config_from_dict
        return cls(sparsity_config_from_dict(sparse_attention_section,
                                             num_heads), **kwargs)

    def get_layout(self, seq_len: int) -> np.ndarray:
        if seq_len not in self._layout_cache:
            self._layout_cache[seq_len] = \
                self.sparsity_config.make_layout(seq_len)
        return self._layout_cache[seq_len]

    def __call__(self, query: jnp.ndarray, key: jnp.ndarray,
                 value: jnp.ndarray,
                 rpe: Optional[jnp.ndarray] = None,
                 key_padding_mask: Optional[jnp.ndarray] = None,
                 attn_mask: Optional[jnp.ndarray] = None,
                 rng=None, deterministic: bool = True) -> jnp.ndarray:
        """query/key/value: [B, S, nH, dH] (unlike the reference's
        [B, nH, S, dH] torch layout — [B, S, ...] is the JAX norm here).

        key_padding_mask: [B, S], 1 = keep. attn_mask: additive
        broadcastable to [B, 1, S, S] ("add" mode) or multiplicative 0/1
        ("mul" mode), matching the reference's two mask modes
        (sparse_self_attention.py:118-141).
        """
        S = query.shape[1]
        layout = self.get_layout(S)
        mask = None
        if key_padding_mask is not None:
            pad = (1.0 - key_padding_mask.astype(jnp.float32))
            mask = pad[:, None, None, :] * -1e30
            if self.key_padding_mask_mode != "add":
                raise NotImplementedError("mul key_padding_mask_mode")
        if attn_mask is not None:
            if self.attn_mask_mode == "mul":
                attn_mask = jnp.where(attn_mask != 0, 0.0, -1e30)
            mask = attn_mask if mask is None else mask + attn_mask
        return sparse_attention(query, key, value, layout,
                                causal=False, mask=mask, rpe=rpe, rng=rng,
                                deterministic=deterministic)
