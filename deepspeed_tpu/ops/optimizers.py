"""Optimizer construction — the engine's selection matrix.

Parity with reference ``runtime/engine.py:588-628`` (Adam/AdamW → fused or
CPU variant, Lamb → FusedLamb, OneBitAdam, arbitrary torch optimizers) and
the op-level optimizers ``ops/adam/fused_adam.py``, ``ops/lamb/
fused_lamb.py``. The Adam family defaults to the Pallas single-pass
multi-tensor apply (ops/fused_update.py — the structural equivalent of
csrc/adam/multi_tensor_adam.cu); ``optimizer.params.fused=false`` restores
the optax chain, whose elementwise math XLA fuses per leaf on its own.
Everything else builds on optax transforms; ds_config param names are
translated.

``onebitadam`` runs standard Adam in its warmup phase; the compressed
communication variant lives in ``ops/onebit.py`` (engaged via config).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Union

import optax

from .. import constants as C
from ..utils.logging import logger

ScheduleOrFloat = Union[Callable, float]


def _common(params: Dict[str, Any]):
    lr = params.get("lr", 1e-3)
    betas = params.get("betas", (0.9, 0.999))
    eps = params.get("eps", 1e-8)
    weight_decay = params.get("weight_decay", 0.0)
    return lr, tuple(betas), eps, weight_decay


def _scale_by_clamped_trust_ratio(min_coeff: float, max_coeff: float):
    """optax.scale_by_trust_ratio with the reference's per-tensor clamp
    (fused_lamb_cuda.cpp max_coeff/min_coeff)."""
    import jax
    import jax.numpy as jnp

    def init_fn(params):
        return optax.EmptyState()

    def update_fn(updates, state, params):
        if params is None:
            raise ValueError("trust ratio requires params")

        def one(u, p):
            p_norm = jnp.linalg.norm(p.astype(jnp.float32))
            u_norm = jnp.linalg.norm(u.astype(jnp.float32))
            ratio = jnp.where((p_norm > 0.0) & (u_norm > 0.0),
                              p_norm / u_norm, 1.0)
            ratio = jnp.clip(ratio, min_coeff, max_coeff)
            return (u.astype(jnp.float32) * ratio).astype(u.dtype)

        return jax.tree_util.tree_map(one, updates, params), state

    return optax.GradientTransformation(init_fn, update_fn)


def build_optimizer(name: str, params: Dict[str, Any],
                    schedule_fn: ScheduleOrFloat = None, mesh=None,
                    shard_axis=None) -> optax.GradientTransformation:
    """Build an optax transformation from a ds_config optimizer section.

    ``schedule_fn`` (step -> lr) overrides the static ``lr`` param, matching
    how the reference's scheduler mutates param_group lr each step.
    ``mesh``/``shard_axis`` (engine-provided under ZeRO on a pure-dp mesh)
    make the fused apply run shard-local over the dp axis; ignored by the
    per-leaf optax chains (their leaves shard declaratively).
    """
    name = name.lower()
    lr, betas, eps, weight_decay = _common(params)
    learning_rate = schedule_fn if schedule_fn is not None else lr

    if name in (C.ADAM_OPTIMIZER, C.ADAMW_OPTIMIZER, C.ONEBIT_ADAM_OPTIMIZER):
        adam_w_mode = params.get("adam_w_mode", name == C.ADAMW_OPTIMIZER)
        if name == C.ONEBIT_ADAM_OPTIMIZER:
            logger.info("OnebitAdam: uncompressed warmup uses standard Adam; "
                        "compressed collectives engage via ops.onebit")
        elif params.get(C.OPTIMIZER_FUSED, C.OPTIMIZER_FUSED_DEFAULT):
            # Single-pass Pallas multi-tensor apply (the reference's
            # csrc/adam/multi_tensor_adam.cu equivalent). optax-compatible
            # (init/update); the engine's train steps call its fused_apply
            # for the clip-folded single-HBM-pass write.
            from .fused_update import fused_adam
            return fused_adam(learning_rate, b1=betas[0], b2=betas[1],
                              eps=eps, weight_decay=weight_decay,
                              adam_w_mode=adam_w_mode, mesh=mesh,
                              shard_axis=shard_axis)
        if adam_w_mode:
            return optax.adamw(learning_rate, b1=betas[0], b2=betas[1], eps=eps,
                               weight_decay=weight_decay)
        if weight_decay:
            # Coupled L2 (classic Adam): decay folded into the gradient
            # *before* the moment update, as reference FusedAdam does with
            # adam_w_mode=False.
            return optax.chain(
                optax.add_decayed_weights(weight_decay),
                optax.scale_by_adam(b1=betas[0], b2=betas[1], eps=eps),
                optax.scale_by_learning_rate(learning_rate))
        return optax.adam(learning_rate, b1=betas[0], b2=betas[1], eps=eps)

    if name == C.LAMB_OPTIMIZER:
        # Reference FusedLamb (ops/lamb/fused_lamb.py:12): Adam-style moments
        # + per-tensor trust ratio CLAMPED to [min_coeff, max_coeff]
        # (fused_lamb_cuda_kernel.cu). optax.lamb has no clamp, so the chain
        # is built explicitly with a clamped trust-ratio transform.
        max_coeff = float(params.get("max_coeff", 10.0))
        min_coeff = float(params.get("min_coeff", 0.01))
        return optax.chain(
            optax.scale_by_adam(b1=betas[0], b2=betas[1], eps=eps),
            optax.add_decayed_weights(weight_decay),
            _scale_by_clamped_trust_ratio(min_coeff, max_coeff),
            optax.scale_by_learning_rate(learning_rate))

    if name == C.SGD_OPTIMIZER:
        momentum = params.get("momentum", 0.0)
        tx = optax.sgd(learning_rate, momentum=momentum or None,
                       nesterov=params.get("nesterov", False))
        if weight_decay:
            tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
        return tx

    if name == C.ADAGRAD_OPTIMIZER:
        return optax.adagrad(learning_rate, eps=params.get("eps", 1e-10))

    if name == C.RMSPROP_OPTIMIZER:
        return optax.rmsprop(learning_rate, decay=params.get("alpha", 0.99),
                             eps=eps, momentum=params.get("momentum", 0.0))

    if name == C.LION_OPTIMIZER and hasattr(optax, "lion"):
        return optax.lion(learning_rate, b1=betas[0], b2=betas[1],
                          weight_decay=weight_decay)

    # Fall through: any optax optimizer by attribute name (parity with the
    # reference accepting arbitrary torch.optim names, engine.py:624-628).
    if hasattr(optax, name):
        logger.info(f"Using optax.{name} for optimizer '{name}'")
        return getattr(optax, name)(learning_rate)
    raise ValueError(f"Unknown optimizer '{name}'")
