"""JIT builder for the native host ops.

Parity with reference ``op_builder/builder.py`` (OpBuilder.jit_load,
builder.py:182): compile C++ sources to a shared library on first use,
cache by source hash, load via ctypes. No nvcc/torch extension machinery —
the native surface here is host-side (TPU kernels are Pallas, which needs
no build step), so a plain g++ invocation suffices.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
import tempfile
from typing import List, Optional

from ..utils.logging import logger

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")
_CACHE_ENV = "DS_BUILD_CACHE"


def _cache_dir() -> str:
    d = os.environ.get(_CACHE_ENV) or os.path.join(
        tempfile.gettempdir(), "deepspeed_tpu_ops")
    os.makedirs(d, exist_ok=True)
    return d


def _compiler() -> Optional[str]:
    for cc in ("g++", "clang++"):
        if shutil.which(cc):
            return cc
    return None


class OpBuilder:
    """Compile-and-load one shared object from csrc sources."""

    def __init__(self, name: str, sources: List[str],
                 extra_flags: Optional[List[str]] = None):
        self.name = name
        self.sources = [s if os.path.isabs(s) else os.path.join(_CSRC, s)
                        for s in sources]
        self.extra_flags = extra_flags or []
        self._lib: Optional[ctypes.CDLL] = None

    def is_compatible(self) -> bool:
        return _compiler() is not None and all(
            os.path.isfile(s) for s in self.sources)

    def _hash(self) -> str:
        h = hashlib.sha1()
        for s in self.sources:
            with open(s, "rb") as f:
                h.update(f.read())
        h.update(" ".join(self.extra_flags).encode())
        return h.hexdigest()[:16]

    def so_path(self) -> str:
        return os.path.join(_cache_dir(), f"{self.name}_{self._hash()}.so")

    def jit_load(self) -> ctypes.CDLL:
        """Compile if needed, then dlopen (reference builder.py:182)."""
        if self._lib is not None:
            return self._lib
        cc = _compiler()
        if cc is None:
            raise RuntimeError(f"op '{self.name}': no C++ compiler found")
        so = self.so_path()
        if not os.path.isfile(so):
            # -fno-math-errno/-fno-trapping-math: without these gcc keeps
            # sqrtf as a libm call (errno!) and the Adam inner loop stays
            # scalar — 3-4x on the single-core offload host.
            flags = ["-O3", "-shared", "-fPIC", "-std=c++17", "-fopenmp",
                     "-march=native", "-funroll-loops", "-fno-math-errno",
                     "-fno-trapping-math"] + self.extra_flags
            cmd = [cc] + flags + self.sources + ["-o", so + ".tmp"]
            logger.info(f"building op '{self.name}': {' '.join(cmd)}")
            try:
                subprocess.run(cmd, check=True, capture_output=True, text=True)
            except subprocess.CalledProcessError as e:
                if "-march=native" in flags:  # unsupported on some hosts
                    flags.remove("-march=native")
                    cmd = [cc] + flags + self.sources + ["-o", so + ".tmp"]
                    subprocess.run(cmd, check=True, capture_output=True,
                                   text=True)
                else:
                    raise RuntimeError(
                        f"op '{self.name}' build failed:\n{e.stderr}") from e
            os.replace(so + ".tmp", so)
        self._lib = ctypes.CDLL(so)
        return self._lib


def cpu_adam_builder() -> OpBuilder:
    return OpBuilder("cpu_adam", ["cpu_adam.cpp"])
