"""Flash attention — Pallas TPU kernels with custom VJP and block-sparsity.

The TPU-native replacement for the reference's fused attention core inside
the transformer kernel (csrc/transformer/softmax_kernels.cu +
strided_batch_gemm.h: QK^T → scale+mask softmax → AV, with saved softmax
output replayed in backward) AND its Triton block-sparse kernels
(ops/sparse_attention/trsrc/matmul.tr, softmax_fwd.tr). On TPU the dense
[S,S] fp32 score tensor is the HBM bottleneck, so we never materialize it:
the classic flash pattern computes attention block-by-block in VMEM with a
running (max, sum) softmax, and the backward recomputes scores per block
from the saved logsumexp — the same memory story as the reference's
``attn_dropout_checkpoint`` knob taken to its limit.

One kernel family serves three modes via static specialization:
- dense bidirectional (layout=None, causal=False)
- causal (block-skip above the diagonal band)
- block-sparse (an int32 layout [H, nQ, nK] gates each (q-block, k-block)
  pair — the splash-attention pattern; masked blocks skip their matmuls)

Layout: kernels run over [BH, S, D] (batch×heads flattened, head_dim last).
Grid is (BH, q_blocks, k_blocks); the innermost (k) dimension iterates
sequentially on TPU so VMEM scratch carries the running softmax state
across k-blocks of one q-block.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend bits are importable everywhere; interpret=True runs on CPU
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


import os

_BLOCK_TARGET = int(os.environ.get("DS_FLASH_BLOCK", "1024"))
# Backward block for CAUSAL kernels. The dq/dkv grids skip above-diagonal
# blocks entirely, so finer blocks trade per-grid-step overhead for real
# compute skipped; 512 measured best on v5e (gpt2-large bench sweep:
# bwd 1024/512/256/128 -> 207.5/201.7/215.5/259.2 ms fwd+bwd). The forward
# stays at DS_FLASH_BLOCK: it runs TWICE under remat and its per-step
# overhead dominates the causal saving (fwd 512 -> +14 ms).
# 0 = follow DS_FLASH_BLOCK.
_BLOCK_TARGET_BWD = int(os.environ.get("DS_FLASH_BLOCK_BWD", "512"))


def _pick_block(s: int, target: int = 0) -> int:
    target = target or _BLOCK_TARGET
    for b in (target, 512, 256, 128):
        if b <= s and s % b == 0:
            return b
    return s  # small sequences: single block


def _pick_block_bwd(s: int, causal: bool) -> int:
    if not causal:       # no blocks to skip: finer only adds overhead
        return _pick_block(s)
    return _pick_block(s, _BLOCK_TARGET_BWD or _BLOCK_TARGET)


def _block_candidates(s: int):
    """Legal kernel blocks for a sequence of length s: the power-of-two
    grid the heuristic targets draw from, each dividing s."""
    return tuple(b for b in (128, 256, 512, 1024)
                 if b <= s and s % b == 0) or (s,)


def _resolve_blocks(kernel: str, q, k, causal: bool, heur, run_at):
    """Route a (bq, bk) pick through ops.autotune.  ``run_at(tile)``
    executes the real kernel pinned to a candidate tile (the measure);
    DS_AUTOTUNE=0 / CPU return ``heur`` — today's _BLOCK_TARGET
    heuristics (and their env overrides) bit-for-bit.  fwd and bwd
    resolve under separate kernel keys: the causal-bwd tile trade (finer
    blocks skip real compute) is real and shape-dependent."""
    from . import autotune
    BH, S, D = q.shape
    Sk = k.shape[1]
    cands = [(cq, ck) for cq in _block_candidates(S)
             for ck in _block_candidates(Sk)]
    measure = autotune.measure_from_runner(run_at) \
        if autotune.search_allowed() else None
    return autotune.resolve(kernel, (BH, S, Sk, D, int(causal)),
                            str(q.dtype), heur, cands, measure)


def _run_pred(causal: bool, qi, kj, bq: int, bk: int, layout_block=None):
    """Static-or-traced predicate for whether a (q,k) block pair runs."""
    conds = []
    if causal:
        conds.append(kj * bk < (qi + 1) * bq)
    if layout_block is not None:
        conds.append(layout_block != 0)
    if not conds:
        return True
    pred = conds[0]
    for c in conds[1:]:
        pred = jnp.logical_and(pred, c)
    return pred


def _dropout_keep(seed, bh, qi, kj, bq: int, bk: int, rate: float,
                  transposed: bool = False):
    """Regenerable dropout keep-mask for one (q-block, k-block) tile.

    A stateless position hash (murmur3 finalizer over
    ``seed ^ bh`` and the global (q, k) element index) rather than a
    sequential PRNG stream: forward and both backward kernels regenerate
    the exact same mask from the seed in whichever block orientation they
    iterate — the TPU-native replacement for the reference's *saved*
    dropout masks replayed in backward (ops/transformer/transformer.py:
    330-466, csrc/transformer/dropout_kernels.cu).
    """
    shape = (bk, bq) if transposed else (bq, bk)
    rows = jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
    cols = jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
    if transposed:
        qpos = cols + jnp.uint32(qi * bq)
        kpos = rows + jnp.uint32(kj * bk)
    else:
        qpos = rows + jnp.uint32(qi * bq)
        kpos = cols + jnp.uint32(kj * bk)
    # Element id mixed with the (seed, head) stream id; uint32 wraparound is
    # fine (stays deterministic).
    stream = seed.astype(jnp.uint32) ^ (bh.astype(jnp.uint32) *
                                        jnp.uint32(0x85EBCA6B))
    x = qpos * jnp.uint32(0x9E3779B9) + kpos
    x = x + stream
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    # keep iff uniform[0,1) >= rate. Mosaic has no uint32->f32 cast; use the
    # top 24 bits via int32 (exact in f32).
    u = (x >> 8).astype(jnp.int32).astype(jnp.float32) * (1.0 / 16777216.0)
    return u >= rate


def _causal_mask(s, qi, kj, bq: int, bk: int, transposed: bool = False):
    # Narrow iotas broadcast in the compare: one [bq,bk] pass instead of
    # materializing two full-tile index planes.
    if transposed:
        krows = jax.lax.broadcasted_iota(jnp.int32, (bk, 1), 0) + kj * bk
        qcols = jax.lax.broadcasted_iota(jnp.int32, (1, bq), 1) + qi * bq
        return jnp.where(qcols >= krows, s, NEG_INF)
    rows = jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0) + qi * bq
    cols = jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1) + kj * bk
    return jnp.where(rows >= cols, s, NEG_INF)


# --------------------------------------------------------------------- #
# Forward kernel
# --------------------------------------------------------------------- #
def _fwd_kernel(*refs, scale: float, causal: bool, bq: int, bk: int,
                has_layout: bool, dropout: float = 0.0,
                single_k: bool = False):
    if has_layout and dropout > 0.0:
        (layout_ref, seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
         m_scr, l_scr, acc_scr) = refs
    elif has_layout:
        (layout_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
         m_scr, l_scr, acc_scr) = refs
    elif dropout > 0.0:
        (seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    bh, qi, kj = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    if single_k:
        # One k-block covers the whole row: no running-softmax state, no
        # scratch round-trips — direct softmax + PV (saves several VPU
        # passes; with S<=DS_FLASH_BLOCK this is the only fwd shape).
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, kj, bq, bk)
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=1, keepdims=True)
        if dropout > 0.0:
            keep = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk, dropout)
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout)), 0.0)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (pv / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m[:, 0] + jnp.log(l_safe[:, 0])
        return

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = _run_pred(causal, qi, kj, bq, bk,
                    _layout_gate(layout_ref, qi, kj) if has_layout else None)

    @pl.when(run)
    def _compute():
        q = q_ref[0]                       # [BQ, D]
        k = k_ref[0]                       # [BK, D]
        v = v_ref[0]                       # [BK, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [BQ, BK]
        if causal:
            s = _causal_mask(s, qi, kj, bq, bk)

        m_prev = m_scr[:, 0:1]                            # [BQ, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)                   # [BQ, 1]
        p = jnp.exp(s - m_new)                            # [BQ, BK]
        # l (the softmax normalizer) accumulates the UNdropped p: dropout
        # applies to the normalized weights w = p/l, so dropping p before
        # the PV matmul while normalizing by the full l is exactly
        # w' = mask * w / keep.
        l_new = l_scr[:, 0:1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        if dropout > 0.0:
            keep = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk, dropout)
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout)), 0.0)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BQ, D]
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:, 0:1] = m_new
        l_scr[:, 0:1] = l_new

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_scr[:, 0] + jnp.log(l_safe[:, 0]))


def _pad_layout(layout):
    """Pad [H, nQ, nK] to TPU tile multiples (8, 128) on the last two dims
    so the gate can ride a (1, 8, 128) VMEM block; the kernel reads
    [0, qi % 8, kj % 128] from the (qi // 8, kj // 128) block."""
    H, nQ, nK = layout.shape
    pq = (-nQ) % 8
    pk = (-nK) % 128
    if pq or pk:
        layout = jnp.pad(layout, ((0, 0), (0, pq), (0, pk)))
    return layout


def _layout_gate(layout_ref, qi, kj):
    """Read one int gate out of the (1, 8, 128) layout tile. Dynamic scalar
    indexing into VMEM doesn't lower on TPU; a masked VPU reduction does."""
    tile = layout_ref[0]                                   # [8, 128] int32
    r = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 0) == (qi % 8)
    c = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 1) == (kj % 128)
    return jnp.sum(jnp.where(jnp.logical_and(r, c), tile, 0))


def _seed_spec():
    """(1,1) int32 dropout seed rides SMEM (scalar memory)."""
    if pltpu is not None and jax.default_backend() == "tpu":
        return pl.BlockSpec(memory_space=pltpu.SMEM)
    return pl.BlockSpec((1, 1), lambda *_: (0, 0))


def _seed_arr(seed):
    return jnp.asarray(seed, jnp.int32).reshape(1, 1)


def _layout_spec(num_heads: int, role: str):
    """BlockSpec for the padded layout; bh grid index → head index."""
    if role == "fwd" or role == "dq":
        return pl.BlockSpec((1, 8, 128),
                            lambda b, i, j: (b % num_heads, i // 8, j // 128))
    # dkv grid is (BH, nK, nQ)
    return pl.BlockSpec((1, 8, 128),
                        lambda b, j, i: (b % num_heads, i // 8, j // 128))


def _qkv_spec(blk: int, D: int, role: str):
    """Block spec for a q/k/v/do/dq/dk/dv operand over [BH, S, D] arrays.
    ``role``: 'q' indexes the q-block dim, 'k' the k-block dim; '*T'
    variants are for the dkv grid whose program ids are (bh, kj, qi).

    NOTE a native-4D [B, S, nH, D] variant (per-head blocks (1, blk, 1, D)
    to skip the host-side transposes) was tried and REVERTED: Mosaic
    requires the last two block dims divisible by (8, 128) or equal to the
    array dims, which a 1-of-nH head block can never satisfy."""
    idx = {"q": lambda b, i, j: (b, i, 0),
           "k": lambda b, i, j: (b, j, 0),
           "qT": lambda b, j, i: (b, i, 0),
           "kT": lambda b, j, i: (b, j, 0)}[role]
    return pl.BlockSpec((1, blk, D), idx)


def _flash_fwd(q, k, v, layout, scale: float, causal: bool,
               dropout: float = 0.0, seed=None, _blocks=None):
    """q,k,v: [BH, S, D]; layout int32 [H, nQ, nK] or None.
    → (o [BH,S,D], lse [BH,1,S] f32)."""
    BH, S, D = q.shape
    Sk = k.shape[1]
    has_layout = layout is not None
    if has_layout:
        # Kernel blocks must match the layout's block granularity.
        bq = bk = S // layout.shape[-1]
    elif _blocks is not None:
        bq, bk = _blocks
    else:
        def run_at(tile):
            return _flash_fwd(jnp.zeros((BH, S, D), q.dtype),
                              jnp.zeros((BH, Sk, D), k.dtype),
                              jnp.zeros((BH, Sk, D), v.dtype),
                              None, scale, causal, _blocks=tile)
        bq, bk = _resolve_blocks(
            "flash_fwd", q, k, causal,
            (_pick_block(S), _pick_block(Sk)), run_at)
    grid = (BH, S // bq, Sk // bk)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, has_layout=has_layout,
                               dropout=dropout,
                               single_k=(Sk // bk == 1 and not has_layout))
    in_specs = [
        _qkv_spec(bq, D, "q"),
        _qkv_spec(bk, D, "k"),
        _qkv_spec(bk, D, "k"),
    ]
    args = (q, k, v)
    if dropout > 0.0:
        in_specs = [_seed_spec()] + in_specs
        args = (_seed_arr(seed),) + args
    if has_layout:
        in_specs = [_layout_spec(layout.shape[0], "fwd")] + in_specs
        args = (_pad_layout(layout),) + args
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            _qkv_spec(bq, D, "q"),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return o, lse


# --------------------------------------------------------------------- #
# Backward kernels
# --------------------------------------------------------------------- #
def _bwd_dq_kernel(*refs, scale: float, causal: bool, bq: int, bk: int,
                   has_layout: bool, dropout: float = 0.0):
    refs = list(refs)
    layout_ref = refs.pop(0) if has_layout else None
    seed_ref = refs.pop(0) if dropout > 0.0 else None
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
     acc_scr) = refs
    bh, qi, kj = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = _run_pred(causal, qi, kj, bq, bk,
                    _layout_gate(layout_ref, qi, kj) if has_layout else None)

    @pl.when(run)
    def _compute():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        do = do_ref[0]                                    # [BQ, D]
        lse = lse_ref[0, 0][:, None]                      # [BQ, 1]
        delta = delta_ref[0, 0][:, None]                  # [BQ, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, kj, bq, bk)
        p = jnp.exp(s - lse)                              # softmax [BQ, BK]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BQ, BK]
        if dropout > 0.0:
            # d/dw of w' = mask*w/keep: route do·v^T through the regenerated
            # mask. delta = rowsum(do*o) already equals sum_j p_j g_j.
            keep = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk, dropout)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout)), 0.0)
        ds = p * (dp - delta) * scale
        acc_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale: float, causal: bool, bq: int, bk: int,
                    has_layout: bool, dropout: float = 0.0):
    refs = list(refs)
    layout_ref = refs.pop(0) if has_layout else None
    seed_ref = refs.pop(0) if dropout > 0.0 else None
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
     dk_scr, dv_scr) = refs
    bh, kj, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = _run_pred(causal, qi, kj, bq, bk,
                    _layout_gate(layout_ref, qi, kj) if has_layout else None)

    @pl.when(run)
    def _compute():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0][None, :]                      # [1, BQ]
        delta = delta_ref[0, 0][None, :]                  # [1, BQ]
        # s2[i, j] = k_i · q_j (transposed score block)
        s2 = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [BK, BQ]
        if causal:
            s2 = _causal_mask(s2, qi, kj, bq, bk, transposed=True)
        p2 = jnp.exp(s2 - lse)                            # [BK, BQ] = p.T
        if dropout > 0.0:
            keep2 = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk,
                                  dropout, transposed=True)
            inv = 1.0 / (1.0 - dropout)
            p2_drop = jnp.where(keep2, p2 * inv, 0.0)     # = w'.T * l ... w'
        else:
            p2_drop = p2
        dv_scr[:] += jax.lax.dot_general(
            p2_drop.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp2 = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BK, BQ] = dp.T
        if dropout > 0.0:
            dp2 = jnp.where(keep2, dp2 * inv, 0.0)
        ds2 = p2 * (dp2 - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds2.astype(q.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_fused_kernel(*refs, scale: float, causal: bool, S: int,
                      dropout: float = 0.0):
    """Whole-sequence fused backward: when one block covers S, compute the
    score/softmax replay ONCE and emit dq, dk, dv together — the split
    dq/dkv kernels each redo the s/p/exp work in their own iteration
    order (6 matmuls + 2 softmax replays vs 5 + 1 here)."""
    refs = list(refs)
    seed_ref = refs.pop(0) if dropout > 0.0 else None
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
     dq_ref, dk_ref, dv_ref) = refs
    bh = pl.program_id(0)
    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    lse = lse_ref[0, 0][:, None]                       # [S, 1]
    delta = delta_ref[0, 0][:, None]                   # [S, 1]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale    # [S, S]
    if causal:
        s = _causal_mask(s, 0, 0, S, S)
    p = jnp.exp(s - lse)                               # softmax replay
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # [S, S]
    if dropout > 0.0:
        keep = _dropout_keep(seed_ref[0, 0], bh, 0, 0, S, S, dropout)
        inv = 1.0 / (1.0 - dropout)
        p_drop = jnp.where(keep, p * inv, 0.0)
        dp = jnp.where(keep, dp * inv, 0.0)
    else:
        p_drop = p
    dv_ref[0] = jax.lax.dot_general(
        p_drop.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    ds = p * (dp - delta) * scale                      # [S, S]
    dsc = ds.astype(q.dtype)
    dq_ref[0] = jax.lax.dot_general(
        dsc, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dk_ref[0] = jax.lax.dot_general(
        dsc, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)


def _flash_bwd_fused(q, k, v, lse, do, delta, scale, causal, dropout, seed):
    BH, S, D = q.shape
    full = pl.BlockSpec((1, S, D), lambda b: (b, 0, 0))
    row = pl.BlockSpec((1, 1, S), lambda b: (b, 0, 0))
    in_specs = [full, full, full, full, row, row]
    args = (q, k, v, do, lse, delta)
    if dropout > 0.0:
        in_specs = [_seed_spec()] + in_specs
        args = (_seed_arr(seed),) + args
    return pl.pallas_call(
        functools.partial(_bwd_fused_kernel, scale=scale, causal=causal,
                          S=S, dropout=dropout),
        grid=(BH,),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, S, D), lambda b: (b, 0, 0))] * 3,
        out_shape=[jax.ShapeDtypeStruct((BH, S, D), q.dtype),
                   jax.ShapeDtypeStruct((BH, S, D), k.dtype),
                   jax.ShapeDtypeStruct((BH, S, D), v.dtype)],
        interpret=_interpret(),
    )(*args)


def _flash_bwd(q, k, v, o, lse, do, layout, scale: float, causal: bool,
               dropout: float = 0.0, seed=None, _blocks=None):
    BH, S, D = q.shape
    Sk = k.shape[1]
    has_layout = layout is not None
    if has_layout:
        bq = bk = S // layout.shape[-1]
    elif _blocks is not None:
        bq, bk = _blocks
    else:
        bq, bk = _pick_block_bwd(S, causal), _pick_block_bwd(Sk, causal)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True).transpose(0, 2, 1)  # [BH, 1, S]

    if _blocks is None and not has_layout and S == Sk and \
            _pick_block(S) == S and \
            os.environ.get("DS_FLASH_FUSED_BWD", "1") == "1":
        return _flash_bwd_fused(q, k, v, lse, do, delta, scale, causal,
                                dropout, seed)

    if _blocks is None and not has_layout:
        def run_at(tile):
            z = lambda s: jnp.zeros(s.shape, s.dtype)  # noqa: E731
            lse0 = jnp.zeros((BH, 1, S), jnp.float32)
            return _flash_bwd(z(q), z(k), z(v), z(o), lse0, z(do), None,
                              scale, causal, _blocks=tile)
        bq, bk = _resolve_blocks("flash_bwd", q, k, causal, (bq, bk),
                                 run_at)

    dq_specs = [
        _qkv_spec(bq, D, "q"),
        _qkv_spec(bk, D, "k"),
        _qkv_spec(bk, D, "k"),
        _qkv_spec(bq, D, "q"),
        pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
        pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
    ]
    dq_args = (q, k, v, do, lse, delta)
    if dropout > 0.0:
        dq_specs = [_seed_spec()] + dq_specs
        dq_args = (_seed_arr(seed),) + dq_args
    if has_layout:
        dq_specs = [_layout_spec(layout.shape[0], "dq")] + dq_specs
        dq_args = (_pad_layout(layout),) + dq_args
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, has_layout=has_layout,
                          dropout=dropout),
        grid=(BH, S // bq, Sk // bk),
        in_specs=dq_specs,
        out_specs=_qkv_spec(bq, D, "q"),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=_interpret(),
    )(*dq_args)

    dkv_specs = [
        _qkv_spec(bq, D, "qT"),
        _qkv_spec(bk, D, "kT"),
        _qkv_spec(bk, D, "kT"),
        _qkv_spec(bq, D, "qT"),
        pl.BlockSpec((1, 1, bq), lambda b, j, i: (b, 0, i)),
        pl.BlockSpec((1, 1, bq), lambda b, j, i: (b, 0, i)),
    ]
    dkv_args = (q, k, v, do, lse, delta)
    if dropout > 0.0:
        dkv_specs = [_seed_spec()] + dkv_specs
        dkv_args = (_seed_arr(seed),) + dkv_args
    if has_layout:
        dkv_specs = [_layout_spec(layout.shape[0], "dkv")] + dkv_specs
        dkv_args = (_pad_layout(layout),) + dkv_args
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, has_layout=has_layout,
                          dropout=dropout),
        grid=(BH, Sk // bk, S // bq),
        in_specs=dkv_specs,
        out_specs=[
            _qkv_spec(bk, D, "kT"),
            _qkv_spec(bk, D, "kT"),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Sk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(*dkv_args)
    return dq, dk, dv


# --------------------------------------------------------------------- #
# custom_vjp wrappers (dense/causal and block-sparse variants)
# --------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, seed, scale: float, causal: bool, dropout: float = 0.0):
    o, _ = _flash_fwd(q, k, v, None, scale, causal, dropout, seed)
    return o


def _tag_residuals(o, lse):
    """Name the flash residuals so remat policies can elect to SAVE them
    (``save_only_these_names``): pallas outputs aren't ``dot_general``s, so
    under ``checkpoint_dots`` the whole forward kernel would re-run in
    backward. transformer._remat_policy("dots_flash") keys on these names."""
    from jax.ad_checkpoint import checkpoint_name
    return checkpoint_name(o, "flash_out"), checkpoint_name(lse, "flash_lse")


def _flash_vjp_fwd(q, k, v, seed, scale, causal, dropout):
    o, lse = _flash_fwd(q, k, v, None, scale, causal, dropout, seed)
    o, lse = _tag_residuals(o, lse)
    return o, (q, k, v, seed, o, lse)


def _flash_vjp_bwd(scale, causal, dropout, res, do):
    q, k, v, seed, o, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, None, scale, causal,
                            dropout, seed)
    return dq, dk, dv, None


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash_sparse(q, k, v, layout, seed, scale: float, causal: bool,
                  dropout: float = 0.0):
    o, _ = _flash_fwd(q, k, v, layout, scale, causal, dropout, seed)
    return o


def _flash_sparse_vjp_fwd(q, k, v, layout, seed, scale, causal, dropout):
    o, lse = _flash_fwd(q, k, v, layout, scale, causal, dropout, seed)
    o, lse = _tag_residuals(o, lse)
    return o, (q, k, v, layout, seed, o, lse)


def _flash_sparse_vjp_bwd(scale, causal, dropout, res, do):
    q, k, v, layout, seed, o, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, layout, scale, causal,
                            dropout, seed)
    return dq, dk, dv, None, None


_flash_sparse.defvjp(_flash_sparse_vjp_fwd, _flash_sparse_vjp_bwd)


def _lut_fits_smem(layout, budget_bytes: int = 384 * 1024) -> bool:
    """Flattened-nnz LUTs must fit TPU scalar memory (~1 MB on v5e; leave
    headroom), and every row/column must have >=1 active block (else its
    output block would never be written by the nnz-grid kernel)."""
    import numpy as np
    lay = np.asarray(layout) != 0
    row_cnt = lay.sum(-1)
    col_cnt = lay.sum(-2)
    if (row_cnt == 0).any() or (col_cnt == 0).any():
        return False
    H = lay.shape[0]
    nnz = int(lay.reshape(H, -1).sum(-1).max())
    # qid+kid+kmask ([H, NNZ] each) for both orientations + the two nnz
    # vectors (conservative: k-widening only shrinks NNZ).
    bytes_needed = 4 * H * (6 * nnz + 2)
    return bytes_needed <= budget_bytes


def _to_bh(x):
    B, S, nH, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * nH, S, D)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    mask: Optional[jnp.ndarray] = None, causal: bool = False,
                    attn_dropout: float = 0.0, rng=None,
                    deterministic: bool = True,
                    layout: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Drop-in for models.transformer.dense_attention: q,k,v [B,S,nH,dH].

    ``layout`` [nH, S//block, S//block] int32 enables block-sparse mode.
    Attention dropout runs IN-KERNEL (mask regenerated in backward from the
    seed — see _dropout_keep); only additive masks and non-128-aligned
    sequences fall back to the dense path (the reference keeps a non-fused
    path for the same cases, transformer.py:153).
    """
    B, S, nH, D = q.shape
    layout_block = None
    if layout is not None:
        if layout.ndim != 3 or layout.shape[0] != nH or \
                layout.shape[-2] != layout.shape[-1] or \
                S % layout.shape[-1] != 0:
            raise ValueError(
                f"layout shape {layout.shape} incompatible with "
                f"{nH} heads / seq {S}: need (num_heads, S//block, S//block)")
        # The Pallas path needs 128-aligned kernel blocks; a sparse layout
        # fixes the block to S // n_blocks, which must itself be 128-aligned.
        layout_block = S // layout.shape[-1]
    dropout = float(attn_dropout) if (attn_dropout > 0.0 and not deterministic
                                      and rng is not None) else 0.0
    if mask is not None or S % 128 != 0 \
            or (layout_block is not None and layout_block % 128 != 0):
        from ..models.transformer import dense_attention
        if layout is not None:
            mask = _layout_to_mask(layout, S, mask)
        return dense_attention(q, k, v, mask=mask, causal=causal,
                               attn_dropout=attn_dropout, rng=rng,
                               deterministic=deterministic)
    scale = 1.0 / math.sqrt(D)
    seed = jax.random.bits(rng, (), jnp.uint32).astype(jnp.int32) \
        if dropout > 0.0 else jnp.zeros((), jnp.int32)
    qt, kt, vt = _to_bh(q), _to_bh(k), _to_bh(v)
    if layout is None:
        o = _flash(qt, kt, vt, seed, scale, causal, dropout)
    elif not isinstance(layout, jax.core.Tracer) and \
            _lut_fits_smem(layout):
        # Concrete layout (the normal case): LUT-driven kernels touch only
        # the live blocks — compute/bandwidth scale with nnz, not S^2
        # (reference csrc/sparse_attention LUT design; see sparse_flash.py).
        from .sparse_flash import sparse_flash_attention
        o = sparse_flash_attention(qt, kt, vt, layout, causal=causal,
                                   scale=scale, seed=seed, dropout=dropout)
    else:
        # Traced layout, or LUTs too large for SMEM (e.g. global-attention
        # rows at huge S make max-nnz ~ nK): full-grid gated kernel.
        o = _flash_sparse(qt, kt, vt, jnp.asarray(layout, jnp.int32),
                          seed, scale, causal, dropout)
    return o.reshape(B, nH, S, D).transpose(0, 2, 1, 3)


def _layout_to_mask(layout, seq_len: int, mask):
    """Expand a block layout to an additive [1, nH, S, S] element mask
    (dense-fallback semantics of the sparse path)."""
    layout = jnp.asarray(layout)
    block = seq_len // layout.shape[-1]
    elem = jnp.repeat(jnp.repeat(layout, block, axis=-2), block, axis=-1)
    add = jnp.where(elem[None] != 0, 0.0, NEG_INF).astype(jnp.float32)
    return add if mask is None else add + mask


def auto_attention(q, k, v, mask=None, causal=False, attn_dropout=0.0,
                   rng=None, deterministic=True):
    """Best attention for the current backend: flash kernels on TPU, plain
    XLA dense elsewhere (Pallas interpret mode is for correctness tests,
    not speed)."""
    if jax.default_backend() == "tpu":
        return flash_attention(q, k, v, mask=mask, causal=causal,
                               attn_dropout=attn_dropout, rng=rng,
                               deterministic=deterministic)
    from ..models.transformer import dense_attention
    return dense_attention(q, k, v, mask=mask, causal=causal,
                           attn_dropout=attn_dropout, rng=rng,
                           deterministic=deterministic)
