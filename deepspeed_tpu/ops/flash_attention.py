"""Flash attention — Pallas TPU kernels with custom VJP.

The TPU-native replacement for the reference's fused attention core inside
the transformer kernel (csrc/transformer/softmax_kernels.cu +
strided_batch_gemm.h: QK^T → scale+mask softmax → AV, with saved softmax
output replayed in backward). On TPU the dense [S,S] fp32 score tensor is
the HBM bottleneck, so we never materialize it: the classic flash pattern
computes attention block-by-block in VMEM with a running (max, sum)
softmax, and the backward recomputes scores per block from the saved
logsumexp — the same memory story as the reference's
``attn_dropout_checkpoint`` knob taken to its limit.

Layout: kernels run over [BH, S, D] (batch×heads flattened, head_dim last).
Grid is (BH, q_blocks, k_blocks); the innermost (k) dimension iterates
sequentially on TPU so VMEM scratch carries the running softmax state
across k-blocks of one q-block. Causal skips fully-masked k-blocks.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend bits are importable everywhere; interpret=True runs on CPU
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block(s: int, target: int = 512) -> int:
    for b in (target, 256, 128):
        if s % b == 0:
            return b
    return s  # small sequences: single block


# --------------------------------------------------------------------- #
# Forward kernel
# --------------------------------------------------------------------- #
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale: float, causal: bool, bq: int, bk: int):
    qi, kj = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: skip k-blocks strictly above the diagonal band.
    run = True
    if causal:
        run = kj * bk < (qi + 1) * bq

    @pl.when(run)
    def _compute():
        q = q_ref[0]                       # [BQ, D]
        k = k_ref[0]                       # [BK, D]
        v = v_ref[0]                       # [BK, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [BQ, BK]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + qi * bq
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + kj * bk
            s = jnp.where(rows >= cols, s, NEG_INF)

        m_prev = m_scr[:, 0:1]                            # [BQ, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)                   # [BQ, 1]
        p = jnp.exp(s - m_new)                            # [BQ, BK]
        l_new = l_scr[:, 0:1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BQ, D]
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:, 0:1] = m_new
        l_scr[:, 0:1] = l_new

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_scr[:, 0] + jnp.log(l_safe[:, 0]))


def _flash_fwd(q, k, v, scale: float, causal: bool):
    """q,k,v: [BH, S, D] → (o [BH,S,D], lse [BH,S] f32)."""
    BH, S, D = q.shape
    Sk = k.shape[1]
    bq, bk = _pick_block(S), _pick_block(Sk)
    grid = (BH, S // bq, Sk // bk)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------- #
# Backward kernels
# --------------------------------------------------------------------- #
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_scr, *, scale: float, causal: bool, bq: int, bk: int):
    qi, kj = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = True
    if causal:
        run = kj * bk < (qi + 1) * bq

    @pl.when(run)
    def _compute():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        do = do_ref[0]                                    # [BQ, D]
        lse = lse_ref[0, 0][:, None]                      # [BQ, 1]
        delta = delta_ref[0, 0][:, None]                  # [BQ, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + qi * bq
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + kj * bk
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                              # softmax [BQ, BK]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BQ, BK]
        ds = p * (dp - delta) * scale
        acc_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale: float, causal: bool, bq: int, bk: int):
    kj, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if causal:
        run = kj * bk < (qi + 1) * bq

    @pl.when(run)
    def _compute():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0][None, :]                      # [1, BQ]
        delta = delta_ref[0, 0][None, :]                  # [1, BQ]
        # s2[i, j] = k_i · q_j (transposed score block)
        s2 = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [BK, BQ]
        if causal:
            krows = jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 0) + kj * bk
            qcols = jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 1) + qi * bq
            s2 = jnp.where(qcols >= krows, s2, NEG_INF)
        p2 = jnp.exp(s2 - lse)                            # [BK, BQ] = p.T
        dv_scr[:] += jax.lax.dot_general(
            p2.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp2 = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BK, BQ] = dp.T
        ds2 = p2 * (dp2 - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds2.astype(q.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, scale: float, causal: bool):
    BH, S, D = q.shape
    Sk = k.shape[1]
    bq, bk = _pick_block(S), _pick_block(Sk)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True).transpose(0, 2, 1)  # [BH, 1, S]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk),
        grid=(BH, S // bq, Sk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk),
        grid=(BH, Sk // bk, S // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((1, 1, bq), lambda b, j, i: (b, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Sk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------------- #
# custom_vjp wrapper
# --------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, scale: float, causal: bool):
    o, _ = _flash_fwd(q, k, v, scale, causal)
    return o


def _flash_vjp_fwd(q, k, v, scale, causal):
    o, lse = _flash_fwd(q, k, v, scale, causal)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(scale, causal, res, do):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, do, scale, causal)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    mask: Optional[jnp.ndarray] = None, causal: bool = False,
                    attn_dropout: float = 0.0, rng=None,
                    deterministic: bool = True) -> jnp.ndarray:
    """Drop-in for models.transformer.dense_attention: q,k,v [B,S,nH,dH].

    Falls back to the dense path for additive masks or attention dropout
    (the reference keeps a non-fused path for the same cases,
    transformer.py:153 vs the vanilla BertSelfAttention it replaces).
    """
    if mask is not None or (attn_dropout > 0.0 and not deterministic):
        from ..models.transformer import dense_attention
        return dense_attention(q, k, v, mask=mask, causal=causal,
                               attn_dropout=attn_dropout, rng=rng,
                               deterministic=deterministic)
    B, S, nH, D = q.shape
    if S % 128 != 0:
        from ..models.transformer import dense_attention
        return dense_attention(q, k, v, mask=mask, causal=causal,
                               attn_dropout=attn_dropout, rng=rng,
                               deterministic=deterministic)
    scale = 1.0 / math.sqrt(D)
    qt = q.transpose(0, 2, 1, 3).reshape(B * nH, S, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * nH, S, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * nH, S, D)
    o = _flash(qt, kt, vt, scale, causal)
    return o.reshape(B, nH, S, D).transpose(0, 2, 1, 3)


def auto_attention(q, k, v, mask=None, causal=False, attn_dropout=0.0,
                   rng=None, deterministic=True):
    """Best attention for the current backend: flash kernels on TPU, plain
    XLA dense elsewhere (Pallas interpret mode is for correctness tests,
    not speed)."""
    if jax.default_backend() == "tpu":
        return flash_attention(q, k, v, mask=mask, causal=causal,
                               attn_dropout=attn_dropout, rng=rng,
                               deterministic=deterministic)
    from ..models.transformer import dense_attention
    return dense_attention(q, k, v, mask=mask, causal=causal,
                           attn_dropout=attn_dropout, rng=rng,
                           deterministic=deterministic)
