"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

Long-context capability beyond the reference snapshot: v0.3.11 has no
sequence/context parallelism (its ``slice_parallel`` accessors alias the
model-parallel axis, topology.py:445-455) and handles long sequences
algorithmically via block-sparse attention. Ring attention shards the
SEQUENCE across chips so attention memory AND compute scale 1/sp per chip
while remaining exact — the modern long-context story (Ring Attention /
Context Parallelism), built here from the same primitives as the rest of
the framework: ``shard_map`` over the ``seq`` axis, ``lax.ppermute``
rotations over ICI, and flash-style online-softmax merging.

Algorithm (per rank, holding local q,k,v [B, S/sp, nH, dH]):
  for step in 0..sp-1:
      partial = flash(q_local, k_chunk, v_chunk) -> (o_chunk, lse_chunk)
      merge into (o, lse) with the online-softmax rule
      (k_chunk, v_chunk) <- ppermute from the next rank   # ring hop
  o is EXACT full attention of q_local against the whole sequence.

Causal masking uses global positions: chunk c covers columns
[c*S_loc, (c+1)*S_loc); a rank skips nothing (uniform SPMD program) but
masks per-element, so correctness holds for any rotation order.

Backward is jax autodiff through the scan: the ppermute transposes into
counter-rotations of the gradient chunks — the reverse ring — and the
per-chunk attention recomputes under ``jax.checkpoint`` (memory stays
O(S_loc) per rank).
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel import comm
from ..parallel.topology import SP_AXIS

NEG_INF = -1e30


def _chunk_attention(q, k, v, scale: float, causal: bool,
                     q_start, k_start):
    """Dense attention of local q against one k/v chunk, returning
    (acc [B,Sq,nH,dH] fp32 UNnormalized, m [B,nH,Sq] rowmax,
    l [B,nH,Sq] rowsum) for online-softmax merging. Global positions
    ``q_start``/``k_start`` drive the causal mask."""
    B, Sq, nH, D = q.shape
    Sk = k.shape[1]
    s = jnp.einsum("bsnd,btnd->bnst", q, k).astype(jnp.float32) * scale
    if causal:
        rows = q_start + lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        cols = k_start + lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where((rows >= cols)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                               # [B,nH,Sq]
    # rows fully masked (causal, all cols in the future): exp(NEG_INF-m)=...
    # guard by clamping m so exp() sees finite numbers.
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe[..., None])                    # [B,nH,Sq,Sk]
    l = jnp.sum(p, axis=-1)                               # [B,nH,Sq]
    acc = jnp.einsum("bnst,btnd->bsnd", p.astype(v.dtype), v)
    return acc.astype(jnp.float32), m_safe, l


def _merge(acc1, m1, l1, acc2, m2, l2):
    """Online-softmax merge of two partial attention states."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    acc = acc1 * a1.transpose(0, 2, 1)[..., None] + \
        acc2 * a2.transpose(0, 2, 1)[..., None]
    l = l1 * a1 + l2 * a2
    return acc, m, l


def _ring_attention_local(q, k, v, *, scale: float, causal: bool,
                          sp: int, axis_name: str):
    """Runs inside shard_map: q,k,v are the rank-local [B, S_loc, nH, dH]."""
    B, S_loc, nH, D = q.shape
    rank = lax.axis_index(axis_name)
    q_start = rank * S_loc

    perm = [(i, (i - 1) % sp) for i in range(sp)]  # pull chunks from right

    def step(carry, i):
        acc, m, l, kc, vc = carry
        # chunk currently held = the one that started on rank (rank + i)
        k_start = ((rank + i) % sp) * S_loc
        acc2, m2, l2 = _chunk_attention(q, kc, vc, scale, causal,
                                        q_start, k_start)
        acc, m, l = _merge(acc, m, l, acc2, m2, l2)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (acc, m, l, kc, vc), None

    # Carries must be marked varying-over-seq like the data they merge with.
    vary = lambda x: comm.pvary(x, axis_name)
    acc0 = vary(jnp.zeros((B, S_loc, nH, D), jnp.float32))
    m0 = vary(jnp.full((B, nH, S_loc), NEG_INF / 2, jnp.float32))
    l0 = vary(jnp.zeros((B, nH, S_loc), jnp.float32))
    (acc, m, l, _, _), _ = lax.scan(
        jax.checkpoint(step), (acc0, m0, l0, k, v), jnp.arange(sp))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, causal: bool = False,
                   axis_name: str = SP_AXIS) -> jnp.ndarray:
    """Exact attention with the sequence sharded over ``axis_name``.

    q,k,v: [B, S, nH, dH] GLOBAL arrays (jit/GSPMD handles placement; the
    sequence dim is split over the seq axis inside). Returns [B, S, nH, dH].
    Per-chip attention memory/compute is 1/sp of the full sequence.
    """
    sp = int(mesh.shape.get(axis_name, 1))
    B, S, nH, D = q.shape
    scale = 1.0 / math.sqrt(D)
    if sp <= 1:
        from ..models.transformer import dense_attention
        return dense_attention(q, k, v, mask=None, causal=causal)
    if S % sp != 0:
        raise ValueError(f"sequence {S} not divisible by seq axis {sp}")

    # Only the seq axis is manual; batch/model axes stay auto (GSPMD
    # partitions them outside the manual region), so the specs mention
    # ONLY the manual axis.
    spec = P(None, axis_name, None, None)
    fn = comm.shard_map(
        partial(_ring_attention_local, scale=scale, causal=causal,
                sp=sp, axis_name=axis_name),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names={axis_name})
    return fn(q, k, v)


def ring_attention_fn(mesh: Mesh, axis_name: str = SP_AXIS):
    """AttentionFn adapter for models.transformer (attention_fn plug)."""
    def attn(q, k, v, mask=None, causal=False, attn_dropout=0.0, rng=None,
             deterministic=True):
        if mask is not None or (attn_dropout > 0.0 and not deterministic):
            raise NotImplementedError(
                "ring attention supports causal/bidirectional without "
                "additive masks or attention dropout (match the reference "
                "posture: dropout lives outside the sp path)")
        return ring_attention(q, k, v, mesh, causal=causal,
                              axis_name=axis_name)
    return attn
