"""Grouped-GEMM expert FFN: one Pallas kernel over ``[E,C,H] x [E,H,F]``.

MoE expert compute was a batched ``jnp.einsum`` over the capacity-bucketed
dispatch buffer (``moe/layer.py``): two einsums plus separate bias-add and
GELU passes, each a full HBM round-trip of the ``[E, C, F]`` intermediate.
This module is the kernel-tier replacement (ROADMAP item 5's grouped-GEMM
rung; the reference's fused transformer kernels play the same role on
GPU): a grouped matmul whose grid runs experts x row blocks x col blocks,
accumulates on the MXU in fp32 (``preferred_element_type``), and fuses the
bias + GELU epilogue in-register via the exact ``_gelu_f32``/``_dgelu_f32``
forms ``fused_elementwise`` ships — so the up-projection's activation
never makes a separate pass over HBM.

Structure:

- ``_grouped_matmul(a [E,M,K], b [E,K,N], bias [E,N]?, act?)`` — the raw
  ``pallas_call`` (no autodiff).  Block sizes resolve through
  ``ops.autotune`` (kernel key ``grouped_gemm``) with the same 12 MiB
  VMEM budget math as ``fused_elementwise``; ``DS_AUTOTUNE=0`` or CPU
  pins the heuristic.  Epilogue numerics mirror ``fused_bias_gelu``:
  ``z = round(acc + bias)`` once to the storage dtype, GELU evaluated in
  fp32 on z, rounded once at the output.
- ``grouped_ffn(x, w1, b1, w2, b2, exact)`` — the expert FFN as a
  ``jax.custom_vjp``: forward is two fused grouped GEMMs; backward
  RECOMPUTES the pre-activation from (x, w1, b1) instead of saving the
  ``[E, C, F]`` intermediate (the ``normalize_invertible`` idea again —
  no fp32 expert-wide residual ever materializes, which is what keeps
  the moe lint flagship's materialization pass clean), and expresses
  every gradient contraction as the SAME grouped kernel on swapped
  axes.

Numerics contract (tests/test_moe.py): vs the einsum path, fp32 agrees
to a few f32 ulp (cross-program dot association — the PR-1 tolerance
class), bf16 to ~2 bf16 ulp (the fused epilogue rounds once where the
unfused chain rounds per op).  ``num_experts=1`` keeps its dense
bit-parity through the DEFAULT dispatch ("auto" = off on CPU, einsum);
with the kernel forced on it lands in the ulp class above.

Sharding: the kernel is shard-LOCAL.  Under ep > 1 it runs inside the
fully-manual ``expert`` shard_map scope on the ``[E/ep, ...]`` slices —
``pallas_call`` is opaque to GSPMD, and here every operand is already
device-local, so no collective moves (the ``materialization`` lint pass
gates that, same as the elementwise kernels).

Enable/disable mirrors ``TransformerConfig.fused_kernels``:
``MoEConfig.grouped_gemm`` is ``"auto"`` (TPU on / CPU off, overridable
with DS_GROUPED_GEMM=0/1) or forced True/False — True on CPU runs
interpret mode, which is how tier-1's dp=8 mesh exercises the kernel.
The knob is cfg-static: it changes the compiled program, never the
compiled signature, and checkpoints resume across it.
"""
from __future__ import annotations

import functools
import math
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend bits are importable everywhere; interpret=True on CPU
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from . import autotune
from .fused_elementwise import _dgelu_f32, _gelu_f32

_LANE = 128
_VMEM_BUDGET = 12 * 2 ** 20          # same budget math as fused_elementwise
_ENV_KNOB = "DS_GROUPED_GEMM"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def grouped_gemm_enabled(flag="auto") -> bool:
    """Resolve ``MoEConfig.grouped_gemm`` to on/off — the same contract
    as ``fused_elementwise_enabled``: True/False forced, "auto" on
    exactly when the backend is TPU, DS_GROUPED_GEMM=0/1 overrides
    "auto" (the bench/ablation switch)."""
    if flag is True or flag is False:
        return bool(flag)
    env = os.environ.get(_ENV_KNOB)
    if env in ("0", "1"):
        return env == "1"
    return jax.default_backend() == "tpu"


def _pad_to(n: int, q: int) -> int:
    return -(-n // q) * q


def _tile_heuristic(M: int, K: int, N: int, itemsize: int
                    ) -> Tuple[int, int]:
    """(bm, bn): bn is the largest power-of-two column block <= 512 (and
    <= lane-padded N); bm starts at 128 — clamped down to the padded row
    count for small capacities so a C=40 bucket doesn't run a 128-row
    block 69% empty — then halves while the fp32 working set (a block +
    b block + acc) exceeds the VMEM budget."""
    Kp = _pad_to(K, _LANE)
    Np = _pad_to(N, _LANE)
    bn = 512
    while bn > _LANE and bn > Np:
        bn //= 2
    bm = 128
    while bm > 16 and bm >= 2 * _pad_to(M, bm // 2):
        bm //= 2
    while bm > 16 and 4 * (bm * Kp + Kp * bn + bm * bn) > _VMEM_BUDGET:
        bm //= 2
    return bm, bn


def _tile_candidates(M: int, K: int, N: int) -> Tuple[Tuple[int, int], ...]:
    Kp = _pad_to(K, _LANE)
    Np = _pad_to(N, _LANE)

    def fits(bm, bn):
        return 4 * (bm * Kp + Kp * bn + bm * bn) <= _VMEM_BUDGET

    out = []
    for bm in (16, 32, 64, 128, 256):
        for bn in (128, 256, 512):
            if bn <= Np and fits(bm, bn):
                out.append((bm, bn))
    return tuple(out)


def _gg_kernel(a_ref, b_ref, bias_ref, o_ref, *, act: Optional[str],
               has_bias: bool, out_dtype):
    """One (expert, row-block, col-block) grid step: fp32 MXU dot +
    fused epilogue. Epilogue rounding mirrors _gelu_fwd_kernel: the
    bias sum rounds ONCE to the storage dtype before GELU reads it."""
    acc = jax.lax.dot_general(
        a_ref[0], b_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # [bm, bn] f32
    if has_bias:
        z = (acc + bias_ref[...].astype(jnp.float32)).astype(out_dtype)
    else:
        z = acc.astype(out_dtype)
    if act is not None:
        z = _gelu_f32(z.astype(jnp.float32),
                      exact=(act == "gelu_exact")).astype(out_dtype)
    o_ref[0] = z


def _spec(block, index_map):
    if pltpu is not None and jax.default_backend() == "tpu":
        return pl.BlockSpec(block, index_map, memory_space=pltpu.VMEM)
    return pl.BlockSpec(block, index_map)


def _grouped_matmul(a: jax.Array, b: jax.Array,
                    bias: Optional[jax.Array] = None,
                    act: Optional[str] = None,
                    out_dtype=None, _tile=None) -> jax.Array:
    """``out[e] = act(a[e] @ b[e] + bias[e])`` for every expert e.

    ``a``: [E, M, K]; ``b``: [E, K, N]; ``bias``: [E, N] or None; ``act``
    None | "gelu_tanh" | "gelu_exact".  fp32 accumulation, one fused
    epilogue, output in ``out_dtype`` (default ``a.dtype``).  ``_tile``
    is the autotune recursion guard (the measure runner pins it).
    """
    E, M, K = a.shape
    Eb, Kb, N = b.shape
    assert E == Eb and K == Kb, (a.shape, b.shape)
    out_dtype = out_dtype or a.dtype

    if _tile is None:
        bm, bn = _tile_heuristic(M, K, N, jnp.dtype(a.dtype).itemsize)
        measure = None
        if autotune.search_allowed():
            def runner(tile):
                da = jnp.zeros((E, M, K), a.dtype)
                db = jnp.zeros((E, K, N), b.dtype)
                dbias = None if bias is None else \
                    jnp.zeros((E, N), jnp.float32)
                return _grouped_matmul(da, db, dbias, act, out_dtype,
                                       _tile=tile)
            measure = autotune.measure_from_runner(runner)
        bm, bn = autotune.resolve(
            "grouped_gemm", (E, M, K, N), str(jnp.dtype(a.dtype)),
            (bm, bn), _tile_candidates(M, K, N), measure)
    else:
        bm, bn = _tile

    Mp, Kp, Np = _pad_to(M, bm), _pad_to(K, _LANE), _pad_to(N, bn)
    if (Mp, Kp) != (M, K):
        a = jnp.pad(a, ((0, 0), (0, Mp - M), (0, Kp - K)))
    if (Kp, Np) != (K, N):
        b = jnp.pad(b, ((0, 0), (0, Kp - K), (0, Np - N)))
    has_bias = bias is not None
    if has_bias:
        bias2 = bias.astype(jnp.float32)
        if Np != N:
            bias2 = jnp.pad(bias2, ((0, 0), (0, Np - N)))
    else:  # dummy broadcast row (the _ln_forward no-residual idiom)
        bias2 = jnp.zeros((E, Np), jnp.float32)

    kernel = functools.partial(_gg_kernel, act=act, has_bias=has_bias,
                               out_dtype=out_dtype)
    out = pl.pallas_call(
        kernel,
        grid=(E, Mp // bm, Np // bn),
        in_specs=[
            _spec((1, bm, Kp), lambda e, i, j: (e, i, 0)),
            _spec((1, Kp, bn), lambda e, i, j: (e, 0, j)),
            _spec((1, bn), lambda e, i, j: (e, j)),
        ],
        out_specs=_spec((1, bm, bn), lambda e, i, j: (e, i, j)),
        out_shape=jax.ShapeDtypeStruct((E, Mp, Np), out_dtype),
        interpret=_interpret(),
    )(a, b, bias2)
    return out[:, :M, :N]


def _swap(x: jax.Array) -> jax.Array:
    return jnp.swapaxes(x, 1, 2)


def _act_name(exact: bool) -> str:
    return "gelu_exact" if exact else "gelu_tanh"


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def grouped_ffn(x, w1, b1, w2, b2, exact: bool = False):
    """The expert FFN ``gelu(x @ w1 + b1) @ w2 + b2`` per expert, as two
    fused grouped GEMMs.  ``x``: [E, C, H]; ``w1``: [E, H, F]; ``b1``:
    [E, F]; ``w2``: [E, F, H]; ``b2``: [E, H].  Default GELU is the tanh
    approximation (``exact=True`` selects erf — ``cfg.gelu_exact``)."""
    h = _grouped_matmul(x, w1, bias=b1, act=_act_name(exact))
    return _grouped_matmul(h, w2, bias=b2)


def _gff_fwd(x, w1, b1, w2, b2, exact):
    # Residuals are the INPUTS only: the [E, C, F] pre-activation is
    # recomputed in the backward rather than saved (materialization-pass
    # clean; recompute is one grouped GEMM the bwd needs anyway).
    return grouped_ffn(x, w1, b1, w2, b2, exact), (x, w1, b1, w2, b2)


def _gff_bwd(exact, res, dy):
    x, w1, b1, w2, b2 = res
    z1 = _grouped_matmul(x, w1, bias=b1)               # [E, C, F] pre-act
    z32 = z1.astype(jnp.float32)
    h = _gelu_f32(z32, exact).astype(z1.dtype)
    dh = _grouped_matmul(dy, _swap(w2))                # [E, C, F]
    dz = (dh.astype(jnp.float32) *
          _dgelu_f32(z32, exact)).astype(z1.dtype)
    dw2 = _grouped_matmul(_swap(h), dy).astype(w2.dtype)
    db2 = jnp.sum(dy.astype(jnp.float32), axis=1).astype(b2.dtype)
    dw1 = _grouped_matmul(_swap(x), dz).astype(w1.dtype)
    db1 = jnp.sum(dz.astype(jnp.float32), axis=1).astype(b1.dtype)
    dx = _grouped_matmul(dz, _swap(w1))
    return dx, dw1, db1, dw2, db2


grouped_ffn.defvjp(_gff_fwd, _gff_bwd)


__all__ = ["grouped_ffn", "grouped_gemm_enabled"]
