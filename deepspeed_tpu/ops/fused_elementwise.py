"""Fused elementwise Pallas kernels: residual-add+LayerNorm and the
bias+GELU epilogue of the FFN up-projection.

Why these exist (ROADMAP item 2, the non-GEMM third of the step):
``profile_matmul_bound.py`` puts the pure-GEMM floor of the bench step at
~2/3 of the achieved time; part of the rest is elementwise passes XLA
schedules as separate HBM round-trips — LayerNorm reads the residual
stream, computes mean/var in fp32, and writes it back; the residual add
that feeds it is another full read+write; GELU and its bias add are two
more.  The reference attacked the same class of overhead with fused CUDA
transformer kernels (``csrc/transformer/normalize_kernels.cu``,
``gelu_kernels.cu``); the TPU-native answer is Pallas row kernels that
make the one-pass property structural:

- ``fused_layer_norm``: LN over the last axis, fp32 statistics, one read
  of x and one write of y (fwd) — plus a custom-vjp backward kernel that
  RECOMPUTES mean/rstd in-block instead of saving them (the
  ``normalize_invertible`` idea: stats are rank-1 per row, recompute is
  cheaper than an HBM round-trip).
- ``fused_residual_layer_norm``: ``s = x + delta; y = LN(s)`` in one
  pass, returning BOTH (the residual stream continues from ``s``).  The
  backward fuses the LN input-gradient with the pass-through residual
  cotangent, so the residual stream's gradient is also one pass.
- ``fused_bias_gelu``: ``gelu(y + bias)`` (tanh approximation by
  default, exact-erf behind a flag) with the analytic derivative in the
  backward kernel — no saved activations beyond the matmul output that
  already exists.

Numerics contract (tests/test_fused_ln.py): all statistics and
transcendentals evaluate in fp32 exactly like the jnp reference
(``models.transformer.layer_norm`` / ``jax.nn.gelu``); fp32 tensors
agree with the reference to <= a few f32 ulp (cross-program reduction
association — the PR-1 FMA-contraction tolerance class), bf16 tensors to
<= 2 bf16 ulp (the fused path rounds ONCE at the output where the
unfused chain rounds per op — the fused value is the more accurate one).

Sharding caveat (same class as ``ops/flash_attention``): a
``pallas_call`` is opaque to GSPMD, so under a mesh that shards
activations *declaratively* XLA gathers the operand around the kernel.
Every hot path that enables these kernels runs them where tensors are
already device-local: the ZeRO-2 engines' explicit shard_map gradient
path, the single-chip bench, and the serving decode/prefill programs
(slot-sharded caches enter via their own shard_map-free slot math).  The
``materialization`` lint pass is the watchdog: an activation gather
around the kernel shows up as a tree-scale buffer and fails CI.

Enable/disable: resolved per model config (``TransformerConfig.
fused_kernels``): ``"auto"`` = on when the backend is TPU, off on CPU
(interpret-mode Pallas is a correctness tool, not a fast path);
``DS_FUSED_ELEMENTWISE=0/1`` overrides "auto" (the bench ablation knob);
``True``/``False`` force — True on CPU runs the kernels in interpret
mode, which is how the tier-1 dp=8 mesh tests them.
"""
from __future__ import annotations

import functools
import math
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU backend bits are importable everywhere; interpret=True on CPU
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from . import autotune

_LANE = 128
_SQRT_2_OVER_PI = math.sqrt(2.0 / math.pi)
_GELU_C = 0.044715
_ENV_KNOB = "DS_FUSED_ELEMENTWISE"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def fused_elementwise_enabled(flag="auto") -> bool:
    """Resolve a config knob value to on/off.

    ``True``/``False`` are forced; ``"auto"`` (the TransformerConfig
    default) is on exactly when the backend is TPU, overridable with
    DS_FUSED_ELEMENTWISE=0/1 (the bench/ablation switch).
    """
    if flag is True or flag is False:
        return bool(flag)
    env = os.environ.get(_ENV_KNOB)
    if env in ("0", "1"):
        return env == "1"
    return jax.default_backend() == "tpu"


_VMEM_BUDGET = 12 * 2 ** 20


def _geom(rows: int, H: int, n_bufs: int, kernel: str = None,
          dtype=None, runner=None, rb: int = None
          ) -> Tuple[int, int, int]:
    """(rows_pad, Hpad, rb): lane-pad H to a 128 multiple, pick the
    largest power-of-two row block whose ``n_bufs`` fp32 copies fit a
    conservative VMEM budget, pad rows to a block multiple.

    When ``kernel`` is given the row block resolves through
    ``ops.autotune`` (heuristic = the budget loop below, candidates =
    powers of two under the same budget); DS_AUTOTUNE=0 and CPU reduce
    to the heuristic bit-for-bit.  ``rb`` pins the block (the autotune
    measure runner's recursion guard)."""
    Hpad = -(-H // _LANE) * _LANE
    if rb is None:
        rb = 128
        while rb > 16 and rb * Hpad * 4 * n_bufs > _VMEM_BUDGET:
            rb //= 2
        if kernel is not None:
            cands = autotune.pow2_candidates(
                16, 256, lambda c: c * Hpad * 4 * n_bufs <= _VMEM_BUDGET)
            measure = autotune.measure_from_runner(runner) \
                if (runner is not None and autotune.search_allowed()) \
                else None
            rb = autotune.resolve(kernel, (rows, H, n_bufs),
                                  str(jnp.dtype(dtype or jnp.float32)),
                                  rb, cands, measure)
    rows_pad = -(-rows // rb) * rb
    return rows_pad, Hpad, rb


def _row_spec(rb: int, Hpad: int):
    if pltpu is not None and jax.default_backend() == "tpu":
        return pl.BlockSpec((rb, Hpad), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    return pl.BlockSpec((rb, Hpad), lambda i: (i, 0))


def _whole_spec(Hpad: int):
    """(1, Hpad) broadcast block (scale/bias rows, per-grid partials) —
    the same sublane-1 block shape the fused-optimizer sqnorm kernel
    ships on TPU."""
    if pltpu is not None and jax.default_backend() == "tpu":
        return pl.BlockSpec((1, Hpad), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    return pl.BlockSpec((1, Hpad), lambda i: (0, 0))


def _part_spec(Hpad: int):
    if pltpu is not None and jax.default_backend() == "tpu":
        return pl.BlockSpec((1, Hpad), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    return pl.BlockSpec((1, Hpad), lambda i: (i, 0))


def _pad2(x2: jax.Array, rows_pad: int, Hpad: int) -> jax.Array:
    r, h = x2.shape
    if rows_pad > r or Hpad > h:
        x2 = jnp.pad(x2, ((0, rows_pad - r), (0, Hpad - h)))
    return x2


def _pad_row(v: jax.Array, Hpad: int) -> jax.Array:
    if Hpad > v.shape[0]:
        v = jnp.pad(v, (0, Hpad - v.shape[0]))
    return v.reshape(1, Hpad)


def _col_mask(shape, H: int):
    """True on real columns (H may be lane-padded)."""
    return lax.broadcasted_iota(jnp.int32, shape, 1) < H


# --------------------------------------------------------------------- #
# LayerNorm kernels
# --------------------------------------------------------------------- #
def _ln_stats(xs: jax.Array, H: int, Hpad: int, eps: float):
    """Row mean / rstd in fp32; pad columns are zero so they drop out of
    the mean for free, the variance masks them explicitly."""
    mean = jnp.sum(xs, axis=-1, keepdims=True) / H
    c = xs - mean
    if Hpad != H:
        c = jnp.where(_col_mask(c.shape, H), c, 0.0)
    var = jnp.sum(c * c, axis=-1, keepdims=True) / H
    return mean, lax.rsqrt(var + eps)


def _ln_fwd_kernel(x_ref, d_ref, scale_ref, bias_ref, *out_refs,
                   eps: float, H: int, Hpad: int, has_resid: bool,
                   out_dtype):
    """One row block: (optional residual add) + LayerNorm.

    The residual sum is rounded to the storage dtype BEFORE the
    statistics read it — bit-parity with the unfused ``x + attn`` (a
    bf16 add IS round(f32 sum)); the stats then widen back to fp32
    exactly like the reference ``layer_norm``.
    """
    x = x_ref[...].astype(jnp.float32)
    if has_resid:
        s_cast = (x + d_ref[...].astype(jnp.float32)).astype(out_dtype)
        out_refs[0][...] = s_cast
        xs = s_cast.astype(jnp.float32)
        y_out = out_refs[1]
    else:
        xs = x
        y_out = out_refs[0]
    mean, rstd = _ln_stats(xs, H, Hpad, eps)
    y = ((xs - mean) * rstd) * scale_ref[...].astype(jnp.float32) + \
        bias_ref[...].astype(jnp.float32)
    y_out[...] = y.astype(out_dtype)


def _ln_bwd_kernel(s_ref, scale_ref, dy_ref, gs_ref, dx_ref, dsc_ref,
                   dbi_ref, *, eps: float, H: int, Hpad: int,
                   has_gs: bool, out_dtype):
    """LN input-gradient + per-block dscale/dbias partials; mean/rstd
    recomputed in-block (rank-1 per row — cheaper than an HBM
    round-trip of saved stats).  ``gs`` is the residual-stream cotangent
    of the fused residual variant, added in the same pass."""
    s = s_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    mean, rstd = _ln_stats(s, H, Hpad, eps)
    xhat = (s - mean) * rstd
    dxhat = dy * scale_ref[...].astype(jnp.float32)
    if Hpad != H:
        # dy's pad columns are zero by padding, but xhat's are not —
        # mask the terms that multiply xhat alone.
        dxhat = jnp.where(_col_mask(dxhat.shape, H), dxhat, 0.0)
    m1 = jnp.sum(dxhat, axis=-1, keepdims=True) / H
    m2 = jnp.sum(dxhat * xhat, axis=-1, keepdims=True) / H
    dx = (dxhat - m1 - xhat * m2) * rstd
    if has_gs:
        dx = dx + gs_ref[...].astype(jnp.float32)
    dx_ref[...] = dx.astype(out_dtype)
    dsc_ref[...] = jnp.sum(dy * xhat, axis=0, keepdims=True)
    dbi_ref[...] = jnp.sum(dy, axis=0, keepdims=True)


def _ln_forward(x, delta, scale, bias, eps: float, _rb: int = None):
    """Shared fwd driver: returns (s, y) — s is x when no residual."""
    shape, dtype = x.shape, x.dtype
    H = shape[-1]
    rows = int(math.prod(shape[:-1])) if len(shape) > 1 else 1
    has_resid = delta is not None

    def runner(rb_):
        dx = jnp.zeros((rows, H), dtype)
        dd = jnp.zeros((rows, H), dtype) if has_resid else None
        v = jnp.zeros((H,), jnp.float32)
        return _ln_forward(dx, dd, v, v, eps, _rb=rb_)

    rows_pad, Hpad, rb = _geom(rows, H, n_bufs=6 if has_resid else 5,
                               kernel="fused_ln_fwd", dtype=dtype,
                               runner=runner, rb=_rb)
    x2 = _pad2(x.reshape(rows, H), rows_pad, Hpad)
    args = [x2]
    if has_resid:
        args.append(_pad2(delta.reshape(rows, H), rows_pad, Hpad))
    else:
        args.append(jnp.zeros((1, Hpad), dtype))
    args.append(_pad_row(scale.astype(jnp.float32), Hpad))
    args.append(_pad_row(bias.astype(jnp.float32), Hpad))
    kernel = functools.partial(_ln_fwd_kernel, eps=eps, H=H, Hpad=Hpad,
                               has_resid=has_resid, out_dtype=dtype)
    n_out = 2 if has_resid else 1
    outs = pl.pallas_call(
        kernel,
        grid=(rows_pad // rb,),
        in_specs=[_row_spec(rb, Hpad),
                  _row_spec(rb, Hpad) if has_resid else _whole_spec(Hpad),
                  _whole_spec(Hpad), _whole_spec(Hpad)],
        out_specs=[_row_spec(rb, Hpad)] * n_out,
        out_shape=[jax.ShapeDtypeStruct((rows_pad, Hpad), dtype)] * n_out,
        interpret=_interpret(),
    )(*args)
    def unpad(a):
        return a[:rows, :H].reshape(shape)
    if has_resid:
        return unpad(outs[0]), unpad(outs[1])
    return x, unpad(outs[0])


def _ln_backward(s, scale, dy, gs, eps: float, _rb: int = None):
    """Shared bwd driver: (ds, dscale, dbias)."""
    shape, dtype = s.shape, s.dtype
    H = shape[-1]
    rows = int(math.prod(shape[:-1])) if len(shape) > 1 else 1
    has_gs = gs is not None

    def runner(rb_):
        d2 = jnp.zeros((rows, H), dtype)
        dg = jnp.zeros((rows, H), dtype) if has_gs else None
        v = jnp.zeros((H,), jnp.float32)
        return _ln_backward(d2, v, d2, dg, eps, _rb=rb_)

    rows_pad, Hpad, rb = _geom(rows, H, n_bufs=7 if has_gs else 6,
                               kernel="fused_ln_bwd", dtype=dtype,
                               runner=runner, rb=_rb)
    grid = rows_pad // rb
    s2 = _pad2(s.reshape(rows, H), rows_pad, Hpad)
    dy2 = _pad2(dy.reshape(rows, H), rows_pad, Hpad)
    gs2 = _pad2(gs.reshape(rows, H), rows_pad, Hpad) if has_gs \
        else jnp.zeros((1, Hpad), dtype)
    kernel = functools.partial(_ln_bwd_kernel, eps=eps, H=H, Hpad=Hpad,
                               has_gs=has_gs, out_dtype=dtype)
    dx, dsc, dbi = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[_row_spec(rb, Hpad), _whole_spec(Hpad),
                  _row_spec(rb, Hpad),
                  _row_spec(rb, Hpad) if has_gs else _whole_spec(Hpad)],
        out_specs=[_row_spec(rb, Hpad), _part_spec(Hpad),
                   _part_spec(Hpad)],
        out_shape=[jax.ShapeDtypeStruct((rows_pad, Hpad), dtype),
                   jax.ShapeDtypeStruct((grid, Hpad), jnp.float32),
                   jax.ShapeDtypeStruct((grid, Hpad), jnp.float32)],
        interpret=_interpret(),
    )(s2, _pad_row(scale.astype(jnp.float32), Hpad), dy2, gs2)
    ds = dx[:rows, :H].reshape(shape)
    dscale = jnp.sum(dsc, axis=0)[:H].astype(scale.dtype)
    dbias = jnp.sum(dbi, axis=0)[:H].astype(scale.dtype)
    return ds, dscale, dbias


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x, scale, bias, eps: float = 1e-5):
    """LayerNorm over the last axis, fp32 statistics, one fused pass.
    Drop-in for ``models.transformer.layer_norm``."""
    return _ln_forward(x, None, scale, bias, eps)[1]


def _fln_fwd(x, scale, bias, eps):
    y = _ln_forward(x, None, scale, bias, eps)[1]
    return y, (x, scale)


def _fln_bwd(eps, res, dy):
    x, scale = res
    return _ln_backward(x, scale, dy, None, eps)


fused_layer_norm.defvjp(_fln_fwd, _fln_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_residual_layer_norm(x, delta, scale, bias, eps: float = 1e-5):
    """``s = x + delta; y = LN(s)`` in one pass; returns ``(s, y)``.

    ``s`` continues the residual stream, ``y`` feeds the next sublayer —
    the fusion the reference's ``normalize_invertible`` fused LN
    performs between every transformer sublayer.
    """
    return _ln_forward(x, delta, scale, bias, eps)


def _frln_fwd(x, delta, scale, bias, eps):
    s, y = _ln_forward(x, delta, scale, bias, eps)
    return (s, y), (s, scale)


def _frln_bwd(eps, res, cotangents):
    s, scale = res
    gs, gy = cotangents
    ds, dscale, dbias = _ln_backward(s, scale, gy, gs, eps)
    # d(x + delta)/dx == d(x + delta)/ddelta == identity: both inputs
    # receive the same combined cotangent.
    return ds, ds, dscale, dbias


fused_residual_layer_norm.defvjp(_frln_fwd, _frln_bwd)


# --------------------------------------------------------------------- #
# Bias + GELU epilogue
# --------------------------------------------------------------------- #
def _gelu_f32(z: jax.Array, exact: bool) -> jax.Array:
    if exact:
        return 0.5 * z * (1.0 + lax.erf(z / math.sqrt(2.0)))
    u = _SQRT_2_OVER_PI * (z + _GELU_C * z * z * z)
    return 0.5 * z * (1.0 + jnp.tanh(u))


def _dgelu_f32(z: jax.Array, exact: bool) -> jax.Array:
    if exact:
        phi = jnp.exp(-0.5 * z * z) / math.sqrt(2.0 * math.pi)
        return 0.5 * (1.0 + lax.erf(z / math.sqrt(2.0))) + z * phi
    u = _SQRT_2_OVER_PI * (z + _GELU_C * z * z * z)
    t = jnp.tanh(u)
    du = _SQRT_2_OVER_PI * (1.0 + 3.0 * _GELU_C * z * z)
    return 0.5 * (1.0 + t) + 0.5 * z * (1.0 - t * t) * du


def _gelu_fwd_kernel(y_ref, b_ref, o_ref, *, exact: bool, out_dtype):
    z = (y_ref[...].astype(jnp.float32) +
         b_ref[...].astype(jnp.float32)).astype(out_dtype)
    o_ref[...] = _gelu_f32(z.astype(jnp.float32), exact).astype(out_dtype)


def _gelu_bwd_kernel(y_ref, b_ref, g_ref, dy_ref, db_ref, *, exact: bool,
                     out_dtype):
    """dz = g * gelu'(z) with z recomputed from the saved matmul output
    (no extra residual); db partial = column sum of dz per block."""
    z = (y_ref[...].astype(jnp.float32) +
         b_ref[...].astype(jnp.float32)).astype(out_dtype)
    dz = g_ref[...].astype(jnp.float32) * \
        _dgelu_f32(z.astype(jnp.float32), exact)
    dy_ref[...] = dz.astype(out_dtype)
    db_ref[...] = jnp.sum(dz, axis=0, keepdims=True)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fused_bias_gelu(y, bias, exact: bool = False):
    """``gelu(y + bias)`` in one fused pass — the FFN up-projection
    epilogue (``y`` is the raw matmul output).  ``exact`` selects the
    erf form; default is the tanh approximation the reference's
    ``gelu_kernels.cu`` computes (and GPT-2's gelu_new)."""
    return _gelu_apply(y, bias, exact)


def _gelu_apply(y, bias, exact, _rb: int = None):
    shape, dtype = y.shape, y.dtype
    F = shape[-1]
    rows = int(math.prod(shape[:-1])) if len(shape) > 1 else 1

    def runner(rb_):
        return _gelu_apply(jnp.zeros((rows, F), dtype),
                           jnp.zeros((F,), jnp.float32), exact, _rb=rb_)

    rows_pad, Fpad, rb = _geom(rows, F, n_bufs=4, kernel="fused_gelu_fwd",
                               dtype=dtype, runner=runner, rb=_rb)
    y2 = _pad2(y.reshape(rows, F), rows_pad, Fpad)
    out = pl.pallas_call(
        functools.partial(_gelu_fwd_kernel, exact=exact, out_dtype=dtype),
        grid=(rows_pad // rb,),
        in_specs=[_row_spec(rb, Fpad), _whole_spec(Fpad)],
        out_specs=_row_spec(rb, Fpad),
        out_shape=jax.ShapeDtypeStruct((rows_pad, Fpad), dtype),
        interpret=_interpret(),
    )(y2, _pad_row(bias.astype(jnp.float32), Fpad))
    return out[:rows, :F].reshape(shape)


def _fbg_fwd(y, bias, exact):
    return _gelu_apply(y, bias, exact), (y, bias)


def _fbg_bwd(exact, res, g):
    y, bias = res
    return _fbg_bwd_impl(y, bias, g, exact)


def _fbg_bwd_impl(y, bias, g, exact, _rb: int = None):
    shape, dtype = y.shape, y.dtype
    F = shape[-1]
    rows = int(math.prod(shape[:-1])) if len(shape) > 1 else 1

    def runner(rb_):
        z = jnp.zeros((rows, F), dtype)
        return _fbg_bwd_impl(z, jnp.zeros((F,), jnp.float32), z, exact,
                             _rb=rb_)

    rows_pad, Fpad, rb = _geom(rows, F, n_bufs=5, kernel="fused_gelu_bwd",
                               dtype=dtype, runner=runner, rb=_rb)
    grid = rows_pad // rb
    y2 = _pad2(y.reshape(rows, F), rows_pad, Fpad)
    g2 = _pad2(g.reshape(rows, F), rows_pad, Fpad)
    dy, dbp = pl.pallas_call(
        functools.partial(_gelu_bwd_kernel, exact=exact, out_dtype=dtype),
        grid=(grid,),
        in_specs=[_row_spec(rb, Fpad), _whole_spec(Fpad),
                  _row_spec(rb, Fpad)],
        out_specs=[_row_spec(rb, Fpad), _part_spec(Fpad)],
        out_shape=[jax.ShapeDtypeStruct((rows_pad, Fpad), dtype),
                   jax.ShapeDtypeStruct((grid, Fpad), jnp.float32)],
        interpret=_interpret(),
    )(y2, _pad_row(bias.astype(jnp.float32), Fpad), g2)
    dbias = jnp.sum(dbp, axis=0)[:F].astype(bias.dtype)
    return dy[:rows, :F].reshape(shape), dbias


fused_bias_gelu.defvjp(_fbg_fwd, _fbg_bwd)


__all__ = ["fused_layer_norm", "fused_residual_layer_norm",
           "fused_bias_gelu", "fused_elementwise_enabled"]
