"""Pallas block-size autotuner: one resolver for every tile decision.

Every Pallas kernel in the tree (``fused_elementwise``, ``fused_update``,
``flash_attention``/``sparse_flash``, ``grouped_gemm``) used to pick its
tiles from a scattered set of static heuristics — a fixed VMEM budget
loop here, a hand-set ``_BLOCK_TARGET`` there — and ``ablate_flash.py``
existed precisely because no one value wins across shapes.  This module
replaces those call-site constants with ONE resolver:

    tile = autotune.resolve(kernel, shape, dtype, heuristic,
                            candidates, measure)

Semantics (the determinism contract, in priority order):

1. ``DS_AUTOTUNE=0`` — the resolver returns ``heuristic`` unconditionally:
   bit-for-bit today's tiles, no registry read, no search.
2. CPU / interpret mode never searches: ``search_allowed()`` is False off
   TPU, call sites pass ``measure=None``, and ``resolve`` returns the
   heuristic — tier-1 stays deterministic on any machine regardless of
   what a TPU session recorded (``DS_AUTOTUNE_FORCE=1`` is the explicit
   test/tooling escape hatch).
3. On TPU, the first resolve of a new (kernel, abstract shape, dtype,
   chip-kind) key times the candidate grid ONCE — powers of two bounded
   by the same VMEM budget math the heuristics used — and records the
   winner; every later resolve of that key (this process or the next)
   hits the registry with zero search.

The registry is keyed like the recompile sentinel's abstract signatures
(``kernel|dtype[dims]|chip``, host metadata only — never tracers) and
written like the async checkpoint's commit: process 0 only, tmp file +
``os.replace`` so a preempted writer can never leave a torn file.  A
corrupt registry (killed mid-copy, hand-edited) degrades to empty with a
warning — the heuristic still stands underneath.  Path override:
``DS_AUTOTUNE_REGISTRY`` (default ``~/.cache/deepspeed_tpu/autotune.json``).

Tiles move the SCHEDULE, not the arithmetic: every kernel computes the
same per-row/per-block fp32 expressions under any tile choice, so an
autotuned tile is bit-identical to the heuristic tile (asserted in
``tests/test_autotune.py``) — which is what makes an on-disk cache safe
to share across runs at all.
"""
from __future__ import annotations

import json
import math
import os
import tempfile
import time
import warnings
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax

_ENV_KNOB = "DS_AUTOTUNE"
_ENV_PATH = "DS_AUTOTUNE_REGISTRY"
_ENV_FORCE = "DS_AUTOTUNE_FORCE"

# Observability for tests/tooling: how many resolves searched, hit the
# registry, or fell back to the heuristic since import (or reset()).
counters: Dict[str, int] = {"search": 0, "hit": 0, "heuristic": 0}

# In-memory registry cache: path -> {key: entry}. Loaded once per path;
# invalidate() drops it (tests point DS_AUTOTUNE_REGISTRY at tmp files).
_CACHE: Dict[str, Dict[str, Any]] = {}


def enabled() -> bool:
    """DS_AUTOTUNE=0 disables everything: heuristics bit-for-bit."""
    return os.environ.get(_ENV_KNOB, "1") != "0"


def search_allowed() -> bool:
    """True when this process may time candidates: TPU backend only
    (interpret-mode timings measure the interpreter, and tier-1 must be
    deterministic). DS_AUTOTUNE_FORCE=1 is the test/tooling override."""
    if not enabled():
        return False
    if os.environ.get(_ENV_FORCE) == "1":
        return True
    return jax.default_backend() == "tpu"


def chip_kind() -> str:
    """Registry key component: the accelerator generation (tiles tuned
    on v5e are not evidence about v4), ``cpu`` off-TPU."""
    try:
        dev = jax.devices()[0]
        if dev.platform == "tpu":
            return str(dev.device_kind).replace(" ", "_")
    except Exception:  # pragma: no cover - no backend at all
        pass
    return "cpu"


def registry_path() -> str:
    env = os.environ.get(_ENV_PATH)
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "deepspeed_tpu", "autotune.json")


def reset() -> None:
    """Drop the in-memory registry cache and zero the counters (tests)."""
    _CACHE.clear()
    for k in counters:
        counters[k] = 0


def _key(kernel: str, shape: Sequence[int], dtype: Any) -> str:
    """``kernel|dtype[d0,d1,...]|chip`` — the recompile sentinel's
    per-leaf descriptor idiom (monitor/recompile.abstract_signature)."""
    dims = ",".join(str(int(d)) for d in shape)
    return f"{kernel}|{dtype}[{dims}]|{chip_kind()}"


def _load(path: str) -> Dict[str, Any]:
    if path in _CACHE:
        return _CACHE[path]
    reg: Dict[str, Any] = {}
    try:
        with open(path) as f:
            loaded = json.load(f)
        if isinstance(loaded, dict):
            reg = loaded
        else:
            raise ValueError(f"registry root is {type(loaded).__name__}")
    except FileNotFoundError:
        pass
    except Exception as e:  # corrupt file: degrade to empty, keep going
        warnings.warn(f"autotune registry {path} unreadable ({e}); "
                      f"starting empty — heuristics still apply")
    _CACHE[path] = reg
    return reg


def _write(path: str, reg: Dict[str, Any]) -> None:
    """Atomic, process-0-only: tmp in the same directory + os.replace
    (the async_ckpt/op_builder commit idiom)."""
    try:
        if jax.process_index() != 0:
            return
    except Exception:  # pragma: no cover
        pass
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                                   prefix=".autotune_", suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(reg, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except OSError as e:  # read-only FS etc.: in-memory cache still wins
        warnings.warn(f"autotune registry {path} not writable ({e}); "
                      f"keeping the winner in memory only")


def _encode(tile: Any) -> Any:
    if isinstance(tile, tuple):
        return [int(t) for t in tile]
    return int(tile)


def _decode(raw: Any, like: Any) -> Any:
    """Registry JSON -> the call site's tile type (int or int tuple)."""
    if isinstance(like, tuple):
        if not isinstance(raw, (list, tuple)) or len(raw) != len(like):
            return None
        return tuple(int(v) for v in raw)
    if isinstance(raw, (list, tuple)):
        return None
    try:
        return int(raw)
    except (TypeError, ValueError):
        return None


def resolve(kernel: str, shape: Sequence[int], dtype: Any, heuristic,
            candidates: Optional[Sequence] = None,
            measure: Optional[Callable[[Any], float]] = None):
    """Resolve one tile decision.

    ``heuristic`` is today's static choice (int row block or tile tuple)
    and is ALWAYS the answer when autotuning is off, search is not
    allowed here (CPU/interpret), or no usable registry entry exists and
    no ``measure`` was provided.  ``candidates`` is the legal grid the
    call site's VMEM budget math admits (the heuristic is appended if
    missing).  ``measure(tile) -> seconds`` times one candidate; a
    candidate that raises is discarded.  The winner is recorded in the
    on-disk registry so the search runs once per (kernel, shape, dtype,
    chip) key — across processes.
    """
    if not search_allowed():
        counters["heuristic"] += 1
        return heuristic
    cands = [c for c in (candidates or ())]
    if heuristic not in cands:
        cands.append(heuristic)
    key = _key(kernel, shape, dtype)
    path = registry_path()
    reg = _load(path)
    ent = reg.get(key)
    if isinstance(ent, dict):
        tile = _decode(ent.get("tile"), heuristic)
        if tile is not None and tile in cands:
            counters["hit"] += 1
            return tile
        # Entry exists but is outside today's legal grid (budget math or
        # candidate set changed since it was recorded): ignore it.
    if measure is None or len(cands) < 2:
        counters["heuristic"] += 1
        return heuristic
    counters["search"] += 1
    timings: Dict[Any, float] = {}
    for c in cands:
        try:
            t = float(measure(c))
        except Exception:  # candidate fails to compile/run: not a winner
            continue
        if math.isfinite(t):
            timings[c] = t
    if not timings:
        return heuristic
    best = min(timings, key=lambda c: timings[c])
    t_h = timings.get(heuristic)
    ent = {
        "tile": _encode(best),
        "heuristic": _encode(heuristic),
        "timings_s": {str(c): round(timings[c], 9) for c in timings},
        "speedup_vs_heuristic":
            round(t_h / timings[best], 4) if t_h else None,
        "recorded_unix": int(time.time()),
    }
    reg[key] = ent
    _write(path, reg)
    return best


def measure_from_runner(runner: Callable[[Any], Any],
                        repeats: int = 3) -> Callable[[Any], float]:
    """Wrap ``runner(tile) -> jax value(s)`` into a wall-clock measure:
    one warmup call (compile), then best-of-``repeats`` with
    ``block_until_ready`` fencing both sides."""
    def measure(tile) -> float:
        jax.block_until_ready(runner(tile))  # compile + warm
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(runner(tile))
            best = min(best, time.perf_counter() - t0)
        return best
    return measure


def pow2_candidates(lo: int, hi: int,
                    fits: Optional[Callable[[int], bool]] = None
                    ) -> Tuple[int, ...]:
    """Powers of two in [lo, hi] passing the call site's VMEM-budget
    predicate — the shared candidate-grid constructor."""
    out = []
    c = 1 << max(0, (lo - 1).bit_length())
    while c <= hi:
        if c >= lo and (fits is None or fits(c)):
            out.append(c)
        c *= 2
    return tuple(out)


__all__ = ["resolve", "measure_from_runner", "pow2_candidates",
           "enabled", "search_allowed", "chip_kind", "registry_path",
           "reset", "counters"]
