from .optimizers import build_optimizer
from . import autotune
from .grouped_gemm import grouped_ffn, grouped_gemm_enabled
