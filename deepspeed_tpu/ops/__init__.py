from .optimizers import build_optimizer
