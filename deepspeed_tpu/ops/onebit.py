"""1-bit Adam — error-feedback sign-compressed momentum communication.

Parity target: reference ``runtime/fp16/onebit_adam.py:18-374`` (OnebitAdam:
full-precision Adam warmup, then a "compression stage" where the variance is
FROZEN and the momentum is communicated as sign bits + a per-chunk scale,
with error-feedback compensation on both the worker and the server side —
``Compressed_Allreduce`` at :104-228) and its mpi4py/cupy collectives
(``runtime/custom_collectives.py:10-130``).

TPU-native redesign: the compressed allreduce is expressed as ordinary XLA
collectives inside ``shard_map`` over the dp mesh axis. Each rank updates
the momentum with its LOCAL (unreduced) gradient, compensates with its
worker error, compresses to ``scale * sign(...)``, and the ranks psum the
compressed tensors — semantically the gather+average of sign-decompressed
worker momenta. A second compression round with a server-side error buffer
reproduces the reference's two-phase (worker-compress → server-compress)
pipeline. On a real multi-slice deployment the wire format over DCN is the
packed sign bits + scales (1/32 of fp32 volume, ``comm_bytes`` below); the
single-program emulation reproduces the numerics, which is what training
behavior depends on.

The update skips bias correction in the compression stage, like the
reference (onebit_adam.py applies the raw m / (sqrt(v_frozen) + eps) step);
warmup uses standard bias-corrected Adam.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax


class OnebitState(NamedTuple):
    """Carried optimizer state (all leaves mirror the param tree except the
    scalar step)."""
    step: jnp.ndarray          # int32, number of optimizer steps taken
    m: Any                     # momentum (exp_avg)
    v: Any                     # variance (exp_avg_sq) — FROZEN after warmup
    worker_error: Any          # per-rank error feedback (compression stage)
    server_error: Any          # server-side error feedback


def init_state(params: Any) -> OnebitState:
    zeros = lambda: jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    return OnebitState(step=jnp.asarray(0, jnp.int32), m=zeros(), v=zeros(),
                       worker_error=zeros(), server_error=zeros())


def _compress(x: jnp.ndarray, error: jnp.ndarray):
    """Error-feedback 1-bit compression of one tensor.

    compensated = x + error; transmitted = scale * sign(compensated) with
    scale = mean |compensated| (the L1 scale the reference uses per chunk);
    new_error = compensated - transmitted. Returns (transmitted, new_error).
    """
    compensated = x + error
    scale = jnp.mean(jnp.abs(compensated))
    transmitted = scale * jnp.sign(compensated)
    return transmitted, compensated - transmitted


def _clip_tree(g, clip: float, norm):
    coeff = jnp.minimum(1.0, clip / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda t: t * coeff, g)


def _tree_sumsq(g):
    return sum(jnp.sum(jnp.square(t.astype(jnp.float32)))
               for t in jax.tree_util.tree_leaves(g))


def onebit_adam_update(grads_local: Any, state: OnebitState, params: Any,
                       *, lr, b1: float = 0.9, b2: float = 0.999,
                       eps: float = 1e-8, weight_decay: float = 0.0,
                       freeze_step: int = 100,
                       axis_name: Optional[str] = None,
                       dp: int = 1, clip: float = 0.0):
    """One 1-bit Adam step. Must run where ``lax.psum(axis_name)`` is legal
    (inside shard_map / pmap over the dp axis) when dp > 1; ``grads_local``
    are the rank-LOCAL unreduced gradients.

    ``clip`` > 0 clips by global norm: in warmup the TRUE norm of the
    dp-averaged gradient (identical to the standard engine's clipping); in
    the compression stage the RMS of per-rank local norms (the global
    gradient is never materialized there — that is the point), which
    over-estimates and therefore clips conservatively.

    Returns (new_params, new_state).
    """
    def psum_mean(t):
        if axis_name is None or dp <= 1:
            return t
        return lax.psum(t, axis_name) / dp

    step = state.step + 1
    in_warmup = step <= freeze_step

    def warmup(_):
        # Standard (bias-corrected) Adam on the full-precision psum'd grads
        # — reference warmup phase.
        g = jax.tree_util.tree_map(psum_mean, grads_local)
        if clip and clip > 0:
            g = _clip_tree(g, clip, jnp.sqrt(_tree_sumsq(g)))
        m = jax.tree_util.tree_map(
            lambda mm, gg: b1 * mm + (1 - b1) * gg, state.m, g)
        v = jax.tree_util.tree_map(
            lambda vv, gg: b2 * vv + (1 - b2) * gg * gg, state.v, g)
        bc1 = 1 - b1 ** step.astype(jnp.float32)
        bc2 = 1 - b2 ** step.astype(jnp.float32)
        upd = jax.tree_util.tree_map(
            lambda mm, vv: (mm / bc1) / (jnp.sqrt(vv / bc2) + eps), m, v)
        return m, v, state.worker_error, state.server_error, upd

    def compressed(_):
        # Local momentum update with LOCAL grads, then the two-phase
        # error-feedback compressed allreduce; variance frozen.
        g_local = grads_local
        if clip and clip > 0:
            sumsq = psum_mean(_tree_sumsq(g_local))
            g_local = _clip_tree(g_local, clip, jnp.sqrt(sumsq))
        m_local = jax.tree_util.tree_map(
            lambda mm, gg: b1 * mm + (1 - b1) * gg, state.m, g_local)

        def comm(mm, werr, serr):
            sent, new_werr = _compress(mm, werr)           # worker side
            gathered = psum_mean(sent)                     # "igather+avg"
            final, new_serr = _compress(gathered, serr)    # server side
            return final, new_werr, new_serr

        out = jax.tree_util.tree_map(comm, m_local, state.worker_error,
                                     state.server_error)
        treedef = jax.tree_util.tree_structure(state.m)
        leaves = treedef.flatten_up_to(out)
        m_new = jax.tree_util.tree_unflatten(
            treedef, [l[0] for l in leaves])
        werr = jax.tree_util.tree_unflatten(
            treedef, [l[1] for l in leaves])
        serr = jax.tree_util.tree_unflatten(
            treedef, [l[2] for l in leaves])
        upd = jax.tree_util.tree_map(
            lambda mm, vv: mm / (jnp.sqrt(vv) + eps), m_new, state.v)
        return m_new, state.v, werr, serr, upd

    m, v, werr, serr, upd = lax.cond(in_warmup, warmup, compressed, None)

    new_params = jax.tree_util.tree_map(
        lambda p, u: (p.astype(jnp.float32) - lr * (u + weight_decay *
                                                    p.astype(jnp.float32))
                      ).astype(p.dtype),
        params, upd)
    return new_params, OnebitState(step=step, m=m, v=v, worker_error=werr,
                                   server_error=serr)


def comm_bytes(n_elements: int, *, compressed: bool,
               chunks: int = 1) -> int:
    """Per-rank communicated payload for one allreduce of ``n_elements``.

    Full-precision warmup: 4 bytes/element (fp32). Compression stage: 1
    sign bit/element + one fp32 scale per chunk — the reference's packed
    ``cupy.packbits`` wire format (onebit_adam.py:141-168). This is the
    quantity the 5x/16x volume-reduction claims are about (BASELINE.md).
    """
    if not compressed:
        return 4 * n_elements
    return (n_elements + 7) // 8 + 4 * chunks


def compression_ratio(n_elements: int, chunks: int = 1) -> float:
    return comm_bytes(n_elements, compressed=False) / \
        comm_bytes(n_elements, compressed=True, chunks=chunks)
