"""1-bit Adam — error-feedback sign-compressed momentum communication.

Parity target: reference ``runtime/fp16/onebit_adam.py:18-374`` (OnebitAdam:
full-precision Adam warmup, then a "compression stage" where the variance is
FROZEN and the momentum is communicated as sign bits + a per-chunk scale,
with error-feedback compensation on both the worker and the server side —
``Compressed_Allreduce`` at :104-228) and its mpi4py/cupy collectives
(``runtime/custom_collectives.py:10-130``).

TPU-native redesign: the compressed allreduce is expressed as ordinary XLA
collectives inside ``shard_map`` over the dp mesh axis. Each rank updates
the momentum with its LOCAL (unreduced) gradient, compensates with its
worker error, compresses to ``scale * sign(...)``, and the ranks psum the
compressed tensors — semantically the gather+average of sign-decompressed
worker momenta. A second compression round with a server-side error buffer
reproduces the reference's two-phase (worker-compress → server-compress)
pipeline. On a real multi-slice deployment the wire format over DCN is the
packed sign bits + scales (1/32 of fp32 volume, ``comm_bytes`` below); the
single-program emulation reproduces the numerics, which is what training
behavior depends on.

The update skips bias correction in the compression stage, like the
reference (onebit_adam.py applies the raw m / (sqrt(v_frozen) + eps) step);
warmup uses standard bias-corrected Adam.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax


class OnebitState(NamedTuple):
    """Carried optimizer state (all leaves mirror the param tree except the
    scalar step)."""
    step: jnp.ndarray          # int32, number of optimizer steps taken
    m: Any                     # momentum (exp_avg)
    v: Any                     # variance (exp_avg_sq) — FROZEN after warmup
    worker_error: Any          # per-rank error feedback (compression stage)
    server_error: Any          # server-side error feedback


def init_state(params: Any) -> OnebitState:
    zeros = lambda: jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    return OnebitState(step=jnp.asarray(0, jnp.int32), m=zeros(), v=zeros(),
                       worker_error=zeros(), server_error=zeros())


def _compress(x: jnp.ndarray, error: jnp.ndarray, chunks: int = 1):
    """Error-feedback 1-bit compression of one tensor.

    compensated = x + error; transmitted = scale * sign(compensated) with
    one L1 scale (mean |compensated|) PER CHUNK, matching the reference's
    per-worker-chunk scaling (onebit_adam.py splits the flat tensor into
    world_size chunks and scales each independently, :141-168). ``chunks``
    should be the dp degree; tensors smaller than ``chunks`` elements fall
    back to a single scale. new_error = compensated - transmitted.
    Returns (transmitted, new_error).
    """
    compensated = x + error
    if chunks <= 1 or compensated.size < chunks:
        scale = jnp.mean(jnp.abs(compensated))
        transmitted = scale * jnp.sign(compensated)
        return transmitted, compensated - transmitted
    flat = compensated.reshape(-1)
    n = flat.shape[0]
    pad = (-n) % chunks
    rows = jnp.pad(flat, (0, pad)).reshape(chunks, -1)
    # Per-chunk L1 scale over the REAL elements only: padded zeros add
    # nothing to the |.| sum, so divide by the true per-chunk count. Padding
    # can span several trailing chunks (tiny tensors at high dp); the count
    # floor of 1 keeps all-pad rows finite (they transmit sign(0)=0 anyway).
    width = rows.shape[1]
    counts = jnp.clip(n - jnp.arange(chunks, dtype=jnp.float32) * width,
                      1.0, float(width))
    scale = jnp.sum(jnp.abs(rows), axis=1) / counts
    transmitted = (scale[:, None] * jnp.sign(rows)).reshape(-1)[:n]
    transmitted = transmitted.reshape(x.shape)
    return transmitted, compensated - transmitted


def _clip_tree(g, clip: float, norm):
    coeff = jnp.minimum(1.0, clip / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda t: t * coeff, g)


def _tree_sumsq(g):
    return sum(jnp.sum(jnp.square(t.astype(jnp.float32)))
               for t in jax.tree_util.tree_leaves(g))


def onebit_adam_update(grads_local: Any, state: OnebitState, params: Any,
                       *, lr, b1: float = 0.9, b2: float = 0.999,
                       eps: float = 1e-8, weight_decay: float = 0.0,
                       freeze_step: int = 100000,
                       axis_name: Optional[str] = None,
                       dp: int = 1, clip: float = 0.0,
                       loss_scale=None):
    """One 1-bit Adam step. Must run where ``lax.psum(axis_name)`` is legal
    (inside shard_map / pmap over the dp axis) when dp > 1; ``grads_local``
    are the rank-LOCAL unreduced gradients.

    ``clip`` > 0 clips by global norm: in warmup the TRUE norm of the
    dp-averaged gradient (identical to the standard engine's clipping); in
    the compression stage the RMS of per-rank local norms (the global
    gradient is never materialized there — that is the point), which
    over-estimates and therefore clips conservatively. The same quantity is
    the reported ``grad_norm``.

    ``loss_scale`` (fp16 static scaling): grads_local are assumed to be
    grads of ``loss * loss_scale``; they are unscaled in fp32 here.

    Overflow semantics (reference onebit_adam.py keeps the fp16 overflow
    machinery through the compression phase): if any rank's gradient is
    non-finite the step is SKIPPED — params, m, v, both error buffers and
    the Adam step count are all left untouched, in both phases. In the
    compressed phase this matters doubly: committing error feedback from a
    garbage momentum would poison every subsequent step.

    Returns ``(new_params, new_state, aux)`` with
    ``aux = {"grad_norm": f32, "overflow": bool}``.
    """
    def psum_mean(t):
        if axis_name is None or dp <= 1:
            return t
        return lax.psum(t, axis_name) / dp

    if loss_scale is not None:
        inv = 1.0 / loss_scale
        grads_local = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) * inv, grads_local)

    step = state.step + 1
    in_warmup = step <= freeze_step

    def warmup(_):
        # Standard (bias-corrected) Adam on the full-precision psum'd grads
        # — reference warmup phase.
        g = jax.tree_util.tree_map(psum_mean, grads_local)
        norm = jnp.sqrt(_tree_sumsq(g))
        if clip and clip > 0:
            g = _clip_tree(g, clip, norm)
        m = jax.tree_util.tree_map(
            lambda mm, gg: b1 * mm + (1 - b1) * gg, state.m, g)
        v = jax.tree_util.tree_map(
            lambda vv, gg: b2 * vv + (1 - b2) * gg * gg, state.v, g)
        bc1 = 1 - b1 ** step.astype(jnp.float32)
        bc2 = 1 - b2 ** step.astype(jnp.float32)
        upd = jax.tree_util.tree_map(
            lambda mm, vv: (mm / bc1) / (jnp.sqrt(vv / bc2) + eps), m, v)
        return m, v, state.worker_error, state.server_error, upd, norm

    def compressed(_):
        # Local momentum update with LOCAL grads, then the two-phase
        # error-feedback compressed allreduce; variance frozen.
        g_local = grads_local
        norm = jnp.sqrt(psum_mean(_tree_sumsq(g_local)))
        if clip and clip > 0:
            g_local = _clip_tree(g_local, clip, norm)
        m_local = jax.tree_util.tree_map(
            lambda mm, gg: b1 * mm + (1 - b1) * gg, state.m, g_local)

        def comm(mm, werr, serr):
            sent, new_werr = _compress(mm, werr, chunks=dp)  # worker side
            gathered = psum_mean(sent)                       # "igather+avg"
            final, new_serr = _compress(gathered, serr, chunks=dp)  # server
            return final, new_werr, new_serr

        out = jax.tree_util.tree_map(comm, m_local, state.worker_error,
                                     state.server_error)
        treedef = jax.tree_util.tree_structure(state.m)
        leaves = treedef.flatten_up_to(out)
        m_new = jax.tree_util.tree_unflatten(
            treedef, [l[0] for l in leaves])
        werr = jax.tree_util.tree_unflatten(
            treedef, [l[1] for l in leaves])
        serr = jax.tree_util.tree_unflatten(
            treedef, [l[2] for l in leaves])
        upd = jax.tree_util.tree_map(
            lambda mm, vv: mm / (jnp.sqrt(vv) + eps), m_new, state.v)
        return m_new, state.v, werr, serr, upd, norm

    m, v, werr, serr, upd, norm = lax.cond(in_warmup, warmup, compressed,
                                           None)

    # Overflow vote: the norm folds every leaf on every rank (psum'd), so a
    # single non-finite grad anywhere makes it non-finite. Skip = identity.
    overflow = ~jnp.isfinite(norm)

    def commit(old, new):
        return jax.tree_util.tree_map(
            lambda o, n: jnp.where(overflow, o, n), old, new)

    new_params = jax.tree_util.tree_map(
        lambda p, u: (p.astype(jnp.float32) - lr * (u + weight_decay *
                                                    p.astype(jnp.float32))
                      ).astype(p.dtype),
        params, upd)
    new_state = OnebitState(
        step=jnp.where(overflow, state.step, step),
        m=commit(state.m, m), v=commit(state.v, v),
        worker_error=commit(state.worker_error, werr),
        server_error=commit(state.server_error, serr))
    return commit(params, new_params), new_state, \
        {"grad_norm": norm, "overflow": overflow}


def comm_bytes(n_elements: int, *, compressed: bool,
               chunks: int = 1) -> int:
    """Per-rank communicated payload for one allreduce of ``n_elements``.

    Full-precision warmup: 4 bytes/element (fp32). Compression stage: 1
    sign bit/element + one fp32 scale per chunk — the reference's packed
    ``cupy.packbits`` wire format (onebit_adam.py:141-168). This is the
    quantity the 5x/16x volume-reduction claims are about (BASELINE.md).
    """
    if not compressed:
        return 4 * n_elements
    return (n_elements + 7) // 8 + 4 * chunks


def compression_ratio(n_elements: int, chunks: int = 1) -> float:
    return comm_bytes(n_elements, compressed=False) / \
        comm_bytes(n_elements, compressed=True, chunks=chunks)
