"""LUT-driven block-sparse flash attention — only active blocks are touched.

The layout-gated kernels in flash_attention.py iterate the FULL (q,k) block
grid and gate the compute, so HBM loads and grid overhead still scale
O(S^2). This module is the reference's actual design point
(csrc/sparse_attention/utils.cpp builds LUTs for its Triton kernels,
sdd_segment :14-117), taken to the splash-attention form: the layout
flattens into ONE list of active (q-block, k-block) pairs per head, and the
Pallas grid iterates exactly those nnz steps — scalar-prefetch index maps
pick each step's blocks, the online-softmax state resets on q-row
transitions, and the output block flushes when the row advances. Compute,
bandwidth, AND grid steps all scale with nnz; there is no padding to the
widest row (global-attention rows cost only their own entries).

Forward and dq iterate the row-major pair list; dkv iterates the
column-major list (state carried per k block). Dropout composes via the
same stateless position hash as the dense kernels (keyed by the ACTUAL
block indices read from the LUT), so masks agree across fwd/dq/dkv.

Requirement: every q-block row and k-block column of the layout must have
at least one active block (else its output block would never be written);
``build_flat_luts`` returns None in that case and the caller falls back to
the gated kernel.
"""
from __future__ import annotations

import functools
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .flash_attention import (NEG_INF, _causal_mask, _dropout_keep,
                              _interpret)

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None


def build_flat_luts(layout: np.ndarray, widen: int = 1):
    """layout [H, nQ, nK] -> (qid, kid, nnz, kmask, qidT, kidT, nnzT,
    kmaskT) int32 arrays ([H, NNZ] / [H]), row-major for fwd/dq and
    column-major for dkv; padded tails repeat the last pair. None if any
    row/column is empty.

    ``widen`` > 1 coarsens the K dimension by that factor: one LUT entry
    covers ``widen`` adjacent 1-wide k-blocks (kid indexes WIDE blocks)
    and ``kmask`` is a per-entry bitmask of which sub-blocks are live
    (inactive sub-columns are softmax-masked in-kernel). Window-shaped
    layouts (local attention bands) coarsen nearly for free, and each grid
    step's matmuls grow ``widen``x — amortizing the fixed per-step cost
    that dominates at head-dim 64 (see sparse_flash_attention's auto
    pick). Padded tail entries carry kmask=0, so they are hard no-ops."""
    lay = np.asarray(layout) != 0
    H, nQ, nK = lay.shape
    if (lay.sum(-1) == 0).any() or (lay.sum(-2) == 0).any():
        return None
    w = int(widen)
    if nK % w != 0:
        return None
    nK2 = nK // w
    # bits[h, q, k2] = bitmask of live sub-blocks in wide block k2
    sub = lay.reshape(H, nQ, nK2, w)
    bits = (sub.astype(np.int32) << np.arange(w, dtype=np.int32)).sum(-1)

    def flatten(mask, bit_lookup):   # row-major active pairs per head
        pairs = [np.argwhere(mask[h]) for h in range(H)]
        nnz = np.asarray([len(p) for p in pairs], np.int32)
        NNZ = int(nnz.max())
        rid = np.zeros((H, NNZ), np.int32)
        cid = np.zeros((H, NNZ), np.int32)
        bm = np.zeros((H, NNZ), np.int32)
        for h, p in enumerate(pairs):
            rid[h, :len(p)] = p[:, 0]
            cid[h, :len(p)] = p[:, 1]
            bm[h, :len(p)] = bit_lookup(h, p[:, 0], p[:, 1])
            rid[h, len(p):] = p[-1, 0]
            cid[h, len(p):] = p[-1, 1]
            # kmask stays 0 on the padded tail: a hard no-op
        return rid, cid, nnz, bm

    lay2 = bits != 0
    qid, kid, nnz, kmask = flatten(lay2, lambda h, q, k2: bits[h, q, k2])
    kidT, qidT, nnzT, kmaskT = flatten(
        lay2.transpose(0, 2, 1), lambda h, k2, q: bits[h, q, k2])
    return qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT


# --------------------------------------------------------------------- #
# Kernels — grid (BH, NNZ); state carries across same-row steps
# --------------------------------------------------------------------- #
def _submask(s, bits, bk: int, widen: int, transposed: bool = False):
    """NEG_INF-mask the sub-blocks of a widened k tile whose LUT bit is 0.
    s: [bq, bk] (or [bk, bq] transposed), bk = widen * sub_width."""
    if widen == 1:
        return s
    subw = bk // widen
    axis = 0 if transposed else 1
    sub = jax.lax.broadcasted_iota(jnp.int32, s.shape, axis) // subw
    live = jax.lax.shift_right_logical(bits, sub) & 1
    return jnp.where(live == 1, s, NEG_INF)


def _sfwd_kernel(qid_ref, kid_ref, nnz_ref, kmask_ref, q_ref, k_ref, v_ref,
                 seed_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                 *, scale, causal, bq, bk, nH, dropout, widen):
    bh, n = pl.program_id(0), pl.program_id(1)
    h = bh % nH
    qi = qid_ref[h, n]
    kj = kid_ref[h, n]
    prev_qi = qid_ref[h, jnp.maximum(n - 1, 0)]
    new_row = jnp.logical_or(n == 0, qi != prev_qi)
    active = n < nnz_ref[h]

    @pl.when(jnp.logical_and(new_row, active))
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(active)
    def _compute():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, kj, bq, bk)
        s = _submask(s, kmask_ref[h, n], bk, widen)
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_scr[:, 0:1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        if dropout > 0.0:
            keep = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk, dropout)
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout)), 0.0)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:, 0:1] = m_new
        l_scr[:, 0:1] = l_new

    # Finalize only on the row's LAST active step (one divide/log/store per
    # row; the flush to HBM happens when the output block index advances).
    nj = pl.num_programs(1)
    next_qi = qid_ref[h, jnp.minimum(n + 1, nj - 1)]
    row_last = jnp.logical_or(n == nnz_ref[h] - 1,
                              jnp.logical_and(active, next_qi != qi))

    @pl.when(row_last)
    def _finalize():
        l_new = l_scr[:, 0:1]
        m_new = m_scr[:, 0:1]
        l_safe = jnp.where(l_new == 0.0, 1.0, l_new)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m_new[:, 0] + jnp.log(l_safe[:, 0])


def _sdq_kernel(qid_ref, kid_ref, nnz_ref, kmask_ref, q_ref, k_ref, v_ref,
                do_ref, lse_ref, delta_ref, seed_ref, dq_ref, acc_scr,
                *, scale, causal, bq, bk, nH, dropout, widen):
    bh, n = pl.program_id(0), pl.program_id(1)
    h = bh % nH
    qi = qid_ref[h, n]
    kj = kid_ref[h, n]
    prev_qi = qid_ref[h, jnp.maximum(n - 1, 0)]
    new_row = jnp.logical_or(n == 0, qi != prev_qi)
    active = n < nnz_ref[h]

    @pl.when(jnp.logical_and(new_row, active))
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(active)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, kj, bq, bk)
        s = _submask(s, kmask_ref[h, n], bk, widen)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout > 0.0:
            keep = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk, dropout)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout)), 0.0)
        ds = p * (dp - delta) * scale
        acc_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    nj = pl.num_programs(1)
    next_qi = qid_ref[h, jnp.minimum(n + 1, nj - 1)]
    row_last = jnp.logical_or(n == nnz_ref[h] - 1,
                              jnp.logical_and(active, next_qi != qi))

    @pl.when(row_last)
    def _store():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _sdkv_kernel(kidT_ref, qidT_ref, nnzT_ref, kmaskT_ref, q_ref, k_ref,
                 v_ref, do_ref, lse_ref, delta_ref, seed_ref, dk_ref, dv_ref,
                 dk_scr, dv_scr, *, scale, causal, bq, bk, nH, dropout,
                 widen):
    bh, n = pl.program_id(0), pl.program_id(1)
    h = bh % nH
    kj = kidT_ref[h, n]
    qi = qidT_ref[h, n]
    prev_kj = kidT_ref[h, jnp.maximum(n - 1, 0)]
    new_col = jnp.logical_or(n == 0, kj != prev_kj)
    active = n < nnzT_ref[h]

    @pl.when(jnp.logical_and(new_col, active))
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(active)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0][None, :]
        delta = delta_ref[0, 0][None, :]
        s2 = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s2 = _causal_mask(s2, qi, kj, bq, bk, transposed=True)
        s2 = _submask(s2, kmaskT_ref[h, n], bk, widen, transposed=True)
        p2 = jnp.exp(s2 - lse)
        if dropout > 0.0:
            keep2 = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk,
                                  dropout, transposed=True)
            inv = 1.0 / (1.0 - dropout)
            p2_drop = jnp.where(keep2, p2 * inv, 0.0)
        else:
            p2_drop = p2
        dv_scr[:] += jax.lax.dot_general(
            p2_drop.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp2 = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout > 0.0:
            dp2 = jnp.where(keep2, dp2 * inv, 0.0)
        ds2 = p2 * (dp2 - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds2.astype(q.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    nj = pl.num_programs(1)
    next_kj = kidT_ref[h, jnp.minimum(n + 1, nj - 1)]
    col_last = jnp.logical_or(n == nnzT_ref[h] - 1,
                              jnp.logical_and(active, next_kj != kj))

    @pl.when(col_last)
    def _store():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


# --------------------------------------------------------------------- #
# pallas_call wrappers
# --------------------------------------------------------------------- #
def _sparse_fwd(q, k, v, qid, kid, nnz, kmask, seed, scale, causal, nH, bq,
                bk, dropout, widen):
    BH, S, D = q.shape
    NNZ = qid.shape[-1]
    kernel = functools.partial(_sfwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, nH=nH, dropout=dropout,
                               widen=widen)
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(BH, NNZ),
            in_specs=[
                pl.BlockSpec((1, bq, D),
                             lambda b, n, qid, kid, nnz, km:
                             (b, qid[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, qid, kid, nnz, km:
                             (b, kid[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, qid, kid, nnz, km:
                             (b, kid[b % nH, n], 0)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, D),
                             lambda b, n, qid, kid, nnz, km:
                             (b, qid[b % nH, n], 0)),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, qid, kid, nnz, km:
                             (b, 0, qid[b % nH, n])),
            ],
            scratch_shapes=[
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, D), jnp.float32),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
        ],
        interpret=_interpret(),
    )(qid, kid, nnz, kmask, q, k, v, seed)
    return o, lse


def _sparse_bwd(q, k, v, o, lse, do, luts, seed, scale, causal, nH, bq, bk,
                dropout, widen):
    qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT = luts
    BH, S, D = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True).transpose(0, 2, 1)  # [BH,1,S]

    dq = pl.pallas_call(
        functools.partial(_sdq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nH=nH, dropout=dropout, widen=widen),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(BH, qid.shape[-1]),
            in_specs=[
                pl.BlockSpec((1, bq, D),
                             lambda b, n, qi, ki, nz, km:
                             (b, qi[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, qi, ki, nz, km:
                             (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, qi, ki, nz, km:
                             (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bq, D),
                             lambda b, n, qi, ki, nz, km:
                             (b, qi[b % nH, n], 0)),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, qi, ki, nz, km:
                             (b, 0, qi[b % nH, n])),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, qi, ki, nz, km:
                             (b, 0, qi[b % nH, n])),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=pl.BlockSpec(
                (1, bq, D),
                lambda b, n, qi, ki, nz, km: (b, qi[b % nH, n], 0)),
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)]),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        interpret=_interpret(),
    )(qid, kid, nnz, kmask, q, k, v, do, lse, delta, seed)

    dk, dv = pl.pallas_call(
        functools.partial(_sdkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nH=nH, dropout=dropout, widen=widen),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(BH, kidT.shape[-1]),
            in_specs=[
                pl.BlockSpec((1, bq, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, qi[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bq, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, qi[b % nH, n], 0)),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, ki, qi, nz, km:
                             (b, 0, qi[b % nH, n])),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, ki, qi, nz, km:
                             (b, 0, qi[b % nH, n])),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bk, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, ki[b % nH, n], 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, D), jnp.float32),
                pltpu.VMEM((bk, D), jnp.float32),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct((BH, k.shape[1], D), k.dtype),
            jax.ShapeDtypeStruct((BH, v.shape[1], D), v.dtype),
        ],
        interpret=_interpret(),
    )(kidT, qidT, nnzT, kmaskT, q, k, v, do, lse, delta, seed)
    return dq, dk, dv


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(12, 13, 14, 15, 16, 17, 18))
def _sparse_flash(q, k, v, qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT,
                  seed, scale, causal, nH, bq, bk, dropout, widen):
    o, _ = _sparse_fwd(q, k, v, qid, kid, nnz, kmask, seed, scale, causal,
                       nH, bq, bk, dropout, widen)
    return o


def _sparse_vjp_fwd(q, k, v, qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT,
                    seed, scale, causal, nH, bq, bk, dropout, widen):
    o, lse = _sparse_fwd(q, k, v, qid, kid, nnz, kmask, seed, scale, causal,
                         nH, bq, bk, dropout, widen)
    from .flash_attention import _tag_residuals
    o, lse = _tag_residuals(o, lse)
    return o, (q, k, v, qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT,
               seed, o, lse)


def _sparse_vjp_bwd(scale, causal, nH, bq, bk, dropout, widen, res, do):
    (q, k, v, qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT, seed, o,
     lse) = res
    dq, dk, dv = _sparse_bwd(
        q, k, v, o, lse, do,
        (qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT), seed,
        scale, causal, nH, bq, bk, dropout, widen)
    return (dq, dk, dv) + (None,) * 9


_sparse_flash.defvjp(_sparse_vjp_fwd, _sparse_vjp_bwd)


# Per-grid-step fixed cost (Mosaic sequencing latency, ~2 us on v5e),
# expressed in block-compute units: one unit = a 128x128 tile's work, so
# at base block b the fixed cost is ALPHA_128 * (128/b)^2 units. The auto
# picker charges candidate widening w a cost of nnz_w * (alpha + w) and
# takes the cheapest. Calibrated on v5e BigBird sweeps (S=32768, D=64):
# block=128 w=1/2/4/8/16 -> 19.8/19.0/14.4/16.3/20.5 ms; block=256
# w=1/2 -> 22.6/21.7; block=512 w=1/2 -> 17.0/19.7 — alpha=16*(128/b)^2
# reproduces all three measured orderings.
_WIDEN_ALPHA_128 = 16.0


def pick_widen(layout: np.ndarray, block: int = 128,
               choices=(1, 2, 4, 8)) -> int:
    lay = np.asarray(layout) != 0
    H, nQ, nK = lay.shape
    alpha = _WIDEN_ALPHA_128 * (128.0 / max(block, 1)) ** 2
    best_w, best_cost = 1, None
    for w in choices:
        if nK % w != 0:
            continue
        nnz_w = int(lay.reshape(H, nQ, nK // w, w).any(-1).sum())
        cost = nnz_w * (alpha + w)
        if best_cost is None or cost < best_cost:
            best_w, best_cost = w, cost
    return best_w


def sparse_flash_attention(q, k, v, layout, *, causal=False, scale,
                           seed=None, dropout: float = 0.0,
                           widen: int = 0):
    """q,k,v: [BH, S, D] (batch*heads flattened); layout: CONCRETE
    [nH, nQ, nK] array with no empty rows/columns. Grid steps == nnz of
    the (possibly k-widened) layout.

    ``widen``: 0 = auto (pick_widen cost model; DS_SPARSE_WIDEN overrides),
    else an explicit k-coarsening factor."""
    import os
    BH, S, D = q.shape
    nH = int(layout.shape[0])
    bq = S // layout.shape[1]
    bk = k.shape[1] // layout.shape[2]
    lay_np = np.asarray(layout)
    if widen == 0:
        widen = int(os.environ.get("DS_SPARSE_WIDEN", "0")) or \
            pick_widen(lay_np, block=bk)
    if layout.shape[2] % widen != 0:
        widen = 1          # non-dividing override/choice: plain 1-wide LUTs
    luts = build_flat_luts(lay_np, widen=widen)
    if luts is None:
        raise ValueError("layout has an empty row/column (or nK % widen "
                         "!= 0); caller should use the gated kernel")
    (qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT) = \
        (jnp.asarray(a) for a in luts)
    seed = jnp.zeros((1, 1), jnp.int32) if seed is None \
        else jnp.asarray(seed, jnp.int32).reshape(1, 1)
    return _sparse_flash(q, k, v, qid, kid, nnz, kmask, qidT, kidT, nnzT,
                         kmaskT, seed, scale, causal, nH, bq, bk * widen,
                         float(dropout), widen)
