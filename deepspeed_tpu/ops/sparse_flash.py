"""LUT-driven block-sparse flash attention — only active blocks are touched.

The layout-gated kernels in flash_attention.py iterate the FULL (q,k) block
grid and gate the compute, so HBM loads and grid overhead still scale
O(S^2). This module is the reference's actual design point
(csrc/sparse_attention/utils.cpp builds LUTs for its Triton kernels,
sdd_segment :14-117), taken to the splash-attention form: the layout
flattens into ONE list of active (q-block, k-block) pairs per head, and the
Pallas grid iterates exactly those nnz steps — scalar-prefetch index maps
pick each step's blocks, the online-softmax state resets on q-row
transitions, and the output block flushes when the row advances. Compute,
bandwidth, AND grid steps all scale with nnz; there is no padding to the
widest row (global-attention rows cost only their own entries).

Forward and dq iterate the row-major pair list; dkv iterates the
column-major list (state carried per k block). Dropout composes via the
same stateless position hash as the dense kernels (keyed by the ACTUAL
block indices read from the LUT), so masks agree across fwd/dq/dkv.

Requirement: every q-block row and k-block column of the layout must have
at least one active block (else its output block would never be written);
``build_flat_luts`` returns None in that case and the caller falls back to
the gated kernel.
"""
from __future__ import annotations

import functools
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .flash_attention import (NEG_INF, _causal_mask, _dropout_keep,
                              _interpret)

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None


def build_flat_luts(layout: np.ndarray):
    """layout [H, nQ, nK] -> (qid, kid, nnz, qidT, kidT, nnzT) int32 arrays
    ([H, NNZ] / [H]), row-major for fwd/dq and column-major for dkv; padded
    tails repeat the last pair. None if any row/column is empty."""
    lay = np.asarray(layout) != 0
    H, nQ, nK = lay.shape
    if (lay.sum(-1) == 0).any() or (lay.sum(-2) == 0).any():
        return None

    def flatten(mask):      # row-major active pairs per head
        pairs = [np.argwhere(mask[h]) for h in range(H)]
        nnz = np.asarray([len(p) for p in pairs], np.int32)
        NNZ = int(nnz.max())
        rid = np.zeros((H, NNZ), np.int32)
        cid = np.zeros((H, NNZ), np.int32)
        for h, p in enumerate(pairs):
            rid[h, :len(p)] = p[:, 0]
            cid[h, :len(p)] = p[:, 1]
            rid[h, len(p):] = p[-1, 0]
            cid[h, len(p):] = p[-1, 1]
        return rid, cid, nnz

    qid, kid, nnz = flatten(lay)
    kidT, qidT, nnzT = flatten(lay.transpose(0, 2, 1))
    return qid, kid, nnz, qidT, kidT, nnzT


# --------------------------------------------------------------------- #
# Kernels — grid (BH, NNZ); state carries across same-row steps
# --------------------------------------------------------------------- #
def _sfwd_kernel(qid_ref, kid_ref, nnz_ref, q_ref, k_ref, v_ref, seed_ref,
                 o_ref, lse_ref, m_scr, l_scr, acc_scr,
                 *, scale, causal, bq, bk, nH, dropout):
    bh, n = pl.program_id(0), pl.program_id(1)
    h = bh % nH
    qi = qid_ref[h, n]
    kj = kid_ref[h, n]
    prev_qi = qid_ref[h, jnp.maximum(n - 1, 0)]
    new_row = jnp.logical_or(n == 0, qi != prev_qi)
    active = n < nnz_ref[h]

    @pl.when(jnp.logical_and(new_row, active))
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(active)
    def _compute():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, kj, bq, bk)
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_scr[:, 0:1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        if dropout > 0.0:
            keep = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk, dropout)
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout)), 0.0)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:, 0:1] = m_new
        l_scr[:, 0:1] = l_new

    # Finalize only on the row's LAST active step (one divide/log/store per
    # row; the flush to HBM happens when the output block index advances).
    nj = pl.num_programs(1)
    next_qi = qid_ref[h, jnp.minimum(n + 1, nj - 1)]
    row_last = jnp.logical_or(n == nnz_ref[h] - 1,
                              jnp.logical_and(active, next_qi != qi))

    @pl.when(row_last)
    def _finalize():
        l_new = l_scr[:, 0:1]
        m_new = m_scr[:, 0:1]
        l_safe = jnp.where(l_new == 0.0, 1.0, l_new)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m_new[:, 0] + jnp.log(l_safe[:, 0])


def _sdq_kernel(qid_ref, kid_ref, nnz_ref, q_ref, k_ref, v_ref, do_ref,
                lse_ref, delta_ref, seed_ref, dq_ref, acc_scr,
                *, scale, causal, bq, bk, nH, dropout):
    bh, n = pl.program_id(0), pl.program_id(1)
    h = bh % nH
    qi = qid_ref[h, n]
    kj = kid_ref[h, n]
    prev_qi = qid_ref[h, jnp.maximum(n - 1, 0)]
    new_row = jnp.logical_or(n == 0, qi != prev_qi)
    active = n < nnz_ref[h]

    @pl.when(jnp.logical_and(new_row, active))
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(active)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, kj, bq, bk)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout > 0.0:
            keep = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk, dropout)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout)), 0.0)
        ds = p * (dp - delta) * scale
        acc_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    nj = pl.num_programs(1)
    next_qi = qid_ref[h, jnp.minimum(n + 1, nj - 1)]
    row_last = jnp.logical_or(n == nnz_ref[h] - 1,
                              jnp.logical_and(active, next_qi != qi))

    @pl.when(row_last)
    def _store():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _sdkv_kernel(kidT_ref, qidT_ref, nnzT_ref, q_ref, k_ref, v_ref, do_ref,
                 lse_ref, delta_ref, seed_ref, dk_ref, dv_ref,
                 dk_scr, dv_scr, *, scale, causal, bq, bk, nH, dropout):
    bh, n = pl.program_id(0), pl.program_id(1)
    h = bh % nH
    kj = kidT_ref[h, n]
    qi = qidT_ref[h, n]
    prev_kj = kidT_ref[h, jnp.maximum(n - 1, 0)]
    new_col = jnp.logical_or(n == 0, kj != prev_kj)
    active = n < nnzT_ref[h]

    @pl.when(jnp.logical_and(new_col, active))
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(active)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0][None, :]
        delta = delta_ref[0, 0][None, :]
        s2 = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s2 = _causal_mask(s2, qi, kj, bq, bk, transposed=True)
        p2 = jnp.exp(s2 - lse)
        if dropout > 0.0:
            keep2 = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk,
                                  dropout, transposed=True)
            inv = 1.0 / (1.0 - dropout)
            p2_drop = jnp.where(keep2, p2 * inv, 0.0)
        else:
            p2_drop = p2
        dv_scr[:] += jax.lax.dot_general(
            p2_drop.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp2 = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout > 0.0:
            dp2 = jnp.where(keep2, dp2 * inv, 0.0)
        ds2 = p2 * (dp2 - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds2.astype(q.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    nj = pl.num_programs(1)
    next_kj = kidT_ref[h, jnp.minimum(n + 1, nj - 1)]
    col_last = jnp.logical_or(n == nnzT_ref[h] - 1,
                              jnp.logical_and(active, next_kj != kj))

    @pl.when(col_last)
    def _store():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


# --------------------------------------------------------------------- #
# pallas_call wrappers
# --------------------------------------------------------------------- #
def _sparse_fwd(q, k, v, qid, kid, nnz, seed, scale, causal, nH, bq, bk,
                dropout):
    BH, S, D = q.shape
    NNZ = qid.shape[-1]
    kernel = functools.partial(_sfwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, nH=nH, dropout=dropout)
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(BH, NNZ),
            in_specs=[
                pl.BlockSpec((1, bq, D),
                             lambda b, n, qid, kid, nnz:
                             (b, qid[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, qid, kid, nnz:
                             (b, kid[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, qid, kid, nnz:
                             (b, kid[b % nH, n], 0)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, D),
                             lambda b, n, qid, kid, nnz:
                             (b, qid[b % nH, n], 0)),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, qid, kid, nnz:
                             (b, 0, qid[b % nH, n])),
            ],
            scratch_shapes=[
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, D), jnp.float32),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
        ],
        interpret=_interpret(),
    )(qid, kid, nnz, q, k, v, seed)
    return o, lse


def _sparse_bwd(q, k, v, o, lse, do, luts, seed, scale, causal, nH, bq, bk,
                dropout):
    qid, kid, nnz, qidT, kidT, nnzT = luts
    BH, S, D = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True).transpose(0, 2, 1)  # [BH,1,S]

    dq = pl.pallas_call(
        functools.partial(_sdq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nH=nH, dropout=dropout),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(BH, qid.shape[-1]),
            in_specs=[
                pl.BlockSpec((1, bq, D),
                             lambda b, n, qi, ki, nz: (b, qi[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, qi, ki, nz: (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, qi, ki, nz: (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bq, D),
                             lambda b, n, qi, ki, nz: (b, qi[b % nH, n], 0)),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, qi, ki, nz: (b, 0, qi[b % nH, n])),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, qi, ki, nz: (b, 0, qi[b % nH, n])),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=pl.BlockSpec(
                (1, bq, D), lambda b, n, qi, ki, nz: (b, qi[b % nH, n], 0)),
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)]),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        interpret=_interpret(),
    )(qid, kid, nnz, q, k, v, do, lse, delta, seed)

    dk, dv = pl.pallas_call(
        functools.partial(_sdkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nH=nH, dropout=dropout),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(BH, kidT.shape[-1]),
            in_specs=[
                pl.BlockSpec((1, bq, D),
                             lambda b, n, ki, qi, nz: (b, qi[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, ki, qi, nz: (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, ki, qi, nz: (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bq, D),
                             lambda b, n, ki, qi, nz: (b, qi[b % nH, n], 0)),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, ki, qi, nz: (b, 0, qi[b % nH, n])),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, ki, qi, nz: (b, 0, qi[b % nH, n])),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bk, D),
                             lambda b, n, ki, qi, nz: (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, ki, qi, nz: (b, ki[b % nH, n], 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, D), jnp.float32),
                pltpu.VMEM((bk, D), jnp.float32),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct((BH, k.shape[1], D), k.dtype),
            jax.ShapeDtypeStruct((BH, v.shape[1], D), v.dtype),
        ],
        interpret=_interpret(),
    )(kidT, qidT, nnzT, q, k, v, do, lse, delta, seed)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(10, 11, 12, 13, 14, 15))
def _sparse_flash(q, k, v, qid, kid, nnz, qidT, kidT, nnzT, seed,
                  scale, causal, nH, bq, bk, dropout):
    o, _ = _sparse_fwd(q, k, v, qid, kid, nnz, seed, scale, causal, nH,
                       bq, bk, dropout)
    return o


def _sparse_vjp_fwd(q, k, v, qid, kid, nnz, qidT, kidT, nnzT, seed,
                    scale, causal, nH, bq, bk, dropout):
    o, lse = _sparse_fwd(q, k, v, qid, kid, nnz, seed, scale, causal, nH,
                         bq, bk, dropout)
    from .flash_attention import _tag_residuals
    o, lse = _tag_residuals(o, lse)
    return o, (q, k, v, qid, kid, nnz, qidT, kidT, nnzT, seed, o, lse)


def _sparse_vjp_bwd(scale, causal, nH, bq, bk, dropout, res, do):
    q, k, v, qid, kid, nnz, qidT, kidT, nnzT, seed, o, lse = res
    dq, dk, dv = _sparse_bwd(q, k, v, o, lse, do,
                             (qid, kid, nnz, qidT, kidT, nnzT), seed,
                             scale, causal, nH, bq, bk, dropout)
    return (dq, dk, dv) + (None,) * 7


_sparse_flash.defvjp(_sparse_vjp_fwd, _sparse_vjp_bwd)


def sparse_flash_attention(q, k, v, layout, *, causal=False, scale,
                           seed=None, dropout: float = 0.0):
    """q,k,v: [BH, S, D] (batch*heads flattened); layout: CONCRETE
    [nH, nQ, nK] array with no empty rows/columns. Grid steps == nnz."""
    BH, S, D = q.shape
    nH = int(layout.shape[0])
    bq = S // layout.shape[1]
    bk = k.shape[1] // layout.shape[2]
    luts = build_flat_luts(np.asarray(layout))
    if luts is None:
        raise ValueError("layout has an empty row/column; caller should "
                         "use the gated kernel")
    qid, kid, nnz, qidT, kidT, nnzT = (jnp.asarray(a) for a in luts)
    seed = jnp.zeros((1, 1), jnp.int32) if seed is None \
        else jnp.asarray(seed, jnp.int32).reshape(1, 1)
    return _sparse_flash(q, k, v, qid, kid, nnz, qidT, kidT, nnzT, seed,
                         scale, causal, nH, bq, bk, float(dropout))
