"""LUT-driven block-sparse flash attention — only active blocks are touched.

The layout-gated kernels in flash_attention.py iterate the FULL (q,k) block
grid and gate the compute, so HBM loads and grid overhead still scale
O(S^2). This module is the reference's actual design point
(csrc/sparse_attention/utils.cpp builds LUTs for its Triton kernels,
sdd_segment :14-117), taken to the splash-attention form: the layout
flattens into ONE list of active (q-block, k-block) pairs per head, and the
Pallas grid iterates exactly those nnz steps — scalar-prefetch index maps
pick each step's blocks, the online-softmax state resets on q-row
transitions, and the output block flushes when the row advances. Compute,
bandwidth, AND grid steps all scale with nnz; there is no padding to the
widest row (global-attention rows cost only their own entries).

Forward and dq iterate the row-major pair list; dkv iterates the
column-major list (state carried per k block). Dropout composes via the
same stateless position hash as the dense kernels (keyed by the ACTUAL
block indices read from the LUT), so masks agree across fwd/dq/dkv.

Requirement: every q-block row and k-block column of the layout must have
at least one active block (else its output block would never be written);
``build_flat_luts`` returns None in that case and the caller falls back to
the gated kernel.
"""
from __future__ import annotations

import functools
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .flash_attention import (NEG_INF, _causal_mask, _dropout_keep,
                              _interpret)

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None


def build_flat_luts(layout: np.ndarray, widen: int = 1, qwiden: int = 1):
    """layout [H, nQ, nK] -> (qid, kid, nnz, kmask, qidT, kidT, nnzT,
    kmaskT) int32 arrays ([H, NNZ] / [H]), row-major for fwd/dq and
    column-major for dkv; padded tails repeat the last pair. None if any
    row/column is empty.

    ``widen``/``qwiden`` > 1 coarsen the K/Q dimensions by those factors:
    one LUT entry covers a ``qwiden x widen`` super-tile of base blocks
    (qid/kid index WIDE blocks) and ``kmask`` is a per-entry bitmask of
    which sub-blocks are live — bit ``sq * widen + sk`` for sub-row sq,
    sub-col sk; dead sub-blocks are softmax-masked in-kernel. Banded
    layouts (local attention) coarsen nearly for free in BOTH dims, and
    each grid step's matmuls grow ``qwiden*widen``x — amortizing the fixed
    per-step sequencing cost that dominates at head-dim 64, and deepening
    the MXU tiles (a 128-row step at D=64 underfills the systolic array;
    qwiden=2+ feeds it 256+ rows). Padded tail entries carry kmask=0, so
    they are hard no-ops."""
    lay = np.asarray(layout) != 0
    H, nQ, nK = lay.shape
    if (lay.sum(-1) == 0).any() or (lay.sum(-2) == 0).any():
        return None
    w, qw = int(widen), int(qwiden)
    if nK % w != 0 or nQ % qw != 0 or qw * w > 31:
        return None
    nK2, nQ2 = nK // w, nQ // qw
    # bits[h, q2, k2]: bit (sq * w + sk) = live(sub-row sq, sub-col sk)
    sub = lay.reshape(H, nQ2, qw, nK2, w).transpose(0, 1, 3, 2, 4)
    flat = sub.reshape(H, nQ2, nK2, qw * w)
    bits = (flat.astype(np.int64) <<
            np.arange(qw * w, dtype=np.int64)).sum(-1).astype(np.int32)

    def flatten(mask, bit_lookup):   # row-major active pairs per head
        pairs = [np.argwhere(mask[h]) for h in range(H)]
        nnz = np.asarray([len(p) for p in pairs], np.int32)
        NNZ = int(nnz.max())
        rid = np.zeros((H, NNZ), np.int32)
        cid = np.zeros((H, NNZ), np.int32)
        bm = np.zeros((H, NNZ), np.int32)
        for h, p in enumerate(pairs):
            rid[h, :len(p)] = p[:, 0]
            cid[h, :len(p)] = p[:, 1]
            bm[h, :len(p)] = bit_lookup(h, p[:, 0], p[:, 1])
            rid[h, len(p):] = p[-1, 0]
            cid[h, len(p):] = p[-1, 1]
            # kmask stays 0 on the padded tail: a hard no-op
        return rid, cid, nnz, bm

    lay2 = bits != 0
    qid, kid, nnz, kmask = flatten(lay2, lambda h, q, k2: bits[h, q, k2])
    kidT, qidT, nnzT, kmaskT = flatten(
        lay2.transpose(0, 2, 1), lambda h, k2, q: bits[h, q, k2])
    return qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT


# --------------------------------------------------------------------- #
# Kernels — grid (BH, NNZ); state carries across same-row steps
# --------------------------------------------------------------------- #
def _submask(s, bits, bq: int, bk: int, qwiden: int, widen: int,
             transposed: bool = False):
    """NEG_INF-mask the sub-blocks of a qwiden x widen super-tile whose
    LUT bit is 0. s: [bq, bk] (or [bk, bq] transposed); bit index is
    sub_q * widen + sub_k."""
    if widen == 1 and qwiden == 1:
        return s
    subq, subk = bq // qwiden, bk // widen
    q_axis, k_axis = (1, 0) if transposed else (0, 1)
    sq = jax.lax.broadcasted_iota(jnp.int32, s.shape, q_axis) // subq
    sk = jax.lax.broadcasted_iota(jnp.int32, s.shape, k_axis) // subk
    live = jax.lax.shift_right_logical(bits, sq * widen + sk) & 1
    return jnp.where(live == 1, s, NEG_INF)


def _sfwd_kernel(qid_ref, kid_ref, nnz_ref, kmask_ref, q_ref, k_ref, v_ref,
                 seed_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                 *, scale, causal, bq, bk, nH, dropout, widen, qwiden):
    bh, n = pl.program_id(0), pl.program_id(1)
    h = bh % nH
    qi = qid_ref[h, n]
    kj = kid_ref[h, n]
    prev_qi = qid_ref[h, jnp.maximum(n - 1, 0)]
    new_row = jnp.logical_or(n == 0, qi != prev_qi)
    active = n < nnz_ref[h]

    @pl.when(jnp.logical_and(new_row, active))
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(active)
    def _compute():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, kj, bq, bk)
        s = _submask(s, kmask_ref[h, n], bq, bk, qwiden, widen)
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_scr[:, 0:1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        if dropout > 0.0:
            keep = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk, dropout)
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout)), 0.0)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:, 0:1] = m_new
        l_scr[:, 0:1] = l_new

    # Finalize only on the row's LAST active step (one divide/log/store per
    # row; the flush to HBM happens when the output block index advances).
    nj = pl.num_programs(1)
    next_qi = qid_ref[h, jnp.minimum(n + 1, nj - 1)]
    row_last = jnp.logical_or(n == nnz_ref[h] - 1,
                              jnp.logical_and(active, next_qi != qi))

    @pl.when(row_last)
    def _finalize():
        l_new = l_scr[:, 0:1]
        m_new = m_scr[:, 0:1]
        l_safe = jnp.where(l_new == 0.0, 1.0, l_new)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m_new[:, 0] + jnp.log(l_safe[:, 0])


def _sdq_kernel(qid_ref, kid_ref, nnz_ref, kmask_ref, q_ref, k_ref, v_ref,
                do_ref, lse_ref, delta_ref, seed_ref, dq_ref, acc_scr,
                *, scale, causal, bq, bk, nH, dropout, widen, qwiden):
    bh, n = pl.program_id(0), pl.program_id(1)
    h = bh % nH
    qi = qid_ref[h, n]
    kj = kid_ref[h, n]
    prev_qi = qid_ref[h, jnp.maximum(n - 1, 0)]
    new_row = jnp.logical_or(n == 0, qi != prev_qi)
    active = n < nnz_ref[h]

    @pl.when(jnp.logical_and(new_row, active))
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(active)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, kj, bq, bk)
        s = _submask(s, kmask_ref[h, n], bq, bk, qwiden, widen)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout > 0.0:
            keep = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk, dropout)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout)), 0.0)
        ds = p * (dp - delta) * scale
        acc_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    nj = pl.num_programs(1)
    next_qi = qid_ref[h, jnp.minimum(n + 1, nj - 1)]
    row_last = jnp.logical_or(n == nnz_ref[h] - 1,
                              jnp.logical_and(active, next_qi != qi))

    @pl.when(row_last)
    def _store():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _sfused_bwd_kernel(kidT_ref, qidT_ref, nnzT_ref, kmaskT_ref, q_ref,
                       k_ref, v_ref, do_ref, lse_ref, delta_ref, seed_ref,
                       dk_ref, dv_ref, dqp_ref, dk_scr, dv_scr, *, scale,
                       causal, bq, bk, nH, dropout, widen, qwiden):
    """Fused backward: ONE column-major pass emits dk, dv AND per-step dq
    partials (segment-summed by q-row outside the kernel). Compared to
    the split dq+dkv pair this computes s/p/dp/ds once instead of twice —
    the per-block cost that dominates at head-dim 64 — and drops a whole
    kernel's per-step fixed cost. The dense kernels' fused whole-S
    backward is the same idea; here the partial-sum trick stands in for
    whole-S row coverage (a k-column's steps touch arbitrary q rows, so
    dq cannot be accumulated in scratch across them)."""
    bh, n = pl.program_id(0), pl.program_id(1)
    h = bh % nH
    kj = kidT_ref[h, n]
    qi = qidT_ref[h, n]
    prev_kj = kidT_ref[h, jnp.maximum(n - 1, 0)]
    new_col = jnp.logical_or(n == 0, kj != prev_kj)
    active = n < nnzT_ref[h]

    @pl.when(jnp.logical_and(new_col, active))
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(active)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0][None, :]
        delta = delta_ref[0, 0][None, :]
        s2 = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s2 = _causal_mask(s2, qi, kj, bq, bk, transposed=True)
        s2 = _submask(s2, kmaskT_ref[h, n], bq, bk, qwiden, widen,
                      transposed=True)
        p2 = jnp.exp(s2 - lse)
        if dropout > 0.0:
            keep2 = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk,
                                  dropout, transposed=True)
            inv = 1.0 / (1.0 - dropout)
            p2_drop = jnp.where(keep2, p2 * inv, 0.0)
        else:
            p2_drop = p2
        dv_scr[:] += jax.lax.dot_general(
            p2_drop.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp2 = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout > 0.0:
            dp2 = jnp.where(keep2, dp2 * inv, 0.0)
        ds2 = p2 * (dp2 - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds2.astype(q.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dq partial for THIS step's q rows: ds^T @ k, shipped per step
        # (garbage on inactive tail steps is routed to a dump segment by
        # the host-built segment ids, never summed into a real row).
        dqp_ref[0, 0] = jax.lax.dot_general(
            ds2.astype(k.dtype), k, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dqp_ref.dtype)

    nj = pl.num_programs(1)
    next_kj = kidT_ref[h, jnp.minimum(n + 1, nj - 1)]
    col_last = jnp.logical_or(n == nnzT_ref[h] - 1,
                              jnp.logical_and(active, next_kj != kj))

    @pl.when(col_last)
    def _store():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _sdkv_kernel(kidT_ref, qidT_ref, nnzT_ref, kmaskT_ref, q_ref, k_ref,
                 v_ref, do_ref, lse_ref, delta_ref, seed_ref, dk_ref, dv_ref,
                 dk_scr, dv_scr, *, scale, causal, bq, bk, nH, dropout,
                 widen, qwiden):
    bh, n = pl.program_id(0), pl.program_id(1)
    h = bh % nH
    kj = kidT_ref[h, n]
    qi = qidT_ref[h, n]
    prev_kj = kidT_ref[h, jnp.maximum(n - 1, 0)]
    new_col = jnp.logical_or(n == 0, kj != prev_kj)
    active = n < nnzT_ref[h]

    @pl.when(jnp.logical_and(new_col, active))
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(active)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0][None, :]
        delta = delta_ref[0, 0][None, :]
        s2 = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s2 = _causal_mask(s2, qi, kj, bq, bk, transposed=True)
        s2 = _submask(s2, kmaskT_ref[h, n], bq, bk, qwiden, widen,
                      transposed=True)
        p2 = jnp.exp(s2 - lse)
        if dropout > 0.0:
            keep2 = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk,
                                  dropout, transposed=True)
            inv = 1.0 / (1.0 - dropout)
            p2_drop = jnp.where(keep2, p2 * inv, 0.0)
        else:
            p2_drop = p2
        dv_scr[:] += jax.lax.dot_general(
            p2_drop.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp2 = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout > 0.0:
            dp2 = jnp.where(keep2, dp2 * inv, 0.0)
        ds2 = p2 * (dp2 - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds2.astype(q.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    nj = pl.num_programs(1)
    next_kj = kidT_ref[h, jnp.minimum(n + 1, nj - 1)]
    col_last = jnp.logical_or(n == nnzT_ref[h] - 1,
                              jnp.logical_and(active, next_kj != kj))

    @pl.when(col_last)
    def _store():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


# --------------------------------------------------------------------- #
# pallas_call wrappers
# --------------------------------------------------------------------- #
def _sparse_fwd(q, k, v, qid, kid, nnz, kmask, seed, scale, causal, nH, bq,
                bk, dropout, widen, qwiden):
    BH, S, D = q.shape
    NNZ = qid.shape[-1]
    kernel = functools.partial(_sfwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, nH=nH, dropout=dropout,
                               widen=widen, qwiden=qwiden)
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(BH, NNZ),
            in_specs=[
                pl.BlockSpec((1, bq, D),
                             lambda b, n, qid, kid, nnz, km:
                             (b, qid[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, qid, kid, nnz, km:
                             (b, kid[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, qid, kid, nnz, km:
                             (b, kid[b % nH, n], 0)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, D),
                             lambda b, n, qid, kid, nnz, km:
                             (b, qid[b % nH, n], 0)),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, qid, kid, nnz, km:
                             (b, 0, qid[b % nH, n])),
            ],
            scratch_shapes=[
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, D), jnp.float32),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
        ],
        interpret=_interpret(),
    )(qid, kid, nnz, kmask, q, k, v, seed)
    return o, lse


def _sparse_bwd_fused(q, k, v, o, lse, do, luts, seed, scale, causal, nH,
                      bq, bk, dropout, widen, qwiden):
    """One column-major pass for dk+dv+dq-partials, then a scatter-add
    over q rows. Vs the split dq+dkv pair: s/p/dp/ds computed once per
    block instead of twice, and one kernel's per-step fixed cost gone."""
    qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT = luts
    BH, S, D = q.shape
    NNZT = kidT.shape[-1]
    nQ2 = S // bq
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True).transpose(0, 2, 1)  # [BH,1,S]

    dk, dv, dqp = pl.pallas_call(
        functools.partial(_sfused_bwd_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nH=nH, dropout=dropout, widen=widen,
                          qwiden=qwiden),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(BH, NNZT),
            in_specs=[
                pl.BlockSpec((1, bq, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, qi[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bq, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, qi[b % nH, n], 0)),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, ki, qi, nz, km:
                             (b, 0, qi[b % nH, n])),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, ki, qi, nz, km:
                             (b, 0, qi[b % nH, n])),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bk, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, 1, bq, D),
                             lambda b, n, ki, qi, nz, km: (b, n, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, D), jnp.float32),
                pltpu.VMEM((bk, D), jnp.float32),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct((BH, k.shape[1], D), k.dtype),
            jax.ShapeDtypeStruct((BH, v.shape[1], D), v.dtype),
            jax.ShapeDtypeStruct((BH, NNZT, bq, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(kidT, qidT, nnzT, kmaskT, q, k, v, do, lse, delta, seed)

    # Route each step's dq partial to its q row; tail steps (n >= nnzT[h],
    # whose dqp blocks are unwritten garbage) go to a dump segment.
    steps = jnp.arange(NNZT)[None, :]                       # [1, NNZT]
    ids = jnp.where(steps < nnzT[:, None], qidT, nQ2)       # [nH, NNZT]

    def seg(dqp_bh, ids_h):
        out = jnp.zeros((nQ2 + 1, bq, D), jnp.float32)
        return out.at[ids_h].add(dqp_bh)[:nQ2]

    dq = jax.vmap(seg)(dqp, ids[jnp.arange(BH) % nH])
    return dq.reshape(BH, S, D).astype(q.dtype), dk, dv


def _sparse_bwd(q, k, v, o, lse, do, luts, seed, scale, causal, nH, bq, bk,
                dropout, widen, qwiden):
    # DS_SPARSE_FUSED_BWD=1 opts into the fused single-pass backward.
    # Measured v5e (S=32768, d=0.023): fused 16.7/15.5 ms (q2k4/q1k4) vs
    # split 15.2/16.2 — the f32 dq-partials traffic + segment scatter
    # offsets the saved s/p recompute, so the split pair stays default.
    # Kept because the balance flips where HBM is faster relative to the
    # per-step fixed cost (larger D, future chips).
    import os
    if os.environ.get("DS_SPARSE_FUSED_BWD", "0") == "1":
        return _sparse_bwd_fused(q, k, v, o, lse, do, luts, seed, scale,
                                 causal, nH, bq, bk, dropout, widen, qwiden)
    qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT = luts
    BH, S, D = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True).transpose(0, 2, 1)  # [BH,1,S]

    dq = pl.pallas_call(
        functools.partial(_sdq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nH=nH, dropout=dropout, widen=widen,
                          qwiden=qwiden),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(BH, qid.shape[-1]),
            in_specs=[
                pl.BlockSpec((1, bq, D),
                             lambda b, n, qi, ki, nz, km:
                             (b, qi[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, qi, ki, nz, km:
                             (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, qi, ki, nz, km:
                             (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bq, D),
                             lambda b, n, qi, ki, nz, km:
                             (b, qi[b % nH, n], 0)),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, qi, ki, nz, km:
                             (b, 0, qi[b % nH, n])),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, qi, ki, nz, km:
                             (b, 0, qi[b % nH, n])),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=pl.BlockSpec(
                (1, bq, D),
                lambda b, n, qi, ki, nz, km: (b, qi[b % nH, n], 0)),
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)]),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        interpret=_interpret(),
    )(qid, kid, nnz, kmask, q, k, v, do, lse, delta, seed)

    dk, dv = pl.pallas_call(
        functools.partial(_sdkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nH=nH, dropout=dropout, widen=widen,
                          qwiden=qwiden),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(BH, kidT.shape[-1]),
            in_specs=[
                pl.BlockSpec((1, bq, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, qi[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bq, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, qi[b % nH, n], 0)),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, ki, qi, nz, km:
                             (b, 0, qi[b % nH, n])),
                pl.BlockSpec((1, 1, bq),
                             lambda b, n, ki, qi, nz, km:
                             (b, 0, qi[b % nH, n])),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bk, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, ki[b % nH, n], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, n, ki, qi, nz, km:
                             (b, ki[b % nH, n], 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, D), jnp.float32),
                pltpu.VMEM((bk, D), jnp.float32),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct((BH, k.shape[1], D), k.dtype),
            jax.ShapeDtypeStruct((BH, v.shape[1], D), v.dtype),
        ],
        interpret=_interpret(),
    )(kidT, qidT, nnzT, kmaskT, q, k, v, do, lse, delta, seed)
    return dq, dk, dv


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(12, 13, 14, 15, 16, 17, 18, 19))
def _sparse_flash(q, k, v, qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT,
                  seed, scale, causal, nH, bq, bk, dropout, widen, qwiden):
    o, _ = _sparse_fwd(q, k, v, qid, kid, nnz, kmask, seed, scale, causal,
                       nH, bq, bk, dropout, widen, qwiden)
    return o


def _sparse_vjp_fwd(q, k, v, qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT,
                    seed, scale, causal, nH, bq, bk, dropout, widen, qwiden):
    o, lse = _sparse_fwd(q, k, v, qid, kid, nnz, kmask, seed, scale, causal,
                         nH, bq, bk, dropout, widen, qwiden)
    from .flash_attention import _tag_residuals
    o, lse = _tag_residuals(o, lse)
    return o, (q, k, v, qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT,
               seed, o, lse)


def _sparse_vjp_bwd(scale, causal, nH, bq, bk, dropout, widen, qwiden, res,
                    do):
    (q, k, v, qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT, seed, o,
     lse) = res
    dq, dk, dv = _sparse_bwd(
        q, k, v, o, lse, do,
        (qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT), seed,
        scale, causal, nH, bq, bk, dropout, widen, qwiden)
    return (dq, dk, dv) + (None,) * 9


_sparse_flash.defvjp(_sparse_vjp_fwd, _sparse_vjp_bwd)


# Per-grid-step fixed cost (Mosaic sequencing latency), expressed in
# block-compute units: one unit = a 128x128 tile's work, so at base block
# b the fixed cost is ALPHA_128 * (128/b)^2 units. The auto picker
# charges candidate super-tile (qw, kw) a cost of
# nnz_{qw,kw} * (alpha + qw*kw + QW_PENALTY*(qw-1)) and takes the
# cheapest. Round-5 calibration from the v5e BigBird sweep (S=32768,
# D=64, block=128, fwd+bwd): 1x1/1x4/2x2/2x4/4x2/2x8/4x4 ->
# 19.4/16.2/17.2/15.7/17.7/18.9/18.3 ms fits t = steps*(3.75us +
# 0.49us*blocks) => alpha ~= 7.7; the residual q-widening overhead (row
# state grows with bq; measured q2k2 > q1k4 despite equal model cost) is
# the QW_PENALTY term. The law also names the remaining ceiling: per
# 128x128 block ~0.49us across three passes is MXU time on shallow
# D=64-contraction dots — cutting it further needs a fused backward (one
# s/p computation feeding dq+dk+dv, as the dense kernel does) rather
# than better tiling.
_WIDEN_ALPHA_128 = 7.7
_QW_PENALTY = 1.0


def pick_widen(layout: np.ndarray, block: int = 128,
               choices=(1, 2, 4, 8)) -> int:
    """K-only tiling pick (kept for API compatibility): pick_tile with
    q_choices=(1,)."""
    return pick_tile(layout, block=block, k_choices=tuple(choices),
                     q_choices=(1,))[1]


def supertile_nnz(layout: np.ndarray, qw: int, kw: int) -> int:
    """Occupied qw x kw super-tiles of a [H, nQ, nK] layout (= grid steps
    per full pass at that tiling)."""
    lay = np.asarray(layout) != 0
    H, nQ, nK = lay.shape
    return int(lay.reshape(H, nQ // qw, qw, nK // kw, kw)
               .any(axis=(2, 4)).sum())


def pick_tile(layout: np.ndarray, block: int = 128,
              k_choices=(1, 2, 4, 8), q_choices=(1, 2)):
    """(qwiden, kwiden) minimizing the calibrated step-cost model (see
    _WIDEN_ALPHA_128). Banded layouts coarsen nearly for free in both
    dimensions, so the optimum moves to super-tiles whose compute drowns
    the fixed per-step cost; q_choices stops at 2 because measured
    q-widening overhead outgrows its step savings beyond that."""
    lay = np.asarray(layout) != 0
    H, nQ, nK = lay.shape
    alpha = _WIDEN_ALPHA_128 * (128.0 / max(block, 1)) ** 2
    cands = {}
    for qw in q_choices:
        if nQ % qw != 0:
            continue
        for kw in k_choices:
            if nK % kw != 0 or qw * kw > 31:
                continue
            cands[(qw, kw)] = supertile_nnz(lay, qw, kw) * \
                (alpha + qw * kw + _QW_PENALTY * (qw - 1))
    if not cands:
        return (1, 1)
    lo = min(cands.values())
    # The model cannot order near-ties (its residuals are ~8%); among
    # those, the LARGEST super-tile measures fastest (deeper MXU work per
    # step) — v5e sweep: q2k4 beats q1k4/q2k2 despite equal model cost.
    near = [t for t, c in cands.items() if c <= 1.08 * lo]
    return max(near, key=lambda t: (t[0] * t[1], t[1]))


def sparse_flash_attention(q, k, v, layout, *, causal=False, scale,
                           seed=None, dropout: float = 0.0,
                           widen: int = 0, qwiden: int = 0):
    """q,k,v: [BH, S, D] (batch*heads flattened); layout: CONCRETE
    [nH, nQ, nK] array with no empty rows/columns. Grid steps == nnz of
    the (possibly super-tiled) layout.

    ``widen``/``qwiden``: 0 = auto (pick_tile cost model;
    DS_SPARSE_WIDEN / DS_SPARSE_QWIDEN override), else explicit k/q
    coarsening factors."""
    import os
    BH, S, D = q.shape
    nH = int(layout.shape[0])
    bq = S // layout.shape[1]
    bk = k.shape[1] // layout.shape[2]
    lay_np = np.asarray(layout)
    if widen == 0:
        widen = int(os.environ.get("DS_SPARSE_WIDEN", "0"))
    if qwiden == 0:
        qwiden = int(os.environ.get("DS_SPARSE_QWIDEN", "0"))
    if widen == 0 and qwiden == 0:
        # The cost-model pick routes through ops.autotune: on TPU the
        # first compile of a (shape, layout) key times the legal
        # super-tile grid (fwd pass — the bwd kernels share the tiling)
        # and caches the winner; DS_AUTOTUNE=0 / CPU keep the calibrated
        # pick_tile model bit-for-bit.
        from . import autotune
        heur = pick_tile(lay_np, block=bk)
        nQ, nK = int(layout.shape[1]), int(layout.shape[2])
        cands = [(qw, kw) for qw in (1, 2) for kw in (1, 2, 4, 8)
                 if nQ % qw == 0 and nK % kw == 0 and qw * kw <= 31]
        measure = None
        if autotune.search_allowed():
            def run_at(tile):
                return sparse_flash_attention(
                    jnp.zeros((BH, S, D), q.dtype),
                    jnp.zeros(k.shape, k.dtype),
                    jnp.zeros(v.shape, v.dtype), lay_np, causal=causal,
                    scale=scale, qwiden=tile[0], widen=tile[1])
            measure = autotune.measure_from_runner(run_at)
        nnz = int((lay_np != 0).sum())
        qwiden, widen = autotune.resolve(
            "sparse_flash", (BH, S, D, nH, nQ, nK, nnz, int(causal)),
            str(q.dtype), heur, cands, measure)
    # Pinning one factor explicitly leaves the other at 1 (not auto):
    # callers sweeping a single dimension get exactly that dimension.
    widen = widen or 1
    qwiden = qwiden or 1
    req = (qwiden, widen)
    if layout.shape[2] % widen != 0 or widen > 31:
        widen = 1          # non-dividing/overwide: plain 1-wide LUTs
    if layout.shape[1] % qwiden != 0 or qwiden * widen > 31:
        qwiden = 1
    if (qwiden, widen) != req:
        from ..utils.logging import logger
        logger.warning(
            f"sparse_flash_attention: requested super-tile q{req[0]}xk"
            f"{req[1]} does not fit this layout (divisibility or the "
            f"31-bit mask cap); running q{qwiden}xk{widen}")
    luts = build_flat_luts(lay_np, widen=widen, qwiden=qwiden)
    if luts is None:
        raise ValueError("layout has an empty q-block row or k-block "
                         "column; caller should use the gated kernel")
    (qid, kid, nnz, kmask, qidT, kidT, nnzT, kmaskT) = \
        (jnp.asarray(a) for a in luts)
    seed = jnp.zeros((1, 1), jnp.int32) if seed is None \
        else jnp.asarray(seed, jnp.int32).reshape(1, 1)
    return _sparse_flash(q, k, v, qid, kid, nnz, kmask, qidT, kidT, nnzT,
                         kmaskT, seed, scale, causal, nH, bq * qwiden,
                         bk * widen, float(dropout), widen, qwiden)
