"""LUT-driven block-sparse flash attention — only active blocks are touched.

The layout-gated kernel in flash_attention.py iterates the FULL (q,k) block
grid and gates the compute, so HBM block loads and grid overhead still scale
O(S^2) — fine for moderate sparsity, useless for long-context layouts where
<5% of blocks are live. This module is the reference's actual design point
(csrc/sparse_attention/utils.cpp builds per-row LUTs for the Triton kernels;
sdd_segment at :14-117): compress the layout into per-q-block lists of
active k-block indices and drive the Pallas grid with SCALAR-PREFETCH index
maps, so the kernel only ever loads and computes the live blocks — compute
and bandwidth scale with nnz, the splash-attention pattern.

Forward and dq iterate the row LUT (active k per q block); dkv iterates the
column LUT (active q per k block). Padded LUT tail entries repeat a valid
index (their loads are harmless) and are gated off the accumulators by the
per-row count.

Dropout composes via the same stateless position hash as the dense kernels
(flash_attention._dropout_keep) keyed by the ACTUAL block indices read from
the LUT, so masks agree across fwd/dq/dkv regardless of iteration order.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .flash_attention import (NEG_INF, _causal_mask, _dropout_keep,
                              _interpret)

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None


def build_luts(layout: np.ndarray):
    """layout [H, nQ, nK] (0/1) -> (lut [H,nQ,maxn], cnt [H,nQ],
    lutT [H,nK,maxnT], cntT [H,nK]) int32. Pad entries repeat the last
    valid index (or 0 for empty rows)."""
    layout = np.asarray(layout) != 0
    H, nQ, nK = layout.shape

    def one(mask):      # mask [H, R, C] -> (lut, cnt)
        cnt = mask.sum(-1).astype(np.int32)
        maxn = max(1, int(cnt.max()))
        lut = np.zeros(mask.shape[:2] + (maxn,), np.int32)
        for h in range(mask.shape[0]):
            for r in range(mask.shape[1]):
                idx = np.flatnonzero(mask[h, r])
                if idx.size:
                    lut[h, r, :idx.size] = idx
                    lut[h, r, idx.size:] = idx[-1]
        return lut, cnt

    lut, cnt = one(layout)
    lutT, cntT = one(layout.transpose(0, 2, 1))
    return lut, cnt, lutT, cntT


# --------------------------------------------------------------------- #
# Kernels
# --------------------------------------------------------------------- #
def _sfwd_kernel(lut_ref, cnt_ref, q_ref, k_ref, v_ref, seed_ref,
                 o_ref, lse_ref, m_scr, l_scr, acc_scr,
                 *, scale, causal, bq, bk, nH, dropout):
    bh, qi, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nj = pl.num_programs(2)
    h = bh % nH

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    kj = lut_ref[h, qi, j]

    @pl.when(j < cnt_ref[h, qi])
    def _compute():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, kj, bq, bk)
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_scr[:, 0:1] = l_scr[:, 0:1] * alpha + \
            jnp.sum(p, axis=1, keepdims=True)
        if dropout > 0.0:
            keep = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk, dropout)
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout)), 0.0)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:, 0:1] = m_new

    @pl.when(j == nj - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.where(
            l[:, 0] == 0.0, NEG_INF, m_scr[:, 0] + jnp.log(l_safe[:, 0]))


def _sdq_kernel(lut_ref, cnt_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                delta_ref, seed_ref, dq_ref, acc_scr,
                *, scale, causal, bq, bk, nH, dropout):
    bh, qi, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nj = pl.num_programs(2)
    h = bh % nH

    @pl.when(j == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    kj = lut_ref[h, qi, j]

    @pl.when(j < cnt_ref[h, qi])
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, kj, bq, bk)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout > 0.0:
            keep = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk, dropout)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout)), 0.0)
        ds = p * (dp - delta) * scale
        acc_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nj - 1)
    def _finalize():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _sdkv_kernel(lutT_ref, cntT_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                 delta_ref, seed_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                 *, scale, causal, bq, bk, nH, dropout):
    bh, kj, t = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nt = pl.num_programs(2)
    h = bh % nH

    @pl.when(t == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    qi = lutT_ref[h, kj, t]

    @pl.when(t < cntT_ref[h, kj])
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0][None, :]
        delta = delta_ref[0, 0][None, :]
        s2 = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s2 = _causal_mask(s2, qi, kj, bq, bk, transposed=True)
        p2 = jnp.exp(s2 - lse)
        if dropout > 0.0:
            keep2 = _dropout_keep(seed_ref[0, 0], bh, qi, kj, bq, bk,
                                  dropout, transposed=True)
            inv = 1.0 / (1.0 - dropout)
            p2_drop = jnp.where(keep2, p2 * inv, 0.0)
        else:
            p2_drop = p2
        dv_scr[:] += jax.lax.dot_general(
            p2_drop.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp2 = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout > 0.0:
            dp2 = jnp.where(keep2, dp2 * inv, 0.0)
        ds2 = p2 * (dp2 - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds2.astype(q.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == nt - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


# --------------------------------------------------------------------- #
# pallas_call wrappers
# --------------------------------------------------------------------- #
def _sparse_fwd(q, k, v, lut, cnt, seed, scale, causal, nH, bq, bk,
                dropout):
    BH, S, D = q.shape
    nQ = S // bq
    maxn = lut.shape[-1]
    grid = (BH, nQ, maxn)
    kernel = functools.partial(_sfwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, nH=nH, dropout=dropout)
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, bq, D), lambda b, i, j, lut, cnt: (b, i, 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, i, j, lut, cnt:
                             (b, lut[b % nH, i, j], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, i, j, lut, cnt:
                             (b, lut[b % nH, i, j], 0)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, D), lambda b, i, j, lut, cnt: (b, i, 0)),
                pl.BlockSpec((1, 1, bq), lambda b, i, j, lut, cnt: (b, 0, i)),
            ],
            scratch_shapes=[
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, D), jnp.float32),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
        ],
        interpret=_interpret(),
    )(lut, cnt, q, k, v, seed)
    return o, lse


def _sparse_bwd(q, k, v, o, lse, do, lut, cnt, lutT, cntT, seed, scale,
                causal, nH, bq, bk, dropout):
    BH, S, D = q.shape
    nQ, nK = S // bq, k.shape[1] // bk
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True).transpose(0, 2, 1)  # [BH,1,S]

    dq = pl.pallas_call(
        functools.partial(_sdq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nH=nH, dropout=dropout),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BH, nQ, lut.shape[-1]),
            in_specs=[
                pl.BlockSpec((1, bq, D), lambda b, i, j, l, c: (b, i, 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, i, j, l, c: (b, l[b % nH, i, j], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, i, j, l, c: (b, l[b % nH, i, j], 0)),
                pl.BlockSpec((1, bq, D), lambda b, i, j, l, c: (b, i, 0)),
                pl.BlockSpec((1, 1, bq), lambda b, i, j, l, c: (b, 0, i)),
                pl.BlockSpec((1, 1, bq), lambda b, i, j, l, c: (b, 0, i)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=pl.BlockSpec((1, bq, D),
                                   lambda b, i, j, l, c: (b, i, 0)),
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)]),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        interpret=_interpret(),
    )(lut, cnt, q, k, v, do, lse, delta, seed)

    dk, dv = pl.pallas_call(
        functools.partial(_sdkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nH=nH, dropout=dropout),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BH, nK, lutT.shape[-1]),
            in_specs=[
                pl.BlockSpec((1, bq, D),
                             lambda b, kk, t, l, c: (b, l[b % nH, kk, t], 0)),
                pl.BlockSpec((1, bk, D), lambda b, kk, t, l, c: (b, kk, 0)),
                pl.BlockSpec((1, bk, D), lambda b, kk, t, l, c: (b, kk, 0)),
                pl.BlockSpec((1, bq, D),
                             lambda b, kk, t, l, c: (b, l[b % nH, kk, t], 0)),
                pl.BlockSpec((1, 1, bq),
                             lambda b, kk, t, l, c: (b, 0, l[b % nH, kk, t])),
                pl.BlockSpec((1, 1, bq),
                             lambda b, kk, t, l, c: (b, 0, l[b % nH, kk, t])),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bk, D), lambda b, kk, t, l, c: (b, kk, 0)),
                pl.BlockSpec((1, bk, D), lambda b, kk, t, l, c: (b, kk, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, D), jnp.float32),
                pltpu.VMEM((bk, D), jnp.float32),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct((BH, k.shape[1], D), k.dtype),
            jax.ShapeDtypeStruct((BH, v.shape[1], D), v.dtype),
        ],
        interpret=_interpret(),
    )(lutT, cntT, q, k, v, do, lse, delta, seed)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10, 11, 12, 13))
def _sparse_flash(q, k, v, lut, cnt, lutT, cntT, seed,
                  scale, causal, nH, bq, bk, dropout):
    o, _ = _sparse_fwd(q, k, v, lut, cnt, seed, scale, causal, nH, bq, bk,
                       dropout)
    return o


def _sparse_vjp_fwd(q, k, v, lut, cnt, lutT, cntT, seed,
                    scale, causal, nH, bq, bk, dropout):
    o, lse = _sparse_fwd(q, k, v, lut, cnt, seed, scale, causal, nH, bq, bk,
                         dropout)
    return o, (q, k, v, lut, cnt, lutT, cntT, seed, o, lse)


def _sparse_vjp_bwd(scale, causal, nH, bq, bk, dropout, res, do):
    q, k, v, lut, cnt, lutT, cntT, seed, o, lse = res
    dq, dk, dv = _sparse_bwd(q, k, v, o, lse, do, lut, cnt, lutT, cntT,
                             seed, scale, causal, nH, bq, bk, dropout)
    return dq, dk, dv, None, None, None, None, None


_sparse_flash.defvjp(_sparse_vjp_fwd, _sparse_vjp_bwd)


def sparse_flash_attention(q, k, v, layout, *, causal=False, scale,
                           seed=None, dropout: float = 0.0):
    """q,k,v: [BH, S, D] (batch*heads flattened); layout: CONCRETE
    [nH, nQ, nK] array. Only the layout's live blocks are loaded/computed."""
    BH, S, D = q.shape
    nH = int(layout.shape[0])
    bq = S // layout.shape[1]
    bk = k.shape[1] // layout.shape[2]
    lut, cnt, lutT, cntT = build_luts(np.asarray(layout))
    seed = jnp.zeros((1, 1), jnp.int32) if seed is None \
        else jnp.asarray(seed, jnp.int32).reshape(1, 1)
    return _sparse_flash(q, k, v, jnp.asarray(lut), jnp.asarray(cnt),
                         jnp.asarray(lutT), jnp.asarray(cntT), seed,
                         scale, causal, nH, bq, bk, float(dropout))
