"""The lint passes: five static checks over one compiled program.

Each pass is a pure function ``(LintContext) -> List[LintFinding]`` over
host-side artifacts only (the program's jaxpr and its optimized-HLO
text) — no step executes, no device fence is issued. The catalog:

- ``materialization`` — an HLO intermediate whose buffer exceeds a
  configurable fraction of the declared (sharded, per-device) state
  bytes: the "XLA materialized what the sharding said it wouldn't"
  gate ZeRO-3 depends on, and the generalization of COMM_AUDIT.json's
  ``fused_chunk_gather`` finding.
- ``dtype_flow`` — ``convert_element_type`` round-trips in the jaxpr
  (a value upcast to a wider float whose widened form feeds ONLY the
  converts back down): pure HBM waste on the hot path, the cast class
  ROADMAP item 2 targets.
- ``donation`` — declared ``donate_argnums`` diffed against the compiled
  module's input/output alias table: a donated-but-unaliased buffer
  stays live across the call and silently doubles its share of the
  memory watermark.
- ``host_sync`` — ``pure_callback``/``debug_callback``/``io_callback``
  primitives and host-transfer HLO (callback custom-calls, infeed/
  outfeed) inside a compiled step fn: each is a host round-trip that
  stalls the async dispatch pipeline; this is the compile-time
  complement of the runtime ``device_sync_count`` fence counter.
- ``collective_placement`` — the compiled gradient-sync collectives
  diffed against the engine's DECLARED grad-sync mode: grads
  materializing unpartitioned via all-reduce under declared ZeRO-2
  sharding, reduce-scatters hoisted out of (or all-reduces trapped
  inside) the gas scan, or a declared reduce-scatter that emits none.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from . import hlo_text
from .findings import LintConfig, LintContext, LintFinding

# ------------------------------------------------------------------ #
# 1. materialization
# ------------------------------------------------------------------ #
# Opcodes that never allocate a fresh buffer of their shape (views,
# tuple plumbing) or that ARE the declared inputs.
_NO_ALLOC_OPS = frozenset({
    "parameter", "get-tuple-element", "tuple", "bitcast",
    "bitcast-convert",
})


def materialization_pass(ctx: LintContext) -> List[LintFinding]:
    declared = int(ctx.meta.get("declared_state_bytes") or 0)
    if declared <= 0:
        return []
    # A buffer the size of ONE full (unsharded) leaf is inherent to any
    # lowering (a per-micro-batch gradient before its scatter, a ZeRO-3
    # per-layer gather) — the invariant this pass guards is TREE-scale
    # materialization, so the largest single leaf is exempt. Stage-3
    # engines additionally budget their declared gather working set
    # (``zero3_gather_bytes``: the compute-dtype leaf-at-use gathers, or
    # prefetch_depth+1 layers on the scan path) — peak live buffers must
    # stay under declared per-device state + that bound, NEVER the full
    # fp32 master tree (the stage-3 correctness gate; a concat of
    # gathered leaves into one tree-scale buffer still fires). Paged
    # serving engines running the ONE-HOT attend similarly budget their
    # fp32 score transient (``paged_score_bytes``: [G, Q, K, nH, B, bs]
    # per layer — it scales with pool capacity, so pool growth alone
    # must not blow the watermark); a full-pool K/V GATHER is head_dim
    # times bigger and still fires. Kernel-on engines declare 0 — the
    # transient must not exist at all.
    thresh = max(int(ctx.config.materialize_floor_bytes),
                 int(ctx.config.materialize_fraction * declared)
                 + int(ctx.meta.get("zero3_gather_bytes") or 0)
                 + int(ctx.meta.get("paged_score_bytes") or 0),
                 int(ctx.meta.get("largest_leaf_bytes") or 0))
    # Aggregate by largest-buffer SHAPE: one oversized buffer flows
    # through many opcodes (broadcast -> fusion -> copy -> ...); the
    # shape is the stable identity a waiver can pin, the opcode list is
    # detail. Instruction names are compile-run noise and never used.
    agg: Dict[str, Dict[str, Any]] = {}
    for ins in hlo_text.iter_instructions(ctx.hlo_text):
        op = ins.opcode[:-6] if ins.opcode.endswith("-start") else ins.opcode
        if op in _NO_ALLOC_OPS:
            continue
        nbytes, shapes = hlo_text.parse_shape_bytes(ins.shape_str,
                                                    largest_only=True)
        if nbytes <= thresh:
            continue
        shape = max(shapes, key=lambda s: hlo_text.parse_shape_bytes(s)[0]) \
            if shapes else ins.shape_str
        rec = agg.setdefault(shape, {
            "bytes": nbytes, "count": 0, "in_loop": False, "op_name": "",
            "opcodes": set()})
        rec["count"] += 1
        rec["opcodes"].add(op)
        rec["in_loop"] = rec["in_loop"] or ins.in_loop
        if not rec["op_name"] and ins.op_name:
            rec["op_name"] = ins.op_name
    out: List[LintFinding] = []
    for shape, rec in sorted(agg.items(), key=lambda kv: -kv[1]["bytes"]):
        out.append(LintFinding(
            lint="materialization", path=ctx.name, key=shape,
            summary=(f"{shape} materialized ({rec['bytes']:,} B, "
                     f"{rec['count']} instruction(s): "
                     f"{', '.join(sorted(rec['opcodes']))}) — "
                     f"{rec['bytes'] / declared:.1f}x the declared "
                     f"per-device state ({declared:,} B)"),
            bytes=rec["bytes"], priced=False, in_loop=rec["in_loop"],
            count=rec["count"],
            details={"opcodes": sorted(rec["opcodes"]), "shape": shape,
                     "declared_state_bytes": declared,
                     "threshold_bytes": thresh,
                     "op_name": rec["op_name"]}))
    return out


# ------------------------------------------------------------------ #
# 2. dtype_flow
# ------------------------------------------------------------------ #
def _subjaxprs(eqn) -> List[Any]:
    """Inner jaxprs of a higher-order eqn (scan/while/cond/pjit/...)."""
    subs: List[Any] = []
    for v in eqn.params.values():
        vs = v if isinstance(v, (list, tuple)) else [v]
        for x in vs:
            j = getattr(x, "jaxpr", None)     # ClosedJaxpr
            if j is not None and hasattr(j, "eqns"):
                subs.append(j)
            elif hasattr(x, "eqns"):          # open Jaxpr
                subs.append(x)
    return subs


def _is_float(dtype) -> bool:
    # NOT dtype.kind: the ml_dtypes extension floats (bfloat16, f8) have
    # kind 'V', and bf16 is precisely the dtype this pass exists for.
    try:
        import jax.numpy as jnp
        return bool(jnp.issubdtype(dtype, jnp.floating))
    except Exception:   # pragma: no cover - jax-less use
        return getattr(dtype, "kind", "") == "f"


def dtype_flow_pass(ctx: LintContext) -> List[LintFinding]:
    findings: Dict[str, LintFinding] = {}

    def walk(jaxpr, in_loop: bool) -> None:
        uses: Dict[Any, List[Any]] = {}
        for eqn in jaxpr.eqns:
            for v in eqn.invars:
                # Vars (hashable, carry .count) index the use map;
                # Literals are unhashable constants — never a cast chain.
                if hasattr(v, "aval") and hasattr(v, "count"):
                    uses.setdefault(v, []).append(eqn)
        outvars = {v for v in jaxpr.outvars if hasattr(v, "count")}
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim in ("scan", "while", "cond"):
                for sub in _subjaxprs(eqn):
                    walk(sub, True)
                continue
            if prim not in ("convert_element_type",):
                for sub in _subjaxprs(eqn):
                    walk(sub, in_loop)
                continue
            src = eqn.invars[0]
            if not hasattr(src, "aval"):      # literal operand
                continue
            src_dt, dst_dt = src.aval.dtype, eqn.outvars[0].aval.dtype
            if not (_is_float(src_dt) and _is_float(dst_dt)):
                continue
            if dst_dt.itemsize <= src_dt.itemsize:
                continue                      # only upcasts start a trip
            wide = eqn.outvars[0]
            if wide in outvars:
                continue                      # the widened value escapes
            consumers = uses.get(wide, [])
            if not consumers:
                continue
            if not all(c.primitive.name == "convert_element_type" and
                       c.outvars[0].aval.dtype == src_dt
                       for c in consumers):
                continue                      # widened form does real work
            aval = wide.aval
            nbytes = int(aval.size) * int(dst_dt.itemsize)
            if nbytes < ctx.config.dtype_floor_bytes:
                continue
            shape = f"{dst_dt.name}[{','.join(str(d) for d in aval.shape)}]"
            key = f"{src_dt.name}->{dst_dt.name}->{src_dt.name}:{shape}"
            f = findings.get(key)
            if f is None:
                findings[key] = LintFinding(
                    lint="dtype_flow", path=ctx.name, key=key,
                    summary=(f"cast round-trip {src_dt.name} -> "
                             f"{dst_dt.name} -> {src_dt.name} on {shape} "
                             f"({nbytes:,} B widened and thrown away)"),
                    bytes=nbytes, priced=False, in_loop=in_loop,
                    details={"src_dtype": src_dt.name,
                             "wide_dtype": dst_dt.name, "shape": shape})
            else:
                f.count += 1
                f.bytes += nbytes
                f.in_loop = f.in_loop or in_loop

    if ctx.jaxpr is not None:
        inner = getattr(ctx.jaxpr, "jaxpr", ctx.jaxpr)
        walk(inner, False)
    return list(findings.values())


# ------------------------------------------------------------------ #
# 3. donation
# ------------------------------------------------------------------ #
def _aval_desc(aval) -> str:
    shape = ",".join(str(d) for d in getattr(aval, "shape", ()))
    return f"{getattr(aval, 'dtype', '?')}[{shape}]"


def donation_pass(ctx: LintContext) -> List[LintFinding]:
    donated = ctx.donated_invars or ()
    if not any(donated):
        return []
    param_shapes = hlo_text.entry_parameter_shapes(ctx.hlo_text)
    aliased = set(hlo_text.input_output_alias_params(ctx.hlo_text))
    # Entry parameter j holds flat input kept[j]: jit's keep_unused=False
    # drops unused inputs from the executable, so alias-table parameter
    # numbers must be mapped back onto the declared donation vector. A
    # DROPPED donated input never reaches the device — its donation is
    # trivially honored (jax deletes it at dispatch).
    kept = list(ctx.kept_var_idx) if ctx.kept_var_idx is not None \
        else list(range(len(donated)))
    attributable = len(kept) == len(param_shapes)
    if not attributable:
        # Mapping unavailable (exotic backend / API drift): judge by
        # count only — fewer aliases than kept donated inputs means
        # un-returned buffers exist, but per-leaf attribution is gone.
        # A DROPPED donated arg must not count toward the expectation:
        # with kept_var_idx in hand the kept donated args are exact;
        # without it, at most len(donated)-len(param_shapes) args were
        # dropped, bounding the donated-and-kept count from below.
        if ctx.kept_var_idx is not None:
            n_donated_kept = sum(1 for flat in kept
                                 if flat < len(donated) and donated[flat])
        else:
            n_dropped_max = max(0, len(donated) - len(param_shapes))
            n_donated_kept = max(
                0, sum(1 for d in donated if d) - n_dropped_max)
        if len(aliased) >= n_donated_kept:
            return []
        missing = list(range(n_donated_kept - len(aliased)))
        un_bytes = 0
        leaves = ["<unattributable: executable parameter mapping "
                  "unavailable>"]
    else:
        missing = [p for p, flat in enumerate(kept)
                   if flat < len(donated) and donated[flat]
                   and p not in aliased]
        # Entry-layout shapes are the PER-DEVICE truth (post
        # partitioning), so sharded donated leaves are priced at what a
        # device actually holds live.
        un_bytes = sum(hlo_text.parse_shape_bytes(param_shapes[p])[0]
                       for p in missing)
        leaves = [f"param{p}(arg{kept[p]}):{param_shapes[p]}"
                  for p in missing]
    if not missing:
        return []
    # The byte floor only applies when bytes are attributable — the
    # degraded count-only fallback prices nothing (un_bytes == 0) and a
    # floor of 0 would otherwise silently swallow its findings.
    if attributable and un_bytes <= ctx.config.donation_floor_bytes:
        return []
    return [LintFinding(
        lint="donation", path=ctx.name,
        key=f"unaliased:{len(missing)}x:{un_bytes}B",
        summary=(f"{len(missing)} donated input buffer(s) "
                 f"({un_bytes:,} B) have no entry in the compiled "
                 "input/output alias table — the donation freed nothing "
                 "and the buffers stay live across the call"),
        bytes=int(un_bytes), priced=False, count=len(missing),
        details={"unaliased_params": leaves[:16],
                 "aliased_param_count": len(aliased),
                 "donated_arg_count": sum(1 for d in donated if d)})]


# ------------------------------------------------------------------ #
# 4. host_sync
# ------------------------------------------------------------------ #
_CALLBACK_PRIMS = frozenset({"pure_callback", "io_callback",
                             "debug_callback"})
_HOST_HLO_OPS = frozenset({"infeed", "outfeed"})


def host_sync_pass(ctx: LintContext) -> List[LintFinding]:
    out: List[LintFinding] = []

    hits: Dict[str, Dict[str, Any]] = {}

    def walk(jaxpr, in_loop: bool) -> None:
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            loop_here = in_loop or prim in ("scan", "while")
            if prim in _CALLBACK_PRIMS:
                rec = hits.setdefault(prim, {"count": 0, "in_loop": False})
                rec["count"] += 1
                rec["in_loop"] = rec["in_loop"] or in_loop
            for sub in _subjaxprs(eqn):
                walk(sub, loop_here)

    if ctx.jaxpr is not None:
        walk(getattr(ctx.jaxpr, "jaxpr", ctx.jaxpr), False)
    for prim, rec in sorted(hits.items()):
        out.append(LintFinding(
            lint="host_sync", path=ctx.name, key=prim,
            summary=(f"{prim} inside the compiled step fn "
                     f"({rec['count']}x"
                     f"{', in a scan body' if rec['in_loop'] else ''}) — "
                     "every call is a host round-trip that stalls the "
                     "async dispatch pipeline"),
            priced=False, in_loop=rec["in_loop"], count=rec["count"],
            details={"primitive": prim}))

    # HLO side: callback custom-calls (belt and suspenders for programs
    # whose jaxpr was unavailable) and explicit host transfers.
    hlo_hits: Dict[str, Dict[str, Any]] = {}
    for ins in hlo_text.iter_instructions(ctx.hlo_text):
        key = None
        if ins.opcode == "custom-call" and "callback" in ins.rest:
            key = "custom-call:callback"
        elif ins.opcode in _HOST_HLO_OPS or "is_host_transfer=true" in \
                ins.rest:
            key = f"host-transfer:{ins.opcode}"
        if key is None:
            continue
        rec = hlo_hits.setdefault(key, {"count": 0, "in_loop": False})
        rec["count"] += 1
        rec["in_loop"] = rec["in_loop"] or ins.in_loop
    jaxpr_total = sum(r["count"] for r in hits.values())
    for key, rec in sorted(hlo_hits.items()):
        if jaxpr_total and key == "custom-call:callback":
            continue    # already attributed at the jaxpr level
        out.append(LintFinding(
            lint="host_sync", path=ctx.name, key=key,
            summary=(f"{key} in the compiled program ({rec['count']}x) — "
                     "a host transfer inside the step"),
            priced=False, in_loop=rec["in_loop"], count=rec["count"],
            details={"hlo": key}))
    return out


# ------------------------------------------------------------------ #
# 5. collective_placement
# ------------------------------------------------------------------ #
def collective_placement_pass(ctx: LintContext) -> List[LintFinding]:
    meta = ctx.meta
    out: List[LintFinding] = []
    # MoE expert placement: an expert-sharded gradient may all-reduce
    # over `data` (within its expert group) ONLY — replica groups wider
    # than the data axis span the `expert` axis, i.e. the lowering
    # treated experts as replicas and ships every group every other
    # group's expert grads (the seeded-violation case; engine meta
    # carries the legal per-device payload sizes + the max group width).
    expert_bytes = {int(b) for b in (meta.get("expert_leaf_bytes") or ())}
    if expert_bytes and ctx.audit is not None:
        max_group = int(meta.get("expert_group_size") or 1)
        for o in ctx.audit.of_kind("all-reduce"):
            if o.payload_bytes in expert_bytes and o.group_size > max_group:
                out.append(LintFinding(
                    lint="collective_placement", path=ctx.name,
                    key=f"expert-grad-allreduce:{','.join(o.out_shapes)}",
                    summary=("expert-sharded gradient all-reduced ACROSS "
                             f"the expert axis: {o.out_shapes} in groups "
                             f"of {o.group_size} (data axis is "
                             f"{max_group}) — experts are not replicas; "
                             "their grads sync over data within the "
                             "expert group only"),
                    bytes=o.payload_bytes, wire_bytes=o.wire_bytes,
                    priced=True, in_loop=o.in_loop,
                    details={"op_name": o.op_name,
                             "group_size": o.group_size,
                             "expert_group_size": max_group}))
    if not meta.get("grad_sync_path"):
        return out
    mode = str(meta.get("grad_sync_mode", "none"))
    gas = int(meta.get("gas", 1))
    scatterable = {int(b) for b in (meta.get("scatterable_leaf_bytes") or ())}
    if not scatterable or ctx.audit is None:
        return out
    expects_rs = mode in ("explicit", "declarative")
    grad_ars = [o for o in ctx.audit.of_kind("all-reduce")
                if o.payload_bytes in scatterable]
    grad_rs = [o for o in ctx.audit.of_kind("reduce-scatter")
               if o.payload_bytes in scatterable]
    # Factored replica hierarchy (multislice slices > 1, or the MoE
    # explicit path's ep > 1): the LEGAL wire is an in-group reduce-
    # scatter (groups of dp) + ONE outer-axis all-reduce (groups of
    # `slices` / `ep`) carrying only the 1/dp residual payloads
    # (dcn_shard_bytes). Whitelist that outer hop out of the
    # grad-allreduce check — a shard payload can coincide byte-for-byte
    # with a smaller leaf's full size. On multislice meshes additionally
    # flag any grad-sized collective whose groups SPAN the slice axis
    # (wider than dp): a flat joint-(slice, data) sync pushes grad-sized
    # traffic over every DCN boundary link.
    slices = int(meta.get("slices", 1) or 1)
    ep = int(meta.get("ep", 1) or 1)
    dp = int(meta.get("dp", 1) or 1)
    # The expected schedule is DERIVED from the mesh factorization (the
    # axis-algebra planner — the same derivation the builders execute
    # and the wire model prices), not re-cased per axis pair here.
    from ..parallel.axis_algebra import MeshFactorization
    fact = MeshFactorization.from_sizes(slice=slices, expert=ep, data=dp)
    try:
        outer_axis = fact.outer_axis
    except ValueError:
        outer_axis = None   # unsupported factorization: no legal hop
    outer = fact.size(outer_axis) if outer_axis else 1
    dcn_shard = {int(b) for b in (meta.get("dcn_shard_bytes") or ())}
    if outer > 1 and str(meta.get("grad_sync_mode")) == "explicit":
        grad_ars = [o for o in grad_ars
                    if not (o.group_size == outer
                            and o.payload_bytes in dcn_shard)]
    if slices > 1:
        for o in ctx.audit.ops:
            if o.kind not in ("all-reduce", "reduce-scatter"):
                continue
            if o.payload_bytes not in scatterable:
                continue
            # The whitelisted inter-slice hop itself: when slices > dp
            # its groups are wider than dp while carrying only a 1/dp
            # shard whose size collides with a smaller leaf's full size
            # — same exclusion as the grad-allreduce check above.
            if o.group_size == slices and o.payload_bytes in dcn_shard:
                continue
            if o.group_size > dp:
                out.append(LintFinding(
                    lint="collective_placement", path=ctx.name,
                    key=f"grad-spans-dcn:{','.join(o.out_shapes)}",
                    summary=(f"grad-sized {o.kind} of {o.out_shapes} in "
                             f"groups of {o.group_size} (> dp={dp}) "
                             f"spans the slice axis — a flat joint sync "
                             "pushes grad-sized traffic over DCN; the "
                             "hierarchy moves only the 1/dp residual "
                             "there"),
                    bytes=o.payload_bytes, wire_bytes=o.wire_bytes,
                    priced=True, in_loop=o.in_loop,
                    details={"op_name": o.op_name,
                             "group_size": o.group_size,
                             "dp": dp, "slices": slices}))
    # Stage 3 across slices: the planner binds BOTH param gathers to
    # `data` — an ICI axis on every factorization — so a param-sized
    # gather whose replica groups are wider than dp spans the slice
    # axis and ships param bytes over DCN (the joint-axis schedule the
    # hierarchy exists to avoid). Engine meta carries the legal
    # gathered-leaf payload sizes (zero3_gather_leaf_bytes).
    z3_gather = {int(b)
                 for b in (meta.get("zero3_gather_leaf_bytes") or ())}
    if slices > 1 and z3_gather:
        for o in ctx.audit.of_kind("all-gather"):
            if o.payload_bytes not in z3_gather:
                continue
            if o.group_size > dp:
                out.append(LintFinding(
                    lint="collective_placement", path=ctx.name,
                    key=f"param-spans-dcn:{','.join(o.out_shapes)}",
                    summary=(f"param-sized all-gather of {o.out_shapes} "
                             f"in groups of {o.group_size} (> dp={dp}) "
                             "spans the slice axis — stage-3 gathers "
                             "bind `data` (ICI only); a joint-axis "
                             "gather ships param bytes over DCN every "
                             "micro-step"),
                    bytes=o.payload_bytes, wire_bytes=o.wire_bytes,
                    priced=True, in_loop=o.in_loop,
                    details={"op_name": o.op_name,
                             "group_size": o.group_size,
                             "dp": dp, "slices": slices}))
    if expects_rs:
        for o in grad_ars:
            out.append(LintFinding(
                lint="collective_placement", path=ctx.name,
                key=f"grad-allreduce:{','.join(o.out_shapes)}",
                summary=("gradient materializes unpartitioned: all-reduce "
                         f"of {o.out_shapes} under declared ZeRO "
                         f"grad sharding (grad_sync={mode}) — the known "
                         "GSPMD fallback, 2x the reduce-scatter wire"),
                bytes=o.payload_bytes, wire_bytes=o.wire_bytes,
                priced=True, in_loop=o.in_loop,
                details={"op_name": o.op_name, "group_size": o.group_size,
                         "declared_mode": mode}))
        if gas > 1:
            for o in grad_rs:
                if not o.in_loop:
                    out.append(LintFinding(
                        lint="collective_placement", path=ctx.name,
                        key=f"rs-hoisted:{','.join(o.in_shapes)}",
                        summary=("reduce-scatter of "
                                 f"{o.in_shapes} sits OUTSIDE the gas={gas} "
                                 "accumulation scan — the carry holds the "
                                 "full unpartitioned gradient across every "
                                 "micro-step"),
                        bytes=o.payload_bytes, wire_bytes=o.wire_bytes,
                        priced=True, in_loop=False,
                        details={"op_name": o.op_name, "gas": gas,
                                 "declared_mode": mode}))
        if not grad_rs and not grad_ars:
            out.append(LintFinding(
                lint="collective_placement", path=ctx.name,
                key="no-grad-sync",
                summary=(f"grad_sync={mode} declares a reduce-scattered "
                         "gradient sync but the compiled program emits no "
                         "gradient-sized reduce-scatter (or all-reduce) "
                         "at all"),
                priced=False,
                details={"declared_mode": mode,
                         "scatterable_leaf_bytes": sorted(scatterable)}))
    else:   # "none" (stage<2 dense) / "allreduce" (reduce_scatter: false)
        for o in grad_rs:
            out.append(LintFinding(
                lint="collective_placement", path=ctx.name,
                key=f"unexpected-rs:{','.join(o.in_shapes)}",
                summary=("reduce-scatter of "
                         f"{o.in_shapes} under a REPLICATED grad "
                         f"declaration (grad_sync={mode}) — downstream "
                         "consumers see 1/dp shards the declaration "
                         "promised whole"),
                bytes=o.payload_bytes, wire_bytes=o.wire_bytes,
                priced=True, in_loop=o.in_loop,
                details={"op_name": o.op_name, "declared_mode": mode}))
        if gas > 1:
            for o in grad_ars:
                if o.in_loop:
                    out.append(LintFinding(
                        lint="collective_placement", path=ctx.name,
                        key=f"ar-in-scan:{','.join(o.out_shapes)}",
                        summary=("gradient all-reduce of "
                                 f"{o.out_shapes} TRAPPED inside the "
                                 f"gas={gas} scan — dense sync pays "
                                 f"{gas}x the wire it needs (accumulate "
                                 "locally, reduce once)"),
                        bytes=o.payload_bytes,
                        wire_bytes=o.wire_bytes * gas, priced=True,
                        in_loop=True,
                        details={"op_name": o.op_name, "gas": gas,
                                 "wire_bytes_per_trip": o.wire_bytes}))
    return out


# The pipeline, in report order. Dict, not list: tools/tests select
# subsets by name and the names are part of the finding fingerprint.
PASSES = {
    "materialization": materialization_pass,
    "dtype_flow": dtype_flow_pass,
    "donation": donation_pass,
    "host_sync": host_sync_pass,
    "collective_placement": collective_placement_pass,
}

__all__ = ["PASSES", "materialization_pass", "dtype_flow_pass",
           "donation_pass", "host_sync_pass", "collective_placement_pass"]
