"""Compile-time program auditor: a lint suite over jaxpr + optimized HLO.

The static-analysis layer the repo's "verifiable by construction" story
stands on: every compiled step path an engine owns is re-lowered
host-side (from the recompile sentinel's recorded abstract signatures —
zero device fences) and run through five passes: materialization,
dtype_flow, donation, host_sync, collective_placement. Findings are
structured, waivable, and CI-gated via ``tools/ds_lint.py`` +
``LINT_AUDIT.json``. See docs/tutorials/static_analysis.md.

Submodule imports are lazy so ``parallel/hlo_audit.py`` can import
``analysis.hlo_text`` without pulling jax-heavy modules (or itself,
transitively) at package-import time.
"""
from __future__ import annotations

_LAZY = {
    "hlo_text": ".hlo_text",
    "findings": ".findings",
    "passes": ".passes",
    "auditor": ".auditor",
    # Convenience re-exports.
    "LintConfig": ".findings", "LintFinding": ".findings",
    "LintReport": ".findings", "Waiver": ".findings",
    "load_waivers": ".findings", "apply_waivers": ".findings",
    "PASSES": ".passes",
    "lint_jit": ".auditor", "lint_engine": ".auditor",
    "lint_sentinel": ".auditor", "lint_path": ".auditor",
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    mod_name = _LAZY.get(name)
    if mod_name is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    mod = importlib.import_module(mod_name, __name__)
    if name in ("hlo_text", "findings", "passes", "auditor"):
        return mod
    return getattr(mod, name)
