"""Shared HLO-text parsing for the static-analysis layer.

One home for the mechanics every compiled-program pass needs — splitting
optimized-HLO text into computations, walking instructions, sizing
(possibly tuple) shapes, attributing computations to ``while`` loops
(``lax.scan`` bodies), and reading the module header's input/output alias
table. ``parallel/hlo_audit.py`` (the original collective auditor) and
``analysis/passes.py`` (the lint suite) both parse compiled programs; the
primitives live here so the two stay byte-for-byte consistent.

Everything operates on ``jit(...).lower(...).compile().as_text()`` output
— pure host-side string work, no jax import, no device traffic.
"""
from __future__ import annotations

import re
from typing import Dict, Iterator, List, NamedTuple, Optional, Tuple

# Bytes per element for the HLO primitive types that can appear in
# instruction shapes. (f8 variants share one entry per byte width.)
DTYPE_BYTES: Dict[str, int] = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# `%name = <shape> <opcode>(<operands>), attr=..., ...` — async ops
# appear as `<opcode>-start`; the matching `-done` carries no new buffer.
# Tuple shapes allow one nesting level (async variadic collectives wrap
# the operand/result tuples in an outer pair) but NOT `[^=]*`: XLA
# annotates long tuples with `/*index=N*/` comments whose `=` would kill
# that match (the 8-way all-to-all result tuple is the canonical victim).
INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<shape>\((?:[^()]|\([^()]*\))*\)"
    r"|[a-z0-9]+\[[^\]]*\](?:\{[^}]*\})?)\s+"
    r"(?P<op>[a-z\-]+(?:-start)?)\(")
SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
COMP_RE = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*->.*\{\s*$")
BODY_RE = re.compile(r"body=%([\w.\-]+)")
CALLEE_RE = re.compile(
    r"(?:calls|to_apply|condition|body|branch_computations)="
    r"(?:\{)?%([\w.\-]+(?:,\s*%[\w.\-]+)*)")
OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_CONST_RE = re.compile(r"constant\((\d+)\)")
_COND_RE = re.compile(r"condition=%([\w.\-]+)")
# Module-header alias table: `input_output_alias={ {1}: (0, {}, may-alias),
# {0,2}: (3, {}, must-alias) }` — output tuple index -> (param number,
# param index, kind). Braces nest, so the block is cut by scanning, not
# by regex.
_ALIAS_ENTRY_RE = re.compile(
    r"\{(?P<out>[\d,\s]*)\}:\s*\((?P<param>\d+),\s*\{(?P<pidx>[\d,\s]*)\}")
_ENTRY_LAYOUT_RE = re.compile(
    r"entry_computation_layout=\{\((?P<params>.*?)\)->")


def _header_attr_block(hlo_text: str, attr: str) -> Optional[str]:
    """The brace-balanced `{...}` value of a module-header attribute."""
    marker = f"{attr}={{"
    start = hlo_text.find(marker)
    if start < 0:
        return None
    i = start + len(marker)
    depth = 1
    while i < len(hlo_text) and depth:
        if hlo_text[i] == "{":
            depth += 1
        elif hlo_text[i] == "}":
            depth -= 1
        i += 1
    return hlo_text[start + len(marker):i - 1]


def parse_shape_bytes(shape_str: str, largest_only: bool = False
                      ) -> Tuple[int, List[str]]:
    """Total bytes + the individual `dtype[dims]` strings of a (possibly
    tuple) HLO shape. Layout annotations (`{1,0}`) are ignored.

    ``largest_only``: return the LARGEST component's bytes instead of the
    sum — for async ``-start`` results (whose tuple aliases the input
    buffer alongside the output, plus u32 context scalars) and for sizing
    "what is the biggest buffer this instruction materializes".
    """
    shapes, total, largest = [], 0, 0
    for dtype, dims in SHAPE_RE.findall(shape_str):
        if dtype not in DTYPE_BYTES:
            continue    # token types (after-all etc.) carry no payload
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        nbytes = n * DTYPE_BYTES[dtype]
        total += nbytes
        largest = max(largest, nbytes)
        shapes.append(f"{dtype}[{dims}]")
    return (largest if largest_only else total), shapes


def split_computations(hlo_text: str) -> Dict[str, List[str]]:
    """{computation name: its instruction lines}. Header lines are
    `%name (params) -> result {`; instruction lines always contain an
    ` = ` assignment (a bare `=` check would misfire on the `/*index=N*/`
    markers in long tuple params)."""
    comp_lines: Dict[str, List[str]] = {}
    computation = ""
    for line in hlo_text.splitlines():
        comp = COMP_RE.match(line)
        if comp and " = " not in line:
            computation = comp.group(1)
            comp_lines.setdefault(computation, [])
            continue
        comp_lines.setdefault(computation, []).append(line)
    return comp_lines


def loop_computations(comp_lines: Dict[str, List[str]]) -> set:
    """Computation names reachable from any ``while`` body — collectives
    (or any op) there run once per trip, not once per step. Follows
    calls/branches transitively so an op inside a ``lax.cond`` inside a
    scan is still loop-tagged."""
    callees: Dict[str, set] = {}
    roots: set = set()
    for name, lines in comp_lines.items():
        refs: set = set()
        for line in lines:
            for mm in CALLEE_RE.finditer(line):
                for ref in mm.group(1).split(","):
                    refs.add(ref.strip().lstrip("%"))
            bm = BODY_RE.search(line)
            if bm and " while(" in line:
                roots.add(bm.group(1))
        callees[name] = refs
    reach, frontier = set(), set(roots)
    while frontier:
        c = frontier.pop()
        if c in reach:
            continue
        reach.add(c)
        frontier |= callees.get(c, set())
    return reach


class Instruction(NamedTuple):
    """One parsed HLO instruction, positioned in its computation."""
    computation: str
    name: str
    opcode: str          # raw (may carry a -start suffix)
    shape_str: str
    rest: str            # the line from the opening call paren onward
    in_loop: bool
    op_name: str         # jax op metadata (attribution), "" if absent


def iter_instructions(hlo_text: str) -> Iterator[Instruction]:
    """Walk every instruction of every computation with loop attribution
    — the shared traversal the lint passes build on."""
    comp_lines = split_computations(hlo_text)
    loops = loop_computations(comp_lines)
    for computation, lines in comp_lines.items():
        in_loop = computation in loops
        for line in lines:
            m = INSTR_RE.match(line)
            if not m:
                continue
            rest = line[m.end():]
            om = OPNAME_RE.search(rest)
            yield Instruction(computation, m.group("name"), m.group("op"),
                              m.group("shape"), rest, in_loop,
                              om.group(1) if om else "")


def while_trip_counts(hlo_text: str) -> List[int]:
    """Best-effort static trip counts: the integer constants appearing in
    each ``while`` instruction's CONDITION computation (a ``lax.scan``'s
    bound compiles to ``compare(i, constant(T)), direction=LT``). Returns
    every candidate, largest first — callers check membership of the
    analytic count rather than assuming a unique bound."""
    comp_lines = split_computations(hlo_text)
    conds: List[str] = []
    for lines in comp_lines.values():
        for line in lines:
            if " while(" in line:
                cm = _COND_RE.search(line)
                if cm:
                    conds.append(cm.group(1))
    counts: List[int] = []
    for cond in conds:
        for line in comp_lines.get(cond, []):
            counts.extend(int(c) for c in _CONST_RE.findall(line))
    return sorted(set(counts), reverse=True)


def input_output_alias_params(hlo_text: str) -> List[int]:
    """Parameter numbers the compiled module aliases to outputs (the
    header's ``input_output_alias`` table). Donated inputs jax could pair
    with a matching output appear here; a declared donation MISSING from
    this list kept its buffer live across the call — the memory the
    donation promised back was never returned."""
    block = _header_attr_block(hlo_text, "input_output_alias")
    if block is None:
        return []
    return [int(e.group("param"))
            for e in _ALIAS_ENTRY_RE.finditer(block)]


def entry_parameter_shapes(hlo_text: str) -> List[str]:
    """The entry computation's parameter shape strings (per-device, post
    partitioning), in parameter-number order — from the module header's
    ``entry_computation_layout``."""
    m = _ENTRY_LAYOUT_RE.search(hlo_text)
    if not m:
        return []
    text = m.group("params")
    shapes: List[str] = []
    for sm in SHAPE_RE.finditer(text):
        shapes.append(f"{sm.group(1)}[{sm.group(2)}]")
    return shapes


__all__ = [
    "DTYPE_BYTES", "INSTR_RE", "SHAPE_RE", "COMP_RE", "BODY_RE",
    "CALLEE_RE", "OPNAME_RE", "Instruction", "parse_shape_bytes",
    "split_computations", "loop_computations", "iter_instructions",
    "while_trip_counts", "input_output_alias_params",
    "entry_parameter_shapes",
]
