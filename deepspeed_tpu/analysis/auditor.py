"""The compile-time program auditor: run the lint pipeline over every
compiled step function an engine owns.

Input comes from the recompile sentinel's registry (monitor/recompile.py
records each instrumented function and the abstract signature of its
last compile — ``RecompileSentinel.registered_paths()``), so the audit
re-lowers host-side from metadata that survives buffer donation: zero
device traffic, zero fences. A standalone entry point (``lint_jit``)
audits any jitted callable the same way for tests and tools.

Per path the auditor builds ONE ``LintContext`` — the traced jaxpr (with
the jit-level donation declaration read off the pjit eqn), the
optimized-HLO text, and an ``hlo_audit.CommAudit`` over it — then runs
the pass pipeline (analysis/passes.py). A pass crashing degrades to a
structured error on that path's result, never to a dead audit.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .findings import (LintConfig, LintContext, LintFinding, LintReport,
                       PathResult, Waiver, apply_waivers)
from .passes import PASSES


def _trace_program(fn: Callable, args: Tuple, kwargs: Dict
                   ) -> Tuple[Any, Tuple[bool, ...], Tuple[Any, ...]]:
    """(body ClosedJaxpr, donated_invars, flat in_avals) of one program.

    Tracing the JITTED callable yields an outer jaxpr with a single pjit
    eqn whose params carry the donation declaration — the jit-level truth
    the donation pass diffs against the compiled alias table. A plain
    callable (no pjit eqn) traces with an empty donation vector.
    """
    import jax
    closed = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    outer = closed.jaxpr
    in_avals = tuple(v.aval for v in outer.invars)
    if len(outer.eqns) == 1 and outer.eqns[0].primitive.name == "pjit" \
            and len(outer.eqns[0].invars) == len(outer.invars):
        eqn = outer.eqns[0]
        donated = tuple(eqn.params.get("donated_invars") or
                        (False,) * len(in_avals))
        return eqn.params["jaxpr"], donated, in_avals
    return closed, (False,) * len(in_avals), in_avals


def build_context(name: str, fn: Callable, abstract_args: Tuple,
                  abstract_kwargs: Dict, meta: Optional[Dict[str, Any]],
                  config: Optional[LintConfig] = None) -> LintContext:
    """Lower + compile (AOT, host-side) and trace one program into the
    context the passes consume."""
    import jax
    from ..parallel import hlo_audit
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    compiled = fn.lower(*abstract_args, **abstract_kwargs).compile()
    hlo = compiled.as_text()
    # Which flat inputs survived as entry parameters (keep_unused=False
    # drops unused args): the donation pass needs it to map alias-table
    # parameter numbers back onto the declared donated_invars. Private
    # API with a graceful None fallback — the _cache_size precedent.
    kept = None
    try:
        kv = getattr(getattr(compiled, "_executable", None),
                     "_kept_var_idx", None)
        if kv is not None:
            kept = tuple(sorted(int(i) for i in kv))
    except Exception:
        kept = None
    jaxpr, donated, in_avals = _trace_program(fn, abstract_args,
                                              abstract_kwargs)
    return LintContext(
        name=name, jaxpr=jaxpr, donated_invars=donated, in_avals=in_avals,
        hlo_text=hlo, audit=hlo_audit.audit_text(hlo), kept_var_idx=kept,
        meta=dict(meta or {}), config=config or LintConfig())


def lint_path(name: str, fn: Callable, abstract_args: Tuple,
              abstract_kwargs: Dict,
              meta: Optional[Dict[str, Any]] = None,
              config: Optional[LintConfig] = None,
              passes: Optional[Sequence[str]] = None) -> PathResult:
    """Audit ONE compiled program; per-pass failures become structured
    errors, not exceptions."""
    result = PathResult(name=name)
    try:
        ctx = build_context(name, fn, abstract_args, abstract_kwargs,
                            meta, config)
    except Exception as e:      # lowering failed — report, don't die
        result.errors.append(
            f"{name}: context build failed: {type(e).__name__}: "
            f"{str(e)[:300]}")
        return result
    for pname in (passes or PASSES):
        run = PASSES.get(pname)
        if run is None:
            result.errors.append(f"{name}: unknown lint pass {pname!r}")
            continue
        try:
            result.findings.extend(run(ctx))
        except Exception as e:
            result.errors.append(
                f"{name}/{pname}: {type(e).__name__}: {str(e)[:300]}")
    return result


def lint_jit(fn: Callable, *args, name: str = "program",
             meta: Optional[Dict[str, Any]] = None,
             config: Optional[LintConfig] = None,
             passes: Optional[Sequence[str]] = None,
             **kwargs) -> PathResult:
    """Standalone entry: audit any (jitted or plain) callable on concrete
    or ShapeDtypeStruct args. Compile-only; nothing executes."""
    return lint_path(name, fn, args, kwargs, meta=meta, config=config,
                     passes=passes)


def lint_sentinel(sentinel, meta_by_path: Optional[Dict[str, Dict]] = None,
                  config: Optional[LintConfig] = None,
                  waivers: Optional[Sequence[Waiver]] = None,
                  passes: Optional[Sequence[str]] = None) -> LintReport:
    """Audit every path the recompile sentinel has recorded (the PR-5
    ``fn``/``abstract_args`` registry handoff). ``meta_by_path`` supplies
    the engine-truth each pass needs (grad-sync mode, declared state
    bytes, ...); paths without an entry run with empty meta."""
    config = config or LintConfig()
    meta_by_path = meta_by_path or {}
    results: List[PathResult] = []
    for name, (fn, a_args, a_kwargs) in sentinel.registered_paths().items():
        results.append(lint_path(name, fn, a_args, a_kwargs,
                                 meta=meta_by_path.get(name),
                                 config=config, passes=passes))
    findings = [f for r in results for f in r.findings]
    unwaived, waived, stale = apply_waivers(findings, waivers or [])
    return LintReport(paths=results, unwaived=unwaived, waived=waived,
                      stale_waivers=stale, config=config)


def lint_engine(engine, config: Optional[LintConfig] = None,
                waivers: Optional[Sequence[Waiver]] = None,
                passes: Optional[Sequence[str]] = None) -> LintReport:
    """Audit every compiled path a DeepSpeedEngine has run, with the
    engine's own declarations as pass metadata. Requires telemetry (the
    sentinel IS the registry); raises otherwise so a disabled-telemetry
    run can't silently audit nothing."""
    sentinel = getattr(engine.telemetry, "sentinel", None)
    if sentinel is None:
        raise ValueError(
            "lint_engine needs the recompile sentinel's registry — enable "
            "the telemetry block (telemetry.enabled: true) so compiled "
            "paths are recorded")
    meta = {name: engine._lint_path_meta(name)
            for name in sentinel.registered_paths()}
    return lint_sentinel(sentinel, meta_by_path=meta, config=config,
                         waivers=waivers, passes=passes)


__all__ = ["build_context", "lint_path", "lint_jit", "lint_sentinel",
           "lint_engine"]
