"""Structured lint findings, waivers, and the report they assemble into.

A finding is one defect a lint pass proved about one compiled program:
stable enough to baseline (its ``fingerprint`` survives recompiles and
instruction renumbering), priced where the wire model applies, and JSON-
ready for ``LINT_AUDIT.json``. The waiver file is the CI contract: every
KNOWN-and-roadmapped finding is matched by a waiver (so it doesn't block
the build), every waiver must match a live finding (so the baseline
can't rot — a stale waiver is itself reported), and any NEW finding
fails the gate.
"""
from __future__ import annotations

import dataclasses

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

SEVERITY_ERROR = "error"
SEVERITY_WARN = "warn"


@dataclasses.dataclass
class LintFinding:
    """One verified defect in one compiled program.

    ``key`` is the pass-specific stable discriminator (shape/dtype/op
    attribution — never an HLO instruction id, which changes across
    compiles). ``priced`` says whether ``wire_bytes`` came from the ring
    wire model; unpriced findings carry their buffer ``bytes`` instead,
    so every record is explicitly one or the other.
    """
    lint: str                 # pass name (materialization, dtype_flow, ...)
    path: str                 # compiled-program name (train_step, ...)
    key: str                  # stable discriminator within (lint, path)
    summary: str
    severity: str = SEVERITY_ERROR
    bytes: int = 0            # buffer bytes the finding is about
    wire_bytes: Optional[int] = None
    priced: bool = False      # wire_bytes from the ring wire model
    in_loop: bool = False     # inside a while/scan body (per-trip cost)
    count: int = 1            # occurrences aggregated into this record
    details: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def fingerprint(self) -> str:
        return f"{self.lint}:{self.path}:{self.key}"

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint
        if not self.priced:
            d.pop("wire_bytes", None)
        return d


@dataclasses.dataclass
class Waiver:
    """One baseline entry: a glob over fingerprints plus the reason the
    finding is tolerated (ideally a ROADMAP pointer — waivers are debts,
    not absolutions).

    ``match`` supports ``*`` only (any run of characters) — NOT full
    fnmatch, whose ``[...]`` character classes would silently swallow
    the HLO shape brackets every fingerprint contains."""
    match: str
    reason: str = ""
    roadmap: str = ""

    def matches(self, finding: LintFinding) -> bool:
        import re
        pat = ".*".join(re.escape(p) for p in self.match.split("*"))
        return re.fullmatch(pat, finding.fingerprint) is not None

    def to_dict(self) -> Dict[str, Any]:
        return {"match": self.match, "reason": self.reason,
                "roadmap": self.roadmap}


def load_waivers(path: str) -> List[Waiver]:
    """Read a waiver file: ``{"waivers": [{"match", "reason", "roadmap"}]}``.
    Missing file = empty baseline (everything unwaived)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return []
    return [Waiver(match=w["match"], reason=w.get("reason", ""),
                   roadmap=w.get("roadmap", ""))
            for w in doc.get("waivers", [])]


def apply_waivers(findings: Sequence[LintFinding], waivers: Sequence[Waiver]
                  ) -> Tuple[List[LintFinding],
                             List[Tuple[LintFinding, Waiver]],
                             List[Waiver]]:
    """Split ``findings`` into (unwaived, waived-with-their-waiver) and
    return the STALE waivers — entries that matched nothing. Staleness is
    judged over this finding set only; tools sweeping several configs
    aggregate before judging (a waiver for config B is not stale while
    auditing config A)."""
    unwaived: List[LintFinding] = []
    waived: List[Tuple[LintFinding, Waiver]] = []
    used: set = set()
    for f in findings:
        hit = next((w for w in waivers if w.matches(f)), None)
        if hit is None:
            unwaived.append(f)
        else:
            waived.append((f, hit))
            used.add(hit.match)
    stale = [w for w in waivers if w.match not in used]
    return unwaived, waived, stale


@dataclasses.dataclass
class LintConfig:
    """Pass thresholds. Defaults are tuned so the clean engine paths on
    the dp=8 CPU mesh produce zero findings while the seeded-violation
    tests (and the real fused-chunk/offload findings) still fire."""
    # materialization: flag an intermediate whose largest buffer exceeds
    # this fraction of the declared (per-device, sharded) state bytes...
    materialize_fraction: float = 1.0
    # ...with an absolute floor so byte-level noise on toy models can be
    # suppressed when a caller wants real-model scales only.
    materialize_floor_bytes: int = 0
    # donation: minimum unreturned donated bytes worth a finding.
    donation_floor_bytes: int = 0
    # dtype_flow: minimum round-tripped buffer bytes worth a finding.
    dtype_floor_bytes: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class LintContext:
    """Everything a pass may inspect about ONE compiled program. Built
    host-side by the auditor from the recompile sentinel's recorded
    ``fn``/``abstract_args`` (zero device fences by construction)."""
    name: str                     # program/path name
    jaxpr: Any                    # ClosedJaxpr of the program body
    donated_invars: Tuple[bool, ...]   # per flat input, jit declaration
    in_avals: Tuple[Any, ...]     # flat input avals (aligned with donated)
    hlo_text: str                 # optimized HLO text (compiled, per-device)
    audit: Any                    # parallel.hlo_audit.CommAudit of hlo_text
    # Flat-input indices the executable KEPT as entry parameters (jit
    # drops unused args under keep_unused=False); None = all kept. Maps
    # entry param numbers back onto donated_invars/in_avals indices.
    kept_var_idx: Optional[Tuple[int, ...]] = None
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)
    config: LintConfig = dataclasses.field(default_factory=LintConfig)


@dataclasses.dataclass
class PathResult:
    """One program's lint outcome (pre-waiver)."""
    name: str
    findings: List[LintFinding] = dataclasses.field(default_factory=list)
    errors: List[str] = dataclasses.field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name,
                "findings": [f.to_dict() for f in self.findings],
                "errors": list(self.errors)}


@dataclasses.dataclass
class LintReport:
    """Aggregated outcome over every audited path, waivers applied."""
    paths: List[PathResult]
    unwaived: List[LintFinding]
    waived: List[Tuple[LintFinding, Waiver]]
    stale_waivers: List[Waiver]
    config: LintConfig = dataclasses.field(default_factory=LintConfig)

    @property
    def findings(self) -> List[LintFinding]:
        return [f for p in self.paths for f in p.findings]

    @property
    def errors(self) -> List[str]:
        return [e for p in self.paths for e in p.errors]

    @property
    def clean(self) -> bool:
        return not self.unwaived and not self.errors

    def to_dict(self) -> Dict[str, Any]:
        return {
            "paths": [p.to_dict() for p in self.paths],
            "unwaived": [f.to_dict() for f in self.unwaived],
            "waived": [{"finding": f.to_dict(), "waiver": w.to_dict()}
                       for f, w in self.waived],
            "stale_waivers": [w.to_dict() for w in self.stale_waivers],
            "errors": self.errors,
            "lint_config": self.config.to_dict(),
            "pass": self.clean,
        }


__all__ = ["LintFinding", "Waiver", "load_waivers", "apply_waivers",
           "LintConfig", "LintContext", "PathResult", "LintReport",
           "SEVERITY_ERROR", "SEVERITY_WARN"]
