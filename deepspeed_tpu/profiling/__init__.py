from . import flops_profiler  # noqa: F401
