from .profiler import (FlopsProfiler, ProfileResult, get_model_profile,
                       profile_fn, flops_to_string, macs_to_string,
                       params_to_string, duration_to_string)

__all__ = ["FlopsProfiler", "ProfileResult", "get_model_profile",
           "profile_fn", "flops_to_string", "macs_to_string",
           "params_to_string", "duration_to_string"]
