"""Flops profiler — analytic per-module FLOPs/MACs/params for JAX functions.

Capability parity with the reference's hook-based profiler
(profiling/flops_profiler/profiler.py:11-769): per-module tables, depth
aggregation, top-k module report, and an engine hook that profiles one
training step at a configured step index.

TPU-native redesign: torch profiles by monkey-patching ``torch.nn.functional``
and registering forward hooks per ``nn.Module`` (reference profiler.py:470-551).
JAX functions are traced to a jaxpr, so no patching is needed — we walk the
jaxpr once, count FLOPs per primitive (matching the reference's per-op
formulas, profiler.py:306-456), and attribute each equation to a "module
path" recovered from its source traceback (the chain of user function
names, e.g. ``gpt2_apply / apply_blocks / transformer_block / dense``).
Control-flow primitives multiply through: a ``scan`` body counts
``length``×, a ``pallas_call`` counts ``prod(grid)``× its kernel jaxpr —
so Pallas flash-attention kernels are costed too.

Duration: one measured wall-clock execution of the jitted function is
reported as the total; per-module durations are FLOPs-proportional
estimates (a jaxpr has no per-module clock — unlike torch's eager hooks).
"""
from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.extend import core as jcore

try:
    from jax._src import source_info_util
except Exception:  # pragma: no cover
    source_info_util = None


# --------------------------------------------------------------------- #
# Per-primitive FLOP formulas (reference profiler.py:306-456 equivalents)
# --------------------------------------------------------------------- #
_ELEMENTWISE_1 = {
    "add", "sub", "mul", "div", "max", "min", "pow", "rem", "and", "or",
    "xor", "neg", "abs", "sign", "floor", "ceil", "round", "sqrt", "rsqrt",
    "exp", "exp2", "expm1", "log", "log1p", "sin", "cos", "tan", "atan2",
    "integer_pow", "square", "select_n", "clamp", "nextafter",
}
_ELEMENTWISE_HEAVY = {"tanh", "logistic", "erf", "erfc", "erf_inv",
                      "cbrt", "sinh", "cosh", "asinh", "acosh", "atanh",
                      "asin", "acos", "atan", "digamma", "lgamma"}
# transcendental cost factor, mirroring the reference counting each
# functional call as one "op" per output element
_HEAVY_FACTOR = 4

_ZERO_COST = {
    "reshape", "transpose", "broadcast_in_dim", "squeeze", "convert_element_type",
    "slice", "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
    "rev", "gather", "scatter", "iota", "eq", "ne", "lt", "le", "gt", "ge",
    "is_finite", "stop_gradient", "copy", "device_put", "split",
    "bitcast_convert_type", "expand_dims", "real", "imag", "not",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "population_count", "clz", "random_bits", "random_seed", "random_wrap",
    "random_fold_in", "threefry2x32", "partition_id", "axis_index",
    "empty", "argmax", "argmin", "reduce_precision", "optimization_barrier",
}

_REDUCE = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
           "reduce_and", "reduce_or", "reduce_xor", "cumsum", "cumprod",
           "cummax", "cummin", "cumlogsumexp", "reduce_window_sum",
           "reduce_window_max", "reduce_window_min", "add_any"}


def _size(aval) -> int:
    try:
        return int(np.prod(aval.shape)) if aval.shape else 1
    except Exception:
        return 0


def _dot_general_flops(eqn) -> Tuple[int, int]:
    """2*M*N*K FLOPs / M*N*K MACs (reference _linear_flops_compute,
    profiler.py:306-320)."""
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    (contract_a, _), (batch_a, _) = eqn.params["dimension_numbers"]
    k = int(np.prod([a.shape[i] for i in contract_a])) or 1
    batch = int(np.prod([a.shape[i] for i in batch_a])) or 1
    m = int(np.prod([a.shape[i] for i in range(a.ndim)
                     if i not in contract_a and i not in batch_a])) or 1
    n = int(np.prod([b.shape[i] for i in range(b.ndim)
                     if i not in eqn.params["dimension_numbers"][0][1]
                     and i not in eqn.params["dimension_numbers"][1][1]])) or 1
    macs = batch * m * n * k
    return 2 * macs, macs


def _conv_flops(eqn) -> Tuple[int, int]:
    """output_size * kernel_size * in_channels MACs (reference
    _conv_flops_compute, profiler.py:322-360)."""
    rhs = eqn.invars[1].aval
    out = eqn.outvars[0].aval
    macs = _size(out) * int(np.prod(rhs.shape[:-1] if rhs.ndim else (1,)))
    # rhs layout varies; approximate: total kernel elems / out_channels
    dn = eqn.params.get("dimension_numbers")
    try:
        out_c = rhs.shape[dn.rhs_spec[0]]
        macs = _size(out) * (int(np.prod(rhs.shape)) // max(out_c, 1))
    except Exception:
        pass
    return 2 * macs, macs


def eqn_flops(eqn) -> Tuple[int, int]:
    """(flops, macs) for one jaxpr equation; sub-jaxpr prims return 0 here
    (handled by the recursive walker)."""
    name = eqn.primitive.name
    if name == "dot_general":
        return _dot_general_flops(eqn)
    if name == "conv_general_dilated":
        return _conv_flops(eqn)
    if name in _REDUCE:
        return sum(_size(v.aval) for v in eqn.invars), 0
    if name in _ELEMENTWISE_HEAVY:
        return _HEAVY_FACTOR * _size(eqn.outvars[0].aval), 0
    if name in _ELEMENTWISE_1:
        return _size(eqn.outvars[0].aval), 0
    return 0, 0


def _sub_jaxprs(eqn) -> List[Tuple[Any, int]]:
    """(jaxpr, multiplier) pairs for control-flow/call primitives."""
    p = eqn.params
    name = eqn.primitive.name
    out = []
    if name == "scan":
        out.append((p["jaxpr"], int(p["length"])))
    elif name == "while":
        # Trip count is data-dependent; count one body + one cond pass and
        # let the caller know via module name (reference has no analogue).
        out.append((p["body_jaxpr"], 1))
        out.append((p["cond_jaxpr"], 1))
    elif name == "cond":
        # Cost of the most expensive branch.
        branches = p.get("branches", ())
        if branches:
            best = max(branches, key=lambda b: _jaxpr_total(b)[0])
            out.append((best, 1))
    elif name in ("pjit", "jit"):
        out.append((p["jaxpr"], 1))
    elif name in ("custom_vjp_call", "custom_jvp_call",
                  "custom_vjp_call_jaxpr", "custom_jvp_call_jaxpr"):
        inner = p.get("call_jaxpr") or p.get("fun_jaxpr")
        if inner is not None:
            out.append((inner, 1))
    elif name in ("remat", "checkpoint", "remat2"):
        out.append((p["jaxpr"], 1))
    elif name == "pallas_call":
        grid = p.get("grid_mapping")
        mult = 1
        try:
            mult = int(np.prod([int(g) for g in grid.grid])) if grid else 1
        except Exception:
            mult = 1
        out.append((p["jaxpr"], mult))
    elif name in ("closed_call", "core_call", "xla_call"):
        out.append((p["call_jaxpr"], 1))
    elif name == "shard_map":
        out.append((p["jaxpr"], 1))
    if not out:
        # Version-robust fallback: recurse into any jaxpr-valued param of an
        # unrecognized call-like primitive.
        for v in p.values():
            if isinstance(v, jcore.ClosedJaxpr) or isinstance(v, jcore.Jaxpr):
                out.append((v, 1))
    return out


def _as_jaxpr(j):
    return j.jaxpr if hasattr(j, "jaxpr") else j


def _jaxpr_total(jaxpr) -> Tuple[int, int]:
    """(flops, macs) of a jaxpr, recursing into sub-jaxprs."""
    jaxpr = _as_jaxpr(jaxpr)
    fl = mc = 0
    for eqn in jaxpr.eqns:
        subs = _sub_jaxprs(eqn)
        if subs:
            for sub, mult in subs:
                f, m = _jaxpr_total(sub)
                fl += f * mult
                mc += m * mult
        else:
            f, m = eqn_flops(eqn)
            fl += f
            mc += m
    return fl, mc


# --------------------------------------------------------------------- #
# Module attribution via source tracebacks
# --------------------------------------------------------------------- #
_SKIP_FUNCS = {"<module>", "<lambda>", "tree_map", "wrapper", "inner",
               "reraise_with_filtered_traceback", "cache_miss", "fun",
               "profile_fn", "profile", "get_model_profile"}


def _module_path(eqn, max_depth: int = 12) -> Tuple[str, ...]:
    """Outermost→innermost chain of user function names for an equation."""
    if source_info_util is None or eqn.source_info is None:
        return ()
    tb = getattr(eqn.source_info, "traceback", None)
    if tb is None:
        return ()
    try:
        frames = list(source_info_util.user_frames(tb))
    except Exception:
        try:
            # Older jax: user_frames takes the SourceInfo, not a Traceback.
            frames = list(source_info_util.user_frames(eqn.source_info))
        except Exception:
            return ()
    frames = list(reversed(frames))               # outermost first
    # Drop the harness: everything up to (and including) the innermost frame
    # inside this file — pytest/runpy/engine frames above profile_fn are not
    # part of the profiled model.
    for i in range(len(frames) - 1, -1, -1):
        if frames[i].file_name == __file__:
            frames = frames[i + 1:]
            break
    names = []
    for f in frames:
        fn = f.function_name.rsplit("<locals>.", 1)[-1]   # short qualname
        if fn in _SKIP_FUNCS:
            continue
        names.append(fn)
    return tuple(names[:max_depth])


@dataclass
class ModuleNode:
    """One node of the per-module aggregation tree (≈ one nn.Module row in
    the reference's printed model profile, profiler.py:174-298)."""
    name: str
    flops: int = 0
    macs: int = 0
    children: Dict[str, "ModuleNode"] = field(default_factory=dict)

    def child(self, name: str) -> "ModuleNode":
        if name not in self.children:
            self.children[name] = ModuleNode(name)
        return self.children[name]

    def total_flops(self) -> int:
        return self.flops + sum(c.total_flops() for c in self.children.values())

    def total_macs(self) -> int:
        return self.macs + sum(c.total_macs() for c in self.children.values())


def _walk(jaxpr, root: ModuleNode, mult: int) -> None:
    jaxpr = _as_jaxpr(jaxpr)
    for eqn in jaxpr.eqns:
        subs = _sub_jaxprs(eqn)
        if subs:
            for sub, m in subs:
                _walk(sub, root, mult * m)
            continue
        fl, mc = eqn_flops(eqn)
        if fl == 0 and mc == 0:
            continue
        node = root
        for name in _module_path(eqn):
            node = node.child(name)
        node.flops += fl * mult
        node.macs += mc * mult


# --------------------------------------------------------------------- #
# Public API
# --------------------------------------------------------------------- #
def num_to_string(num: float, precision: int = 2) -> str:
    if num >= 1e12:
        return f"{num / 1e12:.{precision}f} T"
    if num >= 1e9:
        return f"{num / 1e9:.{precision}f} G"
    if num >= 1e6:
        return f"{num / 1e6:.{precision}f} M"
    if num >= 1e3:
        return f"{num / 1e3:.{precision}f} K"
    return f"{num:.{precision}f} "


def params_to_string(n, units=None, precision=2):
    return num_to_string(float(n), precision)


def flops_to_string(f, units=None, precision=2):
    return num_to_string(float(f), precision) + "FLOPs"


def macs_to_string(m, units=None, precision=2):
    return num_to_string(float(m), precision) + "MACs"


def duration_to_string(d, units=None, precision=2):
    if d >= 1:
        return f"{d:.{precision}f} s"
    if d >= 1e-3:
        return f"{d * 1e3:.{precision}f} ms"
    return f"{d * 1e6:.{precision}f} us"


@dataclass
class ProfileResult:
    total_flops: int
    total_macs: int
    total_params: int
    duration: float              # measured seconds for one execution (0 if not run)
    tree: ModuleNode

    # ---- reference-parity getters (profiler.py:105-173) ----
    def get_total_flops(self, as_string: bool = False):
        return flops_to_string(self.total_flops) if as_string else self.total_flops

    def get_total_macs(self, as_string: bool = False):
        return macs_to_string(self.total_macs) if as_string else self.total_macs

    def get_total_params(self, as_string: bool = False):
        return params_to_string(self.total_params) if as_string else self.total_params

    def get_total_duration(self, as_string: bool = False):
        return duration_to_string(self.duration) if as_string else self.duration

    # ---- tables ----
    def _rows(self, node: ModuleNode, depth: int, path: str,
              max_depth: int, out: List[Tuple[str, int, int, int]]):
        for name, c in node.children.items():
            p = f"{path}/{name}" if path else name
            out.append((p, depth, c.total_flops(), c.total_macs()))
            if max_depth < 0 or depth + 1 < max_depth:
                self._rows(c, depth + 1, p, max_depth, out)

    def aggregate_by_depth(self, depth: int = -1) -> List[Tuple[str, int, int]]:
        """Flops aggregated at tree depth (reference's depth-aggregated
        print, profiler.py:221-268)."""
        rows: List[Tuple[str, int, int, int]] = []
        self._rows(self.tree, 0, "", -1, rows)
        if depth < 0:
            return [(p, f, m) for (p, d, f, m) in rows]
        agg: Dict[str, Tuple[int, int]] = {}
        for (p, d, f, m) in rows:
            if d == depth:
                agg[p] = (f, m)
        return [(p, f, m) for p, (f, m) in agg.items()]

    def top_modules(self, k: int = 1, depth: int = 1) -> List[Tuple[str, int, int]]:
        rows = self.aggregate_by_depth(depth - 1 if depth > 0 else 0)
        return sorted(rows, key=lambda r: -r[1])[:k]

    def format_profile(self, module_depth: int = -1, top_modules: int = 1,
                       detailed: bool = True) -> str:
        lines = [
            "-------------------------- DeepSpeed-TPU Flops Profiler "
            "--------------------------",
            f"params:   {params_to_string(self.total_params)}",
            f"fwd+step flops: {flops_to_string(self.total_flops)}",
            f"fwd+step MACs:  {macs_to_string(self.total_macs)}",
        ]
        if self.duration:
            lines.append(f"measured step time: "
                         f"{duration_to_string(self.duration)}  "
                         f"({self.total_flops / self.duration / 1e12:.2f} "
                         f"TFLOPS achieved)")
        lines.append("")
        lines.append(f"Top {top_modules} modules by FLOPs:")
        for (p, f, m) in self.top_modules(top_modules, depth=1):
            lines.append(f"  {p}: {flops_to_string(f)}")
        if detailed:
            lines.append("")
            lines.append("Per-module profile "
                         "(module, flops, MACs, est. duration share):")
            rows: List[Tuple[str, int, int, int]] = []
            self._rows(self.tree, 0, "", module_depth, rows)
            tot = max(self.total_flops, 1)
            for (p, d, f, m) in rows:
                indent = "  " * (d + 1)
                dur = ""
                if self.duration:
                    dur = f", ~{duration_to_string(self.duration * f / tot)}"
                lines.append(f"{indent}{p.rsplit('/', 1)[-1]}: "
                             f"{flops_to_string(f)}, {macs_to_string(m)}"
                             f"{dur}  [{100.0 * f / tot:.1f}%]")
        lines.append("-" * 82)
        return "\n".join(lines)

    def print_model_profile(self, module_depth: int = -1, top_modules: int = 1,
                            detailed: bool = True) -> None:
        print(self.format_profile(module_depth, top_modules, detailed))


def _count_params(tree) -> int:
    return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(tree)
               if hasattr(l, "shape"))


def profile_fn(fn: Callable, *args, params=None, run: bool = True,
               static_argnums=()) -> ProfileResult:
    """Profile ``fn(*args)``: analytic FLOPs/MACs from its jaxpr + one
    measured execution (if ``run``).

    ``params``: pytree counted for the params column (defaults to args[0]).
    """
    jaxpr = jax.make_jaxpr(fn, static_argnums=static_argnums)(*args)
    root = ModuleNode("model")
    _walk(jaxpr, root, 1)
    fl, mc = root.total_flops(), root.total_macs()
    duration = 0.0
    if run:
        jfn = jax.jit(fn, static_argnums=static_argnums)
        out = jfn(*args)            # compile
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        out = jfn(*args)
        jax.block_until_ready(out)
        duration = time.perf_counter() - t0
    p = params if params is not None else (args[0] if args else None)
    return ProfileResult(total_flops=fl, total_macs=mc,
                         total_params=_count_params(p) if p is not None else 0,
                         duration=duration, tree=root)


def get_model_profile(model_fn: Callable, args=(), kwargs=None,
                      print_profile: bool = True, detailed: bool = True,
                      module_depth: int = -1, top_modules: int = 1,
                      warm_up: int = 1, as_string: bool = True,
                      ignore_modules=None):
    """Reference-parity convenience (profiler.py:651-769
    ``get_model_profile``): returns (flops, macs, params) of one forward.

    ``model_fn`` is any JAX-traceable callable; args/kwargs its inputs.
    """
    if ignore_modules:
        import warnings
        warnings.warn("ignore_modules is not supported by the jaxpr-walking "
                      "profiler; counts include all modules")
    kwargs = kwargs or {}
    res = profile_fn(lambda *a: model_fn(*a, **kwargs), *args,
                     run=warm_up > 0)
    if print_profile:
        res.print_model_profile(module_depth=module_depth,
                                top_modules=top_modules, detailed=detailed)
    if as_string:
        return (res.get_total_flops(True), res.get_total_macs(True),
                res.get_total_params(True))
    return res.total_flops, res.total_macs, res.total_params


class FlopsProfiler:
    """Engine-facing profiler object (reference profiler.py:11 FlopsProfiler).

    The engine calls :meth:`profile_step` once at the configured
    ``profile_step``; it traces the engine's already-built train-step
    function on the live batch and prints/stores the table.
    """

    def __init__(self, fn: Optional[Callable] = None, config=None):
        self.fn = fn
        self.config = config
        self.result: Optional[ProfileResult] = None
        self.started = False

    def start_profile(self, ignore_list=None) -> None:
        self.started = True

    def stop_profile(self) -> None:
        self.started = False

    def reset_profile(self) -> None:
        self.result = None

    def end_profile(self) -> None:
        self.stop_profile()
        self.reset_profile()

    def profile(self, fn: Callable, *args, params=None) -> ProfileResult:
        self.result = profile_fn(fn, *args, params=params)
        return self.result

    def print_model_profile(self, profile_step=None, module_depth=-1,
                            top_modules=1, detailed=True, output_file=None):
        if self.result is None:
            return
        text = self.result.format_profile(module_depth, top_modules, detailed)
        if output_file:
            with open(output_file, "w") as f:
                f.write(text)
        else:
            print(text)
