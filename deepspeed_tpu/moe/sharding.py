"""Expert parameter placement: born sharded over the ``expert`` axis.

Expert FFN weights carry a stacked layout ``[n_moe_layers, E, ...]``
(models/transformer.init_block_params); their PartitionSpecs put the
``expert`` mesh axis on the E dim, so each expert group owns exactly its
E/ep experts from birth — no gather ever materializes the full expert
tree. The router is replicated (every token routes against all E
logits).

Composition story (what follows from handing these specs to
``deepspeed_tpu.initialize(param_shardings=...)``):

- **Grads** follow automatically: the MoE shard_map's transpose psums
  expert-weight cotangents over ``data`` ONLY (within-expert-group
  sync), and ``runtime/zero/partition.grad_shardings`` layers the ZeRO
  dp axis onto the expert base spec's first free divisible dim — so
  under stage >= 2 the expert grads land data-sharded *within* their
  expert shard, never replicated across experts.
- **Moments/masters** mirror the same base via ``zero_shardings`` /
  ``stage3_param_specs`` (the param-structured-subtree rule), keeping
  the optimizer apply element-aligned and shard-local on the dense AND
  expert trees alike. ZeRO stages 1-3 on the dense tree are untouched —
  the expert axis factors out of data, so the dense leaves still shard
  over ``data`` exactly as before.
- **Fused optimizer**: engines built with ``param_shardings`` route the
  optax per-leaf apply — the fused multi-tensor front end's flat
  V-interleaved chunks are laid out over the dp axis and concatenating
  an expert-sharded leaf into them would silently all-gather it every
  step (the same reason TP layouts fall back; runtime/engine.py logs
  the downgrade). The per-leaf apply stays shard-local on the declared
  layout.
"""
from __future__ import annotations

from typing import Any, Dict

from jax.sharding import PartitionSpec as P

from ..parallel.topology import EP_AXIS


def is_expert_spec(spec: P, ep_axis: str = EP_AXIS) -> bool:
    """True when a PartitionSpec places any dim on the expert axis."""
    for entry in spec:
        if entry == ep_axis or (isinstance(entry, (tuple, list)) and
                                ep_axis in entry):
            return True
    return False


def expert_block_shardings(ep: int, ep_axis: str = EP_AXIS
                           ) -> Dict[str, P]:
    """Specs for the stacked MoE block params ([n_moe, E, ...] leaves).

    ep == 1 keeps everything replicated (a single expert group — the
    dev/CI path with no expert axis live)."""
    e = ep_axis if ep > 1 else None
    return {
        "router_kernel": P(None, None, None),       # [n_moe, H, E]
        "moe_fc_kernel": P(None, e, None, None),    # [n_moe, E, H, F]
        "moe_fc_bias": P(None, e, None),            # [n_moe, E, F]
        "moe_out_kernel": P(None, e, None, None),   # [n_moe, E, F, H]
        "moe_out_bias": P(None, e, None),           # [n_moe, E, H]
    }


def gpt2_moe_param_shardings(cfg, mp_axis: str = "model",
                             ep_axis: str = EP_AXIS) -> Dict[str, Any]:
    """The gpt2 spec tree with the expert overrides merged in — pass as
    ``initialize(param_shardings=...)`` for an MoE GPT-2."""
    from ..models.gpt2 import gpt2_param_shardings
    assert cfg.moe is not None, "cfg.moe is None — not an MoE config"
    specs = gpt2_param_shardings(cfg, mp_axis)
    blocks = dict(specs["blocks"])
    moe = expert_block_shardings(cfg.moe.expert_parallel_size, ep_axis)
    n_dense = cfg.num_layers - len(
        _moe_layers(cfg.num_layers, cfg.moe_layer_freq))
    if n_dense == 0:
        for k in ("fc_kernel", "fc_bias", "fc_out_kernel", "fc_out_bias"):
            blocks.pop(k, None)
    blocks.update(moe)
    specs["blocks"] = blocks
    return specs


def _moe_layers(num_layers: int, freq: int):
    from .layer import moe_layer_indices
    return moe_layer_indices(num_layers, freq)
