"""Expert-parallel MoE FFN: top-k gating, capacity bucketing, all-to-all.

The layer replaces a transformer block's dense FFN (every
``moe_layer_freq``-th block — models/transformer.py wires it behind
``TransformerConfig.moe``). Design:

- **Routing** is a linear router + softmax + ``top_k`` (k in {1, 2});
  the kept gates renormalize to sum 1 (GShard top-2). Routing math runs
  in fp32 regardless of the compute dtype.
- **Capacity bucketing** gives ONE compiled shape regardless of routing:
  each device builds a ``[E, C, H]`` dispatch buffer (C =
  ``ceil(capacity_factor * k * T / E)`` for its T local tokens) by
  scatter; tokens beyond an expert's capacity are DROPPED — their
  combine contribution is exactly 0, so they ride the block's residual
  path untouched. Assignment priority is j-major (every token's first
  choice before any second choice), position-in-expert by running count.
- **Expert parallelism**: with ``expert_parallel_size`` (ep) > 1 the
  whole token path runs under a fully-manual ``shard_map`` over the
  mesh (old-jax safe: no partial-auto axes) — the batch enters sharded
  over ``(expert, data)``, expert weights enter as their ``expert``-axis
  shards, and dispatch/combine are real ``lax.all_to_all`` collectives
  over the ``expert`` axis (tiled, split=concat=0; applying the same
  exchange twice is the identity, which is exactly the combine). The
  shard_map transpose gives expert-weight gradients their psum over
  ``data`` ONLY — experts are not replicas, and a dense all-reduce
  across the expert axis is the seeded-violation case the
  collective_placement lint pass catches.
- **Losses/stats**: the load-balance aux loss (Switch/GShard:
  ``E * sum(f_e * P_e)``, f from the routed counts treated as constant,
  P the mean router probability) and the router z-loss
  (``mean(logsumexp(logits)^2)``) come back as stats alongside the
  per-expert routed token counts and the drop fraction; the model adds
  the weighted losses to its objective and the engine rides the stats
  on the telemetry drain (no extra syncs).

``num_experts=1, top_k=1`` with unbounded capacity reduces to the dense
FFN bit-for-bit: the single gate renormalizes to exactly 1.0, every
token keeps its slot in order, and the expert einsum contracts the same
[H] axis the dense matmul does (tests/test_moe.py asserts bitwise
equality against the dense block).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.grouped_gemm import grouped_ffn, grouped_gemm_enabled
from ..parallel import comm
from ..parallel.topology import DP_AXIS, EP_AXIS

# The block-param keys the MoE FFN owns (models/transformer.py routes a
# per-layer params dict containing these through moe_ffn instead of the
# dense FFN). Stacked leading axis = the MoE layers only.
MOE_PARAM_KEYS = frozenset({
    "router_kernel", "moe_fc_kernel", "moe_fc_bias",
    "moe_out_kernel", "moe_out_bias",
})


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    """Model-side MoE hyperparameters (``TransformerConfig.moe``).

    Mirrors the ``moe`` ds_config block (constants.py) — build one from
    it with ``MoEConfig.from_ds_config`` so the engine's expert mesh and
    the model's expert count cannot drift apart.
    """
    num_experts: int = 8
    top_k: int = 2                      # k in {1, 2}
    capacity_factor: float = 1.25       # inf => no token ever drops
    aux_loss_weight: float = 1e-2
    z_loss_weight: float = 1e-3
    expert_parallel_size: int = 1       # ep — the `expert` mesh axis size
    # Expert-FFN compute path: "auto" = the grouped-GEMM Pallas kernel
    # on TPU / the einsum path on CPU (DS_GROUPED_GEMM=0/1 overrides),
    # True/False force. cfg-static exactly like TransformerConfig.
    # fused_kernels — flipping it changes the program, never the
    # compiled signature or the checkpoint state.
    grouped_gemm: Any = "auto"

    def __post_init__(self):
        assert self.num_experts >= 1, "num_experts must be >= 1"
        assert self.top_k in (1, 2), "top_k must be 1 or 2"
        assert self.top_k <= self.num_experts
        assert self.capacity_factor > 0
        assert self.expert_parallel_size >= 1
        assert self.num_experts % self.expert_parallel_size == 0, \
            (f"num_experts={self.num_experts} not divisible by "
             f"expert_parallel_size={self.expert_parallel_size}")
        assert self.grouped_gemm in (True, False, "auto"), \
            f"grouped_gemm must be True/False/'auto', got " \
            f"{self.grouped_gemm!r}"

    @classmethod
    def from_ds_config(cls, moe_cfg) -> "MoEConfig":
        """From a parsed ``runtime.config.MoeConfig`` (the ds_config
        ``moe`` block)."""
        return cls(num_experts=moe_cfg.num_experts, top_k=moe_cfg.top_k,
                   capacity_factor=moe_cfg.capacity_factor,
                   aux_loss_weight=moe_cfg.aux_loss_weight,
                   z_loss_weight=moe_cfg.z_loss_weight,
                   expert_parallel_size=moe_cfg.expert_parallel_size,
                   grouped_gemm=getattr(moe_cfg, "grouped_gemm", "auto"))


def expert_capacity(tokens: int, num_experts: int, top_k: int,
                    capacity_factor: float) -> int:
    """Per-expert slot count C for a device routing ``tokens`` local
    tokens: ``ceil(cf * k * T / E)``, clamped to [1, T] (an expert can
    receive at most T distinct tokens from one device — top-k choices
    are distinct experts). ``inf`` capacity => C = T, nothing drops."""
    if math.isinf(capacity_factor):
        return max(1, tokens)
    c = int(math.ceil(capacity_factor * top_k * tokens / num_experts))
    return max(1, min(c, tokens))


def moe_layer_indices(num_layers: int, moe_layer_freq: int) -> List[int]:
    """Which block indices carry the MoE FFN: every ``freq``-th block,
    counting from the first (layer freq-1, 2*freq-1, ...)."""
    assert moe_layer_freq >= 1
    return [i for i in range(num_layers) if (i + 1) % moe_layer_freq == 0]


def router_topk(x32: jnp.ndarray, router_kernel: jnp.ndarray, top_k: int
                ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                           jnp.ndarray]:
    """fp32 routing: ``(gates [T,k], expert_idx [T,k], probs [T,E],
    logits [T,E])``. Gates renormalize over the kept k (exactly 1.0 for
    k=1 — IEEE x/x — which is what makes the E=1 path bit-identical to
    dense)."""
    logits = x32 @ router_kernel.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top_vals, idx = lax.top_k(probs, top_k)
    gates = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    return gates, idx, probs, logits


def _dispatch_plan(idx: jnp.ndarray, num_experts: int, capacity: int
                   ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Token -> bucket-slot assignment. ``idx``: [T, k] expert choices.

    Returns ``(dest [k*T] int32 in [0, E*C] (E*C = dropped), keep [k*T]
    bool, routed_counts [E] f32)`` in j-major order (choice 0 of every
    token outranks any choice 1 — the GShard priority)."""
    T, k = idx.shape
    idx_j = idx.T.reshape(-1)                                   # [k*T]
    oh = jax.nn.one_hot(idx_j, num_experts, dtype=jnp.float32)  # [k*T, E]
    prior = jnp.cumsum(oh, axis=0) - oh
    pos_in_e = jnp.sum(prior * oh, axis=-1).astype(jnp.int32)
    keep = pos_in_e < capacity
    dest = jnp.where(keep, idx_j * capacity + pos_in_e,
                     num_experts * capacity)
    return dest, keep, jnp.sum(oh, axis=0)


def _moe_tokens(params: Dict[str, jnp.ndarray], xt: jnp.ndarray,
                moe: MoEConfig, gelu_approx: bool, ep: int
                ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """The per-device token path: route -> bucket -> (all-to-all) ->
    expert FFN -> (all-to-all) -> weighted combine. ``xt``: [T, H] local
    tokens in the compute dtype; expert weights arrive ep-sliced
    ([E/ep, ...]) when ep > 1. Returns (y [T, H], local stats)."""
    T, H = xt.shape
    E, k = moe.num_experts, moe.top_k
    C = expert_capacity(T, E, k, moe.capacity_factor)
    gates, idx, probs, logits = router_topk(
        xt.astype(jnp.float32), params["router_kernel"], k)
    dest, keep, counts = _dispatch_plan(idx, E, C)

    # Scatter into the fixed [E, C, H] dispatch buffer (row E*C is the
    # drop bin; (e, pos) slots are unique by construction).
    xk = jnp.tile(xt, (k, 1))                                   # [k*T, H]
    buckets = jnp.zeros((E * C + 1, H), xt.dtype).at[dest].set(xk)
    b = buckets[:E * C].reshape(E, C, H)

    if ep > 1:
        # Dispatch: expert-major split. After the tiled exchange, row
        # s*E_loc + j on member r holds source member s's bucket for
        # local expert j — regroup to [E_loc, ep*C, H] so each local
        # expert sees every source's candidates.
        e_loc = E // ep
        b = comm.all_to_all(b, EP_AXIS, 0, 0)
        b = b.reshape(ep, e_loc, C, H).transpose(1, 0, 2, 3) \
             .reshape(e_loc, ep * C, H)

    w1 = params["moe_fc_kernel"].astype(xt.dtype)
    b1 = params["moe_fc_bias"].astype(xt.dtype)
    w2 = params["moe_out_kernel"].astype(xt.dtype)
    b2 = params["moe_out_bias"].astype(xt.dtype)
    if grouped_gemm_enabled(moe.grouped_gemm):
        # One Pallas grouped GEMM per projection: grid over experts x
        # row blocks x col blocks, fp32 MXU accumulation, bias + GELU
        # fused in-register (ops/grouped_gemm.py). Shard-LOCAL: under
        # ep > 1 this runs inside the `expert` shard_map scope on the
        # [E/ep, ...] slices — no collective moves for the kernel.
        y = grouped_ffn(b, w1, b1, w2, b2, not gelu_approx)
    else:
        h = jnp.einsum("ech,ehf->ecf", b, w1) + b1[:, None, :]
        h = jax.nn.gelu(h, approximate=gelu_approx)
        y = jnp.einsum("ecf,efh->ech", h, w2) + b2[:, None, :]

    if ep > 1:
        # Combine: the inverse regroup + the SAME tiled all-to-all (the
        # exchange is an involution), landing each expert output back on
        # its source member in the original [E, C, H] bucket layout.
        e_loc = E // ep
        y = y.reshape(e_loc, ep, C, H).transpose(1, 0, 2, 3) \
             .reshape(E, C, H)
        y = comm.all_to_all(y, EP_AXIS, 0, 0)

    # Gather back per token; dropped tokens hit the appended zero row,
    # so their FFN delta is exactly 0 (pure residual).
    yf = jnp.concatenate([y.reshape(E * C, H),
                          jnp.zeros((1, H), y.dtype)], axis=0)
    yk = yf[dest]                                               # [k*T, H]
    gk = gates.T.reshape(-1).astype(yf.dtype)
    out = jnp.sum((yk * gk[:, None]).reshape(k, T, H), axis=0)

    frac = lax.stop_gradient(counts) / (k * T)
    stats = {
        "expert_tokens": counts,                                # [E] f32
        "drop_fraction":
            1.0 - jnp.sum(keep.astype(jnp.float32)) / (k * T),
        "aux_loss": E * jnp.sum(frac * jnp.mean(probs, axis=0)),
        "z_loss": jnp.mean(jnp.square(
            jax.scipy.special.logsumexp(logits, axis=-1))),
    }
    return out, stats


def moe_ffn(params: Dict[str, jnp.ndarray], x: jnp.ndarray, cfg,
            mesh=None) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """The MoE FFN sublayer. ``x``: [B, S, H] (compute dtype); ``params``
    holds this layer's ``MOE_PARAM_KEYS`` (no stacking axis); ``cfg`` is
    the ``TransformerConfig`` (reads ``cfg.moe`` and ``cfg.gelu_exact``).

    ep == 1 runs the plain jnp path (no collectives; GSPMD partitions
    the token math over ``data`` as usual). ep > 1 needs ``mesh`` and
    runs fully-manual shard_map: batch over ``(expert, data)``, expert
    weights over ``expert``, stats psum/pmean'd to replicated. Returns
    ``(y [B, S, H], stats)`` with GLOBAL stats either way — on the
    engine's explicit-shard_map path (which runs this per-dp-rank with
    ep == 1) the engine reduces the stats itself."""
    moe: MoEConfig = cfg.moe
    gelu_approx = not cfg.gelu_exact
    B, S, H = x.shape
    ep = moe.expert_parallel_size
    if ep <= 1:
        y, stats = _moe_tokens(params, x.reshape(B * S, H), moe,
                               gelu_approx, ep=1)
        return y.reshape(B, S, H), stats

    if comm.axis_in_scope(EP_AXIS):
        # Already INSIDE a fully-manual shard_map over (expert, data) —
        # the engine's factored explicit-gradient path runs the whole
        # loss that way so dense grads can reduce-scatter over `data`
        # (the stage-2 declarative regression this closes). Params
        # arrived as their expert-axis shards and ``x`` is the local
        # batch slab: run the token path bare; dispatch/combine bind to
        # the in-scope `expert` axis directly and the stats psum to
        # global exactly like the self-wrapped path below.
        y, stats = _moe_tokens(params, x.reshape(B * S, H), moe,
                               gelu_approx, ep=ep)
        axes = (EP_AXIS, DP_AXIS)
        stats = {
            "expert_tokens": lax.psum(stats["expert_tokens"], axes),
            "drop_fraction": lax.pmean(stats["drop_fraction"], axes),
            "aux_loss": lax.pmean(stats["aux_loss"], axes),
            "z_loss": lax.pmean(stats["z_loss"], axes),
        }
        return y.reshape(B, S, H), stats

    if mesh is None:
        # No mesh (eval/serving on fully-addressable params —
        # gpt2_apply on a fetched tree): every expert is local, so the
        # ep == 1 path computes the same routed FFN with no collective.
        # Drop margins can differ from the sharded step (capacity
        # derives from the GLOBAL token count here vs per-device there);
        # training always passes the mesh.
        y, stats = _moe_tokens(params, x.reshape(B * S, H), moe,
                               gelu_approx, ep=1)
        return y.reshape(B, S, H), stats
    if EP_AXIS not in mesh.shape or int(mesh.shape[EP_AXIS]) != ep:
        raise ValueError(
            f"mesh has no '{EP_AXIS}' axis of size {ep} "
            f"(mesh shape: {dict(mesh.shape)}); build it with "
            f"build_mesh(ep={ep}, ...)")
    for ax, size in mesh.shape.items():
        if ax not in (EP_AXIS, DP_AXIS) and int(size) > 1:
            raise NotImplementedError(
                f"moe expert parallelism composes with expert x data "
                f"meshes only for now (live '{ax}' axis of size {size})")

    def local(rk, w1, b1, w2, b2, xl):
        bl, sl, hl = xl.shape
        p = {"router_kernel": rk, "moe_fc_kernel": w1, "moe_fc_bias": b1,
             "moe_out_kernel": w2, "moe_out_bias": b2}
        y, stats = _moe_tokens(p, xl.reshape(bl * sl, hl), moe,
                               gelu_approx, ep=ep)
        # Global stats, replicated out: counts SUM over every member
        # (they are counts), the rest mean.
        axes = (EP_AXIS, DP_AXIS)
        stats = {
            "expert_tokens": lax.psum(stats["expert_tokens"], axes),
            "drop_fraction": lax.pmean(stats["drop_fraction"], axes),
            "aux_loss": lax.pmean(stats["aux_loss"], axes),
            "z_loss": lax.pmean(stats["z_loss"], axes),
        }
        return y.reshape(bl, sl, hl), stats

    batch_spec = P((EP_AXIS, DP_AXIS))
    fn = comm.shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(EP_AXIS), P(EP_AXIS), P(EP_AXIS), P(EP_AXIS),
                  batch_spec),
        out_specs=(batch_spec, P()), check_vma=False)
    return fn(params["router_kernel"], params["moe_fc_kernel"],
              params["moe_fc_bias"], params["moe_out_kernel"],
              params["moe_out_bias"], x)


def aggregate_moe_stats(stacked: Dict[str, jnp.ndarray]
                        ) -> Dict[str, jnp.ndarray]:
    """Reduce per-MoE-layer stats (leading layer axis, from the block
    scan's ys or a stacked unrolled list) to the per-step record:
    counts/fractions/losses average over the MoE layers."""
    return jax.tree_util.tree_map(lambda a: jnp.mean(a, axis=0), stacked)
