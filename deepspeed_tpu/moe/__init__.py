"""Mixture-of-Experts expert parallelism (post-dates the reference).

Top-k gated expert FFN sharded over the ``expert`` mesh axis
(parallel/topology.EP_AXIS) with capacity-factor token bucketing and
``lax.all_to_all`` dispatch/combine — the DeepSpeed-MoE design
(Rajbhandari et al., 2022) expressed TPU-natively: one compiled shape
regardless of routing, collectives emitted by construction under
shard_map, expert weights born sharded via PartitionSpecs.
"""
from .layer import (MoEConfig, expert_capacity, moe_ffn, moe_layer_indices,
                    router_topk, MOE_PARAM_KEYS)
from .sharding import (expert_block_shardings, gpt2_moe_param_shardings,
                       is_expert_spec)

__all__ = [
    "MoEConfig", "expert_capacity", "moe_ffn", "moe_layer_indices",
    "router_topk", "MOE_PARAM_KEYS",
    "expert_block_shardings", "gpt2_moe_param_shardings", "is_expert_spec",
]
