"""Multi-replica serving: an admission router over N InferenceEngines.

The thin front end production traffic needs once one replica saturates:
each replica is a full ``InferenceEngine`` (its own KV pool, compiled
paths, ``ServingAggregator`` feed); the router owns the open-loop
arrival queue and decides, per request, WHICH replica admits it —

- **load**: occupancy (active slots / capacity) plus local queue depth,
  straight from each replica's aggregator-fed counters — the router
  never touches a device;
- **prefix affinity**: a replica already holding the prompt's cached
  prefix blocks scores higher, so shared prefixes land where their
  blocks live and the paged cache's hit rate survives scale-out
  (consistent-hashing-by-content, in effect).

Replicas then run the same iteration-level continuous batching the
single-engine scheduler runs: admit from the local queue, one
decode/verify step for every live slot, evict finished. On real
hardware each replica owns a disjoint mesh and the steps run in
parallel; the CPU-mesh emulation interleaves them on one mesh, so
per-iteration WALL times stack — tokens/s and TTFT measured here are a
lower bound on what disjoint replicas would do (the honest-methodology
note SERVE_BENCH.json repeats).

Reports keep replicas apart: per-replica aggregator snapshots plus the
pooled ``ServingAggregator.merged`` aggregate — never
percentiles-of-percentiles, never one interleaved stream.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .scheduler import Request
from ..monitor.serving import ServingAggregator


class ReplicaRouter:
    """Route + serve an open-loop stream over N engine replicas."""

    def __init__(self, engines: Sequence[Any], temperature: float = 0.0,
                 eos_token: Optional[int] = None,
                 affinity_weight: float = 1.0,
                 idle_sleep_s: float = 0.0005,
                 max_wall_s: Optional[float] = None):
        if not engines:
            raise ValueError("ReplicaRouter needs at least one engine")
        self.engines = list(engines)
        self.temperature = float(temperature)
        self.eos_token = eos_token
        self.affinity_weight = float(affinity_weight)
        self.idle_sleep_s = float(idle_sleep_s)
        self.max_wall_s = max_wall_s
        self.routed: List[int] = [0] * len(self.engines)
        self.affinity_hits = 0
        # Per-request decision records: every candidate's occupancy /
        # queue depth / affinity tokens / composite score at route time
        # (ring-capped). The chosen replica is argmax of the recorded
        # scores BY CONSTRUCTION — the test gate replays them.
        self.decisions: List[dict] = []
        self.decision_capacity = 4096
        self.trace = None               # RequestTrace, built in serve()

    # ------------------------------------------------------------------ #
    def _score_parts(self, eng, queue_len: int, req: Request) -> dict:
        """One candidate's routing signals (all host counters): higher
        composite score is better — prefix affinity minus load
        (occupancy + normalized queue depth)."""
        plen = max(len(req.prompt), 1)
        affinity_tokens = eng.prefix_match_tokens(req.prompt)
        occupancy = eng.active_slots / eng.max_slots
        queue_load = queue_len / eng.max_slots
        return {
            "occupancy": round(occupancy, 4),
            "queue_depth": queue_len,
            "affinity_tokens": affinity_tokens,
            "score": self.affinity_weight * (affinity_tokens / plen)
            - (occupancy + queue_load),
        }

    def _score(self, eng, queue_len: int, req: Request) -> float:
        return self._score_parts(eng, queue_len, req)["score"]

    def route(self, req: Request, queues: List[deque]) -> int:
        """Pick the admitting replica for one request (called once, at
        arrival — affinity is sticky by construction afterwards). The
        full candidate table is recorded so every choice is explainable
        after the fact."""
        cands = []
        for i, eng in enumerate(self.engines):
            parts = self._score_parts(eng, len(queues[i]), req)
            parts["replica"] = i
            label = getattr(eng, "replica", None)
            if label:
                parts["label"] = label
            cands.append(parts)
        scores = [c["score"] for c in cands]
        best = int(np.argmax(scores))
        plain = [-(c["occupancy"] + c["queue_depth"]
                   / self.engines[i].max_slots)
                 for i, c in enumerate(cands)]
        if best != int(np.argmax(plain)):
            self.affinity_hits += 1     # affinity overrode pure load
        self.routed[best] += 1
        decision = {"rid": req.rid, "chosen": best, "candidates": cands}
        if len(self.decisions) < self.decision_capacity:
            self.decisions.append(decision)
        if self.trace is not None:
            self.trace.route(req.rid, best, cands)
        return best

    # ------------------------------------------------------------------ #
    def serve(self, requests: Sequence[Request]) -> Dict[str, Any]:
        """Run the stream to completion across all replicas; returns the
        multi-replica report: pooled aggregate + per-replica snapshots +
        per-request records (each naming its replica)."""
        n_rep = len(self.engines)
        t0 = time.perf_counter()
        pending = deque(sorted(requests, key=lambda r: r.arrival_s))
        queues: List[deque] = [deque() for _ in range(n_rep)]
        active: List[Dict[int, Request]] = [{} for _ in range(n_rep)]
        replica_of: Dict[int, int] = {}
        spec = [bool(getattr(e, "spec_enabled", False))
                and self.temperature == 0.0 for e in self.engines]
        # One shared request trace for the fleet: the route decision and
        # the replica-side spans land in the same record; each finished
        # request drains into ITS replica's telemetry stream.
        if self.trace is None and any(e.telemetry.enabled
                                      for e in self.engines):
            from ..monitor.request_trace import RequestTrace
            self.trace = RequestTrace()
        trace = self.trace

        def label(i: int) -> str:
            return getattr(self.engines[i], "replica", "") or f"r{i}"

        def ledger_of(eng):
            return getattr(eng.serving, "ledger", None)

        def finished(req: Request, eng, slot: int) -> bool:
            if len(req.out_tokens) >= req.max_new_tokens:
                return True
            if self.eos_token is not None and req.out_tokens and \
                    req.out_tokens[-1] == self.eos_token:
                return True
            return eng.context_len(slot) >= eng.max_len

        def complete(req: Request, eng) -> None:
            eng.complete_request(req.rid, req.ttft_s or 0.0, req.tpot_s,
                                 prompt_tokens=len(req.prompt),
                                 new_tokens=len(req.out_tokens),
                                 queue_wait_s=req.queue_wait_s,
                                 service_ttft_s=req.service_ttft_s,
                                 admission_attempts=req.admission_attempts)
            if trace is not None:
                trace.complete(req.rid, t=req.t_last,
                               telemetry=eng.telemetry)

        while pending or any(queues) or any(active):
            now = time.perf_counter() - t0
            if self.max_wall_s is not None and now > self.max_wall_s:
                t_ab = time.perf_counter()
                for i, eng in enumerate(self.engines):
                    abort = getattr(eng, "abort_request", None)
                    for slot in list(active[i]):
                        req = active[i][slot]
                        if trace is not None:
                            trace.abort(req.rid, "max_wall", t=t_ab,
                                        telemetry=eng.telemetry)
                        if abort is not None:
                            abort(req.rid, "max_wall")
                        eng.release_slot(slot)
                        del active[i][slot]
                    for req in queues[i]:
                        if trace is not None:
                            trace.abort(req.rid, "starved", t=t_ab,
                                        telemetry=eng.telemetry)
                        if abort is not None:
                            abort(req.rid, "starved")
                break
            # 1. arrivals route to a replica queue immediately.
            while pending and pending[0].arrival_s <= now:
                req = pending.popleft()
                req.t_arrival = t0 + req.arrival_s
                if trace is not None:
                    trace.enqueue(req.rid, t=req.t_arrival)
                i = self.route(req, queues)
                replica_of[req.rid] = i
                queues[i].append(req)
            stepped = False
            for i, eng in enumerate(self.engines):
                # 2. per-replica admissions (FCFS within the replica) —
                # batched one-slot-per-group when the engine pages.
                batched = getattr(eng, "paged", False) and \
                    eng.prefill_chunk > 0
                while queues[i]:
                    batch = []
                    used: set = set()
                    while queues[i]:
                        req = queues[i][0]
                        slot = eng.select_slot(
                            req.prompt, req.max_new_tokens,
                            exclude_groups=used if batched else None)
                        if slot is None:
                            # Genuine head-of-queue rejection only when
                            # no batch exclusions could explain it.
                            if not used:
                                req.admission_attempts += 1
                                reason = getattr(
                                    eng, "last_admit_block",
                                    None) or "no_slot"
                                if trace is not None:
                                    trace.admit_reject(req.rid,
                                                       reason=reason)
                                note = getattr(
                                    eng, "note_admission_reject", None)
                                if note is not None:
                                    note(req.rid, reason,
                                         req.admission_attempts,
                                         len(queues[i]))
                            break
                        queues[i].popleft()
                        req.t_admit = time.perf_counter()
                        used.add(eng.group_of(slot))
                        batch.append((req, slot))
                        if not batched:
                            break
                    if not batch:
                        break
                    t_now = time.perf_counter()
                    if batched:
                        with eng.telemetry.span(
                                "prefill", slots=len(batch),
                                tokens=sum(len(r.prompt)
                                           for r, _ in batch)):
                            results = eng.prefill_many(
                                [(slot, req.prompt, req.max_new_tokens)
                                 for req, slot in batch],
                                self.temperature)
                        t_now = time.perf_counter()
                    else:
                        results = []
                        for req, slot in batch:
                            with eng.telemetry.span(
                                    "prefill", slot=slot,
                                    tokens=len(req.prompt)):
                                results.append(eng.prefill(
                                    req.prompt, slot, self.temperature,
                                    max_new_tokens=req.max_new_tokens))
                        t_now = time.perf_counter()
                    for (req, slot), (tok, _) in zip(batch, results):
                        req.slot = slot
                        req.t_first = req.t_last = t_now
                        req.out_tokens = [tok]
                        eng.activate_slot(slot, len(req.prompt), tok)
                        eng.serving.note_prefill(len(req.prompt))
                        if trace is not None:
                            trace.admit(req.rid, slot, t=req.t_admit,
                                        replica=label(i))
                            info_fn = getattr(eng, "last_admit_info",
                                              None)
                            info = info_fn(slot) if info_fn else {}
                            trace.prefill(
                                req.rid, t_now - (req.t_admit or t_now),
                                tokens=len(req.prompt),
                                chunks=info.get("chunks", 1),
                                cached_tokens=info.get(
                                    "cached_tokens", 0),
                                cow_fork=info.get("cow_fork", False))
                            trace.first_token(req.rid, t=t_now)
                        if finished(req, eng, slot):
                            complete(req, eng)
                            eng.release_slot(slot)
                        else:
                            active[i][slot] = req
                # 3. one iteration for this replica's live slots.
                if not active[i]:
                    continue
                stepped = True
                if spec[i]:
                    emitted, n_new = eng.spec_decode_once(
                        self.temperature)
                    t_now = time.perf_counter()
                    occ = len(active[i])
                    for slot in list(active[i]):
                        req = active[i][slot]
                        budget = req.max_new_tokens - len(req.out_tokens)
                        n = int(n_new[slot])
                        toks = [int(t) for t in emitted[slot, :n]]
                        if self.eos_token is not None and \
                                self.eos_token in toks:
                            toks = toks[:toks.index(self.eos_token) + 1]
                        req.out_tokens.extend(toks[:max(budget, 0)])
                        req.t_last = t_now
                        if trace is not None:
                            trace.tick(req.rid, occ, n, t=t_now,
                                       proposed=eng.spec_k,
                                       accepted=max(n - 1, 0))
                        if finished(req, eng, slot):
                            complete(req, eng)
                            eng.release_slot(slot)
                            del active[i][slot]
                else:
                    sampled, _ = eng.decode_once(self.temperature)
                    t_now = time.perf_counter()
                    occ = len(active[i])
                    for slot in list(active[i]):
                        req = active[i][slot]
                        req.out_tokens.append(int(sampled[slot]))
                        req.t_last = t_now
                        if trace is not None:
                            trace.tick(req.rid, occ, 1, t=t_now)
                        if finished(req, eng, slot):
                            complete(req, eng)
                            eng.release_slot(slot)
                            del active[i][slot]
            if not stepped and (pending or any(queues)):
                # Same loud-failure rule as the single-engine
                # scheduler: when every replica is idle, nothing is in
                # flight, and no future arrival can change the picture,
                # a queued head that still cannot admit NEVER will
                # (its worst-case block need exceeds its replica's
                # per-group pool) — raise instead of spinning.
                if not pending and not any(active) and \
                        not any(e.active.any() for e in self.engines):
                    req = next(q[0] for q in queues if q)
                    raise RuntimeError(
                        f"request {req.rid} can never be admitted on "
                        f"its routed replica: {len(req.prompt)} prompt "
                        f"+ {req.max_new_tokens} new tokens exceeds "
                        "the block pool's per-group capacity")
                for eng in self.engines:
                    eng.telemetry.heartbeat()
                t_sl = time.perf_counter()
                time.sleep(self.idle_sleep_s)
                dt = time.perf_counter() - t_sl
                for i, eng in enumerate(self.engines):
                    led = ledger_of(eng)
                    if led is not None:
                        led.note(
                            "admission_blocked" if queues[i] else "idle",
                            dt)

        wall = time.perf_counter() - t0
        per_replica = []
        for eng in self.engines:
            if eng.telemetry.enabled:
                eng.telemetry.drain({"serving": eng.serving.snapshot(
                    wall_s=wall)})
            per_replica.append(eng.serving.snapshot(wall_s=wall))
        merged = ServingAggregator.merged(
            [e.serving for e in self.engines])
        report = dict(merged.snapshot(wall_s=wall))
        report["recompiles"] = sum(e.telemetry.recompile_count
                                   for e in self.engines)
        report["unfinished"] = len(pending) + sum(map(len, queues)) + \
            sum(map(len, active))
        report["replicas"] = per_replica
        report["router"] = {
            "replicas": len(self.engines),
            "routed": list(self.routed),
            "affinity_overrides": self.affinity_hits,
            "affinity_weight": self.affinity_weight,
            "decisions_recorded": len(self.decisions),
        }
        if trace is not None:
            report["trace"] = trace.summary()
        report["requests"] = [
            {"rid": r.rid, "replica": replica_of.get(r.rid),
             "prompt_tokens": len(r.prompt),
             "new_tokens": len(r.out_tokens),
             "ttft_ms": round(r.ttft_s * 1e3, 3)
             if r.ttft_s is not None else None,
             "tpot_ms": round(r.tpot_s * 1e3, 3)
             if r.tpot_s is not None else None,
             "tokens": list(map(int, r.out_tokens))}
            for r in sorted(requests, key=lambda r: r.rid)]
        return report


__all__ = ["ReplicaRouter"]
