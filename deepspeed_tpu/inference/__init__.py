"""inference/ — the batched autoregressive serving tier.

The training side of this framework ends at a checkpoint; this package
is what stands between that checkpoint and heavy traffic: a paged,
prefix-shared KV cache born sharded over the training mesh — fixed-size
blocks behind a block-table indirection, copy-on-write prefix sharing,
reservation-gated admission (kv_cache.py) — jitted single-token decode,
chunked/whole-prompt prefill, and the speculative draft-then-verify
step over the GPT-2 family (decode.py), the self-drafting n-gram
proposer (spec.py), iteration-level continuous batching with an
open-loop request queue (scheduler.py), weight quantization via the
stochastic-rounding machinery (quantize.py), the InferenceEngine tying
it to the telemetry spine — decode-step JSONL records, prefill spans,
the recompile sentinel over every compiled path, per-request
TTFT/TPOT/occupancy goodput plus HBM-bytes-per-token, prefix-hit and
spec-acceptance accounting (engine.py) — and the prefix-affinity
multi-replica admission router (router.py). See
docs/tutorials/inference.md.
"""
from .engine import InferenceEngine
from .kv_cache import (BlockAllocator, KVCacheSpec, PagedKVCacheSpec,
                       PoolExhausted, cache_partition_spec, init_cache,
                       init_paged_cache, paged_partition_spec)
from .quantize import dequantize, quantize_params
from .router import ReplicaRouter
from .scheduler import (ContinuousBatchingScheduler, Request,
                        shared_prefix_requests, synthetic_requests)
from .spec import NGramDrafter

__all__ = [
    "InferenceEngine", "KVCacheSpec", "PagedKVCacheSpec",
    "BlockAllocator", "PoolExhausted", "cache_partition_spec",
    "paged_partition_spec", "init_cache", "init_paged_cache",
    "quantize_params", "dequantize", "Request", "synthetic_requests",
    "shared_prefix_requests", "ContinuousBatchingScheduler",
    "ReplicaRouter", "NGramDrafter",
]
