"""inference/ — the batched autoregressive serving tier.

The training side of this framework ends at a checkpoint; this package
is what stands between that checkpoint and heavy traffic: a slot-major
KV cache born sharded over the training mesh (kv_cache.py), jitted
single-token decode + chunked/whole-prompt prefill over the GPT-2 family
(decode.py), iteration-level continuous batching with an open-loop
request queue (scheduler.py), weight quantization via the stochastic-
rounding machinery (quantize.py), and the InferenceEngine tying it to
the telemetry spine — decode-step JSONL records, prefill spans, the
recompile sentinel over both compiled paths, and per-request
TTFT/TPOT/occupancy goodput (engine.py). See
docs/tutorials/inference.md.
"""
from .engine import InferenceEngine
from .kv_cache import KVCacheSpec, cache_partition_spec, init_cache
from .quantize import dequantize, quantize_params
from .scheduler import (ContinuousBatchingScheduler, Request,
                        synthetic_requests)

__all__ = [
    "InferenceEngine", "KVCacheSpec", "cache_partition_spec",
    "init_cache", "quantize_params", "dequantize",
    "Request", "synthetic_requests", "ContinuousBatchingScheduler",
]
