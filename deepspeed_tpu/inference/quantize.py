"""Weight quantization for serving — built on the stochastic-rounding
machinery the master-free training mode already ships.

Two modes beyond "none":

- ``bf16``: fp32 checkpoint weights stochastically rounded to bf16 via
  ``ops/stochastic_rounding.tree_stochastic_round_bf16`` — the exact
  add-noise-and-truncate bit trick the bf16 master-free optimizer uses,
  reused verbatim. Unbiased (E[q] == w), halves weight HBM.
- ``int8``: per-output-channel symmetric int8 for every >=2-D float
  leaf, with the SAME unbiased rounding argument extended to the
  integer grid: q = clip(floor(w/scale + u), -127, 127) with u~U[0,1)
  makes E[q*scale] == w exactly (modulo clipping at the channel max,
  where w/scale = ±127 lands on the grid). Scales are fp32, one per
  output channel (last axis), so the tied-embedding matmul and the
  embedding row gather dequantize consistently.

Quantized leaves are stored as ``{"q": int8, "scale": f32}`` dicts in
the param tree; ``dequantize`` collapses them back to compute-dtype
arrays INSIDE the compiled decode/prefill programs — device HBM holds
int8, the bf16 weights exist only as per-step transients. (Honest note:
without a fused dequant-matmul kernel XLA materializes those transients,
so the bandwidth win depends on fusion; the footprint win — 4x vs fp32
weights at rest — is unconditional. A Pallas int8 matmul epilogue is the
real-TPU follow-up.)
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..ops.stochastic_rounding import tree_stochastic_round_bf16

QUANT_KEY = "q"
SCALE_KEY = "scale"


def _is_quantized_leaf(x: Any) -> bool:
    return isinstance(x, dict) and QUANT_KEY in x and SCALE_KEY in x


def quantize_leaf_int8(w: jax.Array, key: jax.Array) -> Dict[str, jax.Array]:
    """Per-output-channel (last axis) symmetric int8 with unbiased
    stochastic rounding onto the integer grid."""
    w32 = w.astype(jnp.float32)
    reduce_axes = tuple(range(w32.ndim - 1))
    amax = jnp.max(jnp.abs(w32), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-12)
    u = jax.random.uniform(key, w32.shape, jnp.float32)
    q = jnp.clip(jnp.floor(w32 / scale + u), -127, 127).astype(jnp.int8)
    return {QUANT_KEY: q, SCALE_KEY: scale.astype(jnp.float32)}


def quantize_params(params: Any, mode: str,
                    key: Optional[jax.Array] = None) -> Any:
    """Quantize a param tree per the ``inference.quantize`` mode.

    int8 targets every float leaf with ndim >= 2 (the matmul kernels and
    embeddings — where the bytes are); vectors (LN scales, biases) stay
    in their checkpoint dtype, they are noise in the footprint and load-
    bearing in accuracy.
    """
    if mode == "none":
        return params
    if key is None:
        key = jax.random.PRNGKey(0)
    if mode == "bf16":
        return tree_stochastic_round_bf16(params, key)
    if mode != "int8":
        raise ValueError(f"unknown quantize mode {mode!r}")
    leaves, treedef = jax.tree_util.tree_flatten(params)
    out = []
    for i, leaf in enumerate(leaves):
        if hasattr(leaf, "dtype") and hasattr(leaf, "ndim") and \
                leaf.ndim >= 2 and jnp.issubdtype(leaf.dtype, jnp.floating):
            out.append(quantize_leaf_int8(leaf, jax.random.fold_in(key, i)))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def dequantize(params: Any, dtype: Any = jnp.bfloat16) -> Any:
    """Collapse quantized {"q","scale"} leaves back to ``dtype`` arrays;
    plain leaves pass through untouched. Called INSIDE the jitted
    serving programs (int8 at rest, compute-dtype transients)."""
    def deq(x):
        if _is_quantized_leaf(x):
            return (x[QUANT_KEY].astype(jnp.float32) *
                    x[SCALE_KEY]).astype(dtype)
        return x
    return jax.tree_util.tree_map(deq, params, is_leaf=_is_quantized_leaf)


def resolve_kv_dtype(mode: str, model_dtype: Any) -> Any:
    """The ``inference.kv_cache_dtype`` knob: storage dtype of the KV
    block pool. ``"model"`` keeps blocks at the compute dtype (bitwise
    parity with the batch path — what the fp32 parity tests run);
    ``"bf16"`` halves fp32 KV HBM at rest. Attention scores are fp32
    either way, so bf16 blocks cost one rounding per written K/V row —
    the same at-rest-vs-transient argument as int8 weights above."""
    if mode == "model":
        return model_dtype
    if mode == "bf16":
        return jnp.bfloat16
    raise ValueError(f"unknown kv_cache_dtype mode {mode!r}")


def quantized_bytes(params: Any) -> int:
    """At-rest bytes of a (possibly quantized) param tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        if hasattr(leaf, "size") and hasattr(leaf, "dtype"):
            total += int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
    return total


__all__ = ["quantize_params", "quantize_leaf_int8", "dequantize",
           "resolve_kv_dtype", "quantized_bytes", "QUANT_KEY",
           "SCALE_KEY"]
