"""Speculative decoding, host half: the self-drafting n-gram cache.

Draft-then-verify (Leviathan et al. 2023) needs a cheap proposer; this
one is prompt-lookup decoding — no drafter model, no extra weights. Per
slot it keeps the request's full token history (prompt + generated) and
proposes the continuation of the most recent PRIOR occurrence of the
current n-gram suffix, backing off n → n-1 → ... → 1 and falling back
to repeat-last-token when nothing matches (cheap, and exactly right in
the repetition regimes greedy decode falls into — which is also where
speculation pays most). The device half
(``decode.gpt2_verify_paged`` + ``decode.spec_accept``) writes the k
drafts through the block table in ONE batched verify step and accepts
the longest agreeing prefix, so greedy output stays bit-identical to
non-speculative decode whatever this proposer suggests — a bad draft
costs compute, never correctness.

All host work is list slicing over small histories: zero device syncs,
zero compiled-shape variance (k is static).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


class NGramDrafter:
    """Per-slot n-gram proposer over the request token histories."""

    def __init__(self, k: int, ngram: int = 3, max_history: int = 4096):
        if k < 1:
            raise ValueError(f"spec_k must be >= 1 to draft, got {k}")
        self.k = int(k)
        self.ngram = max(1, int(ngram))
        self.max_history = int(max_history)
        self._history: Dict[int, List[int]] = {}
        # Cumulative proposer stats (how often the n-gram cache had a
        # real match vs the repeat-last fallback) — the acceptance rate
        # itself is measured at verify time by the engine.
        self.lookups = 0
        self.matches = 0

    # ---- history lifecycle (engine-driven) ---- #
    def begin(self, slot: int, prompt: Sequence[int]) -> None:
        self._history[slot] = [int(t) for t in prompt]

    def observe(self, slot: int, tokens: Sequence[int]) -> None:
        h = self._history.setdefault(slot, [])
        h.extend(int(t) for t in tokens)
        if len(h) > self.max_history:
            del h[:len(h) - self.max_history]

    def reset(self, slot: int) -> None:
        self._history.pop(slot, None)

    # ---- proposal ---- #
    def propose(self, slot: int) -> np.ndarray:
        """k draft tokens continuing the slot's history. Always returns
        a full-k array (the verify step is one fixed shape); the
        repeat-last fallback fills whatever the n-gram cache can't."""
        h = self._history.get(slot) or [0]
        self.lookups += 1
        draft: List[int] = []
        for n in range(min(self.ngram, len(h) - 1), 0, -1):
            suffix = h[-n:]
            # Most recent prior occurrence: scan right-to-left over the
            # history, excluding the suffix occurrence itself.
            for i in range(len(h) - n - 1, -1, -1):
                if h[i:i + n] == suffix:
                    cont = h[i + n:i + n + self.k]
                    if cont:
                        draft = cont
                        break
            if draft:
                self.matches += 1
                break
        while len(draft) < self.k:
            draft.append(draft[-1] if draft else h[-1])
        return np.asarray(draft[:self.k], np.int32)

    def match_rate(self) -> float:
        return self.matches / self.lookups if self.lookups else 0.0


__all__ = ["NGramDrafter"]
