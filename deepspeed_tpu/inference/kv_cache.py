"""KV cache — the static-shape memory plane of the serving tier.

Two layouts share this module:

**Paged (the production layout — the PagedAttention/vLLM design).** The
cache is a pool of fixed-size blocks,

    k, v : [layers, groups, blocks_per_group, heads, block_size, head_dim]

and a request owns a list of BLOCK IDS (its block table row), not a
``max_seq_len`` reservation: short and long requests share HBM, blocks
allocate lazily as a context grows, and common prompt prefixes are
shared copy-on-write across requests — full-block granularity, keyed by
a position-dependent chain hash, reference-counted by the host-side
``BlockAllocator``. The ``groups`` axis is the mesh data axis: a slot's
blocks always live in the slot's own dp shard (the allocator enforces
it), so every decode-step gather through the block table is a
GROUP-BATCHED one-hot contraction — GSPMD partitions it with zero
communication and no per-device transient ever exceeds the pool shard
(the ``materialization`` lint gate proves it: no full-pool gather).

**Slot-major (the PR-7 layout, ``block_size: 0``).** One
``[slots, max_len]`` row per slot — kept as the parity baseline the
paged tests diff against and as the fallback for models whose
``max_seq_len`` the page size does not divide.

In both layouts nothing about admission, progress, or eviction changes
a compiled signature — that is the property the recompile sentinel
gates in the serving tests. Sharding is born on the training mesh's
axes: slots/groups over the data axis, ``heads`` over the model axis
(Megatron TP head sharding, matching
``models/transformer.block_param_shardings``).

Appends and block gathers are one-hot selects/contractions rather than
scatters/gathers: GSPMD partitions them trivially along groups and
heads, while a scatter or gather with per-slot indices risks the exact
full-pool gather the lint gate forbids. The cost is a pool-shard
read+write per layer per step — the honest CPU-mesh tradeoff; a Pallas
paged-attention kernel with real dynamic slices is the optimized path
on TPU hardware (see docs/tutorials/inference.md).
"""
from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.topology import DP_AXIS, MP_AXIS


@dataclasses.dataclass(frozen=True)
class KVCacheSpec:
    """Static geometry of the cache: fixed at engine construction."""
    num_layers: int
    num_slots: int
    num_heads: int
    max_len: int
    head_dim: int
    dtype: Any = jnp.bfloat16

    @property
    def shape(self) -> Tuple[int, int, int, int, int]:
        return (self.num_layers, self.num_slots, self.num_heads,
                self.max_len, self.head_dim)

    def nbytes(self) -> int:
        """Total K+V bytes (global, unsharded)."""
        n = 1
        for d in self.shape:
            n *= d
        return 2 * n * jnp.dtype(self.dtype).itemsize

    def validate(self, mesh: Optional[Mesh] = None) -> None:
        for name in ("num_layers", "num_slots", "num_heads", "max_len",
                     "head_dim"):
            if int(getattr(self, name)) <= 0:
                raise ValueError(f"KVCacheSpec.{name} must be positive, "
                                 f"got {getattr(self, name)}")
        if mesh is not None:
            dp = int(mesh.shape.get(DP_AXIS, 1))
            mp = int(mesh.shape.get(MP_AXIS, 1))
            if self.num_slots % dp != 0:
                raise ValueError(
                    f"inference.max_slots={self.num_slots} must be "
                    f"divisible by the mesh data axis ({dp}) — slots are "
                    "the data-parallel dimension of serving")
            if self.num_heads % mp != 0:
                raise ValueError(
                    f"model heads ({self.num_heads}) not divisible by the "
                    f"mesh model axis ({mp}) for TP head sharding")


def cache_partition_spec() -> P:
    """[layers, slots, heads, max_len, head_dim]: slots over dp, heads
    over mp (the TP head sharding the training blocks already use)."""
    return P(None, DP_AXIS, MP_AXIS, None, None)


def cache_shardings(mesh: Mesh) -> Dict[str, NamedSharding]:
    spec = cache_partition_spec()
    return {"k": NamedSharding(mesh, spec), "v": NamedSharding(mesh, spec)}


def init_cache(spec: KVCacheSpec,
               mesh: Optional[Mesh] = None) -> Dict[str, jax.Array]:
    """Zero-initialized cache, born sharded when a mesh is given (the
    zeros are created directly at the declared sharding — no host-side
    full-size array ever exists)."""
    spec.validate(mesh)

    def make():
        return {"k": jnp.zeros(spec.shape, spec.dtype),
                "v": jnp.zeros(spec.shape, spec.dtype)}

    if mesh is None:
        return make()
    return jax.jit(make, out_shardings=cache_shardings(mesh))()


# --------------------------------------------------------------------- #
# Per-layer update primitives (used inside the jitted decode/prefill
# programs; kc/vc here are ONE layer's [slots, heads, max_len, head_dim])
# --------------------------------------------------------------------- #
def write_token(kc: jax.Array, k_new: jax.Array,
                lengths: jax.Array) -> jax.Array:
    """Append one token's K (or V) per slot at that slot's own length.

    kc: [S, nH, T, D]; k_new: [S, nH, D]; lengths: [S] int32 — slot s
    writes at position lengths[s]. One-hot select over T (see module
    docstring for why not scatter); positions beyond a slot's length are
    dead by masking, so an out-of-range length (a full slot) writes
    nowhere.
    """
    T = kc.shape[2]
    onehot = lax.broadcasted_iota(jnp.int32, (1, T), 1) == \
        lengths[:, None]                                   # [S, T]
    return jnp.where(onehot[:, None, :, None],
                     k_new[:, :, None, :].astype(kc.dtype), kc)


def write_chunk(kc: jax.Array, k_new: jax.Array, slot: jax.Array,
                start: jax.Array) -> jax.Array:
    """Insert a prefilled chunk into one slot: pure dynamic_update_slice.

    kc: [S, nH, T, D]; k_new: [C, nH, D] (chunk-of-tokens layout);
    slot/start: traced scalars. The update block is [1, nH, C, D] at
    (slot, 0, start, 0).
    """
    upd = k_new.transpose(1, 0, 2)[None].astype(kc.dtype)  # [1, nH, C, D]
    return lax.dynamic_update_slice(
        kc, upd, (slot.astype(jnp.int32), jnp.int32(0),
                  start.astype(jnp.int32), jnp.int32(0)))


def slot_rows(kc: jax.Array, slot: jax.Array) -> jax.Array:
    """One slot's [nH, T, D] view (dynamic_slice; the prefill chunk
    attends against its own slot's context only)."""
    sizes = (1,) + tuple(kc.shape[1:])
    return lax.dynamic_slice(
        kc, (slot.astype(jnp.int32), jnp.int32(0), jnp.int32(0),
             jnp.int32(0)), sizes)[0]


def length_mask(lengths: jax.Array, max_len: int) -> jax.Array:
    """[S, T] bool: position t of slot s is live iff t <= lengths[s]
    (inclusive — the decode step masks AFTER writing the current token
    at position lengths[s])."""
    pos = lax.broadcasted_iota(jnp.int32, (1, max_len), 1)
    return pos <= lengths[:, None]


# ===================================================================== #
# Paged layout: block pool + block-table indirection
# ===================================================================== #
DEAD_BLOCK = -1     # block-table entry for "unallocated" — writes through
                    # it land nowhere and gathers through it read zeros


@dataclasses.dataclass(frozen=True)
class PagedKVCacheSpec:
    """Static geometry of the block pool: fixed at engine construction.

    ``num_blocks`` is the GLOBAL pool size; it is laid out as
    ``[num_groups, blocks_per_group]`` with the group axis sharded over
    dp, and the allocator only hands a slot blocks from the slot's own
    group — that locality is what keeps every block-table gather a
    zero-communication batched contraction under GSPMD.
    """
    num_layers: int
    num_slots: int
    num_blocks: int
    block_size: int
    max_len: int
    num_heads: int
    head_dim: int
    num_groups: int = 1
    dtype: Any = jnp.bfloat16

    @property
    def blocks_per_group(self) -> int:
        return self.num_blocks // self.num_groups

    @property
    def slots_per_group(self) -> int:
        return self.num_slots // self.num_groups

    @property
    def max_blocks_per_slot(self) -> int:
        """Block-table width J: logical blocks a full slot spans."""
        return self.max_len // self.block_size

    @property
    def shape(self) -> Tuple[int, int, int, int, int, int]:
        return (self.num_layers, self.num_groups, self.blocks_per_group,
                self.num_heads, self.block_size, self.head_dim)

    def nbytes(self) -> int:
        """Total K+V pool bytes (global, unsharded)."""
        n = 1
        for d in self.shape:
            n *= d
        return 2 * n * jnp.dtype(self.dtype).itemsize

    def block_nbytes(self) -> int:
        """K+V bytes one block holds across all layers — the unit of
        the hbm_bytes_per_token accounting."""
        return (2 * self.num_layers * self.num_heads * self.block_size *
                self.head_dim * jnp.dtype(self.dtype).itemsize)

    def validate(self, mesh: Optional[Mesh] = None) -> None:
        for name in ("num_layers", "num_slots", "num_blocks", "block_size",
                     "max_len", "num_heads", "head_dim", "num_groups"):
            if int(getattr(self, name)) <= 0:
                raise ValueError(f"PagedKVCacheSpec.{name} must be "
                                 f"positive, got {getattr(self, name)}")
        if self.max_len % self.block_size:
            raise ValueError(
                f"inference.block_size={self.block_size} must divide the "
                f"cache capacity ({self.max_len}) — a slot's last logical "
                "block would otherwise overhang the position table")
        if self.num_blocks % self.num_groups:
            raise ValueError(
                f"inference.num_blocks={self.num_blocks} must be divisible "
                f"by the mesh data axis ({self.num_groups}) — blocks are "
                "born sharded over dp alongside the slots they serve")
        if self.num_slots % self.num_groups:
            raise ValueError(
                f"inference.max_slots={self.num_slots} must be divisible "
                f"by the mesh data axis ({self.num_groups})")
        if mesh is not None:
            mp = int(mesh.shape.get(MP_AXIS, 1))
            if self.num_heads % mp != 0:
                raise ValueError(
                    f"model heads ({self.num_heads}) not divisible by the "
                    f"mesh model axis ({mp}) for TP head sharding")


def paged_partition_spec() -> P:
    """[layers, groups, blocks, heads, block_size, head_dim]: groups
    over dp, heads over mp."""
    return P(None, DP_AXIS, None, MP_AXIS, None, None)


def paged_shardings(mesh: Mesh) -> Dict[str, NamedSharding]:
    spec = paged_partition_spec()
    return {"k": NamedSharding(mesh, spec), "v": NamedSharding(mesh, spec)}


def init_paged_cache(spec: PagedKVCacheSpec,
                     mesh: Optional[Mesh] = None) -> Dict[str, jax.Array]:
    """Zero-initialized pool, born sharded when a mesh is given."""
    spec.validate(mesh)

    def make():
        return {"k": jnp.zeros(spec.shape, spec.dtype),
                "v": jnp.zeros(spec.shape, spec.dtype)}

    if mesh is None:
        return make()
    return jax.jit(make, out_shardings=paged_shardings(mesh))()


# --------------------------------------------------------------------- #
# In-graph paged primitives. All of them are group-batched: every array
# carries the [G, ...] group axis so GSPMD partitions over dp with zero
# communication. ``pool`` here is ONE layer's [G, B, nH, bs, D].
# --------------------------------------------------------------------- #
def positions_to_blocks(bt: jax.Array, pos: jax.Array, block_size: int
                        ) -> Tuple[jax.Array, jax.Array]:
    """Resolve token positions through a block table.

    bt: [..., J] physical block ids (DEAD_BLOCK where unallocated);
    pos: [...] int32 token positions, same leading shape. Returns
    (block [...], offset [...]) with block == DEAD_BLOCK for positions
    past the table (pos >= J * block_size) or through a dead entry — a
    write through those lands nowhere by construction.
    """
    J = bt.shape[-1]
    j = pos // block_size
    off = pos % block_size
    jm = j[..., None] == lax.broadcasted_iota(
        jnp.int32, j.shape + (J,), j.ndim)                   # [..., J]
    blk = jnp.where(jm.any(-1), (jm * bt).sum(-1), DEAD_BLOCK)
    return blk.astype(jnp.int32), off.astype(jnp.int32)


def block_select(bt: jax.Array, blocks_per_group: int) -> jax.Array:
    """One-hot block-table selector: bt [G, Q, J] → [G, Q, J, B] f32.
    Dead entries (DEAD_BLOCK) select nothing."""
    iota = lax.broadcasted_iota(jnp.int32, bt.shape + (blocks_per_group,),
                                bt.ndim)
    return (bt[..., None] == iota).astype(jnp.float32)


def paged_write_rows(pool: jax.Array, new: jax.Array, blk: jax.Array,
                     off: jax.Array) -> jax.Array:
    """Write R rows per group into the pool at (block, offset).

    pool: [G, B, nH, bs, D]; new: [G, R, nH, D]; blk/off: [G, R].
    One-hot select over (B, bs) — the paged analogue of ``write_token``'s
    length-axis select (see module docstring for why not scatter). Rows
    with blk == DEAD_BLOCK write nowhere. Distinct live rows always
    target distinct (block, offset) cells — slots never share a
    writable block (the allocator's copy-on-write invariant) — so the
    one-hot sum never accumulates two sources into one cell.
    """
    G, B = pool.shape[0], pool.shape[1]
    bs = pool.shape[3]
    ohb = blk[..., None] == lax.broadcasted_iota(
        jnp.int32, blk.shape + (B,), blk.ndim)               # [G, R, B]
    oht = off[..., None] == lax.broadcasted_iota(
        jnp.int32, off.shape + (bs,), off.ndim)              # [G, R, bs]
    oh = ohb[..., :, None] & oht[..., None, :]               # [G, R, B, bs]
    vals = jnp.einsum("grbt,grnd->gbntd", oh.astype(pool.dtype),
                      new.astype(pool.dtype))
    mask = oh.any(1)                                         # [G, B, bs]
    return jnp.where(mask[:, :, None, :, None], vals, pool)


def paged_attend(q: jax.Array, pool_k: jax.Array, pool_v: jax.Array,
                 sel: jax.Array, pos_mask: jax.Array, scale: float,
                 neg_inf) -> jax.Array:
    """Attention through the block table, group-batched.

    q: [G, Q, K, nH, D] (Q query streams per group, K tokens each);
    pool_k/pool_v: [G, B, nH, bs, D]; sel: [G, Q, J, B] one-hot block
    selector; pos_mask: [G, Q, K, J*bs] bool (True = attendable).
    Returns [G, Q, K, nH, D].

    Scores contract q against the WHOLE group-local pool first
    ([G,Q,K,nH,B,bs] fp32 — no head_dim factor, so it is the small
    transient), then the one-hot selector picks each stream's J blocks;
    the value combine routes the weights back through the selector. No
    gathered K/V copy ever materializes and nothing crosses a group
    boundary.
    """
    J = sel.shape[2]
    bs = pool_k.shape[3]
    s_all = jnp.einsum("gqknd,gbntd->gqknbt", q, pool_k
                       ).astype(jnp.float32) * scale
    scores = jnp.einsum("gqjb,gqknbt->gqknjt", sel, s_all)
    G, Q, K, nH = scores.shape[:4]
    scores = scores.reshape(G, Q, K, nH, J * bs)
    scores = jnp.where(pos_mask[:, :, :, None, :], scores, neg_inf)
    w = jax.nn.softmax(scores, axis=-1).reshape(G, Q, K, nH, J, bs)
    wb = jnp.einsum("gqjb,gqknjt->gqknbt", sel, w)
    return jnp.einsum("gqknbt,gbntd->gqknd", wb.astype(pool_v.dtype),
                      pool_v)


def copy_block_onehots(spec: PagedKVCacheSpec, group: int, src: int,
                       dst: int) -> Tuple[np.ndarray, np.ndarray]:
    """Host-built [G, B] one-hots selecting the copy-on-write source and
    destination blocks (local ids within ``group``)."""
    G, B = spec.num_groups, spec.blocks_per_group
    s = np.zeros((G, B), np.float32)
    d = np.zeros((G, B), bool)
    s[group, src] = 1.0
    d[group, dst] = True
    return s, d


def paged_copy_block(pool: jax.Array, src_onehot: jax.Array,
                     dst_onehot: jax.Array) -> jax.Array:
    """Copy one block's rows to another block of the SAME group, for
    every layer at once: the device half of copy-on-write. pool:
    [L, G, B, nH, bs, D]; src_onehot [G, B] f32; dst_onehot [G, B]
    bool. Groups with all-zero one-hots pass through untouched."""
    src = jnp.einsum("gb,lgbntd->lgntd", src_onehot.astype(pool.dtype),
                     pool)
    return jnp.where(dst_onehot[None, :, :, None, None, None],
                     src[:, :, None], pool)


# --------------------------------------------------------------------- #
# Host-side block allocator: free lists, refcounts, prefix cache, CoW
# --------------------------------------------------------------------- #
def chain_hash(prev: int, tokens: np.ndarray) -> int:
    """Position-dependent hash of one full block's tokens given the
    hash of the preceding chain — two different prefixes never collide
    on position, only on (astronomically unlikely) hash collision."""
    return hash((prev, tokens.astype(np.int64).tobytes()))


class PoolExhausted(RuntimeError):
    """No free or reclaimable block in the group — admission must be
    rejected (the scheduler keeps the request queued; a live slot is
    never touched)."""


class BlockAllocator:
    """Host-authoritative state of the block pool.

    Per group (dp shard): a free list, per-block refcounts, and the
    prefix cache — a chain-hash index over full PROMPT blocks plus an
    LRU of retained blocks whose refcount dropped to zero (they keep
    their bytes until pool pressure reclaims them, so a popular system
    prompt stays resident across request lifetimes).

    Admission is RESERVATION-based: ``can_admit`` checks that the
    group can cover the request's worst-case block need (prompt +
    max_new + spec lookahead, minus the prefix blocks it free-rides
    on), and ``admit_prompt`` books that reservation so later lazy
    allocations (decode appends) can never strand a live slot
    mid-flight. Conservative next to vLLM's optimistic
    preempt-and-recompute, and it never corrupts a running request —
    the tradeoff docs/tutorials/inference.md spells out.
    """

    def __init__(self, spec: PagedKVCacheSpec):
        self.spec = spec
        G, B = spec.num_groups, spec.blocks_per_group
        self._free: List[List[int]] = [list(range(B)) for _ in range(G)]
        self._ref = np.zeros((G, B), np.int64)
        # chain-hash -> local block id, per group; and its inverse for
        # eviction bookkeeping.
        self._hash_index: List[Dict[int, int]] = [{} for _ in range(G)]
        self._block_hash: List[Dict[int, int]] = [{} for _ in range(G)]
        # Retained zero-ref blocks, LRU order (oldest first).
        self._lru: List["OrderedDict[int, None]"] = \
            [OrderedDict() for _ in range(G)]
        self._reserved: List[int] = [0] * G      # outstanding, per group
        self._slot_reserved: Dict[int, int] = {}  # slot -> remaining
        self._slot_group: Dict[int, int] = {}
        # Cumulative telemetry the aggregator snapshots.
        self.cow_copies = 0
        self.reclaimed = 0

    # ---- accounting ---- #
    def blocks_in_use(self) -> int:
        """Live (ref > 0) blocks across all groups — shared blocks count
        once; LRU-retained blocks are reclaimable, not in use."""
        return int((self._ref > 0).sum())

    def bytes_in_use(self) -> int:
        return self.blocks_in_use() * self.spec.block_nbytes()

    def available(self, group: int) -> int:
        """Blocks this group can still hand out: free + reclaimable
        minus outstanding reservations."""
        return (len(self._free[group]) + len(self._lru[group])
                - self._reserved[group])

    def need_blocks(self, prompt_len: int, max_new: int,
                    spec_k: int = 0) -> int:
        """Worst-case logical blocks a request spans (capped at the
        table width)."""
        tokens = prompt_len + max_new + spec_k
        need = -(-tokens // self.spec.block_size)
        return min(need, self.spec.max_blocks_per_slot)

    # ---- prefix cache ---- #
    def match_prefix(self, group: int, prompt: np.ndarray
                     ) -> Tuple[List[int], List[int]]:
        """Longest cached full-block chain matching ``prompt`` in this
        group → (block ids, chain hashes). Walks the chain hash; stops
        at the first miss."""
        bs = self.spec.block_size
        idx = self._hash_index[group]
        blocks: List[int] = []
        hashes: List[int] = []
        h = 0
        for j in range(len(prompt) // bs):
            h = chain_hash(h, prompt[j * bs:(j + 1) * bs])
            b = idx.get(h)
            if b is None:
                break
            blocks.append(b)
            hashes.append(h)
        return blocks, hashes

    def can_admit(self, group: int, prompt: np.ndarray, max_new: int,
                  spec_k: int = 0, share: bool = True) -> bool:
        need = self.need_blocks(len(prompt), max_new, spec_k)
        matched = self.match_prefix(group, prompt)[0] if share else []
        # Only LIVE shared blocks are a free ride; reviving an
        # LRU-retained block consumes reclaimable capacity like any
        # fresh allocation does.
        free_ride = sum(1 for b in matched if self._ref[group, b] > 0)
        return self.available(group) >= need - free_ride

    # ---- allocation primitives ---- #
    def _pop_block(self, group: int) -> int:
        if self._free[group]:
            return self._free[group].pop()
        if self._lru[group]:
            b, _ = self._lru[group].popitem(last=False)   # oldest
            h = self._block_hash[group].pop(b, None)
            if h is not None:
                self._hash_index[group].pop(h, None)
            self.reclaimed += 1
            return b
        raise PoolExhausted(
            f"group {group}: no free or reclaimable block "
            f"({self.spec.blocks_per_group} blocks, "
            f"{self._reserved[group]} reserved)")

    def _draw(self, group: int, slot: int) -> int:
        """Allocate one block for ``slot``, drawing down its
        reservation when one is booked."""
        b = self._pop_block(group)
        self._ref[group, b] = 1
        if self._slot_reserved.get(slot, 0) > 0:
            self._slot_reserved[slot] -= 1
            self._reserved[group] -= 1
        return b

    def _incref(self, group: int, b: int) -> None:
        if self._ref[group, b] == 0:
            self._lru[group].pop(b, None)       # revive from retention
        self._ref[group, b] += 1

    def _decref(self, group: int, b: int) -> None:
        self._ref[group, b] -= 1
        assert self._ref[group, b] >= 0, "block refcount underflow"
        if self._ref[group, b] == 0:
            if b in self._block_hash[group]:
                # Prefix block: retain (LRU) so the next request with
                # this prompt still hits; reclaimed under pressure.
                self._lru[group][b] = None
            else:
                self._free[group].append(b)

    # ---- request lifecycle ---- #
    def admit_prompt(self, slot: int, group: int, prompt: np.ndarray,
                     max_new: int, spec_k: int = 0,
                     share: bool = True) -> "AdmitPlan":
        """Allocate/share the prompt's blocks and book the request's
        worst-case reservation. Returns the plan the engine prefills
        from. Raises PoolExhausted when ``can_admit`` would be False.
        ``share=False`` (the whole-prompt prefill path, which rewrites
        every position) opts out of the prefix cache entirely — no
        matching, no registration."""
        if not self.can_admit(group, prompt, max_new, spec_k,
                              share=share):
            raise PoolExhausted(
                f"group {group}: {self.available(group)} block(s) "
                f"available < worst-case need for a "
                f"{len(prompt)}+{max_new}-token request")
        bs = self.spec.block_size
        plen = len(prompt)
        matched_blocks, hashes = self.match_prefix(group, prompt) \
            if share else ([], [])
        # Always re-prefill at least the prompt's last token: its
        # logits seed the first sampled token, and the block holding it
        # must be privately writable for the decode appends that follow.
        matched = min(len(matched_blocks) * bs, plen - 1)
        n_keep = matched // bs                   # fully shared blocks
        cow_src: Optional[int] = None
        for b in matched_blocks[:n_keep]:
            self._incref(group, b)
        table: List[int] = list(matched_blocks[:n_keep])
        if n_keep < len(matched_blocks):
            # The chain covered the whole prompt; the final shared block
            # must be written (re-prefilled last token + decode appends)
            # → fork it copy-on-write into a private block.
            cow_src = matched_blocks[n_keep]
            table.append(self._draw(group, slot))
            self.cow_copies += 1
        # Private blocks for the unshared prompt tail.
        while len(table) * bs < plen:
            table.append(self._draw(group, slot))
        # Book the rest of the worst-case need.
        need = self.need_blocks(plen, max_new, spec_k)
        remaining = max(0, need - len(table))
        self._slot_reserved[slot] = remaining
        self._slot_group[slot] = group
        self._reserved[group] += remaining
        # Register the prompt's full PRIVATE blocks in the prefix cache
        # (shared ones are already registered; the CoW fork is NOT — its
        # content diverges the moment the slot decodes into it... except
        # it holds exactly the cached chain's tokens until then; keep it
        # out of the index so the cached original stays authoritative).
        h = hashes[n_keep - 1] if n_keep else 0
        for j in range(n_keep, plen // bs) if share else ():
            if cow_src is not None and j == n_keep:
                h = chain_hash(h, prompt[j * bs:(j + 1) * bs])
                continue
            h = chain_hash(h, prompt[j * bs:(j + 1) * bs])
            b = table[j]
            if h not in self._hash_index[group]:
                self._hash_index[group][h] = b
                self._block_hash[group][b] = h
        return AdmitPlan(slot=slot, group=group, table=table,
                         matched=matched, cow_src=cow_src,
                         cow_dst=table[n_keep] if cow_src is not None
                         else None)

    def alloc_block(self, slot: int) -> int:
        """Lazily allocate one more block for a live slot (a decode or
        verify append crossing a block boundary), drawing down the
        slot's reservation. Raises PoolExhausted only for slots
        admitted WITHOUT a reservation (direct engine use) on a drained
        pool — scheduler admissions are always covered."""
        if slot not in self._slot_group:
            raise RuntimeError(
                f"slot {slot} has no admitted prompt — prefill() admits "
                "through the allocator before any decode can append")
        return self._draw(self._slot_group[slot], slot)

    def release(self, slot: int, table: Sequence[int]) -> None:
        """Evict: drop every table reference and the unused
        reservation. Prefix blocks whose refcount hits zero are
        RETAINED (LRU) for future hits; private ones return to the
        free list."""
        group = self._slot_group.pop(slot, None)
        if group is None:
            return
        rem = self._slot_reserved.pop(slot, 0)
        self._reserved[group] -= rem
        for b in table:
            if b != DEAD_BLOCK:
                self._decref(group, int(b))


@dataclasses.dataclass
class AdmitPlan:
    """What ``BlockAllocator.admit_prompt`` decided: the slot's initial
    block-table row, how many prompt tokens ride cached blocks, and the
    copy-on-write fork to perform (device copy src → dst) if any."""
    slot: int
    group: int
    table: List[int]
    matched: int
    cow_src: Optional[int] = None
    cow_dst: Optional[int] = None


__all__ = ["KVCacheSpec", "cache_partition_spec", "cache_shardings",
           "init_cache", "write_token", "write_chunk", "slot_rows",
           "length_mask",
           "DEAD_BLOCK", "PagedKVCacheSpec", "paged_partition_spec",
           "paged_shardings", "init_paged_cache", "positions_to_blocks",
           "block_select", "paged_write_rows", "paged_attend",
           "copy_block_onehots", "paged_copy_block", "chain_hash",
           "PoolExhausted", "BlockAllocator", "AdmitPlan"]
