"""Slot-major KV cache — the static-shape memory plane of the serving
tier.

Design (the memory-layout insight behind iteration-level batching): the
cache is ONE pair of arrays per model,

    k, v : [layers, slots, heads, max_len, head_dim]

whose shape never changes for the lifetime of the engine. A request does
not own a tensor — it owns a SLOT index and a length counter. Insert is
a ``dynamic_update_slice`` of the prefilled K/V block into the slot's
rows; evict is a counter clear (the stale rows are dead by masking and
get overwritten as the next occupant's context grows). Nothing about
admission, progress, or eviction changes any compiled signature — that
is the property the recompile sentinel gates in the serving tests.

Sharding: born on the training mesh's axes — ``slots`` over the data
axis (slot-parallel decode, the serving analogue of the data-parallel
batch) and ``heads`` over the model axis (Megatron TP head sharding,
matching ``models/transformer.block_param_shardings``). Every decode-
step op keeps the slot dim leading and elementwise/contraction-local, so
GSPMD partitions the whole step without gathering the cache.

The per-token append across slots with HETEROGENEOUS lengths (continuous
batching's defining access pattern) is a one-hot select over the length
axis rather than a scatter: GSPMD partitions a select trivially along
slots and heads, while a scatter with per-slot indices risks the exact
full-cache gather the lint gate forbids. The cost is a full cache
read+write per layer per step — the honest CPU-mesh tradeoff; a Pallas
in-place scatter kernel is the optimized path on real TPU hardware (see
docs/tutorials/inference.md).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.topology import DP_AXIS, MP_AXIS


@dataclasses.dataclass(frozen=True)
class KVCacheSpec:
    """Static geometry of the cache: fixed at engine construction."""
    num_layers: int
    num_slots: int
    num_heads: int
    max_len: int
    head_dim: int
    dtype: Any = jnp.bfloat16

    @property
    def shape(self) -> Tuple[int, int, int, int, int]:
        return (self.num_layers, self.num_slots, self.num_heads,
                self.max_len, self.head_dim)

    def nbytes(self) -> int:
        """Total K+V bytes (global, unsharded)."""
        n = 1
        for d in self.shape:
            n *= d
        return 2 * n * jnp.dtype(self.dtype).itemsize

    def validate(self, mesh: Optional[Mesh] = None) -> None:
        for name in ("num_layers", "num_slots", "num_heads", "max_len",
                     "head_dim"):
            if int(getattr(self, name)) <= 0:
                raise ValueError(f"KVCacheSpec.{name} must be positive, "
                                 f"got {getattr(self, name)}")
        if mesh is not None:
            dp = int(mesh.shape.get(DP_AXIS, 1))
            mp = int(mesh.shape.get(MP_AXIS, 1))
            if self.num_slots % dp != 0:
                raise ValueError(
                    f"inference.max_slots={self.num_slots} must be "
                    f"divisible by the mesh data axis ({dp}) — slots are "
                    "the data-parallel dimension of serving")
            if self.num_heads % mp != 0:
                raise ValueError(
                    f"model heads ({self.num_heads}) not divisible by the "
                    f"mesh model axis ({mp}) for TP head sharding")


def cache_partition_spec() -> P:
    """[layers, slots, heads, max_len, head_dim]: slots over dp, heads
    over mp (the TP head sharding the training blocks already use)."""
    return P(None, DP_AXIS, MP_AXIS, None, None)


def cache_shardings(mesh: Mesh) -> Dict[str, NamedSharding]:
    spec = cache_partition_spec()
    return {"k": NamedSharding(mesh, spec), "v": NamedSharding(mesh, spec)}


def init_cache(spec: KVCacheSpec,
               mesh: Optional[Mesh] = None) -> Dict[str, jax.Array]:
    """Zero-initialized cache, born sharded when a mesh is given (the
    zeros are created directly at the declared sharding — no host-side
    full-size array ever exists)."""
    spec.validate(mesh)

    def make():
        return {"k": jnp.zeros(spec.shape, spec.dtype),
                "v": jnp.zeros(spec.shape, spec.dtype)}

    if mesh is None:
        return make()
    return jax.jit(make, out_shardings=cache_shardings(mesh))()


# --------------------------------------------------------------------- #
# Per-layer update primitives (used inside the jitted decode/prefill
# programs; kc/vc here are ONE layer's [slots, heads, max_len, head_dim])
# --------------------------------------------------------------------- #
def write_token(kc: jax.Array, k_new: jax.Array,
                lengths: jax.Array) -> jax.Array:
    """Append one token's K (or V) per slot at that slot's own length.

    kc: [S, nH, T, D]; k_new: [S, nH, D]; lengths: [S] int32 — slot s
    writes at position lengths[s]. One-hot select over T (see module
    docstring for why not scatter); positions beyond a slot's length are
    dead by masking, so an out-of-range length (a full slot) writes
    nowhere.
    """
    T = kc.shape[2]
    onehot = lax.broadcasted_iota(jnp.int32, (1, T), 1) == \
        lengths[:, None]                                   # [S, T]
    return jnp.where(onehot[:, None, :, None],
                     k_new[:, :, None, :].astype(kc.dtype), kc)


def write_chunk(kc: jax.Array, k_new: jax.Array, slot: jax.Array,
                start: jax.Array) -> jax.Array:
    """Insert a prefilled chunk into one slot: pure dynamic_update_slice.

    kc: [S, nH, T, D]; k_new: [C, nH, D] (chunk-of-tokens layout);
    slot/start: traced scalars. The update block is [1, nH, C, D] at
    (slot, 0, start, 0).
    """
    upd = k_new.transpose(1, 0, 2)[None].astype(kc.dtype)  # [1, nH, C, D]
    return lax.dynamic_update_slice(
        kc, upd, (slot.astype(jnp.int32), jnp.int32(0),
                  start.astype(jnp.int32), jnp.int32(0)))


def slot_rows(kc: jax.Array, slot: jax.Array) -> jax.Array:
    """One slot's [nH, T, D] view (dynamic_slice; the prefill chunk
    attends against its own slot's context only)."""
    sizes = (1,) + tuple(kc.shape[1:])
    return lax.dynamic_slice(
        kc, (slot.astype(jnp.int32), jnp.int32(0), jnp.int32(0),
             jnp.int32(0)), sizes)[0]


def length_mask(lengths: jax.Array, max_len: int) -> jax.Array:
    """[S, T] bool: position t of slot s is live iff t <= lengths[s]
    (inclusive — the decode step masks AFTER writing the current token
    at position lengths[s])."""
    pos = lax.broadcasted_iota(jnp.int32, (1, max_len), 1)
    return pos <= lengths[:, None]


__all__ = ["KVCacheSpec", "cache_partition_spec", "cache_shardings",
           "init_cache", "write_token", "write_chunk", "slot_rows",
           "length_mask"]
