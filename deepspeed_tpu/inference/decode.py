"""Incremental GPT-2 forward paths: single-token decode and chunked /
whole-prompt prefill against the slot-major KV cache.

Three compiled programs make up the serving data plane, each with a
FIXED abstract signature (the recompile sentinel wraps all of them):

- ``gpt2_decode``: one token per slot, for every slot at once. Attends
  against the cache only, computes LAST-position logits only (via the
  same tied-unembedding contraction ``models.gpt2.gpt2_logits_at``
  exposes for the batch path), and samples in-graph with a threaded
  PRNG. Slots are independent along the leading axis, so GSPMD
  partitions the step over the data axis without touching another
  slot's cache.
- ``gpt2_prefill_chunk``: one prompt chunk for ONE slot. Writes the
  chunk's K/V into the slot via ``dynamic_update_slice`` and attends
  against the slot's full cache row (prefix + the chunk itself) under a
  global-position causal mask — so any chunk length divides any prompt
  without shape polymorphism. Prefill and decode are separate programs
  on purpose (prefill/decode disaggregation): a long admission never
  changes the decode signature.
- ``gpt2_prefill_full``: the whole (padded) prompt in one shot through
  the standard block math with a pluggable ``attention_fn`` — this is
  where ring attention plugs in for long-context prefill when the mesh
  has a sequence axis (``ops/ring_attention.ring_attention_fn``).

All block math mirrors ``models/transformer.transformer_block`` for the
deterministic pre-LN case (fp32 softmax, compute-dtype matmuls, same
mask constant), so decode logits match ``gpt2_apply``'s final position
to float tolerance — asserted per step in tests/test_inference.py.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from . import kv_cache
from ..models.gpt2 import GPT2Config
from ..models.transformer import (dense, gelu_dense_fn, layer_norm,
                                  layer_norm_fn)

NEG_INF = jnp.float32(-1e9)    # same masking constant as dense_attention


def _check_cfg(cfg: GPT2Config) -> None:
    if not cfg.pre_layer_norm or not cfg.causal:
        raise NotImplementedError(
            "the incremental decode path implements the GPT-2 block "
            "(pre-LN, causal); post-LN/bidirectional models have no "
            "autoregressive serving story")


def _ffn(p: Dict[str, jax.Array], x: jax.Array, cfg: GPT2Config
         ) -> jax.Array:
    # layer_norm_fn / gelu_dense_fn resolve to the fused Pallas kernels
    # when cfg enables them — the SAME static dispatch the training
    # block uses, so flipping the knob never adds a compiled-signature
    # variant to the serving paths (sentinel-asserted in
    # tests/test_fused_ln.py).
    h = layer_norm_fn(cfg)(x, p["ln2_scale"], p["ln2_bias"])
    h = gelu_dense_fn(cfg)(h, p["fc_kernel"], p["fc_bias"])
    h = dense(h, p["fc_out_kernel"], p["fc_out_bias"])
    return x + h


def _qkv(p: Dict[str, jax.Array], x: jax.Array, cfg: GPT2Config
         ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """ln1 + QKV projection; x [..., H] → q,k,v [..., nH, dH]."""
    h = layer_norm_fn(cfg)(x, p["ln1_scale"], p["ln1_bias"])
    qkv = dense(h, p["qkv_kernel"], p["qkv_bias"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    split = x.shape[:-1] + (cfg.num_heads, cfg.head_dim)
    return q.reshape(split), k.reshape(split), v.reshape(split)


# --------------------------------------------------------------------- #
# Decode: one token per slot, all slots at once
# --------------------------------------------------------------------- #
def _decode_block(p, x, kc, vc, lengths, cfg: GPT2Config):
    """x [S, H]; kc/vc [S, nH, T, D]; lengths [S]. Returns (x', kc', vc').

    The current token sits at position lengths[s]: its K/V are written
    first, then attention runs over positions 0..lengths[s] inclusive —
    exactly the causal row the full forward computes at that position.
    """
    S, H = x.shape
    q, k, v = _qkv(p, x, cfg)                       # [S, nH, D] each
    kc = kv_cache.write_token(kc, k, lengths)
    vc = kv_cache.write_token(vc, v, lengths)
    s = jnp.einsum("snd,sntd->snt", q, kc).astype(jnp.float32)
    s = s / math.sqrt(cfg.head_dim)
    mask = kv_cache.length_mask(lengths, kc.shape[2])   # [S, T]
    s = jnp.where(mask[:, None, :], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    attn = jnp.einsum("snt,sntd->snd", w.astype(vc.dtype), vc)
    attn = attn.reshape(S, H).astype(x.dtype)
    x = x + dense(attn, p["proj_kernel"], p["proj_bias"])
    return _ffn(p, x, cfg), kc, vc


def gpt2_decode(params: Dict[str, Any], kc: jax.Array, vc: jax.Array,
                tokens: jax.Array, lengths: jax.Array, cfg: GPT2Config
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step for every slot: tokens/lengths [S] → (logits
    [S, V] fp32, kc', vc'). The caller advances lengths for the slots it
    considers active; position = lengths[s] by construction."""
    _check_cfg(cfg)
    x = params["wte"].astype(cfg.dtype)[tokens] + \
        params["wpe"].astype(cfg.dtype)[lengths]

    def body(h, layer):
        p, kcl, vcl = layer
        h, kcl, vcl = _decode_block(p, h, kcl, vcl, lengths, cfg)
        return h, (kcl, vcl)

    x, (kc, vc) = lax.scan(body, x, (params["blocks"], kc, vc))
    x = layer_norm_fn(cfg)(x, params["ln_f_scale"], params["ln_f_bias"])
    logits = (x @ params["wte"].astype(cfg.dtype).T).astype(jnp.float32)
    return logits, kc, vc


# --------------------------------------------------------------------- #
# Chunked prefill: one chunk of one slot's prompt
# --------------------------------------------------------------------- #
def _prefill_block(p, x, kc, vc, slot, start, cfg: GPT2Config):
    """x [C, H]; writes the chunk's K/V at (slot, start) then attends
    the chunk against the slot's whole cache row under the global causal
    mask (col <= start + row)."""
    C, H = x.shape
    q, k, v = _qkv(p, x, cfg)                       # [C, nH, D]
    kc = kv_cache.write_chunk(kc, k, slot, start)
    vc = kv_cache.write_chunk(vc, v, slot, start)
    krow = kv_cache.slot_rows(kc, slot)             # [nH, T, D]
    vrow = kv_cache.slot_rows(vc, slot)
    s = jnp.einsum("cnd,ntd->nct", q, krow).astype(jnp.float32)
    s = s / math.sqrt(cfg.head_dim)
    T = krow.shape[1]
    rows = start + lax.broadcasted_iota(jnp.int32, (C, 1), 0)
    cols = lax.broadcasted_iota(jnp.int32, (1, T), 1)
    s = jnp.where((cols <= rows)[None], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    attn = jnp.einsum("nct,ntd->cnd", w.astype(vrow.dtype), vrow)
    attn = attn.reshape(C, H).astype(x.dtype)
    x = x + dense(attn, p["proj_kernel"], p["proj_bias"])
    return _ffn(p, x, cfg), kc, vc


def gpt2_prefill_chunk(params: Dict[str, Any], kc: jax.Array,
                       vc: jax.Array, tokens: jax.Array, slot: jax.Array,
                       start: jax.Array, last_idx: jax.Array,
                       cfg: GPT2Config
                       ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Run one prompt chunk (tokens [C]) for one slot. Returns (logits
    [V] fp32 at chunk position ``last_idx``, kc', vc').

    Only ONE position projects through the unembedding (the
    gpt2_logits_at memory contract: never a [C, vocab] tensor) — the
    scheduler uses it on the final chunk to sample the first token;
    earlier chunks compute it too (uniform program) and discard it.
    Padding rows beyond the prompt inside the final chunk produce
    garbage that nothing reads: causal masking keeps them out of every
    real row, and the next token's decode write overwrites their cache
    rows before any attend reaches them.
    """
    _check_cfg(cfg)
    C = tokens.shape[0]
    pos = start + jnp.arange(C, dtype=jnp.int32)
    x = params["wte"].astype(cfg.dtype)[tokens] + \
        params["wpe"].astype(cfg.dtype)[pos]

    def body(h, layer):
        p, kcl, vcl = layer
        h, kcl, vcl = _prefill_block(p, h, kcl, vcl, slot, start, cfg)
        return h, (kcl, vcl)

    x, (kc, vc) = lax.scan(body, x, (params["blocks"], kc, vc))
    x = layer_norm_fn(cfg)(x, params["ln_f_scale"], params["ln_f_bias"])
    h_last = lax.dynamic_slice(x, (last_idx.astype(jnp.int32),
                                   jnp.int32(0)), (1, x.shape[1]))[0]
    logits = (h_last @ params["wte"].astype(cfg.dtype).T
              ).astype(jnp.float32)
    return logits, kc, vc


# --------------------------------------------------------------------- #
# Whole-prompt prefill (prefill_chunk: 0) — the long-context path
# --------------------------------------------------------------------- #
def gpt2_prefill_full(params: Dict[str, Any], kc: jax.Array,
                      vc: jax.Array, tokens: jax.Array, slot: jax.Array,
                      last_idx: jax.Array, cfg: GPT2Config,
                      attention_fn: Optional[Callable] = None
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Single-shot prefill of one slot: tokens [T] padded to the cache's
    max_len. The self-attention over the prompt runs through the
    pluggable ``attention_fn`` — ring attention when the mesh has a
    sequence axis (exact long-context prefill at 1/sp memory per chip),
    the dense/flash default otherwise. Per-layer K/V come out of the
    same scan as the hidden states and splice into the cache with one
    dynamic_update_slice over all layers."""
    _check_cfg(cfg)
    if attention_fn is None:
        from ..ops.flash_attention import auto_attention
        attention_fn = auto_attention
    T = tokens.shape[0]
    x = (params["wte"].astype(cfg.dtype)[tokens] +
         params["wpe"].astype(cfg.dtype)[:T])[None]        # [1, T, H]

    def body(h, p):
        q, k, v = _qkv(p, h, cfg)                  # [1, T, nH, D]
        attn = attention_fn(q, k, v, mask=None, causal=True,
                            deterministic=True)
        attn = attn.reshape(h.shape).astype(h.dtype)
        h = h + dense(attn, p["proj_kernel"], p["proj_bias"])
        return _ffn(p, h, cfg), (k[0], v[0])       # ys: [T, nH, D]

    x, (ks, vs) = lax.scan(body, x, params["blocks"])
    # ks/vs [L, T, nH, D] → cache block [L, 1, nH, T, D] at slot.
    zero = jnp.int32(0)
    at = (zero, slot.astype(jnp.int32), zero, zero, zero)
    kc = lax.dynamic_update_slice(
        kc, ks.transpose(0, 2, 1, 3)[:, None].astype(kc.dtype), at)
    vc = lax.dynamic_update_slice(
        vc, vs.transpose(0, 2, 1, 3)[:, None].astype(vc.dtype), at)
    x = layer_norm_fn(cfg)(x[0], params["ln_f_scale"],
                           params["ln_f_bias"])
    h_last = lax.dynamic_slice(x, (last_idx.astype(jnp.int32),
                                   jnp.int32(0)), (1, x.shape[1]))[0]
    logits = (h_last @ params["wte"].astype(cfg.dtype).T
              ).astype(jnp.float32)
    return logits, kc, vc


# --------------------------------------------------------------------- #
# Sampling (in-graph; PRNG threaded by the engine per iteration)
# --------------------------------------------------------------------- #
def sample_tokens(logits: jax.Array, key: jax.Array,
                  temperature: jax.Array) -> jax.Array:
    """Greedy (temperature == 0) or temperature sampling; logits
    [..., V] fp32. Temperature is a TRACED scalar so changing it never
    recompiles; both branches are cheap relative to the step, so a
    select beats a cond."""
    greedy = jnp.argmax(logits, axis=-1)
    t = jnp.maximum(temperature.astype(jnp.float32), 1e-6)
    sampled = jax.random.categorical(key, logits / t, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)


__all__ = ["gpt2_decode", "gpt2_prefill_chunk", "gpt2_prefill_full",
           "sample_tokens"]
