"""Incremental GPT-2 forward paths: single-token decode, chunked /
whole-prompt prefill, and the speculative verify step — against the
paged block pool (the production layout) or the slot-major cache (the
PR-7 parity baseline).

The PAGED programs (``gpt2_decode_paged`` / ``gpt2_verify_paged`` /
``gpt2_prefill_chunk_paged`` / ``gpt2_prefill_full_paged``) route every
cache access through the block-table one-hot primitives in
``inference/kv_cache.py``: group-batched over the mesh data axis, one
compiled shape whatever the tables hold, no full-pool gather. The
verify step generalizes decode to K tokens per slot and, with
``spec_accept``, implements draft-then-verify speculative decoding
whose greedy output is bit-identical to single-token decode.

The SLOT-MAJOR programs below make up the PR-7 data plane, each with a
FIXED abstract signature (the recompile sentinel wraps all of them):

- ``gpt2_decode``: one token per slot, for every slot at once. Attends
  against the cache only, computes LAST-position logits only (via the
  same tied-unembedding contraction ``models.gpt2.gpt2_logits_at``
  exposes for the batch path), and samples in-graph with a threaded
  PRNG. Slots are independent along the leading axis, so GSPMD
  partitions the step over the data axis without touching another
  slot's cache.
- ``gpt2_prefill_chunk``: one prompt chunk for ONE slot. Writes the
  chunk's K/V into the slot via ``dynamic_update_slice`` and attends
  against the slot's full cache row (prefix + the chunk itself) under a
  global-position causal mask — so any chunk length divides any prompt
  without shape polymorphism. Prefill and decode are separate programs
  on purpose (prefill/decode disaggregation): a long admission never
  changes the decode signature.
- ``gpt2_prefill_full``: the whole (padded) prompt in one shot through
  the standard block math with a pluggable ``attention_fn`` — this is
  where ring attention plugs in for long-context prefill when the mesh
  has a sequence axis (``ops/ring_attention.ring_attention_fn``).

All block math mirrors ``models/transformer.transformer_block`` for the
deterministic pre-LN case (fp32 softmax, compute-dtype matmuls, same
mask constant), so decode logits match ``gpt2_apply``'s final position
to float tolerance — asserted per step in tests/test_inference.py.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from . import kv_cache
from ..models.gpt2 import GPT2Config
from ..ops import paged_attention as paged_attn_ops
from ..models.transformer import (dense, gelu_dense_fn, layer_norm,
                                  layer_norm_fn)

NEG_INF = jnp.float32(-1e9)    # same masking constant as dense_attention


def _check_cfg(cfg: GPT2Config) -> None:
    if not cfg.pre_layer_norm or not cfg.causal:
        raise NotImplementedError(
            "the incremental decode path implements the GPT-2 block "
            "(pre-LN, causal); post-LN/bidirectional models have no "
            "autoregressive serving story")


def _ffn(p: Dict[str, jax.Array], x: jax.Array, cfg: GPT2Config
         ) -> jax.Array:
    # layer_norm_fn / gelu_dense_fn resolve to the fused Pallas kernels
    # when cfg enables them — the SAME static dispatch the training
    # block uses, so flipping the knob never adds a compiled-signature
    # variant to the serving paths (sentinel-asserted in
    # tests/test_fused_ln.py).
    h = layer_norm_fn(cfg)(x, p["ln2_scale"], p["ln2_bias"])
    h = gelu_dense_fn(cfg)(h, p["fc_kernel"], p["fc_bias"])
    h = dense(h, p["fc_out_kernel"], p["fc_out_bias"])
    return x + h


def _qkv(p: Dict[str, jax.Array], x: jax.Array, cfg: GPT2Config
         ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """ln1 + QKV projection; x [..., H] → q,k,v [..., nH, dH]."""
    h = layer_norm_fn(cfg)(x, p["ln1_scale"], p["ln1_bias"])
    qkv = dense(h, p["qkv_kernel"], p["qkv_bias"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    split = x.shape[:-1] + (cfg.num_heads, cfg.head_dim)
    return q.reshape(split), k.reshape(split), v.reshape(split)


# --------------------------------------------------------------------- #
# Decode: one token per slot, all slots at once
# --------------------------------------------------------------------- #
def _decode_block(p, x, kc, vc, lengths, cfg: GPT2Config):
    """x [S, H]; kc/vc [S, nH, T, D]; lengths [S]. Returns (x', kc', vc').

    The current token sits at position lengths[s]: its K/V are written
    first, then attention runs over positions 0..lengths[s] inclusive —
    exactly the causal row the full forward computes at that position.
    """
    S, H = x.shape
    q, k, v = _qkv(p, x, cfg)                       # [S, nH, D] each
    kc = kv_cache.write_token(kc, k, lengths)
    vc = kv_cache.write_token(vc, v, lengths)
    s = jnp.einsum("snd,sntd->snt", q, kc).astype(jnp.float32)
    s = s / math.sqrt(cfg.head_dim)
    mask = kv_cache.length_mask(lengths, kc.shape[2])   # [S, T]
    s = jnp.where(mask[:, None, :], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    attn = jnp.einsum("snt,sntd->snd", w.astype(vc.dtype), vc)
    attn = attn.reshape(S, H).astype(x.dtype)
    x = x + dense(attn, p["proj_kernel"], p["proj_bias"])
    return _ffn(p, x, cfg), kc, vc


def gpt2_decode(params: Dict[str, Any], kc: jax.Array, vc: jax.Array,
                tokens: jax.Array, lengths: jax.Array, cfg: GPT2Config
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step for every slot: tokens/lengths [S] → (logits
    [S, V] fp32, kc', vc'). The caller advances lengths for the slots it
    considers active; position = lengths[s] by construction."""
    _check_cfg(cfg)
    x = params["wte"].astype(cfg.dtype)[tokens] + \
        params["wpe"].astype(cfg.dtype)[lengths]

    def body(h, layer):
        p, kcl, vcl = layer
        h, kcl, vcl = _decode_block(p, h, kcl, vcl, lengths, cfg)
        return h, (kcl, vcl)

    x, (kc, vc) = lax.scan(body, x, (params["blocks"], kc, vc))
    x = layer_norm_fn(cfg)(x, params["ln_f_scale"], params["ln_f_bias"])
    logits = (x @ params["wte"].astype(cfg.dtype).T).astype(jnp.float32)
    return logits, kc, vc


# --------------------------------------------------------------------- #
# Chunked prefill: one chunk of one slot's prompt
# --------------------------------------------------------------------- #
def _prefill_block(p, x, kc, vc, slot, start, cfg: GPT2Config):
    """x [C, H]; writes the chunk's K/V at (slot, start) then attends
    the chunk against the slot's whole cache row under the global causal
    mask (col <= start + row)."""
    C, H = x.shape
    q, k, v = _qkv(p, x, cfg)                       # [C, nH, D]
    kc = kv_cache.write_chunk(kc, k, slot, start)
    vc = kv_cache.write_chunk(vc, v, slot, start)
    krow = kv_cache.slot_rows(kc, slot)             # [nH, T, D]
    vrow = kv_cache.slot_rows(vc, slot)
    s = jnp.einsum("cnd,ntd->nct", q, krow).astype(jnp.float32)
    s = s / math.sqrt(cfg.head_dim)
    T = krow.shape[1]
    rows = start + lax.broadcasted_iota(jnp.int32, (C, 1), 0)
    cols = lax.broadcasted_iota(jnp.int32, (1, T), 1)
    s = jnp.where((cols <= rows)[None], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    attn = jnp.einsum("nct,ntd->cnd", w.astype(vrow.dtype), vrow)
    attn = attn.reshape(C, H).astype(x.dtype)
    x = x + dense(attn, p["proj_kernel"], p["proj_bias"])
    return _ffn(p, x, cfg), kc, vc


def gpt2_prefill_chunk(params: Dict[str, Any], kc: jax.Array,
                       vc: jax.Array, tokens: jax.Array, slot: jax.Array,
                       start: jax.Array, last_idx: jax.Array,
                       cfg: GPT2Config
                       ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Run one prompt chunk (tokens [C]) for one slot. Returns (logits
    [V] fp32 at chunk position ``last_idx``, kc', vc').

    Only ONE position projects through the unembedding (the
    gpt2_logits_at memory contract: never a [C, vocab] tensor) — the
    scheduler uses it on the final chunk to sample the first token;
    earlier chunks compute it too (uniform program) and discard it.
    Padding rows beyond the prompt inside the final chunk produce
    garbage that nothing reads: causal masking keeps them out of every
    real row, and the next token's decode write overwrites their cache
    rows before any attend reaches them.
    """
    _check_cfg(cfg)
    C = tokens.shape[0]
    pos = start + jnp.arange(C, dtype=jnp.int32)
    x = params["wte"].astype(cfg.dtype)[tokens] + \
        params["wpe"].astype(cfg.dtype)[pos]

    def body(h, layer):
        p, kcl, vcl = layer
        h, kcl, vcl = _prefill_block(p, h, kcl, vcl, slot, start, cfg)
        return h, (kcl, vcl)

    x, (kc, vc) = lax.scan(body, x, (params["blocks"], kc, vc))
    x = layer_norm_fn(cfg)(x, params["ln_f_scale"], params["ln_f_bias"])
    h_last = lax.dynamic_slice(x, (last_idx.astype(jnp.int32),
                                   jnp.int32(0)), (1, x.shape[1]))[0]
    logits = (h_last @ params["wte"].astype(cfg.dtype).T
              ).astype(jnp.float32)
    return logits, kc, vc


# --------------------------------------------------------------------- #
# Whole-prompt prefill (prefill_chunk: 0) — the long-context path
# --------------------------------------------------------------------- #
def gpt2_prefill_full(params: Dict[str, Any], kc: jax.Array,
                      vc: jax.Array, tokens: jax.Array, slot: jax.Array,
                      last_idx: jax.Array, cfg: GPT2Config,
                      attention_fn: Optional[Callable] = None
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Single-shot prefill of one slot: tokens [T] padded to the cache's
    max_len. The self-attention over the prompt runs through the
    pluggable ``attention_fn`` — ring attention when the mesh has a
    sequence axis (exact long-context prefill at 1/sp memory per chip),
    the dense/flash default otherwise. Per-layer K/V come out of the
    same scan as the hidden states and splice into the cache with one
    dynamic_update_slice over all layers."""
    _check_cfg(cfg)
    if attention_fn is None:
        from ..ops.flash_attention import auto_attention
        attention_fn = auto_attention
    T = tokens.shape[0]
    x = (params["wte"].astype(cfg.dtype)[tokens] +
         params["wpe"].astype(cfg.dtype)[:T])[None]        # [1, T, H]

    def body(h, p):
        q, k, v = _qkv(p, h, cfg)                  # [1, T, nH, D]
        attn = attention_fn(q, k, v, mask=None, causal=True,
                            deterministic=True)
        attn = attn.reshape(h.shape).astype(h.dtype)
        h = h + dense(attn, p["proj_kernel"], p["proj_bias"])
        return _ffn(p, h, cfg), (k[0], v[0])       # ys: [T, nH, D]

    x, (ks, vs) = lax.scan(body, x, params["blocks"])
    # ks/vs [L, T, nH, D] → cache block [L, 1, nH, T, D] at slot.
    zero = jnp.int32(0)
    at = (zero, slot.astype(jnp.int32), zero, zero, zero)
    kc = lax.dynamic_update_slice(
        kc, ks.transpose(0, 2, 1, 3)[:, None].astype(kc.dtype), at)
    vc = lax.dynamic_update_slice(
        vc, vs.transpose(0, 2, 1, 3)[:, None].astype(vc.dtype), at)
    x = layer_norm_fn(cfg)(x[0], params["ln_f_scale"],
                           params["ln_f_bias"])
    h_last = lax.dynamic_slice(x, (last_idx.astype(jnp.int32),
                                   jnp.int32(0)), (1, x.shape[1]))[0]
    logits = (h_last @ params["wte"].astype(cfg.dtype).T
              ).astype(jnp.float32)
    return logits, kc, vc


# ===================================================================== #
# Paged paths: decode / chunked prefill / speculative verify through the
# block-table indirection (inference/kv_cache.py paged primitives).
# Everything is group-batched over the mesh data axis; ONE compiled
# shape each, whatever the block tables hold.
# ===================================================================== #
def _group_shape(arr: jax.Array, num_groups: int) -> jax.Array:
    """[S, ...] → [G, S/G, ...]: split the slot axis into (group,
    slot-in-group) — a local reshape under the slots-over-dp sharding."""
    return arr.reshape((num_groups, arr.shape[0] // num_groups)
                       + arr.shape[1:])


def _paged_attn_block(p, x, kc, vc, bt_g, cfg: GPT2Config,
                      num_groups: int, write_pos: jax.Array,
                      pos_g: jax.Array, sel, pos_mask,
                      paged_kernel: bool = False, mesh=None):
    """Shared attention step of the paged decode/verify/prefill paths.

    x: [S, K, H] — K tokens for each of S per-slot query streams, with
    S = G * Sg (Sg = 1 stream per group for prefill); kc/vc: one
    layer's [G, B, nH, bs, D]; bt_g: [G, Sg, J]; write_pos: [G, Sg*K]
    token positions to write; pos_g: [G, Sg, K] inclusive last
    attendable position per query row. ``sel`` [G, Sg, J, B] /
    ``pos_mask`` [G, Sg, K, J*bs] drive the one-hot baseline and are
    None when ``paged_kernel`` routes the attend through the Pallas
    kernel (the writes stay one-hot either way — they are O(written
    rows), not O(pool)). Returns (x', kc', vc').
    """
    S, K, H = x.shape
    G = num_groups
    Sg = S // G
    R = Sg * K
    nH, D = cfg.num_heads, cfg.head_dim
    q, k, v = _qkv(p, x, cfg)                        # [S, K, nH, D]
    bs = kc.shape[3]
    bt_rows = jnp.broadcast_to(bt_g[:, :, None, :],
                               (G, Sg, K, bt_g.shape[-1])
                               ).reshape(G, R, -1)
    blk, off = kv_cache.positions_to_blocks(bt_rows, write_pos, bs)
    kc = kv_cache.paged_write_rows(kc, k.reshape(G, R, nH, D), blk, off)
    vc = kv_cache.paged_write_rows(vc, v.reshape(G, R, nH, D), blk, off)
    if paged_kernel:
        attn = paged_attn_ops.paged_attention(
            q.reshape(G, Sg, K, nH, D), kc, vc, bt_g, pos_g,
            scale=1.0 / math.sqrt(D), mesh=mesh)
    else:
        attn = kv_cache.paged_attend(q.reshape(G, Sg, K, nH, D), kc, vc,
                                     sel, pos_mask, 1.0 / math.sqrt(D),
                                     NEG_INF)
    attn = attn.reshape(S, K, H).astype(x.dtype)
    x = x + dense(attn, p["proj_kernel"], p["proj_bias"])
    return _ffn(p, x, cfg), kc, vc


def gpt2_verify_paged(params: Dict[str, Any], kc: jax.Array,
                      vc: jax.Array, tokens: jax.Array,
                      lengths: jax.Array, block_tables: jax.Array,
                      cfg: GPT2Config, num_groups: int,
                      paged_kernel: bool = False, mesh=None
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The speculative verify step — and, at K=1, plain paged decode.

    tokens: [S, K] — column 0 is each slot's pending last token,
    columns 1.. are the drafted continuation; token i sits at position
    lengths[s] + i. Writes all K tokens' K/V through the block table,
    attends each under its own causal row, and returns fp32 logits
    [S, K, V] (the K-bounded spec-decode analogue of last-position-only
    logits — never a [max_len, vocab] tensor). kc/vc: the full pool
    [L, G, B, nH, bs, D]. ``paged_kernel`` swaps the one-hot pool
    contraction for the Pallas table-sliced kernel (ops/
    paged_attention.py) — same logits, O(context) work.
    """
    _check_cfg(cfg)
    S, K = tokens.shape
    G = num_groups
    Sg = S // G
    J = block_tables.shape[-1]
    bs = kc.shape[4]
    pos = lengths[:, None] + jnp.arange(K, dtype=jnp.int32)[None]  # [S,K]
    x = params["wte"].astype(cfg.dtype)[tokens] + \
        params["wpe"].astype(cfg.dtype)[pos]
    bt_g = _group_shape(block_tables, G)             # [G, Sg, J]
    pos_g = _group_shape(pos, G)                     # [G, Sg, K]
    sel = pos_mask = None
    if not paged_kernel:
        sel = kv_cache.block_select(bt_g, kc.shape[2])
        grid = lax.broadcasted_iota(jnp.int32, (1, 1, 1, J * bs), 3)
        pos_mask = grid <= pos_g[..., None]          # [G, Sg, K, J*bs]
    write_pos = pos_g.reshape(G, Sg * K)

    def body(h, layer):
        p, kcl, vcl = layer
        h, kcl, vcl = _paged_attn_block(p, h, kcl, vcl, bt_g, cfg, G,
                                        write_pos, pos_g, sel, pos_mask,
                                        paged_kernel, mesh)
        return h, (kcl, vcl)

    x, (kc, vc) = lax.scan(body, x, (params["blocks"], kc, vc))
    x = layer_norm_fn(cfg)(x, params["ln_f_scale"], params["ln_f_bias"])
    logits = (x @ params["wte"].astype(cfg.dtype).T).astype(jnp.float32)
    return logits, kc, vc


def gpt2_decode_paged(params: Dict[str, Any], kc: jax.Array,
                      vc: jax.Array, tokens: jax.Array,
                      lengths: jax.Array, block_tables: jax.Array,
                      cfg: GPT2Config, num_groups: int,
                      paged_kernel: bool = False, mesh=None
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One paged decode step for every slot: the K=1 verify. Returns
    (logits [S, V] fp32, kc', vc') — same contract as ``gpt2_decode``
    with the block table standing in for the slot-major rows."""
    logits, kc, vc = gpt2_verify_paged(params, kc, vc, tokens[:, None],
                                       lengths, block_tables, cfg,
                                       num_groups, paged_kernel, mesh)
    return logits[:, 0], kc, vc


def gpt2_prefill_chunk_paged(params: Dict[str, Any], kc: jax.Array,
                             vc: jax.Array, tokens: jax.Array,
                             bt_rows: jax.Array, start: jax.Array,
                             last_idx: jax.Array, active: jax.Array,
                             cfg: GPT2Config,
                             paged_kernel: bool = False, mesh=None
                             ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Group-batched chunked prefill: one prompt chunk for ONE slot per
    group (the paged twin of ``gpt2_prefill_chunk``).

    tokens: [G, C]; bt_rows: [G, J] — each group's target slot's block
    table row (DEAD_BLOCK rows for groups with nothing to prefill);
    start/last_idx/active: [G]. Writes each chunk's K/V through its
    group's table and attends against the slot's whole cached row under
    the global-position causal mask. Returns (logits [G, V] fp32 at
    ``last_idx``, kc', vc'). Inactive groups compute garbage that
    writes nowhere — the uniform-program rule that keeps ONE compiled
    shape for any admission pattern.
    """
    _check_cfg(cfg)
    G, C = tokens.shape
    J = bt_rows.shape[-1]
    bs = kc.shape[4]
    pos = start[:, None] + jnp.arange(C, dtype=jnp.int32)[None]  # [G, C]
    x = params["wte"].astype(cfg.dtype)[tokens] + \
        params["wpe"].astype(cfg.dtype)[pos]         # [G, C, H]
    bt_g = jnp.where(active[:, None, None] > 0, bt_rows[:, None],
                     kv_cache.DEAD_BLOCK)            # [G, 1, J]
    pos_g = pos[:, None, :]                          # [G, 1, C]
    sel = pos_mask = None
    if not paged_kernel:
        sel = kv_cache.block_select(bt_g, kc.shape[2])
        grid = lax.broadcasted_iota(jnp.int32, (1, 1, 1, J * bs), 3)
        pos_mask = grid <= pos[:, None, :, None]     # [G, 1, C, J*bs]
    write_pos = pos                                  # [G, C]

    def body(h, layer):
        p, kcl, vcl = layer
        h, kcl, vcl = _paged_attn_block(p, h, kcl, vcl, bt_g, cfg, G,
                                        write_pos, pos_g, sel, pos_mask,
                                        paged_kernel, mesh)
        return h, (kcl, vcl)

    x, (kc, vc) = lax.scan(body, x, (params["blocks"], kc, vc))
    x = layer_norm_fn(cfg)(x, params["ln_f_scale"], params["ln_f_bias"])
    oh = (lax.broadcasted_iota(jnp.int32, (G, C), 1) ==
          last_idx[:, None]).astype(x.dtype)
    h_last = jnp.einsum("gc,gch->gh", oh, x)
    logits = (h_last @ params["wte"].astype(cfg.dtype).T
              ).astype(jnp.float32)
    return logits, kc, vc


def gpt2_prefill_full_paged(params: Dict[str, Any], kc: jax.Array,
                            vc: jax.Array, tokens: jax.Array,
                            bt_rows: jax.Array, last_idx: jax.Array,
                            cfg: GPT2Config,
                            attention_fn: Optional[Callable] = None
                            ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Whole-prompt single-shot prefill (``prefill_chunk: 0``) into the
    block pool: the same pluggable-attention forward as
    ``gpt2_prefill_full`` (ring attention plugs in identically), with
    the per-layer K/V splice routed through the target slot's block
    table instead of a slot-major ``dynamic_update_slice``. tokens: [T]
    padded to max_len; bt_rows: [G, J] — the slot's row in its own
    group, DEAD_BLOCK rows elsewhere, so the write lands only in the
    owning dp shard."""
    _check_cfg(cfg)
    if attention_fn is None:
        from ..ops.flash_attention import auto_attention
        attention_fn = auto_attention
    T = tokens.shape[0]
    G = bt_rows.shape[0]
    bs = kc.shape[4]
    x = (params["wte"].astype(cfg.dtype)[tokens] +
         params["wpe"].astype(cfg.dtype)[:T])[None]        # [1, T, H]

    def body(h, p):
        q, k, v = _qkv(p, h, cfg)                  # [1, T, nH, D]
        attn = attention_fn(q, k, v, mask=None, causal=True,
                            deterministic=True)
        attn = attn.reshape(h.shape).astype(h.dtype)
        h = h + dense(attn, p["proj_kernel"], p["proj_bias"])
        return _ffn(p, h, cfg), (k[0], v[0])       # ys: [T, nH, D]

    x, (ks, vs) = lax.scan(body, x, params["blocks"])
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (G, T))
    bt_per_row = jnp.broadcast_to(bt_rows[:, None, :],
                                  (G, T, bt_rows.shape[-1]))
    blk, off = kv_cache.positions_to_blocks(bt_per_row, pos, bs)

    def splice(pool, rows):
        return kv_cache.paged_write_rows(
            pool, jnp.broadcast_to(rows[None], (G,) + rows.shape),
            blk, off)

    kc = jax.vmap(splice)(kc, ks)
    vc = jax.vmap(splice)(vc, vs)
    x = layer_norm_fn(cfg)(x[0], params["ln_f_scale"],
                           params["ln_f_bias"])
    h_last = lax.dynamic_slice(x, (last_idx.astype(jnp.int32),
                                   jnp.int32(0)), (1, x.shape[1]))[0]
    logits = (h_last @ params["wte"].astype(cfg.dtype).T
              ).astype(jnp.float32)
    return logits, kc, vc


def spec_accept(logits: jax.Array, tokens: jax.Array, key: jax.Array,
                temperature: jax.Array) -> jax.Array:
    """In-graph draft acceptance: the longest agreeing prefix rule.

    logits: [S, K, V] from the verify step over [last, d_1..d_{K-1}];
    tokens: the [S, K] verify input. Greedy target g[s,i] =
    argmax(logits[s,i]); draft d_i is accepted iff every d_{i'<=i}
    matched g at its position, and the emitted stream is g[s, :m+1]
    (accepted drafts ARE the greedy tokens, plus the first correction /
    bonus) — which is exactly what non-speculative greedy decode would
    have produced token by token. Returns [S, K+1] int32: column 0 is
    n_new (how many of the following tokens are real), columns 1..K the
    emitted tokens — one array, ONE host fetch per iteration.
    """
    S, K = tokens.shape
    g = sample_tokens(logits, key, temperature)          # [S, K]
    match = (tokens[:, 1:] == g[:, :-1]).astype(jnp.int32)   # [S, K-1]
    acc = jnp.cumprod(match, axis=-1).sum(-1) if K > 1 else \
        jnp.zeros((S,), jnp.int32)
    n_new = (acc + 1).astype(jnp.int32)                  # [S]
    return jnp.concatenate([n_new[:, None], g], axis=-1)


# --------------------------------------------------------------------- #
# Sampling (in-graph; PRNG threaded by the engine per iteration)
# --------------------------------------------------------------------- #
def sample_tokens(logits: jax.Array, key: jax.Array,
                  temperature: jax.Array) -> jax.Array:
    """Greedy (temperature == 0) or temperature sampling; logits
    [..., V] fp32. Temperature is a TRACED scalar so changing it never
    recompiles; both branches are cheap relative to the step, so a
    select beats a cond."""
    greedy = jnp.argmax(logits, axis=-1)
    t = jnp.maximum(temperature.astype(jnp.float32), 1e-6)
    sampled = jax.random.categorical(key, logits / t, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)


__all__ = ["gpt2_decode", "gpt2_prefill_chunk", "gpt2_prefill_full",
           "gpt2_decode_paged", "gpt2_verify_paged",
           "gpt2_prefill_chunk_paged", "gpt2_prefill_full_paged",
           "spec_accept", "sample_tokens"]
